"""Async HTTP/SSE serving gateway over PagedEngine (ISSUE 9 tentpole;
reference: vLLM's OpenAI front end + continuous-batching engine loop,
restated stdlib-only).

This is the front door ROADMAP item 2 asks for: the piece that turns
"an engine" into "a service". Dependency policy matches
``tools/obs_report.py --serve`` — stdlib only (``asyncio`` +
hand-parsed HTTP/1.1 over ``asyncio.start_server``), so the gateway
runs anywhere the engine does.

Architecture (one process, N replicas):

- **HTTP layer (asyncio)** — ``POST /v1/generate`` takes a JSON body
  (token-id prompt + sampling params + SLO class/tenant/priority) and
  answers either a JSON completion or an SSE token stream
  (``text/event-stream``, one ``data:`` event per token, a final
  ``done`` event carrying the full stop-trimmed token list).
  ``GET /healthz`` is the aggregated health snapshot; ``GET /metrics``
  serves the live observability registry in Prometheus text format —
  the same objects ``health()`` reads, pinned equal by test.
- **Replica workers (one thread per engine)** — ``PagedEngine`` is
  single-threaded by design, so ALL engine access (submit / step /
  cancel) happens on that replica's tick thread. The thread loop:
  drain posted control ops (cancels), reap scheduler-expired requests,
  admit from the :class:`SLOScheduler` exactly while the engine has a
  free slot and an empty queue (iteration-level continuous batching —
  the policy queue stays in the scheduler where it can still be
  reordered or shed), then one ``engine.step()`` and a token dispatch
  that mirrors ``PagedEngine.stream()``'s hold-back semantics, so a
  gateway SSE stream is BIT-IDENTICAL to a direct engine stream (a
  yielded token is never retracted by a stop trim). Ring-mode engines
  (ISSUE 11, the default) surface each dispatch's tokens on the NEXT
  ``step()`` — the tick thread consumes drained ring entries exactly
  as it consumed the synchronous readback, so the dispatch loop below
  is readback-architecture agnostic: against a ``ring_mode=False``
  engine the SSE byte stream is bitwise the pre-ring one, and in ring
  mode each request's byte stream is identical with token batches
  landing one tick later (cancels posted to the tick thread drain the
  in-flight dispatch before releasing the slot — ``/debugz`` shows
  per-engine ring drain/blocking counters).
- **Router** — :class:`PrefixAffinityRouter` keyed by
  ``PagedEngine.prefix_digest()`` picks the replica whose prefix cache
  already holds the prompt's shared span (least-loaded fallback,
  health eviction).
- **Drain** — SIGTERM (via ``utils.shutdown.GracefulShutdown``) latches
  draining: new requests get 503 + Retry-After, in-flight requests
  finish, workers exit once their engines are empty, metrics flush
  (``observability.flush()``), the listener closes. Rolling restarts
  lose nothing that already got a slot.

Token events cross from tick threads to the asyncio loop via
``loop.call_soon_threadsafe`` onto per-request queues; a client that
disconnects mid-stream is detected at the SSE writer (EOF watch or a
failed ``drain()``) and its request is cancelled ON THE TICK THREAD
(``engine.cancel`` frees the slot and blocks immediately — a dropped
stream never strands a slot).
"""
from __future__ import annotations

import asyncio
import itertools
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils import faults
from ..utils import observability as obs
from ..utils.faults import BackpressureError
from ..utils.shutdown import GracefulShutdown
from . import kvxfer
from .reqtrace import RequestTrace, RequestTraceRing
from .router import EngineReplica, NoReplicaError, PrefixAffinityRouter
from .scheduler import (SLO_BATCH, SLO_INTERACTIVE, ServeRequest,
                        ShedError, SLOScheduler)
from .slo import BurnRateEngine
from .supervisor import BREAKER_CLOSED, CircuitBreaker, ReplicaSupervisor

__all__ = ["Gateway"]

_gateway_ids = itertools.count()

_SSE_HEAD = (b"HTTP/1.1 200 OK\r\n"
             b"Content-Type: text/event-stream\r\n"
             b"Cache-Control: no-cache\r\n"
             b"Connection: close\r\n\r\n")


def _http_response(status: int, body: bytes,
                   ctype: str = "application/json",
                   extra: Dict[str, str] = None) -> bytes:
    reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
              429: "Too Many Requests", 500: "Internal Server Error",
              503: "Service Unavailable", 504: "Gateway Timeout"}.get(
                  status, "OK")
    head = [f"HTTP/1.1 {status} {reason}",
            f"Content-Type: {ctype}",
            f"Content-Length: {len(body)}",
            "Connection: close"]
    for k, v in (extra or {}).items():
        head.append(f"{k}: {v}")
    return ("\r\n".join(head) + "\r\n\r\n").encode() + body


def _json_response(status: int, payload: Dict[str, Any],
                   extra: Dict[str, str] = None) -> bytes:
    return _http_response(status, json.dumps(payload).encode(),
                          extra=extra)


def _query_param(query: str, key: str, conv=float):
    """``?key=value`` lookup in a raw query string (last occurrence
    wins), parsed with ``conv``; None when absent or unparseable.
    Shared by the gateway's and the fleet frontend's HTTP handlers."""
    out = None
    for part in query.split("&"):
        k, _, v = part.partition("=")
        if k == key:
            try:
                out = conv(v)
            except ValueError:
                pass
    return out


def _release_probe(req: ServeRequest, replica, success=None):
    """Report a probation probe's terminal outcome to its breaker.
    EVERY path that terminates a probe request must come through here
    (or probe_done directly): a probe that ends without reporting
    leaks the breaker's single in-flight slot and the replica can
    never rejoin. ``None`` = inconclusive (expiry/shed/disconnect —
    releases the slot without moving the state machine)."""
    if req.probe:
        b = getattr(replica, "breaker", None)
        if b is not None:
            b.probe_done(success)
        req.probe = False


class _ReplicaWorker(threading.Thread):
    """Owns ONE PagedEngine: the only thread that ever touches it.

    ``tick_lock`` serializes ``engine.step()`` across replicas that
    share one underlying MODEL object: ``Layer.functional()``'s pure
    fn binds params onto the shared layer tree for the duration of a
    call, so two threads tracing/running through the same model
    concurrently corrupt each other (UnexpectedTracerError at best).
    Replicas built over distinct model instances get distinct locks
    and tick freely."""

    def __init__(self, gw: "Gateway", replica: EngineReplica,
                 sched: SLOScheduler, tick_lock: threading.Lock,
                 ring: Optional[RequestTraceRing] = None):
        super().__init__(daemon=True,
                         name=f"gateway-{gw.name}-{replica.name}")
        self.gw = gw
        self.replica = replica
        self.engine = replica.engine
        self.sched = sched
        self._tick_lock = tick_lock
        self._ops: deque = deque()
        self._wake = threading.Event()
        self._live: Dict[Any, ServeRequest] = {}
        self.draining = False
        # fleet fault tolerance (ISSUE 12): ``failed`` latches once the
        # failover hand-off ran (crash path on this thread, hang/drop
        # on the supervisor — the latch makes them exclusive);
        # ``abandoned`` tells a still-running (hung) thread a
        # replacement owns the engine now — it must exit without
        # touching shared state. ``t_busy`` is the watchdog's
        # dispatch-to-drain deadline anchor: set before the engine
        # step, cleared after the token dispatch. ``_chaos`` is the
        # chaos harness's one-shot replica-addressed fault.
        self.failed = False
        self.fail_reason: Optional[str] = None
        self.rebuild_failed = False
        self.rebuilding = False
        self.abandoned = False
        self.t_busy: Optional[float] = None
        # False until the first dispatch completes: a COLD engine's
        # first step pays the executable build/deserialize, so the
        # watchdog grants it a 10x grace deadline instead of reading
        # the compile as a hang. An engine that has dispatched before
        # (factory-warmed, or rebuilt in place with its jit caches
        # intact) starts warmed and serves under the strict deadline
        # from its first request.
        self.warmed = getattr(replica.engine, "dispatch_count", 0) > 0
        self._chaos: Optional[str] = None
        # orders token emission against the failover snapshot: the
        # tick thread holds it across _dispatch, the failover path
        # holds it while latching ``abandoned`` and snapshotting/
        # clearing ``_live`` — so a slow-but-alive step that outlives
        # the watchdog can never emit concurrently with (or after)
        # the failover's re-delivery of the same requests
        self._io_lock = threading.Lock()
        rl = dict(gw._labels, replica=replica.name)
        # request-trace ring (ISSUE 10 tentpole): this replica's
        # per-request timelines; the engine reports its lifecycle
        # events through trace_sink (resolved via _live, which is
        # populated BEFORE submit so queue-time events land too).
        # A rebuilt replica (ISSUE 12) inherits its predecessor's ring
        # so the failure's timelines survive the restart.
        self.ring = ring
        if gw._trace:
            if self.ring is None:
                self.ring = RequestTraceRing(
                    capacity=gw._trace_capacity,
                    slow_ttft_ms=gw._slow_ttft_ms, labels=rl)
            self.engine.trace_sink = self._engine_trace
        # autoscaler signals (ISSUE 10 satellite / ROADMAP 2c): free
        # capacity gauges an external controller can scrape, updated
        # from the tick loop — the same registry the scheduler's
        # gateway_queue_depth already lives in
        reg = obs.registry()
        self._g_free_slots = reg.gauge("engine_free_slots", **rl)
        self._g_block_free = reg.gauge("block_pool_free_frac", **rl)

    def _engine_trace(self, request_id, kind, **fields):
        """PagedEngine.trace_sink target: resolve the engine's typed
        event onto the live request's trace (tick thread only)."""
        req = self._live.get(request_id)
        if req is not None and req.trace is not None:
            req.trace.ev(kind, **fields)

    def _trace_finish(self, req: ServeRequest, outcome: str,
                      tpot_ms: Optional[float] = None):
        if self.ring is not None and req.trace is not None:
            self.ring.finish(req.trace, outcome, tokens=req.n_out,
                             tpot_ms=tpot_ms)

    def _set_capacity_gauges(self):
        """Autoscaler signals (ISSUE 10 satellite / ROADMAP 2c): free
        slots + allocatable-block fraction, scrapeable from the same
        registry the scheduler's gateway_queue_depth lives in. O(1)
        host reads, refreshed around every tick."""
        eng = self.engine
        self._g_free_slots.set(sum(s is None for s in eng.slots))
        self._g_block_free.set(
            (len(eng.free_blocks) + len(eng.cached_free))
            / max(eng.P - 1, 1))

    # ------------------------------------------------------- cross-thread
    def post(self, fn):
        """Run ``fn`` on the tick thread before the next step."""
        self._ops.append(fn)
        self._wake.set()

    def wake(self):
        self._wake.set()

    def inject_fault(self, kind: str):
        """Chaos-harness hook (``tools/serve_loadgen.py --chaos``):
        arm a one-shot replica fault handled at the top of the next
        tick — the same code paths the seeded ``tick_crash`` /
        ``dispatch_hang`` / ``replica_drop`` fault sites take, but
        addressed to THIS replica deterministically."""
        if kind not in ("crash", "hang", "drop"):
            raise ValueError(f"unknown chaos kind {kind!r}")
        self._chaos = kind
        self._wake.set()

    def cancel_request(self, request_id, req: ServeRequest = None):
        """Client gone: drop it from wherever it currently lives —
        scheduler queue (never reached the engine) or the engine
        itself (slot + blocks free immediately). The engine-side
        record dicts are consumed here too (runs on the tick thread):
        nobody will ever read this request's result, and `_dispatch`
        only reaps rids still in `_live`, so leaving them would leak
        one entry per disconnect in a long-running gateway. ``req``
        lets the caller hand over a still-queued request (not yet in
        ``_live``) so its trace still closes."""
        req = self._live.get(request_id, req)
        if not self.sched.cancel(request_id):
            self.engine.cancel(request_id)
            self.engine.cancelled.pop(request_id, None)
            self.engine.results.pop(request_id, None)
            self.engine.logprobs.pop(request_id, None)
        self._live.pop(request_id, None)
        if req is not None:
            # a disconnected probe proves nothing: slot released only
            _release_probe(req, self.replica)
            self._trace_finish(req, "disconnect")

    def _emit(self, req: ServeRequest, ev):
        if req.sink is None:
            return
        try:
            self.gw._loop.call_soon_threadsafe(req.sink.put_nowait, ev)
        except RuntimeError:   # loop already closed (teardown)
            pass

    # ------------------------------------------------------------ tick loop
    def run(self):
        eng = self.engine
        rname = self.replica.name
        while True:
            if self.abandoned:
                return        # a replacement worker owns the engine now
            # chaos entry points (ISSUE 12): the seeded fault sites +
            # the loadgen's replica-addressed one-shots share one code
            # path, so the chaos harness exercises exactly what real
            # failures would hit. crash/hang stay ARMED until the
            # worker is actually busy (an idle-tick kill that fizzles
            # would understate the harness's injected-kill count).
            if self._chaos == "drop" or faults.inject("replica_drop",
                                                      replica=rname):
                return        # hard exit, NO cleanup: the supervisor
                              # finds the corpse and fails over
            while self._ops:
                op = self._ops.popleft()
                try:
                    op()
                except Exception as e:   # a bad op must not kill serving
                    obs.record_event("gateway_op_error",
                                     gateway=self.gw.name, err=repr(e))
            now = time.monotonic()
            for req in self.sched.reap(now):
                # satellite: expired in QUEUE — cancelled before it
                # ever took a slot; the scheduler already counted it
                _release_probe(req, self.replica)
                self._emit(req, ("done", {"tokens": [],
                                          "finish_reason": "timeout"}))
                self._trace_finish(req, "expired")
            while (req := self._pop_admissible()) is not None:
                self._admit(req, time.monotonic())
            self._set_capacity_gauges()
            if eng.queue or any(s is not None for s in eng.slots):
                chaos, self._chaos = self._chaos, None
                try:
                    if chaos == "crash" or faults.inject("tick_crash",
                                                         replica=rname):
                        raise RuntimeError("injected tick_crash")
                    if chaos == "hang" or faults.inject("dispatch_hang",
                                                        replica=rname):
                        # the injected hang IS dispatch latency: open
                        # the watchdog window before sleeping
                        self.t_busy = time.monotonic()
                        time.sleep(faults.dispatch_hang_seconds())
                    if faults.inject("slow_replica", replica=rname):
                        time.sleep(faults.slow_replica_seconds())
                    if self.abandoned:
                        # the watchdog fired while we slept: requests
                        # failed over, the engine was rebuilt for a
                        # replacement worker — touch NOTHING
                        return
                    with self._tick_lock:
                        # the dispatch-to-drain watchdog window opens
                        # INSIDE the lock: waiting for a shared-model
                        # sibling's tick is not THIS replica's hang,
                        # and must not cascade watchdog fires onto
                        # healthy siblings (a real in-step hang that
                        # never releases the shared lock leaves its
                        # siblings blocked-but-undetected — run
                        # distinct model instances for isolation,
                        # as the chaos loadgen does)
                        self.t_busy = time.monotonic()
                        eng.step()
                except Exception as e:
                    self._fail_all(e)
                    return
                with self._io_lock:
                    if self.abandoned:
                        # a slow-but-not-hung step outlived the
                        # watchdog: the failover path owns every live
                        # request now — emit nothing, touch nothing
                        return
                    self._dispatch()
                self.t_busy = None
                # first full dispatch done: the cold-start compile is
                # paid, so the watchdog's grace multiplier drops and
                # the strict deadline applies from here on
                self.warmed = True
                # post-tick refresh: a scrape between ticks sees the
                # capacity the step just freed, not last tick's view
                self._set_capacity_gauges()
            else:
                if self.draining and self.sched.depth() == 0 \
                        and not self._live:
                    return
                self._wake.wait(0.005)
                self._wake.clear()

    def _pop_admissible(self) -> Optional[ServeRequest]:
        """Hand the engine up to FREE-SLOT-many requests per tick (its
        own step() admits every queued request that fits, so a burst
        fills the batch in ONE tick instead of one-per-forward), but
        never build a deeper engine backlog than that: requests beyond
        the free slots stay in the scheduler, where policy can still
        reorder, promote, or expire them."""
        eng = self.engine
        free = sum(s is None for s in eng.slots)
        if len(eng.queue) >= free:
            return None
        return self.sched.pop()

    def _admit(self, req: ServeRequest, now: float):
        ids = req.input_ids
        if req.resume is None:
            kw = dict(req.gen)
        else:
            # failover resume (ISSUE 12): re-prefill prompt+committed
            # on THIS replica and continue from where the dead one
            # stopped — the engine's preemption fold, across replicas.
            # A seeded sampled request re-derives a per-attempt key
            # (distribution-preserving, not bitwise; an unseeded one
            # just gets this engine's fresh counter stream).
            d = req.resume
            ids = d["prompt"]
            kw = dict(max_new_tokens=max(int(d["remaining"]), 1),
                      temperature=d["temperature"], top_k=d["top_k"],
                      top_p=d["top_p"], repetition_penalty=d["rep"],
                      resume_tokens=d["committed"],
                      resume_lps=d["committed_lps"])
            if d["eos"] is not None:
                kw["eos_token_id"] = d["eos"]
            if d["stop"]:
                kw["stop_sequences"] = d["stop"]
            seed = req.gen.get("seed")
            if seed is not None:
                kw["seed"] = int(seed) + 0x9E3779B1 * req.failovers
        if req.deadline is not None:
            # thread the REMAINING deadline budget into the engine so
            # in-slot expiry uses its own timeout machinery
            kw["timeout_s"] = max(req.deadline - now, 1e-3)
        # register BEFORE submit: the engine's trace_sink resolves
        # request ids through _live, and submit itself emits the
        # engine_queue event
        self._live[req.request_id] = req
        try:
            self.engine.submit(req.request_id,
                               np.asarray([ids], np.int32),
                               **kw)
        except BackpressureError as e:
            # transient overload (an engine also taking out-of-band
            # submit() traffic filled its queue since the free-slot
            # check) — shed, don't tell the client its request was bad
            self._live.pop(req.request_id, None)
            _release_probe(req, self.replica)
            self._emit(req, ("error", 429, str(e)))
            self._trace_finish(req, "shed")
            return
        except Exception as e:
            self._live.pop(req.request_id, None)
            _release_probe(req, self.replica)
            self._emit(req, ("error", 400, str(e)))
            self._trace_finish(req, "error")
            return
        req.t_admit = now

    def _fail_all(self, err: Exception):
        """Tick-thread failure exit. Hardening satellite (ISSUE 12):
        live requests now route through the FAILOVER path — each is
        resubmitted to a surviving replica as prompt + committed
        tokens; the bare error is only the no-survivor fallback inside
        ``Gateway._failover_worker``. The supervisor then rebuilds
        this replica's engine and rejoins it through the breaker."""
        obs.record_event("gateway_replica_error", gateway=self.gw.name,
                         replica=self.replica.name, err=repr(err))
        self.gw._failover_worker(self, reason="crash", err=err)

    def flush_queue(self, status: int, msg: str):
        """Error out every request still waiting in the scheduler —
        the dead/exiting-worker path: a queued client must get an
        answer, never a hang. Safe off the tick thread once the
        thread is gone (the scheduler locks internally)."""
        for req in self.sched.reap():
            _release_probe(req, self.replica)
            self._emit(req, ("done", {"tokens": [],
                                      "finish_reason": "timeout"}))
            self._trace_finish(req, "expired")
        while (req := self.sched.pop()) is not None:
            _release_probe(req, self.replica)
            self._emit(req, ("error", status, msg))
            self._trace_finish(req, "error")

    # ------------------------------------------------------------ dispatch
    def _token_out(self, req: ServeRequest, tok: int, now: float,
                   lp: Optional[float] = None):
        if req.t_first is None:
            req.t_first = now
            self.gw._h_ttft.observe((now - req.t_enqueue) * 1e3,
                                    exemplar=req.request_id)
            if req.trace is not None:
                req.trace.ev("first_token",
                             ttft_ms=round(
                                 (now - req.t_enqueue) * 1e3, 3))
        req.t_last = now
        req.n_out += 1
        self.gw._c_tokens.inc()
        # the event carries the token's logprob too (ISSUE 13): a fleet
        # frontend proxying this stream needs (token, lp) pairs to
        # resubmit prompt+committed WITH logprobs on a surviving peer,
        # so a failed-over stream's final logprob list stays bitwise
        # the uninterrupted run's. NaN (an lp-less resume prefix) maps
        # to null — json.dumps would otherwise emit invalid JSON.
        if lp is not None and lp != lp:
            lp = None
        self._emit(req, ("token", int(tok),
                         float(lp) if lp is not None else None))

    def _finish(self, req: ServeRequest, payload: Dict[str, Any],
                now: float):
        tpot_ms = None
        if req.t_first is not None and req.n_out >= 2:
            tpot_ms = ((req.t_last - req.t_first)
                       / (req.n_out - 1) * 1e3)
            self.gw._h_tpot.observe(tpot_ms, exemplar=req.request_id)
        self.gw._c_completed.inc()
        self.sched.note_service(now - req.t_enqueue)
        self._emit(req, ("done", payload))
        reason = payload.get("finish_reason", "stop")
        outcome = {"stop": "stop", "timeout": "timeout",
                   "cancelled": "cancelled"}.get(reason, "error")
        if req.probe:
            # circuit-breaker probation (ISSUE 12): a clean finish
            # counts toward closing; an engine timeout/cancel proves
            # nothing and just releases the probe slot
            b = getattr(self.replica, "breaker", None)
            if b is not None:
                b.probe_done(True if reason == "stop" else None)
                if b.state == BREAKER_CLOSED and req.trace is not None:
                    req.trace.ev("breaker_close",
                                 replica=self.replica.name)
            req.probe = False
        elif reason == "stop":
            # ordinary successes clear the consecutive-failure count —
            # what makes failure_threshold > 1 mean CONSECUTIVE, not
            # "N failures over the replica's lifetime"
            b = getattr(self.replica, "breaker", None)
            if b is not None:
                b.record_success()
        if req.trace is not None:
            req.trace.ev("finish", reason=reason, tokens=req.n_out)
        self._trace_finish(req, outcome, tpot_ms=tpot_ms)
        # goodput (ISSUE 10 satellite): tokens from requests that met
        # their TTFT SLO (batch traffic has none — completing counts)
        if reason == "stop" and req.n_out:
            ttft_ms = ((req.t_first - req.t_enqueue) * 1e3
                       if req.t_first is not None else None)
            if req.slo != SLO_INTERACTIVE or (
                    ttft_ms is not None
                    and ttft_ms <= self.gw._slow_ttft_ms):
                self.gw._c_good_tokens.inc(req.n_out)
            self.gw._g_goodput.set(
                self.gw._c_good_tokens.value
                / max(self.gw._c_tokens.value, 1.0))

    def _dispatch(self):
        """Push this tick's newly emitted tokens (stream()'s hold-back
        rule, verbatim) and resolve finished / aborted requests."""
        eng = self.engine
        now = time.monotonic()
        for s in eng.slots:
            if s is None:
                continue
            req = self._live.get(s.request_id)
            if req is None:
                continue
            hold = max((len(x) for x in s.stop), default=0)
            n_pre = len(s.prefix)
            start = req.emitted
            upto = max(n_pre + len(s.tokens) - hold, start)
            for i in range(start, upto):
                if i < n_pre:
                    tok = s.prefix[i]
                    lp = (s.prefix_lps[i]
                          if i < len(s.prefix_lps) else None)
                else:
                    tok = s.tokens[i - n_pre]
                    lp = (s.lps[i - n_pre]
                          if i - n_pre < len(s.lps) else None)
                self._token_out(req, tok, now, lp=lp)
            req.emitted = upto
            if upto > start and req.trace is not None:
                req.trace.ev("stream_write", n=upto - start)
        for rid in [r for r in self._live if r in eng.results]:
            req = self._live.pop(rid)
            toks = eng.results.pop(rid)
            lps = eng.logprobs.pop(rid, [])
            n_tail = len(toks) - req.emitted
            for i in range(req.emitted, len(toks)):
                self._token_out(req, toks[i], now,
                                lp=lps[i] if i < len(lps) else None)
            req.emitted = len(toks)
            if n_tail > 0 and req.trace is not None:
                req.trace.ev("stream_write", n=n_tail)
            self._finish(req, {"tokens": [int(t) for t in toks],
                               "logprobs": [float(v) for v in lps],
                               "finish_reason": "stop"}, now)
        for rid in [r for r in self._live if r in eng.cancelled]:
            req = self._live.pop(rid)
            reason = eng.cancelled.pop(rid)
            self._finish(req, {"tokens": [],
                               "finish_reason": reason}, now)


class Gateway:
    """Serve one or more PagedEngine replicas over HTTP/SSE.

    ``engines``: a single engine or a list (each becomes a replica with
    its own tick thread + SLO scheduler). ``port=0`` binds an ephemeral
    port (``self.port`` after ``start()``).
    """

    def __init__(self, engines, host: str = "127.0.0.1", port: int = 0,
                 *, max_queue: int = 256,
                 interactive_ttft_ms: float = 500.0,
                 promote_after_ms: float = 2000.0,
                 routing: str = "prefix", spill_margin: float = 8.0,
                 shutdown: Optional[GracefulShutdown] = None,
                 name: Optional[str] = None,
                 trace: bool = True, trace_capacity: int = 512,
                 slow_ttft_ms: Optional[float] = None,
                 supervise: bool = True,
                 engine_factory=None,
                 spill_arena=None,
                 migrate_on_drain: bool = False,
                 xfer_grace_s: float = 0.5,
                 failover_budget: int = 2,
                 watchdog_timeout_s: float = 30.0,
                 watchdog_interval_s: float = 0.05,
                 breaker_backoff_s: float = 1.0,
                 breaker_backoff_max_s: float = 30.0,
                 breaker_probes: int = 1,
                 sample_interval_s: Optional[float] = 0.25,
                 sample_capacity: int = 512,
                 slo_alerting: bool = True,
                 slo_targets: Optional[Dict[str, float]] = None,
                 slo_rules=None,
                 slo_window_scale: float = 1.0):
        """Fleet fault tolerance (ISSUE 12): ``supervise`` (default on)
        runs the :class:`~.supervisor.ReplicaSupervisor` — tick-thread
        crash/hang detection (``watchdog_timeout_s`` is the
        dispatch-to-drain deadline), engine rebuild
        (``engine_factory()`` when given, ``PagedEngine.hard_reset()``
        in place otherwise) and circuit-breaker rejoin
        (``breaker_backoff_s`` exponential backoff before the first
        probation probe, ``breaker_probes`` successes to close).
        ``failover_budget`` caps how many replica failures one request
        may ride through before it errors out — the amplification
        bound under cascading failures.

        Telemetry plane (ISSUE 15): ``sample_interval_s`` runs a
        :class:`~paddle_tpu.utils.observability.MetricsTimeSeries`
        sampler (None/0 disables — today's snapshot-only behavior)
        that backs ``GET /metricsz?window_s=N`` and the
        ``series_<gateway>.json`` drain artifact; ``slo_alerting``
        runs a :class:`~.slo.BurnRateEngine` over the reqtrace
        outcome stream (requires ``trace=True`` — the ring's
        idempotent finish is the dedupe point), with
        ``slo_window_scale`` shrinking the burn windows for
        CI-speed runs. Both are host-side and pull-only: streams and
        the steady-tick dispatch/upload pins are unchanged with the
        plane on (pinned by ``tests/test_telemetry.py``)."""
        if not isinstance(engines, (list, tuple)):
            engines = [engines]
        self.name = name or f"gw{next(_gateway_ids)}"
        self.host, self.port = host, port
        self._labels = {"gateway": self.name}
        self._shutdown = shutdown
        self._draining = False
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # one /profilez capture at a time (ISSUE 20): concurrent
        # captures would fight over utils.profiler's single-trace
        # ownership — the second caller gets 409, not a corrupt trace
        self._profilez_busy = False
        # request-scoped tracing (ISSUE 10): default ON — the whole
        # path is host-side bookkeeping, pinned to change nothing
        # (bit-identical streams, same dispatch/upload counters).
        # ``slow_ttft_ms`` is the DETERMINISTIC tail-retention
        # threshold (default: the interactive TTFT SLO — "slow" means
        # "missed its SLO"), shared with the goodput gauge.
        self._trace = bool(trace)
        self._trace_capacity = int(trace_capacity)
        self._slow_ttft_ms = float(
            interactive_ttft_ms if slow_ttft_ms is None
            else slow_ttft_ms)
        reg = obs.registry()
        self._c_requests = {
            slo: reg.counter("gateway_requests_total", slo=slo,
                             **self._labels)
            for slo in (SLO_INTERACTIVE, SLO_BATCH)}
        self._c_shed = reg.counter("gateway_shed_total", **self._labels)
        self._c_completed = reg.counter("gateway_completed_total",
                                        **self._labels)
        self._c_tokens = reg.counter("gateway_tokens_total",
                                     **self._labels)
        self._c_disconnects = reg.counter("gateway_disconnects_total",
                                          **self._labels)
        self._h_ttft = reg.histogram("gateway_ttft_ms",
                                     buckets=obs.SERVING_MS_BUCKETS,
                                     **self._labels)
        self._h_tpot = reg.histogram("gateway_tpot_ms",
                                     buckets=obs.SERVING_MS_BUCKETS,
                                     **self._labels)
        # goodput (ISSUE 10 satellite / ROADMAP 2c): tokens from
        # requests that met their TTFT SLO, plus the running fraction —
        # the autoscaler's quality-of-service signal
        self._c_good_tokens = reg.counter("gateway_good_tokens_total",
                                          **self._labels)
        self._g_goodput = reg.gauge("gateway_goodput_frac",
                                    **self._labels)
        # fleet fault tolerance (ISSUE 12): the failover accounting
        # the supervisor/crash paths share. _fo_lock serializes the
        # per-worker failure latch and the worker-list swap.
        self._engine_factory = engine_factory
        # host-RAM KV spill tier (ISSUE 17): the gateway OWNS the arena
        # precisely because engines don't survive supervisor rebuilds —
        # _make_worker re-attaches it to whatever engine a replica
        # currently runs, so a crashed replica comes back warm. One
        # shared arena per gateway: digests are content-addressed over
        # the token chain, so a span spilled by one replica restores
        # bit-exactly into any sibling with the same geometry.
        self._spill_arena = spill_arena
        # cross-replica KV transfer (ISSUE 18): with migration on, a
        # drain cuts live requests over to a survivor as terminal
        # "migrated" SSE events carrying the committed stream + a
        # resume_kv digest the fleet frontend resolves against /kvz —
        # the resubmit restores the span instead of re-prefilling.
        # _xfer_fetch is the fleet-tier hook _resubmit consults on a
        # local arena miss (settable by an embedding frontend/test):
        # digest hex -> wire blob bytes or None.
        self._migrate_on_drain = bool(migrate_on_drain)
        self._xfer_grace_s = float(xfer_grace_s)
        self._xfer_fetch = None
        self._failover_budget = int(failover_budget)
        self._fo_lock = threading.Lock()
        self._c_failovers = reg.counter("gateway_failovers_total",
                                        **self._labels)
        self._c_fo_exhausted = reg.counter(
            "gateway_retry_budget_exhausted_total", **self._labels)
        self._c_migrated = reg.counter(
            "gateway_migrated_requests_total", **self._labels)
        # telemetry plane (ISSUE 15): the windowed time-series sampler
        # behind /metricsz + the SLO burn-rate engine over the trace
        # rings' outcome stream. Built BEFORE the workers so
        # _make_worker can attach the engine to each ring it creates.
        self.sampler = None
        if sample_interval_s:
            self.sampler = obs.MetricsTimeSeries(
                name=self.name, interval_s=float(sample_interval_s),
                capacity=sample_capacity)
        self._slo: Optional[BurnRateEngine] = None
        if slo_alerting and self._trace:
            self._slo = BurnRateEngine(
                targets=slo_targets, rules=slo_rules,
                window_scale=slo_window_scale, labels=self._labels)
        self._workers: List[_ReplicaWorker] = []
        # prefix-gossip generation ratchet (ISSUE 13): keeps the
        # exported generation monotonic across engine_factory rebuilds
        # (see prefix_digest_summary)
        self._prefix_gen_base = 0
        self._prefix_gen_last = 0
        replicas = []
        # replicas sharing one MODEL object must not tick concurrently
        # (functional()'s pure fn binds params onto the shared layer
        # tree); one lock per distinct model serializes exactly those
        self._model_locks: Dict[int, threading.Lock] = {}
        for i, eng in enumerate(engines):
            rep = EngineReplica(f"r{i}", eng)
            sched = SLOScheduler(
                max_queue=max_queue,
                interactive_ttft_ms=interactive_ttft_ms,
                promote_after_ms=promote_after_ms,
                labels=dict(self._labels, replica=rep.name))
            self._workers.append(self._make_worker(rep, sched))
            replicas.append(rep)
        self._router = PrefixAffinityRouter(
            replicas, policy=routing, spill_margin=spill_margin,
            labels=self._labels)
        self._by_replica = {w.replica: w for w in self._workers}
        # the reference engine defines prompt limits + the digest grid
        self._ref = engines[0]
        self._supervisor: Optional[ReplicaSupervisor] = None
        if supervise:
            for rep in replicas:
                rep.breaker = CircuitBreaker(
                    probes_to_close=breaker_probes,
                    backoff_s=breaker_backoff_s,
                    backoff_max_s=breaker_backoff_max_s,
                    on_state=self._breaker_state_cb(rep))
            self._supervisor = ReplicaSupervisor(
                self, check_interval_s=watchdog_interval_s,
                dispatch_timeout_s=watchdog_timeout_s)

    def _make_worker(self, replica: EngineReplica, sched: SLOScheduler,
                     ring: Optional[RequestTraceRing] = None
                     ) -> _ReplicaWorker:
        """Build a tick-thread worker for ``replica``'s CURRENT engine
        (also the supervisor's rebuild hook — a fresh engine reuses
        the replica name, scheduler, trace ring and metric labels)."""
        key = id(getattr(replica.engine, "model", replica.engine))
        lock = self._model_locks.setdefault(key, threading.Lock())
        if len(self._model_locks) > 256:
            # supervisor rebuilds with a fresh-model factory add one
            # entry per restart; prune entries no current worker uses
            # (kept small enough that a hung thread's still-referenced
            # model — whose id therefore can't be recycled — is never
            # re-keyed onto a fresh lock in practice)
            live = {key} | {
                id(getattr(w.engine, "model", w.engine))
                for w in self._workers}
            self._model_locks = {k: v for k, v in
                                 self._model_locks.items()
                                 if k in live}
        if self._spill_arena is not None \
                and hasattr(replica.engine, "attach_spill"):
            # covers initial build AND supervisor rebuilds: the arena
            # outlives the engine, which is what makes restarts warm
            replica.engine.attach_spill(self._spill_arena)
        w = _ReplicaWorker(self, replica, sched, lock, ring=ring)
        if self._slo is not None and w.ring is not None \
                and self._slo_observe not in w.ring.observers:
            # the burn engine rides the ring's idempotent finish — a
            # rebuilt worker inherits its predecessor's ring, so the
            # observer survives supervisor restarts too
            w.ring.observers.append(self._slo_observe)
        return w

    def _slo_observe(self, entry: Dict[str, Any]):
        """Ring-finish observer (ISSUE 15): fold one terminal outcome
        into the burn-rate engine. 'Bad' = the request broke its
        class's promise — any non-stop outcome, plus (interactive
        only) a TTFT over the SLO threshold, the same rule the
        goodput gauge applies. A zero-token clean finish has no TTFT
        and counts good."""
        eng = self._slo
        if eng is None:
            return
        ttft = entry.get("ttft_ms")
        ok = entry["outcome"] == "stop" and (
            entry["slo"] != SLO_INTERACTIVE
            or ttft is None or ttft <= self._slow_ttft_ms)
        eng.observe(entry["slo"], ok)

    def _breaker_state_cb(self, replica: EngineReplica):
        def cb(state: str):
            if state == BREAKER_CLOSED:
                # breaker closed = probation passed: back in rotation
                replica.mark(True)
            obs.record_event("gateway_breaker", gateway=self.name,
                             replica=replica.name, state=state)
        return cb

    # ------------------------------------------------------------ failover
    def _failover_worker(self, worker: _ReplicaWorker, reason: str,
                         err: Optional[Exception] = None,
                         stuck_ms: Optional[float] = None):
        """Fail ONE replica (ISSUE 12 tentpole): latch it out of
        rotation, open its breaker, and move every live/queued request
        to a surviving replica — resubmitted as ``prompt + committed
        tokens`` with the stream-resume offset, so the client sees no
        duplicate and no gap. Requests that FINISHED on the dead
        replica but were never delivered are completed from its result
        mirrors. Runs on the dying tick thread (crash) or the
        supervisor (hang/drop); the ``failed`` latch makes the two
        callers mutually exclusive."""
        with self._fo_lock:
            if worker.failed:
                return
            worker.failed = True
            worker.fail_reason = reason
        # _io_lock orders this snapshot against the old thread's
        # _dispatch: either its in-flight emission completes first and
        # we snapshot the post-dispatch state, or we latch abandoned
        # first and it emits nothing ever again. (The crash path runs
        # ON the tick thread, which never holds the lock here.) The
        # acquire is BOUNDED: a thread wedged INSIDE _dispatch would
        # otherwise pin the fleet's one supervisor forever — on
        # timeout we proceed unordered (abandoned is latched first,
        # so the wedged dispatch can at worst duplicate-emit into
        # sinks whose requests have already moved on).
        worker.abandoned = True
        locked = worker._io_lock.acquire(timeout=1.0)
        try:
            worker.replica.mark(False)
            # host-mirror snapshot of the dead engine —
            # export_resumable and the result dicts are plain host
            # bookkeeping, safe to read whatever state the
            # device/tick thread is stuck in
            try:
                desc = worker.engine.export_resumable()
            except Exception:
                desc = {}
            try:
                results = dict(worker.engine.results)
                res_lps = dict(worker.engine.logprobs)
            except Exception:
                results, res_lps = {}, {}
            live = list(worker._live.values())
            worker._live.clear()
        finally:
            if locked:
                worker._io_lock.release()
        # crash fast-path (ISSUE 18): the tick thread died but the
        # process — and the device pools — did not. Bank every live
        # request's computed span into the shared arena BEFORE the
        # resubmits below, so the survivor's admission restores them
        # through one H2D scatter instead of re-prefilling
        # prompt+committed. A wedged thread ("hang") may still be
        # inside a dispatch touching the pools, so only provably idle
        # engines are salvaged; any failure here costs exactly one
        # re-prefill, never a token.
        if self._spill_arena is not None and reason != "hang" \
                and hasattr(worker.engine, "spill_live"):
            try:
                worker.engine.spill_live()
            except Exception:
                pass
        breaker = getattr(worker.replica, "breaker", None)
        if breaker is not None:
            breaker.record_failure()
        self._router.evict_unhealthy()
        for r in worker.sched.reap():
            _release_probe(r, worker.replica)
            worker._emit(r, ("done", {"tokens": [],
                                      "finish_reason": "timeout"}))
            worker._trace_finish(r, "expired")
        queued = []
        while (r := worker.sched.pop()) is not None:
            queued.append(r)
        now = time.monotonic()
        for req in live + queued:
            if req.trace is not None:
                if stuck_ms is not None:
                    req.trace.ev("watchdog_fire", stuck_ms=stuck_ms)
                req.trace.ev("replica_fail",
                             replica=worker.replica.name, reason=reason)
                if breaker is not None:
                    req.trace.ev("breaker_open",
                                 replica=worker.replica.name)
            # a probe caught in its target's failure IS the probe's
            # answer: re-open with a longer backoff
            _release_probe(req, worker.replica, False)
            toks = results.get(req.request_id)
            if toks is not None:
                # finished on the dead replica, undelivered: deliver
                # from the result mirrors instead of re-running it
                rl = res_lps.get(req.request_id, [])
                for i in range(req.emitted, len(toks)):
                    worker._token_out(req, toks[i], now,
                                      lp=rl[i] if i < len(rl) else None)
                req.emitted = len(toks)
                worker._finish(
                    req, {"tokens": [int(t) for t in toks],
                          "logprobs": [float(v) for v in
                                       res_lps.get(req.request_id, [])],
                          "finish_reason": "stop"}, now)
                continue
            self._resubmit(req, desc.get(req.request_id), worker)
        obs.record_event("gateway_replica_fail", gateway=self.name,
                         replica=worker.replica.name, reason=reason,
                         moved=len(live) + len(queued),
                         err=repr(err) if err is not None else "")

    def _resubmit(self, req: ServeRequest, desc: Optional[Dict],
                  from_worker: _ReplicaWorker):
        """One request's failover hop: charge the retry budget, pick a
        surviving replica (healthy, alive, and NOT draining — a
        draining replica never accepts failover traffic), attach the
        resume descriptor and re-enqueue through that replica's
        scheduler (failover traffic is still subject to shedding:
        bounded budget + shedding is what keeps a replica failure from
        amplifying into a retry storm under overload)."""
        if desc is not None and int(desc["remaining"]) <= 0:
            # budget fully committed at the kill boundary: deliver the
            # committed stream instead of re-running anything (checked
            # BEFORE the retry budget — a complete result in hand must
            # never be 503'd)
            now = time.monotonic()
            toks = [int(t) for t in desc["committed"]]
            clps = desc["committed_lps"]
            for i in range(req.emitted, len(toks)):
                from_worker._token_out(req, toks[i], now,
                                       lp=clps[i] if i < len(clps)
                                       else None)
            req.emitted = len(toks)
            from_worker._finish(
                req, {"tokens": toks,
                      "logprobs": [float(v)
                                   for v in desc["committed_lps"]],
                      "finish_reason": "stop"}, now)
            return
        req.failovers += 1
        if req.failovers > self._failover_budget:
            self._c_fo_exhausted.inc()
            self._fail_request(
                req, from_worker, 503,
                f"failover budget exhausted after "
                f"{self._failover_budget} replica failures")
            return
        if desc is not None:
            # attach BEFORE any enqueue: the target's tick thread may
            # pop the request the moment it lands
            req.resume = desc
            # fleet spill-tier fast-path (ISSUE 18): make the stream's
            # longest span arena-resident (peer /kvz fetch if needed)
            # before the survivor admits it — the resume then restores
            # instead of re-prefilling
            if self._spill_arena is not None:
                self._xfer_restore(req, desc)
        cands = sorted(
            (w for w in self._workers
             if w is not from_worker and not w.failed
             and not w.abandoned and not w.draining
             and w.is_alive() and w.replica.healthy()),
            key=lambda w: w.replica.load() + w.sched.depth())
        for target in cands:
            req.owner = target
            try:
                eng = target.engine
                target.sched.enqueue(
                    req, engine_health={"queued": len(eng.queue),
                                        "queue_capacity": eng.max_queue})
            except ShedError as e:
                self._c_shed.inc()
                self._fail_request(req, from_worker, 503,
                                   f"failover shed: {e}")
                return
            if target.failed or not target.is_alive():
                # the target failed CONCURRENTLY, after its own queue
                # flush — take the request back and try the next
                # survivor (left queued it would hang forever)
                if target.sched.cancel(req.request_id):
                    continue
                # its failover path already claimed the request
                return
            if req.trace is not None:
                req.trace.ev("resubmit",
                             to_replica=target.replica.name,
                             attempt=req.failovers)
                req.trace.ev("resume_offset", offset=req.emitted,
                             committed=len(desc["committed"])
                             if desc else 0)
            self._c_failovers.inc()
            target.wake()
            return
        self._fail_request(req, from_worker, 503,
                           "replica failed; no surviving replica")

    def _xfer_restore(self, req: ServeRequest, desc: Dict):
        """Fleet-tier consult before a failover hop re-prefills
        (ISSUE 18 path 3): walk the resumed stream's digest chain
        longest-first; a span already arena-resident means the
        survivor's admission will restore it — done. Otherwise ask the
        fleet through the ``_xfer_fetch`` hook (peer ``GET /kvz``) and
        inject the wire blob. Every failure — no hook, no peer, any
        decode-ladder rung, over-capacity refusal — leaves the normal
        re-prefill path untouched."""
        eng = self._ref
        if not getattr(eng, "prefix_caching", False):
            return
        try:
            ids = [int(t) for t in desc["prompt"]]
            geo = eng._spill_geometry()
            chain = eng._chunk_digests(ids, len(ids) - 1)
        except Exception:
            return
        for i in range(len(chain) - 1, -1, -1):
            raw = chain[i]
            if self._spill_arena.probe(raw) is not None:
                return                       # already fleet/local warm
            if self._xfer_fetch is None:
                return
            try:
                blob = self._xfer_fetch(raw.hex())
            except Exception:
                blob = None
            if blob is None:
                continue                     # peer may hold a shorter span
            if kvxfer.inject_span(self._spill_arena, blob, geo,
                                  gateway=self.name) is not None:
                if req.trace is not None:
                    req.trace.ev("kv_xfer_restore",
                                 digest=raw.hex()[:12])
                return

    def _fail_request(self, req: ServeRequest,
                      worker: _ReplicaWorker, status: int, msg: str):
        """Terminal failover error: tell the client and close the
        trace on the failed replica's ring."""
        worker._emit(req, ("error", status, msg))
        if req.trace is not None:
            req.trace.ev("finish", reason="error")
        worker._trace_finish(req, "error")

    # -------------------------------------------------------------- digest
    def _affinity_digests(self, ids: List[int]) -> Optional[List[str]]:
        """The prompt's chunk-grid digest chain, LONGEST span first —
        the router probes each span so a unique tail crossing a chunk
        boundary still finds the replica warm on the shared spans."""
        eng = self._ref
        if not getattr(eng, "prefix_caching", False):
            return None
        try:
            chain = eng.prefix_digests(ids)
        except Exception:
            return None
        return chain[::-1] or None

    # ------------------------------------------------------------ lifecycle
    async def start(self):
        self._loop = asyncio.get_running_loop()
        for w in self._workers:
            w.start()
        if self.sampler is not None:
            if self._slo is not None:
                # alerts must RESOLVE on wall time even when traffic
                # stops — the sampler tick is the evaluation heartbeat
                self.sampler.add_hook(self._slo.evaluate)
            self.sampler.start()
        if self._supervisor is not None \
                and not self._supervisor.is_alive():
            self._supervisor.start()
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        obs.record_event("gateway_start", gateway=self.name,
                         port=self.port,
                         replicas=len(self._workers))
        return self

    async def drain(self, timeout: float = 30.0,
                    migrate: Optional[bool] = None):
        """Stop admitting, finish in-flight, flush metrics, close the
        listener (the SIGTERM rolling-restart path). With migration on
        (``migrate_on_drain`` or the override), live requests are CUT
        OVER instead of finished here: each stream ends with a
        terminal ``migrated`` event carrying the committed tokens and
        a ``resume_kv`` digest whose KV span was just banked in the
        arena — the fleet frontend resubmits to a survivor that
        restores the span instead of re-prefilling (ISSUE 18)."""
        if self._draining and self._server is None:
            return
        self._draining = True
        # supervision stops FIRST: a worker exiting because it drained
        # must not be mistaken for a dropped replica and restarted,
        # and a draining fleet never rebuilds (SIGTERM composes with
        # an open breaker — the replica just stays down)
        if self._supervisor is not None:
            self._supervisor.stop()
        for w in self._workers:
            w.draining = True
            w.wake()
        if migrate is None:
            migrate = self._migrate_on_drain
        mig_before = int(self._c_migrated.value)
        if migrate and self._spill_arena is not None:
            # migrate-out runs ON each tick thread (posted op): the
            # D2H span export and the live-request cut must be ordered
            # against that thread's own dispatch
            flags = []
            for w in self._workers:
                if not w.is_alive():
                    continue
                ev = threading.Event()

                def _mig(w=w, ev=ev):
                    try:
                        self._migrate_out(w)
                    finally:
                        ev.set()

                w.post(_mig)
                flags.append(ev)
            mig_deadline = time.monotonic() + min(timeout, 10.0)
            for ev in flags:
                while not ev.is_set() \
                        and time.monotonic() < mig_deadline:
                    await asyncio.sleep(0.005)
        deadline = time.monotonic() + timeout
        for w in self._workers:
            # an abandoned (hung) worker never exits on its own; its
            # replacement — if any — is what _workers holds
            while w.is_alive() and not w.abandoned \
                    and time.monotonic() < deadline:
                await asyncio.sleep(0.01)
        for w in self._workers:
            if not w.is_alive():
                # close the enqueue/exit race: a request that slipped
                # into the scheduler as its tick thread returned gets
                # a terminal answer here instead of a hung client
                w.flush_queue(503, "draining: not admitting new "
                                   "requests")
        if self._spill_arena is not None:
            # the device pools are about to die with the process; the
            # arena (host RAM, handed to the replacement gateway) is
            # what carries the warm spans across the restart (ISSUE 17)
            for w in self._workers:
                try:
                    if hasattr(w.engine, "spill_parked"):
                        w.engine.spill_parked()
                    if hasattr(w.engine, "spill_live"):
                        # requests that outlived the drain deadline
                        # still bank their computed spans — a peer
                        # /kvz fetch can finish what this replica
                        # couldn't (ISSUE 18)
                        w.engine.spill_live()
                except Exception:
                    pass        # a failed drain spill only costs warmth
        obs.record_event("gateway_drain", gateway=self.name)
        if self.sampler is not None:
            # stop the sampler thread and leave the trajectory on disk
            # (series_<gateway>.json, beside the reqtrace rings) so a
            # SIGTERM'd replica's windowed history survives it
            # (ISSUE 15 small fix)
            self.sampler.stop()
            self.sampler.flush_series(
                alerts=self._slo.alerts if self._slo is not None
                else None)
        obs.flush()
        if obs.run_dir():
            # park the request-trace rings next to the other run
            # artifacts so trace_report finds them after a restart
            try:
                self.dump_traces(obs.run_dir())
            except Exception:
                pass
            # ... and the tick-phase rings beside them (ISSUE 20 small
            # fix: a SIGTERM'd replica leaves its phase trajectory too)
            try:
                self.dump_tick_profiles(obs.run_dir())
            except Exception:
                pass
        if int(self._c_migrated.value) > mig_before \
                and self._xfer_grace_s > 0:
            # hold the listener open past the cut-over so the fleet
            # frontend's /kvz fetch of the migrated spans lands —
            # closing immediately would race the survivor's restore
            # (it would still finish correctly via re-prefill, but
            # the whole point of migrating is skipping that)
            await asyncio.sleep(self._xfer_grace_s)
        if self._server is not None:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass
            self._server = None

    def _migrate_out(self, worker: _ReplicaWorker):
        """Cut one replica's live requests over to the fleet (drain
        migration, ISSUE 18; runs on the tick thread via ``post``).
        Banks each request's computed KV span into the shared arena
        (``spill_live``), then ends its stream with a terminal
        ``migrated`` event: the committed tokens/logprobs, the
        remaining budget, and the longest arena-resident span digest
        as ``resume_kv``. The resubmitted stream restores that span —
        greedy continuation is bitwise the uninterrupted stream; every
        failure here just means the resubmit re-prefills instead."""
        eng = worker.engine
        try:
            eng.spill_live()
        except Exception:
            pass            # a failed export only costs a re-prefill
        try:
            desc = eng.export_resumable()
        except Exception:
            desc = {}
        for rid, req in list(worker._live.items()):
            d = desc.get(rid)
            if d is None:
                continue
            digest = ""
            try:
                ids = [int(t) for t in d["prompt"]]
                chain = eng._chunk_digests(ids, len(ids) - 1)
                for raw in reversed(chain):
                    if self._spill_arena.probe(raw) is not None:
                        digest = raw.hex()
                        break
            except Exception:
                digest = ""
            payload = {
                "tokens": [int(t) for t in d["committed"]],
                "logprobs": [float(v) for v in d["committed_lps"]],
                "finish_reason": "migrated",
                "resume_kv": digest,
                "remaining": int(d["remaining"]),
            }
            worker._emit(req, ("done", payload))
            if req.trace is not None:
                req.trace.ev("migrate_out", digest=digest[:12],
                             committed=len(payload["tokens"]),
                             remaining=payload["remaining"])
            worker._trace_finish(req, "migrated")
            try:
                eng.cancel(rid)
                eng.cancelled.pop(rid, None)
                eng.results.pop(rid, None)
                eng.logprobs.pop(rid, None)
            except Exception:
                pass
            worker._live.pop(rid, None)
            self._c_migrated.inc()
        obs.record_event("gateway_migrate_out", gateway=self.name,
                         replica=worker.replica.name,
                         moved=int(self._c_migrated.value))

    async def run_until_shutdown(self, poll_s: float = 0.05):
        """Serve until the GracefulShutdown latch fires (SIGTERM /
        SIGINT / programmatic ``request()``), then drain and return —
        the contract rolling restarts rely on."""
        if self._shutdown is None:
            self._shutdown = GracefulShutdown()
        self._shutdown.install()
        if self._server is None:
            await self.start()
        try:
            while not self._shutdown.requested():
                await asyncio.sleep(poll_s)
        finally:
            await self.drain()
            self._shutdown.uninstall()

    @property
    def draining(self) -> bool:
        if self._shutdown is not None and self._shutdown.requested():
            self._draining = True
            for w in self._workers:
                if not w.draining:
                    w.draining = True
                    w.wake()
        return self._draining

    # -------------------------------------------------------------- traces
    def dump_traces(self, directory: str) -> List[str]:
        """Write every replica's request-trace ring to
        ``reqtrace_<gateway>_<replica>.json`` under ``directory`` (the
        artifacts ``tools/trace_report.py`` ingests). No-op when
        tracing is off."""
        os.makedirs(directory, exist_ok=True)
        out = []
        for w in self._workers:
            if w.ring is None:
                continue
            out.append(w.ring.dump(os.path.join(
                directory,
                f"reqtrace_{self.name}_{w.replica.name}.json")))
        return out

    def dump_tick_profiles(self, directory: str) -> List[str]:
        """Write every replica engine's tick-phase ring to
        ``tickphase_<gateway>_<replica>.json`` under ``directory``
        (ISSUE 20: the synchronized dump a ``/profilez`` capture and a
        drain leave beside the reqtrace rings). No-op for engines
        running with ``tick_profile`` off."""
        os.makedirs(directory, exist_ok=True)
        out = []
        for w in self._workers:
            dump = getattr(w.engine, "dump_tick_profile", None)
            if dump is None or getattr(w.engine, "_prof", None) is None:
                continue
            try:
                out.append(dump(os.path.join(
                    directory,
                    f"tickphase_{self.name}_{w.replica.name}.json")))
            except Exception:
                pass     # a failed dump only costs the phase artifact
        return out

    def prefix_digest_summary(self) -> Dict[str, Any]:
        """Compact prefix-digest-set summary for fleet gossip (ISSUE
        13 satellite): the union of every replica engine's live
        prefix-cache digests plus a monotonic ``generation`` counter
        (sum of the engines' ``prefix_generation``). A poller that
        remembers the generation can skip re-fetching an unchanged set
        (``GET /debugz/prefix?if_gen=N``) — the cheap conditional
        fetch that makes sub-second gossip affordable.

        Monotonicity is RATCHETED at the gateway: the per-engine
        counters never reset in place (``hard_reset`` keeps counting)
        but a supervisor rebuild through ``engine_factory`` swaps in a
        FRESH engine whose counter restarts at 0 — the raw sum could
        regress and later collide with a previously-served value,
        making a poller's ``if_gen`` falsely read "unchanged". On any
        observed regression the base absorbs the drop plus one, so
        the exported generation strictly advances past every value
        ever served (called from the asyncio thread only)."""
        gen = 0
        digests: set = set()
        for w in list(self._workers):
            eng = w.engine
            gen += int(getattr(eng, "prefix_generation", 0))
            try:
                digests.update(k.hex() for k in
                               list(getattr(eng, "prefix_cache", {})))
            except RuntimeError:    # resized mid-iteration: torn read
                pass                # is fine — the next poll catches up
        spilled: List[str] = []
        if self._spill_arena is not None:
            # spill tier (ISSUE 17): advertise arena-resident digests
            # under a separate, cheaper key — a peer router treats them
            # as warm (a restore beats a re-prefill) without confusing
            # them with device-live spans. The arena's own monotonic
            # generation folds into the ratcheted counter so an if_gen
            # poller sees spill-tier changes too.
            gen += int(self._spill_arena.generation)
            live = digests
            spilled = [h for h in self._spill_arena.digest_hexes()
                       if h not in live]
        if gen < self._prefix_gen_last:
            self._prefix_gen_base += self._prefix_gen_last - gen + 1
        self._prefix_gen_last = gen
        doc = {"generation": self._prefix_gen_base + gen,
               "entries": len(digests),
               "digests": sorted(digests)}
        if self._spill_arena is not None:
            doc["spilled"] = spilled
            doc["spilled_entries"] = len(spilled)
        return doc

    def metricsz(self, window_s: Optional[float] = None
                 ) -> Dict[str, Any]:
        """``GET /metricsz?window_s=N`` (ISSUE 15): windowed rates +
        quantiles as JSON, beside the Prometheus text endpoint —
        counter rates, gauge means and TRUE windowed histogram
        quantiles over the last N seconds, derived from the sampler's
        rings, plus the SLO burn/alert block. ``enabled: false`` when
        the sampler is off (the federating frontend skips those)."""
        if self.sampler is None:
            return {"gateway": self.name, "enabled": False}
        w = float(window_s) if window_s else \
            max(self.sampler.interval_s * 8, 2.0)
        doc: Dict[str, Any] = {
            "gateway": self.name,
            "enabled": True,
            "window_s": w,
            "interval_s": self.sampler.interval_s,
            "samples_taken": self.sampler.samples_taken,
            "metrics": self.sampler.window(w),
        }
        if self._slo is not None:
            doc["slo"] = self._slo.snapshot()
        return doc

    def debugz(self) -> Dict[str, Any]:
        """``GET /debugz`` (ISSUE 10): live engine introspection — the
        slot map, block-pool occupancy/fragmentation, the prefix-cache
        digests the router probes, scheduler queues + tenant debt,
        per-replica EMAs, and the request-trace ring summaries. Reads
        cross-thread without pausing the tick threads (debug fidelity,
        not a consistency point)."""
        reps: Dict[str, Any] = {}
        for w in list(self._workers):
            b = getattr(w.replica, "breaker", None)
            rep: Dict[str, Any] = {"healthy": w.replica.healthy(),
                                   "alive": w.is_alive(),
                                   "failed": w.failed,
                                   "load": w.replica.load(),
                                   "breaker": b.snapshot()
                                   if b is not None else None}
            try:
                rep["engine"] = w.engine.debug_snapshot()
            except Exception as e:       # torn mid-tick read: partial
                rep["engine"] = {"error": repr(e)}
            # slot-transition cost counters (ISSUE 14), surfaced at the
            # replica top level so a fleet poller need not dig into the
            # engine snapshot — the snapshot's own block when it read
            # cleanly, rebuilt from the engine counters when it tore
            tr = rep["engine"].get("transitions") \
                if isinstance(rep["engine"], dict) else None
            rep["transitions"] = tr if tr is not None else {
                "delta_enabled": getattr(w.engine, "_delta", None),
                "patch_fuse_enabled": getattr(w.engine, "_fuse_patches",
                                              None),
                **{k: getattr(w.engine, k, None)
                   for k in ("full_rebuilds", "delta_patches",
                             "patches_fused", "patch_queue_overflows",
                             "ring_cursor_rollovers",
                             "h2d_uploads", "h2d_upload_bytes",
                             "dispatch_count")}}
            try:
                rep["scheduler"] = w.sched.debug_snapshot()
            except Exception as e:
                rep["scheduler"] = {"error": repr(e)}
            rep["trace_ring"] = (w.ring.summary()
                                 if w.ring is not None else None)
            # tick-phase profiler (ISSUE 20), surfaced like the
            # transition counters: the snapshot's block when it read
            # cleanly, a minimal enabled-flag otherwise
            tp = rep["engine"].get("tick_profile") \
                if isinstance(rep["engine"], dict) else None
            rep["tick_profile"] = tp if tp is not None else {
                "enabled": getattr(w.engine, "_prof", None) is not None}
            reps[w.replica.name] = rep
        sup = None
        if self._supervisor is not None:
            sup = {
                "alive": self._supervisor.is_alive(),
                "dispatch_timeout_s":
                    self._supervisor.dispatch_timeout_s,
                "watchdog_fires":
                    int(self._supervisor._c_watchdog.value),
            }
        return {
            "gateway": self.name,
            "draining": self.draining,
            "slow_ttft_ms": self._slow_ttft_ms,
            "failover_budget": self._failover_budget,
            "failovers": int(self._c_failovers.value),
            "retry_budget_exhausted": int(self._c_fo_exhausted.value),
            "supervisor": sup,
            "router": self._router.snapshot(),
            "replicas": reps,
            "prefix_digest_set": self.prefix_digest_summary(),
            "kv_spill": (self._spill_arena.snapshot()
                         if self._spill_arena is not None else None),
            # cross-replica transfer plane (ISSUE 18)
            "kv_xfer": dict(
                kvxfer.counters_snapshot(self.name),
                migrate_on_drain=self._migrate_on_drain,
                migrated_requests=int(self._c_migrated.value)),
            # telemetry plane (ISSUE 15)
            "telemetry": {
                "sampler": None if self.sampler is None else {
                    "running": self.sampler.running,
                    "interval_s": self.sampler.interval_s,
                    "capacity": self.sampler.capacity,
                    "samples_taken": self.sampler.samples_taken,
                    "metrics": len(self.sampler.names()),
                    "dropped_metrics": self.sampler.dropped_metrics,
                },
                "slo": self._slo.snapshot()
                if self._slo is not None else None,
            },
        }

    # ------------------------------------------------------------- health
    def health(self) -> Dict[str, Any]:
        """Aggregated snapshot, read from the SAME registry objects a
        /metrics scrape exports (pinned equal by test)."""
        return {
            "gateway": self.name,
            "draining": self.draining,
            "requests": {slo: int(c.value)
                         for slo, c in self._c_requests.items()},
            "shed": int(self._c_shed.value),
            "completed": int(self._c_completed.value),
            "tokens": int(self._c_tokens.value),
            "disconnects": int(self._c_disconnects.value),
            "failovers": int(self._c_failovers.value),
            "retry_budget_exhausted": int(self._c_fo_exhausted.value),
            # the autoscaler's quality signal (ISSUE 13): same counters
            # the gateway_goodput_frac gauge is derived from, readable
            # by a remote fleet probe in one /healthz fetch
            "goodput_frac": round(
                self._c_good_tokens.value
                / max(self._c_tokens.value, 1.0), 4),
            "ttft_ms": self._h_ttft.stats(),
            "tpot_ms": self._h_tpot.stats(),
            "router": self._router.snapshot(),
            "replicas": {
                w.replica.name: dict(
                    healthy=w.replica.healthy(),
                    scheduler=w.sched.snapshot(),
                    engine=w.engine.health())
                for w in self._workers},
        }

    # ---------------------------------------------------------------- HTTP
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        try:
            line = await asyncio.wait_for(reader.readline(), 30)
            parts = line.decode("latin1").split()
            if len(parts) < 3:
                return
            method, path = parts[0], parts[1]
            headers: Dict[str, str] = {}
            while True:
                h = await asyncio.wait_for(reader.readline(), 30)
                if h in (b"\r\n", b"\n", b""):
                    break
                k, _, v = h.decode("latin1").partition(":")
                headers[k.strip().lower()] = v.strip()
            body = b""
            try:
                n = int(headers.get("content-length", "0") or 0)
                if n < 0:
                    raise ValueError("negative")
            except ValueError:
                writer.write(_json_response(
                    400, {"error": "bad Content-Length"}))
                await writer.drain()
                return
            if n:
                body = await asyncio.wait_for(reader.readexactly(n), 30)
            await self._dispatch_http(method, path, body, headers,
                                      reader, writer)
        except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _dispatch_http(self, method, path, body, headers, reader,
                             writer):
        path, _, query = path.partition("?")
        path = path.rstrip("/") or "/"
        if method == "GET" and path == "/debugz/prefix":
            # the gossip poll (ISSUE 13): ``?if_gen=N`` answers a tiny
            # unchanged-marker instead of the digest list when the set
            # generation still equals N
            summary = self.prefix_digest_summary()
            if_gen = _query_param(query, "if_gen", int)
            if if_gen is not None and if_gen == summary["generation"]:
                writer.write(_json_response(
                    200, {"generation": summary["generation"],
                          "unchanged": True}))
            else:
                writer.write(_json_response(200, summary))
            await writer.drain()
            return
        if method == "GET" and path == "/healthz":
            writer.write(_json_response(200, self.health()))
            await writer.drain()
            return
        if method == "GET" and path == "/debugz":
            writer.write(_json_response(200, self.debugz()))
            await writer.drain()
            return
        if method == "GET" and path == "/metrics":
            writer.write(_http_response(
                200, obs.registry().prometheus_text().encode(),
                ctype="text/plain; version=0.0.4"))
            await writer.drain()
            return
        if method == "GET" and path == "/metricsz":
            # windowed JSON beside the Prometheus text (ISSUE 15)
            window_s = _query_param(query, "window_s")
            writer.write(_json_response(200, self.metricsz(window_s)))
            await writer.drain()
            return
        if method == "GET" and path == "/kvz":
            await self._serve_kvz(query, writer)
            return
        if method == "GET" and path == "/profilez":
            await self._serve_profilez(query, writer)
            return
        if method == "POST" and path == "/v1/generate":
            await self._generate(body, headers, reader, writer)
            return
        writer.write(_json_response(404, {"error": f"no route {path}"}))
        await writer.drain()

    async def _serve_kvz(self, query: str, writer):
        """``GET /kvz?digest=<hex>``: one spill-arena span as a kvxfer
        wire record (ISSUE 18 peer fetch — the fleet-fetchable face of
        the gossip ``spilled`` tier; a rebuilt or different replica
        pulls a dead peer's spans instead of re-prefilling). 404 for
        anything not restorable. Chaos: ``xfer_slow`` delays the body
        here (the fetch side bounds it with ``xfer_timeout_s``); the
        encoder's ``xfer_corrupt``/``xfer_trunc`` sites damage it —
        the fetcher's decode ladder turns every one into a counted
        re-prefill fallback, never a token."""
        digest = _query_param(query, "digest", str)
        if self._spill_arena is None or not digest:
            writer.write(_json_response(
                404, {"error": "no spill arena" if
                      self._spill_arena is None else "missing digest"}))
            await writer.drain()
            return
        if faults.inject("xfer_slow", gateway=self.name,
                         digest=str(digest)[:12]):
            await asyncio.sleep(faults.xfer_slow_seconds())
        try:
            blob = kvxfer.export_span(
                self._spill_arena, str(digest),
                self._ref._spill_geometry(), gateway=self.name)
        except Exception:
            blob = None
        if blob is None:
            writer.write(_json_response(
                404, {"error": "span not restorable"}))
        else:
            writer.write(_http_response(
                200, blob, ctype="application/octet-stream"))
        await writer.drain()

    async def _serve_profilez(self, query: str, writer):
        """``GET /profilez?duration_s=N`` (ISSUE 20 capture layer): a
        BOUNDED on-demand capture — open a ``jax.profiler`` trace
        through :class:`~..utils.profiler.Profiler` (whose module latch
        keeps this from corrupting a trace some training loop already
        owns — contention degrades to timer-only, never an error), let
        live traffic run for ``duration_s`` wall seconds, stop the
        trace, then dump every replica engine's tick-phase ring beside
        it (``tickphase_<gateway>_<replica>.json`` in the run dir).
        The response reports per-replica phase totals ACCUMULATED
        DURING THE WINDOW, so a caller gets the slope-vs-intercept
        split inline even with no run dir configured. One capture at a
        time (409 otherwise); duration is clamped to 30 s — this is a
        tap on a serving process, not a profiling session."""
        dur = _query_param(query, "duration_s")
        dur = 1.0 if dur is None else max(0.05, min(float(dur), 30.0))
        if self._profilez_busy:
            writer.write(_json_response(
                409, {"error": "capture already in progress"}))
            await writer.drain()
            return
        self._profilez_busy = True
        try:
            from ..utils.profiler import Profiler
            run_dir = obs.run_dir()
            jax_dir = os.path.join(run_dir, f"jaxprof_{self.name}") \
                if run_dir else None
            prof = Profiler(logdir=jax_dir or "",
                            timer_only=jax_dir is None)
            before = {}
            for w in self._workers:
                p = getattr(w.engine, "_prof", None)
                if p is not None:
                    before[w.replica.name] = (
                        p.ticks, dict(p.totals), p.wall_total_ms)
            traced = False
            try:
                prof.start()
                traced = not prof.timer_only
            except Exception:
                prof = None       # backend without trace support: the
                                  # tick-ring dump still happens
            try:
                await asyncio.sleep(dur)
            finally:
                if prof is not None:
                    try:
                        prof.stop()
                    except Exception:
                        traced = False
            reps: Dict[str, Any] = {}
            for w in self._workers:
                p = getattr(w.engine, "_prof", None)
                if p is None:
                    reps[w.replica.name] = {"enabled": False}
                    continue
                t0, tot0, w0 = before.get(
                    w.replica.name, (0, {}, 0.0))
                reps[w.replica.name] = {
                    "enabled": True,
                    "ticks_in_window": p.ticks - t0,
                    "wall_ms_in_window": round(
                        p.wall_total_ms - w0, 3),
                    "phase_ms_in_window": {
                        k: round(v - tot0.get(k, 0.0), 3)
                        for k, v in p.totals.items()},
                }
            files = self.dump_tick_profiles(run_dir) if run_dir else []
            obs.record_event("profilez_capture", gateway=self.name,
                             duration_s=dur,
                             traced=traced, files=len(files))
            writer.write(_json_response(200, {
                "gateway": self.name,
                "duration_s": dur,
                "jax_trace": jax_dir if traced else None,
                "tickphase_files": files,
                "replicas": reps,
            }))
            await writer.drain()
        finally:
            self._profilez_busy = False

    # ------------------------------------------------------------ generate
    def _parse_request(self, body: bytes,
                       headers: Optional[Dict[str, str]] = None
                       ) -> ServeRequest:
        spec = json.loads(body.decode())
        if not isinstance(spec, dict):
            raise ValueError("request body must be a JSON object")
        ids = spec.get("prompt", spec.get("input_ids"))
        if not isinstance(ids, list) or not ids \
                or not all(isinstance(t, int) for t in ids):
            raise ValueError("prompt must be a non-empty list of "
                             "token ids")
        max_new = int(spec.get("max_new_tokens", 32))
        cap = self._ref.M * self._ref.B
        if len(ids) + max_new > cap:
            raise ValueError(f"prompt+max_new_tokens {len(ids)}+"
                             f"{max_new} exceeds per-request capacity "
                             f"{cap}")
        gen = {"max_new_tokens": max_new}
        for k in ("eos_token_id", "temperature", "top_k", "top_p",
                  "seed", "repetition_penalty"):
            if spec.get(k) is not None:
                gen[k] = spec[k]
        if spec.get("stop") is not None:
            gen["stop_sequences"] = [list(map(int, s))
                                     for s in spec["stop"]]
        # fleet failover resume (ISSUE 13): a fleet frontend whose peer
        # died mid-stream resubmits prompt+committed here; the engine
        # validates resume_tokens is the tail of the prompt and a
        # greedy stream continues bitwise (the in-process failover
        # seam, exposed over HTTP).
        if spec.get("resume_tokens") is not None:
            rt = spec["resume_tokens"]
            if not isinstance(rt, list) \
                    or not all(isinstance(t, int) for t in rt):
                raise ValueError("resume_tokens must be a list of "
                                 "token ids")
            gen["resume_tokens"] = rt
            rl = spec.get("resume_lps")
            if rl is not None:
                if not isinstance(rl, list) \
                        or not all(isinstance(v, (int, float))
                                   or v is None for v in rl):
                    raise ValueError("resume_lps must be a list of "
                                     "floats")
                gen["resume_lps"] = [float("nan") if v is None
                                     else float(v) for v in rl]
        # cross-replica KV transfer (ISSUE 18): optional reference to
        # the resumed stream's KV span — "b64:<wire record>" carries
        # the blob inline (drain migration resubmit), a bare digest
        # hex consults the local arena then the fleet fetch hook.
        # Strictly best-effort: any failure is a counted fallback and
        # the resume re-prefills; never a client-visible error.
        if spec.get("resume_kv"):
            try:
                self._consume_resume_kv(str(spec["resume_kv"]))
            except Exception:
                pass
        timeout_s = spec.get("timeout_s")
        deadline = (time.monotonic() + float(timeout_s)
                    if timeout_s is not None else None)
        digest = spec.get("affinity_key") or self._affinity_digests(ids)
        # trace-context id (ISSUE 10): body request_id wins, then an
        # inbound X-Request-Id header (the loadgen's client-minted id
        # — what lets trace_report join client and server views), then
        # a gateway-minted one. The SAME id keys the response, the
        # engine's ring entry and every metric exemplar.
        rid = spec.get("request_id") \
            or (headers or {}).get("x-request-id") \
            or uuid.uuid4().hex[:16]
        return ServeRequest(
            rid,
            ids, gen, slo=spec.get("slo", SLO_INTERACTIVE),
            tenant=str(spec.get("tenant", "default")),
            priority=int(spec.get("priority", 0)),
            deadline=deadline, digest=digest,
            sink=asyncio.Queue(), stream=bool(spec.get("stream", True)))

    def _consume_resume_kv(self, ref: str):
        """Make a ``resume_kv`` span arena-resident BEFORE admission,
        so the engine's ``_arena_restore`` turns the resume's
        prompt+committed re-prefill into one H2D scatter.
        ``b64:<base64 wire record>`` runs the inline blob through the
        kvxfer decode ladder; a bare digest hex checks residency and,
        on a miss, the fleet ``_xfer_fetch`` hook (peer ``GET /kvz``).
        Every failure mode — bad encoding, any ladder rung, no peer,
        over-capacity — leaves admission exactly as it was: the stream
        re-prefills, bitwise identical."""
        if self._spill_arena is None:
            return
        geo = self._ref._spill_geometry()
        if ref.startswith("b64:"):
            import base64
            import binascii
            try:
                blob = base64.b64decode(ref[4:], validate=True)
            except (binascii.Error, ValueError):
                return
            kvxfer.inject_span(self._spill_arena, blob, geo,
                               gateway=self.name)
            return
        try:
            raw = bytes.fromhex(ref)
        except ValueError:
            return
        if self._spill_arena.probe(raw) is not None \
                or self._xfer_fetch is None:
            return
        try:
            blob = self._xfer_fetch(ref)
        except Exception:
            blob = None
        if blob is not None:
            kvxfer.inject_span(self._spill_arena, blob, geo,
                               gateway=self.name)

    async def _generate(self, body, headers, reader, writer):
        if self.draining:
            writer.write(_json_response(
                503, {"error": "draining: not admitting new requests"},
                extra={"Retry-After": "1"}))
            await writer.drain()
            return
        try:
            req = self._parse_request(body, headers)
        except (ValueError, KeyError, TypeError) as e:
            # TypeError covers wrong-typed fields (int({}) etc.);
            # json.JSONDecodeError is a ValueError subclass
            writer.write(_json_response(400, {"error": str(e)}))
            await writer.drain()
            return
        if self._trace:
            req.trace = RequestTrace(req.request_id, tenant=req.tenant,
                                     slo=req.slo)
            req.trace.ev("accept", stream=req.stream,
                         prompt_tokens=len(req.input_ids))
        worker = None
        for attempt in (0, 1):
            meta: Dict[str, Any] = {}
            try:
                replica = self._router.route(
                    req.digest, trace=req.trace,
                    allow_probe=attempt == 0, meta=meta)
            except NoReplicaError as e:
                writer.write(_json_response(503, {"error": str(e)},
                                            extra={"Retry-After": "5"}))
                await writer.drain()
                return
            worker = self._by_replica[replica]
            # the router's verdict is the AUTHORITATIVE probe signal —
            # only a request the router handed the breaker's probe
            # slot may report probe_done (inferring from healthy()
            # would let a replica failing between route and here
            # impersonate the real probe and corrupt its accounting)
            req.probe = meta.get("verdict") == "probe"
            if req.probe and req.trace is not None:
                req.trace.ev("breaker_half_open",
                             replica=replica.name)
            try:
                # the engine's own backpressure fields, read O(1) (a
                # full health() snapshot per request is scrape-grade
                # work) — live protection for engines that ALSO take
                # out-of-band submit() traffic; the gateway's own
                # admission keeps the engine queue shallower than this
                eng = worker.engine
                worker.sched.enqueue(
                    req, engine_health={"queued": len(eng.queue),
                                        "queue_capacity": eng.max_queue})
            except ShedError as e:
                self._c_shed.inc()
                # a shed probe says "overloaded", not "broken":
                # release the slot without moving the breaker
                _release_probe(req, worker.replica)
                if req.trace is not None:
                    req.trace.ev("shed", retry_after_s=e.retry_after_s)
                    if worker.ring is not None:
                        worker.ring.finish(req.trace, "shed")
                writer.write(_json_response(
                    429, {"error": str(e),
                          "retry_after_s": e.retry_after_s},
                    extra={"Retry-After":
                           str(max(int(e.retry_after_s), 1))}))
                await writer.drain()
                return
            worker.wake()
            if worker.is_alive() and not worker.failed \
                    and (worker.replica.healthy() or req.probe):
                break
            # raced a worker exit/failure: drain (thread checked its
            # queue empty and returned as this request landed),
            # _fail_all (flush drained this request or this check
            # catches it), or a probe that reached a replica whose
            # rebuild isn't live yet — nothing here will serve it;
            # take it back and RE-ROUTE once through the plain ladder
            # (ISSUE 12) before giving up with a 503. A probe that hit
            # a FAILED/dead worker reports failure (re-opens, longer
            # backoff) — treating it as inconclusive would let a
            # permanently-unrebuildable replica turn every future
            # request into a doomed probe detour forever.
            if not worker.sched.cancel(req.request_id):
                # somebody already CLAIMED it — the worker's failover
                # drained its queue (resubmitting this request and
                # updating req.owner) or its queue flush errored it
                # into the sink. Either way events are coming;
                # enqueueing a second copy would serve the request on
                # two replicas into one sink. Probe accounting, if
                # any, was settled by the claimant.
                break
            _release_probe(req, worker.replica,
                           False if (worker.failed
                                     or not worker.is_alive())
                           else None)
        else:
            if worker.ring is not None and req.trace is not None:
                worker.ring.finish(req.trace, "error")
            writer.write(_json_response(
                503, {"error": "replica unavailable; retry"},
                extra={"Retry-After": "1"}))
            await writer.drain()
            return
        self._c_requests[req.slo].inc()
        # the claimed-race break above may have handed ownership to a
        # failover target already — never clobber that
        req.owner = req.owner or worker
        if req.stream:
            await self._stream_sse(worker, req, reader, writer)
        else:
            await self._wait_json(worker, req, reader, writer)

    def _on_disconnect(self, worker: _ReplicaWorker, req: ServeRequest):
        """Client dropped mid-request: cancel on the tick thread so the
        slot/blocks free immediately (satellite: a dropped stream never
        strands a slot). ``req.owner`` tracks failover moves, so the
        cancel lands on the replica CURRENTLY serving the request, not
        the one that accepted it."""
        self._c_disconnects.inc()
        w = req.owner or worker
        w.post(lambda: w.cancel_request(req.request_id, req))

    async def _stream_sse(self, worker, req, reader, writer):
        try:
            writer.write(_SSE_HEAD)
            await writer.drain()
        except (ConnectionError, OSError):
            self._on_disconnect(worker, req)
            return
        eof = asyncio.ensure_future(reader.read())
        try:
            while True:
                get = asyncio.ensure_future(req.sink.get())
                if eof is None:
                    ev = await get
                else:
                    done, _ = await asyncio.wait(
                        {get, eof},
                        return_when=asyncio.FIRST_COMPLETED)
                    if get not in done:
                        # read side closed. A dropped client AND a
                        # legal HTTP half-close (shutdown(SHUT_WR)
                        # after the body, still reading the response)
                        # both look like EOF here — probe with an SSE
                        # comment: only a truly dead peer fails the
                        # write. Later token writes keep catching
                        # disconnects once the watch is off.
                        get.cancel()
                        try:
                            writer.write(b": half-close probe\n\n")
                            await writer.drain()
                        except (ConnectionError, OSError):
                            self._on_disconnect(worker, req)
                            return
                        eof = None
                        continue
                    ev = get.result()
                try:
                    if ev[0] == "token":
                        payload = {"token": ev[1], "lp": ev[2]}
                        if faults.inject("stream_stall",
                                         request=str(req.request_id)):
                            # slow client / congested wire stand-in:
                            # stalls THIS coroutine only — the tick
                            # loop and sibling streams keep moving
                            await asyncio.sleep(
                                faults.stream_stall_seconds())
                    elif ev[0] == "done":
                        payload = dict(ev[1], done=True)
                    else:
                        payload = {"error": ev[2], "done": True}
                    writer.write(b"data: " + json.dumps(payload).encode()
                                 + b"\n\n")
                    await writer.drain()
                except (ConnectionError, OSError):
                    self._on_disconnect(worker, req)
                    return
                if ev[0] != "token":
                    return
        finally:
            if eof is not None and not eof.done():
                eof.cancel()

    async def _wait_json(self, worker, req, reader, writer):
        # no EOF watch here: a JSON response can't carry a mid-wait
        # probe, and a legal half-closing client must still get its
        # response — a vanished one costs only the final failed write
        while True:
            ev = await req.sink.get()
            if ev[0] == "token":
                continue
            try:
                if ev[0] == "error":
                    writer.write(_json_response(
                        ev[1], {"error": ev[2],
                                "request_id": req.request_id}))
                else:
                    info = ev[1]
                    reason = info.get("finish_reason", "stop")
                    if reason == "timeout":
                        writer.write(_json_response(
                            504, {"error": "deadline exceeded",
                                  "request_id": req.request_id,
                                  "finish_reason": reason}))
                    else:
                        writer.write(_json_response(
                            200, dict(info,
                                      request_id=req.request_id)))
                await writer.drain()
            except (ConnectionError, OSError):
                pass
            return
