"""Multiprocess DataLoader workers (VERDICT r2 item 4; reference:
python/paddle/io/dataloader/dataloader_iter.py worker pool). Spawned
workers, ordered results, >=3x speedup on a 5ms-per-sample dataset,
exception propagation, worker_init_fn/get_worker_info, persistence."""
import time

import numpy as np
import pytest

from paddle_tpu.io import (DataLoader, TensorDataset, WorkerError,
                           get_worker_info)


class SlowDataset:
    """5 ms of host work per sample (image decode stand-in)."""

    def __init__(self, n=400, delay=0.005):
        self.n = n
        self.delay = delay

    def __getitem__(self, i):
        time.sleep(self.delay)
        return np.full((4,), i, dtype=np.float32)

    def __len__(self):
        return self.n


class WorkerIdDataset:
    def __getitem__(self, i):
        info = get_worker_info()
        return np.array([i, -1 if info is None else info.id])

    def __len__(self):
        return 64


class FailingDataset:
    def __getitem__(self, i):
        if i == 13:
            raise ValueError("poison sample")
        return np.zeros(2)

    def __len__(self):
        return 32


def test_order_matches_serial():
    X = np.random.randn(64, 8).astype(np.float32)
    ds = TensorDataset([X])
    serial = [b[0] for b in DataLoader(ds, batch_size=8, num_workers=0)]
    par = [b[0] for b in DataLoader(ds, batch_size=8, num_workers=2)]
    assert len(serial) == len(par)
    for a, b in zip(serial, par):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_speedup_4_workers():
    """>= 3x on a steady epoch (persistent workers: spawn cost
    amortizes across epochs exactly as in real training). Wall-clock
    bench -> slow tier (it measured 2.56x under full-suite load on the
    single-core image — pure scheduler noise; the other tests in this
    file keep the worker-pool correctness coverage in tier-1); min of
    2 steady epochs since container noise only ever adds time."""
    ds = SlowDataset(n=240)
    serial = DataLoader(ds, batch_size=4, num_workers=0)
    t0 = time.perf_counter()
    n_serial = sum(1 for _ in serial)
    t_serial = time.perf_counter() - t0

    par = DataLoader(ds, batch_size=4, num_workers=4,
                     persistent_workers=True)
    n_par = sum(1 for _ in par)          # epoch 1: includes spawn
    t_par, n_par2 = float("inf"), 0
    for _ in range(2):                   # steady state, min-of-2
        t0 = time.perf_counter()
        n_par2 = sum(1 for _ in par)
        t_par = min(t_par, time.perf_counter() - t0)
    par.shutdown()
    assert n_serial == n_par == n_par2 == 60
    assert t_serial / t_par >= 3.0, (t_serial, t_par)


def test_worker_exception_propagates():
    dl = DataLoader(FailingDataset(), batch_size=8, num_workers=2)
    with pytest.raises(WorkerError, match="poison sample"):
        list(dl)


def test_get_worker_info_and_distribution():
    dl = DataLoader(WorkerIdDataset(), batch_size=4, num_workers=4)
    rows = np.concatenate([np.asarray(b) for b in dl])
    ids = set(rows[:, 1].tolist())
    assert ids == {0, 1, 2, 3}, ids               # all workers participated
    np.testing.assert_array_equal(rows[:, 0], np.arange(64))  # order kept


def _init_fn(worker_id):
    import numpy as _np
    _np.random.seed(1234 + worker_id)


class RandDataset:
    def __getitem__(self, i):
        return np.random.randint(0, 1_000_000, (2,))

    def __len__(self):
        return 16


def test_worker_init_fn_controls_rng():
    a = [np.asarray(b) for b in DataLoader(
        RandDataset(), batch_size=4, num_workers=2, worker_init_fn=_init_fn)]
    b = [np.asarray(b) for b in DataLoader(
        RandDataset(), batch_size=4, num_workers=2, worker_init_fn=_init_fn)]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_persistent_pool_reused():
    ds = TensorDataset([np.arange(32, dtype=np.float32)])
    dl = DataLoader(ds, batch_size=8, num_workers=2, persistent_workers=True)
    e1 = [np.asarray(b[0]) for b in dl]
    pool = dl._pool
    assert pool is not None
    e2 = [np.asarray(b[0]) for b in dl]
    assert dl._pool is pool                      # same workers, epoch 2
    for a, b in zip(e1, e2):
        np.testing.assert_array_equal(a, b)
    dl.shutdown()
    assert dl._pool is None


def test_consumer_early_break_then_reuse():
    """Breaking out mid-epoch must not wedge or corrupt the next epoch."""
    ds = TensorDataset([np.arange(64, dtype=np.float32)])
    dl = DataLoader(ds, batch_size=4, num_workers=2, persistent_workers=True)
    it = iter(dl)
    next(it), next(it)
    it.close()                                    # abandon epoch
    full = [np.asarray(b[0]) for b in dl]         # fresh epoch: complete
    np.testing.assert_array_equal(np.concatenate(full),
                                  np.arange(64, dtype=np.float32))
    dl.shutdown()


class DyingDataset:
    def __getitem__(self, i):
        if i == 5:
            import os
            os._exit(3)  # simulate OOM-kill / hard crash
        return np.zeros(2)

    def __len__(self):
        return 32


def test_dead_worker_raises_not_hangs():
    dl = DataLoader(DyingDataset(), batch_size=4, num_workers=2)
    with pytest.raises(WorkerError, match="died"):
        list(dl)


def test_concurrent_iterators_rejected():
    ds = TensorDataset([np.arange(32, dtype=np.float32)])
    dl = DataLoader(ds, batch_size=4, num_workers=2, persistent_workers=True)
    it1 = iter(dl)
    next(it1)
    with pytest.raises(RuntimeError, match="active iterator"):
        next(iter(dl))
    it1.close()
    dl.shutdown()
