"""ERNIE family (reference: PaddleNLP paddlenlp/transformers/ernie/
modeling.py — ErnieModel/ErnieForMaskedLM/ErnieForSequenceClassification;
architecturally a BERT encoder plus a task-type embedding stream, with
ERNIE's knowledge-masking pretraining recipe).

TPU-native: reuses the BertModel encoder (post-LN blocks over tp-sharded
Column/RowParallel projections) and adds the task-type embedding table;
heads mirror the reference's MLM / classification heads.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp

from .. import nn
from ..nn import initializer as I
from ..nn.layer import Layer, Parameter
from ..utils.rng import next_key
from .bert import BertConfig, BertModel, TiedMLMHead


@dataclass
class ErnieConfig(BertConfig):
    vocab_size: int = 40000
    task_type_vocab_size: int = 3
    use_task_id: bool = True


def ernie_tiny(**overrides) -> ErnieConfig:
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                max_position_embeddings=64, dtype=jnp.float32)
    base.update(overrides)
    return ErnieConfig(**base)


class ErnieModel(Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.encoder = BertModel(config)
        if config.use_task_id:
            init = I.Normal(std=config.initializer_range)
            self.task_type_embeddings = Parameter(
                init(next_key(), (config.task_type_vocab_size,
                                  config.hidden_size)).astype(config.dtype))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                task_type_ids=None, positions=None):
        # task-type stream adds onto the shared embedding sum (ERNIE 2.0+);
        # reference defaults task_type_ids to zeros when use_task_id is on,
        # so task 0's embedding is always added — not silently skipped.
        extra = None
        if self.config.use_task_id:
            if task_type_ids is None:
                task_type_ids = jnp.zeros_like(input_ids)
            extra = self.task_type_embeddings[task_type_ids]
        return self.encoder(input_ids, token_type_ids, attention_mask,
                            positions, extra_embeds=extra)


class ErnieForMaskedLM(Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.ernie = ErnieModel(config)
        self.mlm_head = TiedMLMHead(config)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                task_type_ids=None):
        seq, _ = self.ernie(input_ids, token_type_ids, attention_mask,
                            task_type_ids)
        word_w = self.ernie.encoder.embeddings.word_embeddings.weight
        return self.mlm_head(seq, word_w)


class ErnieForSequenceClassification(Layer):
    def __init__(self, config: ErnieConfig, num_classes: int = 2):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                task_type_ids=None):
        _, pooled = self.ernie(input_ids, token_type_ids, attention_mask,
                               task_type_ids)
        return self.classifier(self.dropout(pooled)).astype(jnp.float32)


# ----------------------------------------------------------- ERNIE 4.5 MoE
# (reference: PaddleNLP paddlenlp/transformers/ernie4_5[_moe]/modeling.py —
# Baidu's flagship decoder LM: GQA attention + fine-grained MoE FFN with
# always-on shared experts, first k layers dense. Architecturally it is the
# Qwen2MoE/DeepSeekMoE decoder shape, so the TPU build reuses that backbone
# (parallel.moe.MoEMLP capacity dispatch over the ep axis); what ERNIE-4.5
# changes is the config point below.)
from .qwen2_moe import Qwen2MoeConfig, Qwen2MoeForCausalLM  # noqa: E402


@dataclass
class Ernie45MoeConfig(Qwen2MoeConfig):
    """ERNIE-4.5 text-MoE defaults at the 21B-A3B scale (the open release;
    the 300B-A47B recipe is the same architecture scaled up). Exact tensor
    shapes come from the checkpoint via hf_interop at load time; these
    defaults define the architecture family."""
    vocab_size: int = 103424
    hidden_size: int = 2560
    intermediate_size: int = 12288         # dense layers' FFN width
    moe_intermediate_size: int = 1536      # per fine-grained expert
    num_hidden_layers: int = 28
    num_attention_heads: int = 20
    num_key_value_heads: int = 4
    num_experts: int = 64
    num_experts_per_tok: int = 6
    num_shared_experts: int = 2
    shared_expert_intermediate_size: Optional[int] = 1536
    first_k_dense_replace: int = 1         # layer 0 stays dense
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    attention_bias: bool = False
    tie_word_embeddings: bool = False


def ernie45_moe_tiny(**overrides) -> Ernie45MoeConfig:
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                moe_intermediate_size=64, num_experts=4,
                num_experts_per_tok=2, num_shared_experts=1,
                shared_expert_intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=128,
                first_k_dense_replace=1, rope_theta=10000.0,
                dtype=jnp.float32)
    base.update(overrides)
    return Ernie45MoeConfig(**base)


class Ernie45MoeForCausalLM(Qwen2MoeForCausalLM):
    """ERNIE-4.5 CLM = the shared MoE decoder with ERNIE's config point."""

    def __init__(self, config: Optional[Ernie45MoeConfig] = None):
        super().__init__(config or Ernie45MoeConfig())
