"""ISSUE 20: perfetto/Chrome-trace export of the request waterfalls.

Contracts pinned here:

- SCHEMA: ``export()`` emits a document ``validate_chrome_trace``
  accepts (the subset perfetto's legacy JSON importer requires) for a
  synthetic THREE-process fleet failover — frontend + two gateway
  rings sharing one request id.
- FLEET STITCH: cross-process events land on ONE wall-clock axis via
  the ``wall_accept + t_ms/1e3`` convention ``trace_report``'s fleet
  merge defined — the frontend's ``peer_fail``/``resubmit`` instants
  sit between gwA's accept and gwB's finish, in hop order, and the
  acceptance's "mid-stream failover across two gateway processes"
  renders as one timeline.
- WATERFALL SHAPE: a retained entry becomes a request span with
  nested queue_wait / prefill (+ chunk slices) / decode spans and
  instants only for the punctual kinds; ``phase_share`` rides the
  request span args.
- TICK LANES: a ``tickphase/1`` dump becomes its own process with
  per-phase thread lanes whose widths are the recorded phase times,
  wall-anchored by ``dumped_wall - clock_now``; the per-source tick
  cap drops oldest-first.
- CLI: the file round-trip (``main`` over a run dir with ``--check``)
  exits 0 and writes a loadable JSON.
"""
import importlib.util
import json
import os

import numpy as np
import pytest

from paddle_tpu.generation.paged import PagedEngine
from paddle_tpu.generation.stub import TickStubModel
from paddle_tpu.serving.reqtrace import RequestTrace, RequestTraceRing
from paddle_tpu.utils import observability as obs


def _load_tool(name):
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def tx():
    return _load_tool("trace_export")


def _ring(gateway, replica, **kw):
    return RequestTraceRing(capacity=16,
                            labels={"gateway": gateway,
                                    "replica": replica}, **kw)


def _fleet_docs():
    """The synthetic failover: frontend proxies req-x to gwA, gwA
    dies mid-stream, the frontend resubmits to gwB which finishes."""
    # gwB finished clean and fast; a low slow-TTFT threshold keeps its
    # timeline past tail retention so the waterfall has all three hops
    rings = {"fe": _ring("flt", "frontend"),
             "a": _ring("gwA", "r0"),
             "b": _ring("gwB", "r0", slow_ttft_ms=1.0)}
    t_fe = RequestTrace("req-x")
    t_fe.ev("accept", t_ms=0.0)
    t_fe.ev("proxy_to", t_ms=1.0, replica="pA", attempt=0)
    t_fe.ev("peer_fail", t_ms=30.0, replica="pA",
            reason="peer_conn_drop")
    t_fe.ev("resubmit", t_ms=31.0, to_replica="", attempt=1)
    t_fe.ev("resume_offset", t_ms=31.5, offset=3, committed=3)
    t_fe.ev("proxy_to", t_ms=32.0, replica="pB", attempt=1)
    t_a = RequestTrace("req-x")
    t_a.ev("queue_enter", t_ms=0.0, slo="interactive")
    t_a.ev("slot_take", t_ms=2.0, slot=0, prefix_hit_tokens=0,
           blocks=2)
    t_a.ev("prefill_done", t_ms=5.0)
    t_a.ev("first_token", t_ms=6.0)
    t_b = RequestTrace("req-x")
    t_b.ev("queue_enter", t_ms=0.0, slo="interactive")
    t_b.ev("slot_take", t_ms=1.0, slot=0, prefix_hit_tokens=3,
           blocks=2)
    t_b.ev("prefill_done", t_ms=3.0)
    t_b.ev("first_token", t_ms=4.0)
    t_b.ev("tick", t_ms=5.0, n=1,
           phase={"wall_ms": 2.0, "host_ms": 0.5, "h2d_ms": 0.0,
                  "dispatch_ms": 1.0, "device_ms": 0.25,
                  "drain_ms": 0.25})
    t_b.ev("finish", t_ms=20.0, reason="stop")
    # one wall-clock axis: frontend accepts first, gwA right after,
    # gwB at the failover 40ms later
    t_fe.wall0, t_a.wall0, t_b.wall0 = 100.0, 100.002, 100.040
    rings["fe"].finish(t_fe, "stop", tokens=9)
    rings["a"].finish(t_a, "error", tokens=3)
    rings["b"].finish(t_b, "stop", tokens=6)
    return [dict(r.to_doc(), _file=f"reqtrace_{k}.json")
            for k, r in rings.items()]


def test_export_fleet_failover_schema_and_order(tx):
    doc = tx.export(_fleet_docs())
    assert tx.validate_chrome_trace(doc) == []
    evs = doc["traceEvents"]
    assert doc["otherData"]["requests"] == 1
    assert sorted(doc["otherData"]["sources"]) \
        == ["flt/frontend", "gwA/r0", "gwB/r0"]
    # one process lane per source, named via metadata events
    procs = {e["pid"] for e in evs if e["ph"] == "M"
             and e["name"] == "process_name"}
    assert procs == {"flt/frontend", "gwA/r0", "gwB/r0"}
    # request spans exist on every lane, sharing the req-x thread
    spans = [e for e in evs if e["ph"] == "X"
             and e["cat"] == "request"]
    assert {s["pid"] for s in spans} == procs
    assert all(s["tid"] == "req-x" for s in spans)
    # gwB's span args carry the per-request phase share
    b_span = next(s for s in spans if s["pid"] == "gwB/r0")
    assert b_span["args"]["phase_share"]["dispatch_frac"] \
        == pytest.approx(0.5)
    # nested waterfall on gwB: queue_wait, prefill, decode
    b_phases = {e["name"] for e in evs if e["ph"] == "X"
                and e["cat"] == "phase" and e["pid"] == "gwB/r0"}
    assert b_phases == {"queue_wait", "prefill", "decode"}
    # ONE wall-clock axis in hop order: gwA accept < peer_fail <
    # resubmit < gwB accept < gwB finish-span end (the acceptance's
    # mid-stream failover as one left-to-right timeline)
    def ts(pid, name, ph="i"):
        return next(e["ts"] for e in evs
                    if e["pid"] == pid and e["name"] == name
                    and e["ph"] == ph)
    a_accept = next(s["ts"] for s in spans if s["pid"] == "gwA/r0")
    b_accept = next(s["ts"] for s in spans if s["pid"] == "gwB/r0")
    fail = ts("flt/frontend", "peer_fail")
    resub = ts("flt/frontend", "resubmit")
    assert a_accept < fail < resub < b_accept \
        < b_accept + b_span["dur"]
    # instants only for the punctual catalog (ticks are not markers)
    inst = {e["name"] for e in evs if e["ph"] == "i"}
    assert "tick" not in inst and "peer_fail" in inst \
        and "resume_offset" in inst
    # events globally time-sorted (perfetto's importer expectation)
    tss = [e["ts"] for e in evs if e["ph"] != "M"]
    assert tss == sorted(tss)


def test_tickphase_lanes_and_cap(tx, capsys):
    eng = PagedEngine(TickStubModel(), max_slots=4, num_blocks=32,
                      block_size=8, max_blocks_per_seq=8,
                      prefill_buckets=(16,), tick_profile=True)
    eng.submit("a", (np.arange(6) % 5 + 1)[None], max_new_tokens=8)
    eng.run()
    doc = eng.tick_profile_doc()
    assert obs.validate_tickphase_doc(doc) == []
    doc["_file"] = "tickphase_t_r0.json"
    out = tx.export([], [doc])
    assert tx.validate_chrome_trace(out) == []
    evs = out["traceEvents"]
    pid = "tickphase:t_r0"
    assert out["otherData"]["tick_sources"] == ["tickphase_t_r0.json"]
    ticks = [e for e in evs if e["ph"] == "X" and e["cat"] == "tick"]
    assert len(ticks) == doc["ticks"]
    # phase slices stack inside their tick window on per-phase lanes
    ph = [e for e in evs if e["ph"] == "X" and e["cat"] == "tick_phase"]
    assert ph and all(e["pid"] == pid for e in ph)
    assert {e["tid"] for e in ph} <= set(obs.TICK_PHASES)
    t0 = ticks[0]
    inside = [e for e in ph if t0["ts"] - 1e-3 <= e["ts"]
              <= t0["ts"] + t0["dur"] + 1e-3]
    assert sum(e["dur"] for e in inside) \
        == pytest.approx(t0["dur"], rel=0.02)
    # the per-source cap drops oldest ticks, loudly
    big = dict(doc, entries=[dict(doc["entries"][-1],
                                  tick=i, t=doc["entries"][-1]["t"])
                             for i in range(tx.MAX_TICKS_PER_SOURCE
                                            + 10)])
    capped = tx.export([], [big])
    n = sum(1 for e in capped["traceEvents"]
            if e["ph"] == "X" and e["cat"] == "tick")
    assert n == tx.MAX_TICKS_PER_SOURCE
    assert "older dropped" in capsys.readouterr().err


def test_cli_roundtrip_over_run_dir(tx, tmp_path):
    for d in _fleet_docs():
        with open(tmp_path / d["_file"], "w") as f:
            json.dump({k: v for k, v in d.items() if k != "_file"}, f)
    eng = PagedEngine(TickStubModel(), max_slots=4, num_blocks=32,
                      block_size=8, max_blocks_per_seq=8,
                      prefill_buckets=(16,), tick_profile=True)
    eng.submit("a", (np.arange(6) % 5 + 1)[None], max_new_tokens=8)
    eng.run()
    assert eng.dump_tick_profile(str(tmp_path / "tickphase_t_r0.json"))
    out = tmp_path / "trace.json"
    assert tx.main([str(tmp_path), "-o", str(out), "--check"]) == 0
    with open(out) as f:
        doc = json.load(f)
    assert tx.validate_chrome_trace(doc) == []
    assert doc["otherData"]["requests"] == 1
    assert doc["otherData"]["tick_sources"] == ["tickphase_t_r0.json"]
    # --no-ticks leaves only the request lanes
    assert tx.main([str(tmp_path), "-o", str(out), "--no-ticks",
                    "--check"]) == 0
    with open(out) as f:
        doc2 = json.load(f)
    assert doc2["otherData"]["tick_sources"] == []
    assert all(e.get("cat") not in ("tick", "tick_phase")
               for e in doc2["traceEvents"])


def test_validator_catches_malformed_events(tx):
    good = {"traceEvents": [
        {"name": "a", "ph": "X", "ts": 1.0, "dur": 2.0,
         "pid": "p", "tid": "t"}]}
    assert tx.validate_chrome_trace(good) == []
    assert tx.validate_chrome_trace({"traceEvents": 3})
    assert tx.validate_chrome_trace({"traceEvents": [
        {"name": "a", "ph": "Z", "ts": 1.0, "pid": "p", "tid": "t"}]})
    assert tx.validate_chrome_trace({"traceEvents": [
        {"name": "a", "ph": "X", "ts": 1.0, "dur": -1.0,
         "pid": "p", "tid": "t"}]})
    assert tx.validate_chrome_trace({"traceEvents": [
        {"name": "a", "ph": "i", "ts": 1.0, "s": "x",
         "pid": "p", "tid": "t"}]})
    assert tx.validate_chrome_trace({"traceEvents": [
        {"ph": "X", "ts": 1.0, "dur": 1.0}]})
