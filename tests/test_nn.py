"""Layer numerics + module-system semantics (SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.nn import functional as F


def test_linear_numerics():
    lin = nn.Linear(4, 3)
    x = np.random.randn(2, 4).astype(np.float32)
    w = pt.numpy(lin.weight)
    b = pt.numpy(lin.bias)
    out = pt.numpy(lin(pt.to_tensor(x)))
    assert np.allclose(out, x @ w + b, atol=1e-5)


def test_layernorm_matches_formula():
    ln = nn.LayerNorm(8)
    x = np.random.randn(2, 5, 8).astype(np.float32)
    out = pt.numpy(ln(pt.to_tensor(x)))
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    want = (x - mean) / np.sqrt(var + 1e-5)
    assert np.allclose(out, want, atol=1e-4)


def test_rmsnorm():
    rn = nn.RMSNorm(8)
    x = np.random.randn(2, 8).astype(np.float32)
    out = pt.numpy(rn(pt.to_tensor(x)))
    want = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
    assert np.allclose(out, want, atol=1e-4)


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(3)
    x = np.random.randn(4, 3, 5, 5).astype(np.float32) * 2 + 1
    bn.train()
    out = pt.numpy(bn(pt.to_tensor(x)))
    assert abs(out.mean()) < 1e-4 and abs(out.std() - 1) < 1e-2
    # running stats moved toward batch stats
    assert not np.allclose(pt.numpy(bn._mean), 0)
    bn.eval()
    out_eval = bn(pt.to_tensor(x))
    assert out_eval.shape == x.shape


def test_conv2d_matches_torch():
    torch = pytest.importorskip("torch")
    conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
    x = np.random.randn(2, 3, 9, 9).astype(np.float32)
    got = pt.numpy(conv(pt.to_tensor(x)))
    with torch.no_grad():
        want = torch.nn.functional.conv2d(
            torch.from_numpy(x), torch.from_numpy(pt.numpy(conv.weight)),
            torch.from_numpy(pt.numpy(conv.bias)), stride=2, padding=1).numpy()
    assert np.allclose(got, want, atol=1e-4)


def test_conv2d_grouped_and_dilated():
    torch = pytest.importorskip("torch")
    conv = nn.Conv2D(4, 8, 3, padding=2, dilation=2, groups=2)
    x = np.random.randn(1, 4, 8, 8).astype(np.float32)
    got = pt.numpy(conv(pt.to_tensor(x)))
    with torch.no_grad():
        want = torch.nn.functional.conv2d(
            torch.from_numpy(x), torch.from_numpy(pt.numpy(conv.weight)),
            torch.from_numpy(pt.numpy(conv.bias)), padding=2, dilation=2,
            groups=2).numpy()
    assert np.allclose(got, want, atol=1e-4)


def test_conv_transpose_matches_torch():
    torch = pytest.importorskip("torch")
    conv = nn.Conv2DTranspose(3, 6, 4, stride=2, padding=1)
    x = np.random.randn(2, 3, 5, 5).astype(np.float32)
    got = pt.numpy(conv(pt.to_tensor(x)))
    with torch.no_grad():
        want = torch.nn.functional.conv_transpose2d(
            torch.from_numpy(x), torch.from_numpy(pt.numpy(conv.weight)),
            torch.from_numpy(pt.numpy(conv.bias)), stride=2, padding=1).numpy()
    assert got.shape == want.shape
    assert np.allclose(got, want, atol=1e-4)


def test_pooling():
    x = np.random.randn(1, 2, 8, 8).astype(np.float32)
    out = pt.numpy(F.max_pool2d(pt.to_tensor(x), 2))
    assert out.shape == (1, 2, 4, 4)
    assert np.allclose(out[0, 0, 0, 0], x[0, 0, :2, :2].max())
    avg = pt.numpy(F.avg_pool2d(pt.to_tensor(x), 2))
    assert np.allclose(avg[0, 0, 0, 0], x[0, 0, :2, :2].mean(), atol=1e-6)


def test_cross_entropy_matches_torch():
    torch = pytest.importorskip("torch")
    logits = np.random.randn(8, 10).astype(np.float32)
    labels = np.random.randint(0, 10, (8,))
    got = float(F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(labels)))
    want = float(torch.nn.functional.cross_entropy(
        torch.from_numpy(logits), torch.from_numpy(labels)))
    assert abs(got - want) < 1e-5


def test_cross_entropy_ignore_index_and_smoothing():
    torch = pytest.importorskip("torch")
    logits = np.random.randn(8, 10).astype(np.float32)
    labels = np.random.randint(0, 10, (8,))
    labels[0] = -100
    got = float(F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(labels),
                                label_smoothing=0.1))
    want = float(torch.nn.functional.cross_entropy(
        torch.from_numpy(logits), torch.from_numpy(labels),
        ignore_index=-100, label_smoothing=0.1))
    assert abs(got - want) < 1e-4


def test_attention_matches_reference():
    q = np.random.randn(2, 16, 4, 8).astype(np.float32)
    k = np.random.randn(2, 16, 4, 8).astype(np.float32)
    v = np.random.randn(2, 16, 4, 8).astype(np.float32)
    out = F.scaled_dot_product_attention(
        pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v), is_causal=True)
    # manual reference
    scale = 1 / np.sqrt(8)
    qt, kt, vt = [a.transpose(0, 2, 1, 3) for a in (q, k, v)]
    s = np.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    mask = np.tril(np.ones((16, 16), dtype=bool))
    s = np.where(mask, s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, vt).transpose(0, 2, 1, 3)
    assert np.allclose(pt.numpy(out), want, atol=1e-4)


def test_gqa_attention():
    q = np.random.randn(2, 8, 8, 16).astype(np.float32)
    kv = np.random.randn(2, 8, 2, 16).astype(np.float32)
    out = F.scaled_dot_product_attention(pt.to_tensor(q), pt.to_tensor(kv),
                                         pt.to_tensor(kv))
    assert out.shape == (2, 8, 8, 16)


def test_state_dict_roundtrip():
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    sd = model.state_dict()
    assert set(sd) == {"0.weight", "0.bias", "2.weight", "2.bias"}
    model2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    model2.set_state_dict(sd)
    x = pt.ones((1, 4))
    assert np.allclose(pt.numpy(model(x)), pt.numpy(model2(x)))


def test_train_eval_dropout():
    d = nn.Dropout(0.5)
    x = pt.ones((100,))
    d.eval()
    assert np.allclose(pt.numpy(d(x)), 1.0)
    d.train()
    out = pt.numpy(d(x))
    assert (out == 0).any() and (out > 1).any()


def test_sublayer_traversal_and_apply():
    model = nn.Sequential(nn.Linear(2, 2), nn.Sequential(nn.Linear(2, 2)))
    names = [n for n, _ in model.named_parameters()]
    assert "1.0.weight" in names
    count = []
    model.apply(lambda l: count.append(type(l).__name__))
    assert "Linear" in count and "Sequential" in count


def test_transformer_encoder_forward():
    enc = nn.TransformerEncoderLayer(d_model=16, nhead=4, dim_feedforward=32,
                                     dropout=0.0)
    x = pt.to_tensor(np.random.randn(2, 5, 16).astype(np.float32))
    out = enc(x)
    assert out.shape == (2, 5, 16)


def test_mha_cache():
    mha = nn.MultiHeadAttention(16, 4)
    mha.eval()
    x = pt.to_tensor(np.random.randn(1, 3, 16).astype(np.float32))
    k0 = pt.zeros((1, 0, 4, 4))
    out, (k, v) = mha(x, cache=(k0, k0))
    assert k.shape == (1, 3, 4, 4)


def test_recompute_matches_plain():
    lin = nn.Linear(8, 8)
    x = pt.to_tensor(np.random.randn(2, 8).astype(np.float32))

    def loss_plain(p):
        with lin.bound(p):
            return pt.sum(lin(x) ** 2)

    def loss_remat(p):
        with lin.bound(p):
            return pt.sum(nn.recompute(lambda v: lin(v) ** 2, x))

    params = dict(lin.named_parameters())
    g1 = pt.grad(loss_plain)(params)
    g2 = pt.grad(loss_remat)(params)
    for k in g1:
        assert np.allclose(pt.numpy(g1[k]), pt.numpy(g2[k]), atol=1e-5)
