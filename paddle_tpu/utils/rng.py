"""Seed control mirroring paddle.seed / paddle.framework.random (reference:
python/paddle/framework/random.py) plus the model-parallel RNG state
(reference: fleet.meta_parallel RNGStatesTracker).

JAX RNG is explicit-key; this module provides the global stateful facade the
paddle API expects, while everything inside jit receives keys explicitly.

Model-parallel semantics: dropout inside tensor-parallel regions must use
*different* streams per tp rank (activations are sharded) while weight init
and data-order use the *same* stream everywhere. `rng_state(name)` scopes a
named stream; "global" is replicated, "local" is folded with the process
index.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp

_state = threading.local()


def _ensure():
    if not hasattr(_state, "key"):
        _state.key = jax.random.key(0)
        _state.streams = {}
        _state.stack = []


def seed(s: int):
    """paddle.seed equivalent: reset the global generator."""
    _ensure()
    _state.key = jax.random.key(int(s))
    _state.streams = {}
    return s


def get_rng_state():
    _ensure()
    return {"key": _state.key, "streams": dict(_state.streams)}


def set_rng_state(state):
    _ensure()
    _state.key = state["key"]
    _state.streams = dict(state["streams"])


def next_key(n: int = 0):
    """Split a fresh key off the active stream (host-side, eager only)."""
    _ensure()
    name = _state.stack[-1] if _state.stack else None
    if name is None:
        _state.key, sub = jax.random.split(_state.key)
        return sub
    stream = _state.streams.setdefault(name, jax.random.fold_in(_state.key, hash(name) % (2**31)))
    new, sub = jax.random.split(stream)
    _state.streams[name] = new
    return sub


@contextlib.contextmanager
def rng_state(name: str):
    """Scope a named RNG stream (model-parallel tracker parity)."""
    _ensure()
    _state.stack.append(name)
    try:
        yield
    finally:
        _state.stack.pop()


def fold_axis(key, axis_name: str):
    """Inside shard_map/pjit: decorrelate a key across a mesh axis (for
    dropout on sharded activations)."""
    return jax.random.fold_in(key, jax.lax.axis_index(axis_name))
