"""Diffusion scheduler tests (C24): forward-process identities, exact
recovery with oracle models, determinism, scan-based sampling loop.
"""
import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.diffusion import (DDIMScheduler, DDPMScheduler,
                                  FlowMatchScheduler, diffusion_loss,
                                  make_betas, sample_loop)


class TestBetas:
    def test_schedules(self):
        for sched in ("linear", "scaled_linear", "squaredcos_cap_v2"):
            betas = make_betas(100, sched)
            assert betas.shape == (100,)
            assert float(betas.min()) > 0 and float(betas.max()) < 1

    def test_alphas_cumprod_decreasing(self):
        s = DDPMScheduler(num_train_timesteps=50)
        ac = np.asarray(s.alphas_cumprod)
        assert np.all(np.diff(ac) < 0) and ac[0] < 1.0


class TestDDPM:
    def test_add_noise_snr_endpoints(self):
        s = DDPMScheduler(num_train_timesteps=1000)
        x0 = jnp.ones((2, 3, 4, 4))
        noise = jnp.zeros_like(x0)
        # early timestep: mostly signal
        early = s.add_noise(x0, noise, jnp.array([0, 0]))
        late = s.add_noise(x0, noise, jnp.array([999, 999]))
        assert float(early.mean()) > 0.99
        assert float(late.mean()) < 0.3

    def test_epsilon_x0_roundtrip(self):
        """Oracle epsilon → _pred_x0 recovers x0 exactly."""
        s = DDPMScheduler(num_train_timesteps=100)
        key = jax.random.PRNGKey(0)
        x0 = jax.random.normal(key, (2, 3, 4, 4))
        noise = jax.random.normal(jax.random.PRNGKey(1), x0.shape)
        t = jnp.array([10, 70])
        noisy = s.add_noise(x0, noise, t)
        rec = s._pred_x0(noise, noisy, t)
        np.testing.assert_allclose(np.asarray(rec), np.asarray(x0),
                                   atol=1e-4)

    def test_v_prediction_roundtrip(self):
        s = DDPMScheduler(num_train_timesteps=100,
                          prediction_type="v_prediction")
        x0 = jax.random.normal(jax.random.PRNGKey(0), (2, 4))
        noise = jax.random.normal(jax.random.PRNGKey(1), x0.shape)
        t = jnp.array([5, 60])
        noisy = s.add_noise(x0, noise, t)
        v = s.velocity(x0, noise, t)
        rec = s._pred_x0(v, noisy, t)
        np.testing.assert_allclose(np.asarray(rec), np.asarray(x0),
                                   atol=1e-4)

    def test_oracle_reverse_recovers_x0(self):
        """Stepping t=99→0 with the oracle eps model (posterior means,
        no injected noise) lands on x0."""
        s = DDPMScheduler(num_train_timesteps=100)
        x0 = jnp.full((1, 2, 2), 0.5)
        noise = jax.random.normal(jax.random.PRNGKey(2), x0.shape)
        x = s.add_noise(x0, noise, jnp.array([99]))

        def body(x, t):
            ac = s.alphas_cumprod[t]
            eps = (x - jnp.sqrt(ac) * x0) / jnp.sqrt(1.0 - ac)  # oracle
            return s.step(eps, jnp.array([t]), x), None

        x, _ = jax.lax.scan(body, x, jnp.arange(99, -1, -1))
        np.testing.assert_allclose(np.asarray(x), np.asarray(x0), atol=1e-3)


class TestDDIM:
    def test_deterministic(self):
        s = DDIMScheduler(num_train_timesteps=100, eta=0.0)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 2))
        out1 = s.step(x * 0.1, jnp.array([50]), x,
                      key=jax.random.PRNGKey(1))
        out2 = s.step(x * 0.1, jnp.array([50]), x,
                      key=jax.random.PRNGKey(99))
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))

    def test_oracle_full_denoise(self):
        """With an oracle eps model, coarse DDIM recovers x0 by the final
        (prev_t = -1) step."""
        s = DDIMScheduler(num_train_timesteps=100)
        x0 = jax.random.normal(jax.random.PRNGKey(0), (2, 3))
        noise = jax.random.normal(jax.random.PRNGKey(1), x0.shape)
        t = jnp.array([99, 99])
        x = s.add_noise(x0, noise, t)
        out = s.step(noise, t, x, prev_t=jnp.array([-1, -1]))
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0),
                                   atol=1e-4)

    def test_timesteps_grid(self):
        s = DDIMScheduler(num_train_timesteps=1000)
        ts = np.asarray(s.timesteps(50))
        assert len(ts) == 50 and ts[0] > ts[-1] and ts[-1] == 0


class TestFlowMatch:
    def test_interpolation(self):
        s = FlowMatchScheduler(num_train_timesteps=1000)
        x0 = jnp.ones((2, 4))
        noise = jnp.zeros_like(x0)
        early = s.add_noise(x0, noise, jnp.array([0, 0]))
        late = s.add_noise(x0, noise, jnp.array([999, 999]))
        assert float(early.mean()) > 0.99
        assert float(late.mean()) < 1e-5   # sigma(max t) == 1 → pure noise

    def test_shift(self):
        s1 = FlowMatchScheduler(shift=1.0)
        s3 = FlowMatchScheduler(shift=3.0)
        t = jnp.array([200])
        assert float(s3.sigmas_for(t)[0]) > float(s1.sigmas_for(t)[0])

    def test_oracle_velocity_exact(self):
        """Rectified-flow paths are straight: Euler with the oracle
        velocity recovers x0 exactly in ONE step from any sigma."""
        s = FlowMatchScheduler(num_train_timesteps=100)
        x0 = jax.random.normal(jax.random.PRNGKey(0), (2, 5))
        noise = jax.random.normal(jax.random.PRNGKey(1), x0.shape)
        t = jnp.array([70, 30])
        x = s.add_noise(x0, noise, t)
        v = s.training_target(x0, noise, t)   # == noise - x0
        out = s.step(v, t, x)                 # integrate to sigma=0
        np.testing.assert_allclose(np.asarray(out), np.asarray(x0),
                                   atol=1e-5)


class TestLoopAndLoss:
    def test_sample_loop_shapes_jit(self):
        s = DDPMScheduler(num_train_timesteps=20)

        def model_fn(x, t):
            return x * 0.1

        out = jax.jit(lambda k: sample_loop(s, model_fn, (2, 3, 4, 4), 10, k)
                      )(jax.random.PRNGKey(0))
        assert out.shape == (2, 3, 4, 4)
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_flow_sample_loop_oracle(self):
        """Oracle constant-velocity field drives samples to its x0."""
        s = FlowMatchScheduler(num_train_timesteps=100)
        target = jnp.full((1, 2, 2, 2), 0.7)

        # rectified flow oracle: v(x_t, t) = (x_t - x0) / sigma
        def model_fn(x, t):
            sig = s.sigmas_for(t).reshape((-1, 1, 1, 1))
            return (x - target) / sig

        out = sample_loop(s, model_fn, target.shape, 50,
                          jax.random.PRNGKey(0))
        np.testing.assert_allclose(np.asarray(out), np.asarray(target),
                                   atol=1e-2)

    def test_diffusion_loss_with_dit(self):
        from paddle_tpu.models import DiT, dit_tiny
        model = DiT(dit_tiny())
        s = DDPMScheduler(num_train_timesteps=100)
        fn, params = model.functional()
        x0 = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8, 8))
        noise = jax.random.normal(jax.random.PRNGKey(1), x0.shape)
        t = jnp.array([10, 80])
        y = jnp.array([0, 1])

        def loss_of(p):
            return diffusion_loss(s, lambda xt, tt: fn(p, xt, tt, y),
                                  x0, t, noise)

        loss, grads = jax.value_and_grad(loss_of)(params)
        assert jnp.isfinite(loss)
        total = sum(float(jnp.abs(g).sum()) for g in grads.values())
        assert total > 0
