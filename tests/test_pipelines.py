"""Diffusion pipelines + inference Predictor tests (C24 depth, serving)."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu.diffusion import (DDIMScheduler, DiTPipeline,
                                  FlowMatchScheduler,
                                  StableDiffusion3Pipeline)
from paddle_tpu.inference import Config, Predictor
from paddle_tpu.models import (DiT, MMDiT, AutoencoderKL, dit_tiny,
                               mmdit_tiny, vae_tiny)


class TestDiTPipeline:
    def test_latents_shape_finite(self):
        pipe = DiTPipeline(DiT(dit_tiny()))
        out = pipe([0, 1], num_inference_steps=4, key=jax.random.PRNGKey(0))
        assert out.shape == (2, 4, 8, 8)
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_vae_decode_stage(self):
        vae = AutoencoderKL(vae_tiny())
        pipe = DiTPipeline(DiT(dit_tiny()), vae=vae)
        img = pipe([1], num_inference_steps=2, key=jax.random.PRNGKey(1))
        assert img.shape == (1, 3, 16, 16)   # one VAE upsample stage from 8

    def test_guidance_changes_output(self):
        pipe = DiTPipeline(DiT(dit_tiny()))
        # zero-init final layer → output 0 → cfg has no effect on eps, but
        # perturb params so cond/uncond differ
        for k in pipe._params:
            if "final_proj" in k or "ada" in k:
                pipe._params[k] = jax.random.normal(
                    jax.random.PRNGKey(0), pipe._params[k].shape) * 0.02
        a = pipe([0], num_inference_steps=3, guidance_scale=1.0,
                 key=jax.random.PRNGKey(2))
        b = pipe([0], num_inference_steps=3, guidance_scale=8.0,
                 key=jax.random.PRNGKey(2))
        assert not np.allclose(np.asarray(a), np.asarray(b))


class TestSD3Pipeline:
    def test_flow_sampling(self):
        cfg = mmdit_tiny()
        pipe = StableDiffusion3Pipeline(MMDiT(cfg))
        ctx = jnp.ones((1, 6, cfg.context_dim))
        pooled = jnp.ones((1, cfg.pooled_dim))
        out = pipe(ctx, pooled, num_inference_steps=4)
        assert out.shape == (1, 4, 8, 8)
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_negative_prompt_embeddings(self):
        cfg = mmdit_tiny()
        pipe = StableDiffusion3Pipeline(MMDiT(cfg))
        ctx = jnp.ones((1, 6, cfg.context_dim))
        pooled = jnp.ones((1, cfg.pooled_dim))
        out = pipe(ctx, pooled, neg_context=ctx * 0.5, neg_pooled=pooled,
                   num_inference_steps=2)
        assert out.shape == (1, 4, 8, 8)


class TestPredictor:
    def _model(self):
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny
        return LlamaForCausalLM(llama_tiny(hidden_size=128,
                                           intermediate_size=256))

    def test_run_shapes_and_trace_cache(self):
        pred = Predictor(self._model())
        out1 = pred.run(np.array([[1, 2, 3, 4]]))
        assert out1.shape == (1, 4, 256)
        pred.run(np.array([[5, 6, 7, 8]]))        # same shape → cached trace
        n_traces = pred._engine._cache_size()
        pred.run(np.array([[9, 9, 9, 9]]))
        assert pred._engine._cache_size() == n_traces
        pred.run(np.array([[1, 2, 3, 4, 5, 6, 7, 8]]))  # new shape → retrace
        assert pred._engine._cache_size() == n_traces + 1

    def test_quantized_predictor(self):
        pred = Predictor(self._model(),
                         Config().enable_weight_only_quant(8))
        ref = Predictor(self._model())
        kinds = [type(l).__name__ for l in pred.model.sublayers()]
        assert "QuantizedLinear" in kinds
        out = pred.run(np.array([[1, 2, 3]]))
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_generate(self):
        pred = Predictor(self._model())
        out = pred.generate(np.array([[1, 2, 3, 4]]), max_new_tokens=4,
                            key=jax.random.PRNGKey(0))
        tok = out[0] if isinstance(out, tuple) else out
        assert tok.shape == (1, 8)

    def test_checkpoint_roundtrip(self, tmp_path):
        m = self._model()
        path = str(tmp_path / "m.ckpt")
        pt.save(m.state_dict(), path)
        pred = Predictor.from_checkpoint(self._model, path)
        ids = np.array([[1, 2, 3]])
        np.testing.assert_allclose(np.asarray(pred.run(ids)),
                                   np.asarray(m.eval()(ids)), atol=1e-5)
