"""Prefix-cache-aware multi-replica routing (ISSUE 9; reference:
session-affinity LB policies in production LLM serving — SGLang's
cache-aware router, vLLM's prefix-aware scheduling — restated over
PagedEngine's SHA-256 chain digests).

A PagedEngine replica that already holds a prompt's shared-prefix
blocks (system prompt, few-shot preamble) serves it with the prefill
for that span SKIPPED — but only if the request lands on THAT replica.
The router keys affinity off ``PagedEngine.prefix_digest()``: the same
chain digest the engine's prefix cache is keyed by, so "does replica X
have this prefix warm" is one dict lookup (``has_prefix``), not a
heuristic.

Routing order for a request carrying ``digest``:

1. **warm** — healthy replicas whose engine reports the digest live in
   its prefix cache; least-loaded among them wins (a hit).
2. **sticky** — no replica is warm yet, but an earlier request with
   the same digest was routed somewhere and may still be prefilling:
   follow it so the second request arrives after the first registered
   the blocks (a hit — this is what turns a burst of same-prefix
   requests into one miss + N-1 hits instead of N misses).
3. **fallback** — least-loaded healthy replica (a miss; the sticky map
   remembers the choice).

A warm/sticky target that is ``spill_margin`` load units more loaded
than the least-loaded replica is abandoned for the fallback: affinity
is a latency optimization, not a priority override, and a hot prefix
must not melt one replica while others idle.

Health eviction: a replica whose ``healthy()`` is False is skipped and
its sticky entries drop (when it comes back it re-earns affinity by
getting warm again). All replicas unhealthy raises
:class:`NoReplicaError` (the gateway's 503).

Rejoin (ISSUE 12): eviction is no longer one-way. A replica carrying a
:class:`~.supervisor.CircuitBreaker` re-enters rotation through it —
when the breaker's backoff elapses it goes half-open and the router
diverts exactly ONE request at a time to that replica as a probation
probe (verdict ``probe``; the gateway marks the request so its
terminal path reports ``probe_done``). Enough probe successes close
the breaker, the supervisor flips ``healthy()`` back, and the replica
is back in the warm -> sticky -> least-loaded ladder; a probe failure
re-opens with a longer backoff. The probe check runs FIRST so a
recovering replica gets its probe even while healthy siblings could
absorb the traffic — and the failover path protects the probe request
if the replica is still bad.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from ..utils import observability as obs

__all__ = ["NoReplicaError", "EngineReplica", "PrefixAffinityRouter"]


class NoReplicaError(RuntimeError):
    """Every replica is unhealthy/evicted — nothing can take traffic."""


class EngineReplica:
    """Default replica adapter over a local ``PagedEngine``. The
    gateway wraps it to fold its scheduler depth into ``load()`` and to
    flip ``healthy`` on tick-thread failures; remote replicas would
    implement the same three methods over RPC."""

    def __init__(self, name: str, engine):
        self.name = name
        self.engine = engine
        self._healthy = True
        # circuit breaker (ISSUE 12): attached by the gateway's
        # supervisor; None = legacy one-way health eviction. While the
        # breaker is half-open the replica stays healthy()==False and
        # re-enters rotation only via the router's probation probe.
        self.breaker = None

    def healthy(self) -> bool:
        return self._healthy

    def mark(self, healthy: bool):
        self._healthy = bool(healthy)

    def has_prefix(self, digest: str) -> bool:
        return self.engine.has_prefix(digest)

    def load(self) -> float:
        """Outstanding work units: live slots + engine-queued requests.
        Read cross-thread without the engine's tick thread stopping —
        both are O(1) host bookkeeping reads and a slightly stale load
        only costs routing optimality, never correctness."""
        eng = self.engine
        return (sum(s is not None for s in eng.slots) + len(eng.queue))


class PrefixAffinityRouter:
    """Pick a replica per request. ``policy``: ``"prefix"`` (default,
    the full affinity ladder), ``"least_loaded"``, or
    ``"round_robin"`` (the A/B baseline the loadgen compares against).
    """

    POLICIES = ("prefix", "least_loaded", "round_robin")

    def __init__(self, replicas: List[Any], policy: str = "prefix",
                 spill_margin: float = 8.0, sticky_capacity: int = 1024,
                 labels: Optional[Dict[str, str]] = None):
        # an EMPTY initial list is legal (ISSUE 13: a fleet frontend
        # starts bare and grows through add_replica); routing with no
        # healthy replica raises NoReplicaError as always
        if policy not in self.POLICIES:
            raise ValueError(f"unknown routing policy {policy!r}")
        self.replicas = list(replicas)
        self.policy = policy
        self.spill_margin = float(spill_margin)
        self._sticky: "OrderedDict[str, Any]" = OrderedDict()
        self._sticky_cap = int(sticky_capacity)
        self._rr = 0
        self._tie_rr = 0
        self._lock = threading.Lock()
        labels = labels or {}
        reg = obs.registry()
        self._c_hit = reg.counter("gateway_prefix_route_hits_total",
                                  **labels)
        self._c_miss = reg.counter("gateway_prefix_route_misses_total",
                                   **labels)

    # ------------------------------------------------------------ helpers
    def _healthy(self) -> List[Any]:
        up = [r for r in self.replicas if r.healthy()]
        if not up:
            raise NoReplicaError("all replicas unhealthy")
        return up

    def _least_loaded(self, cands: List[Any]):
        """Minimum load, rotating among ties. ``min()`` alone herds:
        load signals are probe snapshots quantized to whole slots, so
        a large mostly-idle fleet has hundreds of replicas tied at
        0.0 and first-minimum sends EVERY miss of a staleness window
        to the same lowest-index replica — at 1000 replicas the fleet
        sim measured ~6% of a light clean load shed off that one herd
        target. One read per candidate (load() takes the peer lock)."""
        loads = [r.load() for r in cands]
        lo = min(loads)
        tied = [i for i, l in enumerate(loads) if l <= lo]
        if len(tied) == 1:
            return cands[tied[0]]
        pick = cands[tied[self._tie_rr % len(tied)]]
        self._tie_rr += 1
        return pick

    def _remember(self, digest: str, replica):
        self._sticky[digest] = replica
        self._sticky.move_to_end(digest)
        while len(self._sticky) > self._sticky_cap:
            self._sticky.popitem(last=False)

    # -------------------------------------------------------------- route
    def route(self, digests=None, trace=None, allow_probe=True,
              meta=None):
        """Choose a replica for a request whose affinity keys are
        ``digests`` — the prompt's chunk-grid digest CHAIN, longest
        span first (a bare str is accepted as a one-element chain;
        None/empty = no shared prefix: pure load balancing). The whole
        chain is probed because a request whose unique tail crosses a
        chunk boundary shares only its SHORTER spans with its
        siblings — the longest digest alone would miss the warm
        replica.

        ``trace`` (ISSUE 10): a :class:`~.reqtrace.RequestTrace` to
        record the route DECISION on — which replica won and WHY
        (``warm``/``sticky``/``miss``/``least_loaded``/
        ``round_robin``/``probe``), so a slow request's timeline says
        whether it missed its warm replica. ``meta`` (ISSUE 12): an
        optional dict the verdict is written into (``meta["verdict"]``)
        — the gateway's authoritative "was this the probation probe"
        signal (inferring it from ``healthy()`` after the fact races a
        concurrent replica failure and could mislabel a normal request
        as the probe, corrupting the real probe's accounting)."""

        def _ev(verdict, pick):
            if meta is not None:
                meta["verdict"] = verdict
            if trace is not None:
                trace.ev("route", verdict=verdict,
                         replica=getattr(pick, "name", str(pick)),
                         policy=self.policy, spans=len(digests))
            return pick

        if isinstance(digests, str):
            digests = [digests]
        digests = [d for d in (digests or ()) if d]
        with self._lock:
            # circuit-breaker probation (ISSUE 12): a half-open replica
            # with a free probe slot takes this request as its probe —
            # checked before the ladder so recovery is traffic-driven,
            # and before _healthy() so a fleet that is ALL half-open
            # probes instead of 503ing. ``allow_probe=False`` is the
            # gateway's race-retry: a request whose probe target died
            # re-routes through the plain ladder.
            if allow_probe:
                for r in self.replicas:
                    b = getattr(r, "breaker", None)
                    if b is not None and not r.healthy() \
                            and b.try_probe():
                        if digests:
                            self._c_miss.inc()
                        return _ev("probe", r)
            up = self._healthy()
            if self.policy == "round_robin":
                pick = up[self._rr % len(up)]
                self._rr += 1
                if digests:
                    self._c_miss.inc()
                return _ev("round_robin", pick)
            floor = self._least_loaded(up)
            if self.policy == "least_loaded" or not digests:
                if digests:
                    self._c_miss.inc()
                return _ev("least_loaded", floor)
            cap = floor.load() + self.spill_margin
            for d in digests:            # longest shared span wins
                warm = [r for r in up if r.has_prefix(d)]
                if warm:
                    pick = self._least_loaded(warm)
                    if pick.load() <= cap:
                        self._c_hit.inc()
                        self._remember(digests[0], pick)
                        return _ev("warm", pick)
                    break                # overloaded: spill, don't scan on
            for d in digests:
                sticky = self._sticky.get(d)
                if sticky is not None and sticky in up \
                        and sticky.load() <= cap:
                    self._c_hit.inc()
                    self._sticky.move_to_end(d)
                    return _ev("sticky", sticky)
            self._c_miss.inc()
            for d in digests:            # future siblings of ANY span
                self._remember(d, floor)
            return _ev("miss", floor)

    def add_replica(self, replica):
        """Fleet membership grows at runtime (ISSUE 13: the autoscaler
        spawning a replica, a rejoining peer). Idempotent."""
        with self._lock:
            if replica not in self.replicas:
                self.replicas.append(replica)

    def remove_replica(self, replica):
        """Drop a replica from rotation (autoscaler drain / permanent
        peer death) and forget its sticky affinity — a future replica
        reusing the name re-earns warmth. Idempotent."""
        with self._lock:
            if replica in self.replicas:
                self.replicas.remove(replica)
            for k in [k for k, r in self._sticky.items()
                      if r is replica]:
                del self._sticky[k]

    def evict_unhealthy(self):
        """Drop sticky entries pointing at replicas that are down, so a
        recovered replica re-earns affinity instead of inheriting stale
        routing decisions."""
        with self._lock:
            dead = {k for k, r in self._sticky.items()
                    if not r.healthy()}
            for k in dead:
                del self._sticky[k]

    # -------------------------------------------- HA sticky-state gossip
    def export_sticky(self) -> Dict[str, str]:
        """Sticky map as ``{digest: replica NAME}`` (ISSUE 16 frontend
        HA): names, not objects, because the map crosses a process
        boundary to a sibling frontend holding its OWN adapter objects
        for the same peers."""
        with self._lock:
            return {d: getattr(r, "name", str(r))
                    for d, r in self._sticky.items()}

    def merge_sticky(self, entries: Dict[str, str],
                     by_name: Dict[str, Any]) -> int:
        """Adopt a sibling frontend's sticky assignments for digests
        we have NO local opinion on (never overriding our own — local
        routing history is fresher evidence than gossip), resolving
        names through ``by_name``. Unknown names are skipped (the
        sibling may know peers we don't yet). Returns adopted count."""
        n = 0
        with self._lock:
            for d, name in (entries or {}).items():
                if d in self._sticky:
                    continue
                r = by_name.get(name)
                if r is None:
                    continue
                self._remember(d, r)
                n += 1
        return n

    def snapshot(self) -> Dict[str, Any]:
        snap = {
            "policy": self.policy,
            "replicas_up": sum(r.healthy() for r in self.replicas),
            "replicas": len(self.replicas),
            "prefix_route_hits": int(self._c_hit.value),
            "prefix_route_misses": int(self._c_miss.value),
            "sticky_entries": len(self._sticky),
        }
        breakers = {r.name: r.breaker.state for r in self.replicas
                    if getattr(r, "breaker", None) is not None}
        if breakers:
            snap["breakers"] = breakers
        return snap
