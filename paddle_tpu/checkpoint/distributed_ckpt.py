"""Distributed / sharded checkpointing (reference:
python/paddle/distributed/checkpoint/save_state_dict.py + load_state_dict
— per-rank shard files, metadata, and PaddleNLP's unified-checkpoint
auto-resume).

TPU-native: orbax-backed. Each host writes only its shards of the
GSPMD-sharded arrays (zarr/tensorstore under the hood), saves are async
(training continues while the write drains), and restore applies the
*target* shardings — so a checkpoint written on one mesh restores onto
another (elastic resume). `latest_complete_step` only ever reports fully
committed saves, giving crash-safe auto-resume.

Integrity layer (chaos hardening): every committed step gets a manifest
(`<dir>/manifests/<step>.json`) with per-file sha256 content checksums.
`verify_step` recomputes them; `latest_complete_step` and `restore` skip
or fall back past steps whose bytes no longer match what was written
(bit rot, torn copies, a preemption mid-gc) instead of handing corrupt
state to the trainer or crashing auto-resume. Steps without a manifest
(pre-integrity checkpoints, or a crash between commit and manifest
write) are trusted as before — verification is an added guarantee, not a
new failure mode. The `ckpt_corrupt` fault site deterministically
corrupts a just-committed step so the fallback path stays tier-1
tested."""
from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import orbax.checkpoint as ocp

from ..utils import faults
from ..utils import observability as obs


class CheckpointCorruptionError(RuntimeError):
    """Every on-disk checkpoint step failed checksum verification."""


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class DistributedCheckpoint:
    """CheckpointManager facade: save(step, state) / restore(step|latest)."""

    MANIFEST_DIR = "manifests"
    META_DIR = "meta"

    def __init__(self, directory: str, max_to_keep: int = 5,
                 async_save: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
        )
        self._pending_manifest: set = set()
        # verification memo: step -> (manifest mtime, verdict). Hashing
        # a big checkpoint is seconds of wall clock; latest_complete_step
        # followed by restore must not pay it twice. Keyed on the
        # manifest's mtime so a rewritten manifest re-verifies.
        self._verify_memo: Dict[int, tuple] = {}
        self._manifest_thread: Optional[threading.Thread] = None
        self.last_restored_step: Optional[int] = None

    # ------------------------------------------------------------- paths
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, str(step))

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, self.MANIFEST_DIR,
                            f"{step}.json")

    def _meta_path(self, step: int) -> str:
        return os.path.join(self.directory, self.META_DIR, f"{step}.json")

    # ---------------------------------------------------- meta sidecar
    def _write_meta(self, step: int, meta: Dict[str, Any]):
        """Host-side JSON sidecar per step (sampler position, topology
        manifest, …) written atomically. Kept OUTSIDE the orbax tree so
        old checkpoints (no meta) and new readers stay compatible and
        the restore `like=` structure never has to guess whether data
        state was saved."""
        mdir = os.path.join(self.directory, self.META_DIR)
        os.makedirs(mdir, exist_ok=True)
        tmp = self._meta_path(step) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, self._meta_path(step))

    def load_meta(self, step: int) -> Optional[Dict[str, Any]]:
        """The step's meta sidecar, or None (pre-meta checkpoint /
        unreadable sidecar — resume falls back to array state only)."""
        try:
            with open(self._meta_path(step)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # --------------------------------------------------------- integrity
    def _write_manifest(self, step: int):
        """Checksum every file of a COMMITTED step dir; write the
        manifest atomically (tmp + rename) so a crash mid-write leaves
        either no manifest (step trusted) or a complete one."""
        d = self._step_dir(step)
        files: Dict[str, Dict[str, Any]] = {}
        for root, _, names in os.walk(d):
            for name in sorted(names):
                p = os.path.join(root, name)
                rel = os.path.relpath(p, d)
                files[rel] = {"sha256": _sha256(p),
                              "size": os.path.getsize(p)}
        total_bytes = sum(f["size"] for f in files.values())
        obs.histogram("ckpt_bytes",
                      buckets=obs.BYTES_BUCKETS).observe(total_bytes)
        obs.record_event("ckpt_committed", step=step, bytes=total_bytes,
                         files=len(files))
        mdir = os.path.join(self.directory, self.MANIFEST_DIR)
        os.makedirs(mdir, exist_ok=True)
        tmp = self._manifest_path(step) + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"step": step, "files": files}, f)
        os.replace(tmp, self._manifest_path(step))
        # chaos hook: corrupt the step AFTER its manifest committed, so
        # verification sees exactly what bit rot would produce
        if faults.inject("ckpt_corrupt", step=step):
            self._corrupt_step(step)

    def _corrupt_step(self, step: int):
        """Deterministically flip bytes in the step's largest file."""
        d = self._step_dir(step)
        largest, size = None, -1
        for root, _, names in os.walk(d):
            for name in names:
                p = os.path.join(root, name)
                s = os.path.getsize(p)
                if s > size:
                    largest, size = p, s
        if largest is None:
            return
        with open(largest, "r+b") as f:
            f.seek(size // 2)
            chunk = f.read(16) or b"\0"
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))

    def _finalize_manifests(self):
        """Write manifests for saves that have committed since the last
        call (async saves commit in the background; a manifest must only
        hash final bytes). Also drops manifests whose step was evicted
        by max_to_keep."""
        committed = set(self._mgr.all_steps())
        for step in sorted(self._pending_manifest & committed):
            try:
                self._write_manifest(step)
            except OSError as e:  # manifest is best-effort, never fatal
                print(f"[ckpt] manifest for step {step} failed: {e}",
                      file=sys.stderr, flush=True)
            self._pending_manifest.discard(step)
        for sub in (self.MANIFEST_DIR, self.META_DIR):
            mdir = os.path.join(self.directory, sub)
            if os.path.isdir(mdir):
                for name in os.listdir(mdir):
                    stem = name.split(".")[0]
                    if stem.isdigit() and int(stem) not in committed \
                            and int(stem) not in self._pending_manifest:
                        try:
                            os.remove(os.path.join(mdir, name))
                        except OSError:
                            pass

    def verify_step(self, step: int) -> Optional[bool]:
        """True = checksums match; False = corruption detected; None =
        no manifest (pre-integrity checkpoint — trusted). Verdicts are
        memoized per manifest mtime (re-hashing multi-GB steps on every
        latest_complete_step/restore would stall the caller)."""
        self._join_manifest_thread()
        mpath = self._manifest_path(step)
        if not os.path.exists(mpath):
            self._verify_memo.pop(step, None)
            return None
        mtime = os.path.getmtime(mpath)
        memo = self._verify_memo.get(step)
        if memo is not None and memo[0] == mtime:
            return memo[1]
        verdict = self._verify_step_uncached(step)
        self._verify_memo[step] = (mtime, verdict)
        return verdict

    def _verify_step_uncached(self, step: int) -> Optional[bool]:
        mpath = self._manifest_path(step)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return None  # unreadable manifest: treat as absent
        d = self._step_dir(step)
        for rel, info in manifest.get("files", {}).items():
            p = os.path.join(d, rel)
            if not os.path.exists(p) \
                    or os.path.getsize(p) != info["size"] \
                    or _sha256(p) != info["sha256"]:
                return False
        return True

    def _join_manifest_thread(self):
        t = self._manifest_thread
        if t is not None:
            t.join()
            self._manifest_thread = None

    # ------------------------------------------------------------ save
    def save(self, step: int, state: Dict[str, Any], wait: bool = False,
             meta: Optional[Dict[str, Any]] = None):
        """Async by default: returns as soon as the device->host copy is
        done; the write drains in the background. The integrity manifest
        (which re-reads and hashes the committed files — seconds for a
        big checkpoint) is written off-thread on the async path so the
        training loop never stalls on it; ``wait=True`` makes both the
        orbax write and the manifest durable before returning.

        ``meta`` (JSON-serializable) is written eagerly to the step's
        sidecar — it is host state (sampler cursor, topology), so there
        is nothing to wait for; a crash before the orbax commit leaves a
        harmless orphan sidecar that the eviction sweep collects."""
        # register the step BEFORE writing anything: the PREVIOUS save's
        # background _finalize_manifests sweep may still be running, and
        # an unregistered, not-yet-committed step's fresh meta sidecar
        # would look like an evicted orphan to it
        t0 = time.perf_counter()
        self._pending_manifest.add(step)
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if meta is not None:
            try:
                self._write_meta(step, meta)
            except (OSError, TypeError, ValueError) as e:
                print(f"[ckpt] meta sidecar for step {step} failed: {e}",
                      file=sys.stderr, flush=True)
        self._join_manifest_thread()
        if wait:
            self._mgr.wait_until_finished()
            self._finalize_manifests()
        else:
            self._manifest_thread = threading.Thread(
                target=self._finalize_manifests, daemon=True)
            self._manifest_thread.start()
        # async saves observe the dispatch cost (what the train loop
        # actually pays); wait=True observes the full durable write
        save_ms = (time.perf_counter() - t0) * 1e3
        obs.histogram("ckpt_save_ms").observe(save_ms)
        obs.record_event("ckpt_save", step=step, wait=wait,
                         ms=round(save_ms, 3))

    # --------------------------------------------------------- restore
    def restore(self, step: Optional[int] = None,
                like: Optional[Dict[str, Any]] = None,
                strict: bool = False) -> Dict[str, Any]:
        """Restore `step` (default: latest complete+verified). `like`
        provides the target structure/shardings (abstract arrays ok) —
        restoring onto a different mesh re-shards on the fly.

        If the requested step fails checksum verification, fall back to
        the next older step that verifies (warning on stderr) instead of
        handing corrupt state to the caller; the step actually loaded is
        recorded in ``last_restored_step`` — check it whenever the exact
        step matters. ``strict=True`` disables the fallback for an
        explicitly requested step (eval/debug: wrong-step weights would
        silently invalidate results) and raises
        CheckpointCorruptionError instead; with no verified step at all
        the same error is raised either way."""
        self._join_manifest_thread()
        self._finalize_manifests()
        steps = sorted(self._mgr.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(
                f"no complete checkpoint in {self.directory}")
        if step is None:
            candidates = steps
        elif step in steps:
            candidates = [step] + [s for s in steps if s < step]
        else:
            raise FileNotFoundError(
                f"no complete checkpoint for step {step} in "
                f"{self.directory}")
        for s in candidates:
            if self.verify_step(s) is False:
                if strict and step is not None:
                    raise CheckpointCorruptionError(
                        f"checkpoint step {s} failed checksum "
                        f"verification (strict restore)")
                print(f"[ckpt] step {s} failed checksum verification; "
                      f"falling back to an older checkpoint",
                      file=sys.stderr, flush=True)
                continue
            t0 = time.perf_counter()
            out = self._restore_step(s, like)
            self.last_restored_step = s
            restore_ms = (time.perf_counter() - t0) * 1e3
            obs.histogram("ckpt_restore_ms").observe(restore_ms)
            obs.record_event("ckpt_restore", step=s,
                             ms=round(restore_ms, 3))
            return out
        raise CheckpointCorruptionError(
            f"every checkpoint step in {self.directory} failed checksum "
            f"verification ({candidates})")

    def _restore_step(self, step: int, like):
        if like is not None:
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
            return self._mgr.restore(step,
                                     args=ocp.args.StandardRestore(abstract))
        return self._mgr.restore(step)

    def latest_complete_step(self) -> Optional[int]:
        """Latest step that is both committed AND passes checksum
        verification — auto-resume never lands on a corrupt latest."""
        self._join_manifest_thread()
        self._finalize_manifests()
        for step in sorted(self._mgr.all_steps(), reverse=True):
            if self.verify_step(step) is not False:
                return step
        return None

    def all_steps(self):
        return list(self._mgr.all_steps())

    def wait_until_finished(self):
        self._mgr.wait_until_finished()
        self._join_manifest_thread()
        self._finalize_manifests()

    def close(self):
        self._mgr.wait_until_finished()
        self._join_manifest_thread()
        self._finalize_manifests()
        self._mgr.close()


def auto_resume(directory: str, state: Dict[str, Any]):
    """(state, start_step): restore the latest complete (and verified —
    a corrupt latest is skipped, not fatal) checkpoint if one exists,
    else return the passed-in initial state (reference: PaddleNLP
    Trainer's resume_from_checkpoint=True behavior)."""
    ckpt = DistributedCheckpoint(directory)
    step = ckpt.latest_complete_step()
    if step is None:
        ckpt.close()
        return state, 0
    restored = ckpt.restore(step, like=state)
    ckpt.close()
    return restored, step + 1
