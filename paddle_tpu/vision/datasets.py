"""paddle.vision.datasets parity (reference: python/paddle/vision/datasets
— MNIST/FashionMNIST/Cifar10/Cifar100/DatasetFolder/ImageFolder and the
synthetic FakeData).

This image has zero network egress, so ``download=True`` raises with a
clear message; the loaders read the standard on-disk formats (IDX for
MNIST-family, the python-pickle batches for CIFAR, a class-per-directory
tree for DatasetFolder) when the user provides the files, and ``FakeData``
generates deterministic synthetic samples for pipeline tests/benchmarks —
which is also what the framework's own tests use.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..io.dataset import Dataset

__all__ = ["FakeData", "MNIST", "FashionMNIST", "Cifar10", "Cifar100",
           "DatasetFolder", "ImageFolder"]

_NO_EGRESS = ("this environment has no network egress; place the dataset "
              "files at {path} and pass download=False")


class FakeData(Dataset):
    """Deterministic synthetic image dataset (reference:
    paddle.vision.datasets.FakeData): seeded per-index generation, so
    workers/shards see consistent data without materializing it."""

    def __init__(self, num_samples: int = 1000,
                 image_shape: Sequence[int] = (3, 224, 224),
                 num_classes: int = 10, seed: int = 0,
                 transform: Optional[Callable] = None):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.seed = seed
        self.transform = transform

    def __getitem__(self, idx):
        if not 0 <= idx < self.num_samples:
            raise IndexError(idx)
        rs = np.random.RandomState(self.seed + idx)
        img = rs.rand(*self.image_shape).astype(np.float32)
        label = rs.randint(0, self.num_classes)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)

    def __len__(self):
        return self.num_samples


def _read_idx(path: str) -> np.ndarray:
    """IDX (MNIST) format reader; transparently handles .gz."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        shape = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(shape)


class MNIST(Dataset):
    """Reference: paddle.vision.datasets.MNIST. Expects the standard IDX
    files under ``root`` (train-images-idx3-ubyte[.gz], ...)."""

    _FILES = {"train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
              "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")}

    def __init__(self, root: str, mode: str = "train",
                 transform: Optional[Callable] = None,
                 download: bool = False, backend: str = "cv2"):
        if download:
            raise RuntimeError(_NO_EGRESS.format(path=root))
        img_name, lab_name = self._FILES[mode]
        self.images = _read_idx(_find(root, img_name))
        self.labels = _read_idx(_find(root, lab_name))
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self.labels[idx])

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    """Same IDX format, different files (reference: FashionMNIST)."""


def _find(root: str, base: str) -> str:
    for cand in (base, base + ".gz"):
        p = os.path.join(root, cand)
        if os.path.exists(p):
            return p
    raise FileNotFoundError(_NO_EGRESS.format(path=os.path.join(root, base)))


class Cifar10(Dataset):
    """Reference: paddle.vision.datasets.Cifar10 — reads the
    ``cifar-10-batches-py`` pickle batches under ``root``."""

    _TRAIN = [f"data_batch_{i}" for i in range(1, 6)]
    _TEST = ["test_batch"]
    _SUBDIR = "cifar-10-batches-py"
    _LABEL_KEY = b"labels"

    def __init__(self, root: str, mode: str = "train",
                 transform: Optional[Callable] = None,
                 download: bool = False, backend: str = "cv2"):
        if download:
            raise RuntimeError(_NO_EGRESS.format(path=root))
        if mode not in ("train", "test"):
            raise ValueError(f"mode must be 'train' or 'test', got {mode!r}")
        base = os.path.join(root, self._SUBDIR)
        if not os.path.isdir(base):
            base = root
        names = self._TRAIN if mode == "train" else self._TEST
        imgs, labels = [], []
        for n in names:
            p = os.path.join(base, n)
            if not os.path.exists(p):
                raise FileNotFoundError(_NO_EGRESS.format(path=p))
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            imgs.append(d[b"data"].reshape(-1, 3, 32, 32))
            labels.extend(d[self._LABEL_KEY])
        self.images = np.concatenate(imgs)
        self.labels = np.asarray(labels, np.int64)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    _TRAIN = ["train"]
    _TEST = ["test"]
    _SUBDIR = "cifar-100-python"
    _LABEL_KEY = b"fine_labels"


_IMG_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".npy")


def _load_image(path: str) -> np.ndarray:
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image  # pillow rides in with torch/transformers
        return np.asarray(Image.open(path), np.float32) / 255.0
    except ImportError as e:
        raise RuntimeError("loading encoded images needs PIL; store .npy "
                           "arrays instead") from e


class DatasetFolder(Dataset):
    """class-per-subdirectory tree (reference:
    paddle.vision.datasets.DatasetFolder)."""

    def __init__(self, root: str, transform: Optional[Callable] = None,
                 loader: Optional[Callable] = None,
                 extensions: Sequence[str] = _IMG_EXTS):
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise FileNotFoundError(f"no class directories under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples: List[Tuple[str, int]] = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(tuple(extensions)):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))
        self.transform = transform
        self.loader = loader or _load_image

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    """Flat (unlabeled) image folder (reference: ImageFolder)."""

    def __init__(self, root: str, transform: Optional[Callable] = None,
                 loader: Optional[Callable] = None,
                 extensions: Sequence[str] = _IMG_EXTS):
        self.samples = [(os.path.join(root, f), -1)
                        for f in sorted(os.listdir(root))
                        if f.lower().endswith(tuple(extensions))]
        self.classes, self.class_to_idx = [], {}
        self.transform = transform
        self.loader = loader or _load_image

    def __getitem__(self, idx):
        path, _ = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return (img,)
