"""Prompt-lookup drafting shared by the batch and paged speculative
paths (ISSUE 7; reference: Saxena's prompt-lookup decoding, PaddleNLP
"inference with reference" speculate_method).

One jit-able proposer, two consumers:

- ``ngram_speculative_generate`` (generation/speculative.py) calls
  :func:`propose_ngram` on its single-row token buffer inside the
  decode while_loop;
- the PagedEngine's fused speculative tick (generation/paged.py) calls
  :func:`propose_ngram_rows` on its device-resident [R, L] committed-
  stream buffer — one vmap, all rows drafted in the same compiled tick
  program.

The proposer is DRAFT-ONLY: it reads committed positions (< ``n``) for
the n-gram MATCH, and the copied continuation may run into stale tail
positions — harmless, the verify forward guards every proposal. The
accept step is :func:`accept_length`, the longest-matched-prefix count
shared by every speculative strategy (the rest of ``_commit`` — the
token write-back and eos handling — is buffer-layout-specific and stays
with its caller). Because the proposer is DETERMINISTIC (the draft
"distribution" is a one-hot at the copied token), it also feeds the
rejection-sampled verify (ISSUE 11,
``sampling.residual_resample_rows``): sampled rows accept a drafted
token with probability p(token) and resample rejections from the
residual, so the same drafts serve greedy and sampled consumers.
:func:`mask_drafts` is the shared per-row gating — positions past a
row's per-tick draft cap are invalidated to the fill token.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["propose_ngram", "propose_ngram_rows", "accept_length",
           "mask_drafts", "token_buffer_row"]


def token_buffer_row(seq, length: int, fill: int = 0):
    """ONE slot's committed-stream buffer row [length] (prompt +
    emitted tokens, ``fill``-padded) — the row-scoped init shared by
    the PagedEngine's full-state rebuild (which stacks R of these) and
    the ISSUE-14 delta patch descriptor (which uploads exactly one),
    so a patched row's proposer input is byte-identical to what a
    rebuild would have produced for it. Host-side numpy on purpose:
    this is mirror packing, not traced compute."""
    import numpy as np
    row = np.full((length,), fill, np.int32)
    n = min(len(seq), length)
    row[:n] = np.asarray(seq[:n], np.int64)
    return row


def propose_ngram(seq, n, num_draft: int, ngram: int, fill):
    """Continuation of the most recent earlier occurrence of the last
    ``ngram`` committed tokens of ``seq`` [L]; ``fill`` where nothing
    matches. ``n`` is the committed-token count — only windows strictly
    inside ``seq[:n]`` can match. All static shapes; jit/vmap-able."""
    from .sampling import suffix_window_hits
    L = seq.shape[0]
    hit = suffix_window_hits(seq, n, ngram)       # strictly-earlier matches
    any_hit = jnp.any(hit)
    p = L - 1 - jnp.argmax(jnp.flip(hit))         # most recent
    src = jnp.where(any_hit, p + ngram, 0)
    draft = jax.lax.dynamic_slice(seq, (src,), (num_draft,))
    return jnp.where(any_hit, draft,
                     jnp.full((num_draft,), fill, seq.dtype))


def propose_ngram_rows(seqs, ns, num_draft: int, ngram: int, fill=-1):
    """Per-row drafts for continuous batching: ``seqs`` [R, L] committed
    streams, ``ns`` [R] committed counts -> [R, num_draft] drafts. The
    default ``fill=-1`` can never equal a real token id, so a no-match
    row's draft is rejected by the verify instead of accidentally
    accepted (the batch path keeps pad fill for bit-compat with its
    pinned streams)."""
    return jax.vmap(
        lambda s, n: propose_ngram(s, n, num_draft, ngram, fill))(seqs, ns)


def mask_drafts(drafts, kprop, fill=-1):
    """Invalidate draft positions past each row's per-tick cap:
    ``drafts`` [R, k], ``kprop`` [R] drafted-position counts ->
    positions >= kprop become ``fill``. ``fill=-1`` can never equal a
    real token id, so a gated position is rejected by the greedy
    accept AND fails the rejection-sampled accept test (the residual
    then degenerates to a plain sample — the per-row 1-token
    fallback)."""
    k = drafts.shape[-1]
    return jnp.where(jnp.arange(k)[None, :] < kprop[:, None],
                     drafts, fill)


def accept_length(draft, target):
    """Longest matched-prefix count between ``draft`` [..., k] and the
    verify targets ``target`` [..., >=k]: the number of drafted tokens
    the target would have emitted itself. Works row-batched ([R, k] vs
    [R, k+1]) and single-row."""
    k = draft.shape[-1]
    match = jnp.cumprod(
        (draft == target[..., :k]).astype(jnp.int32), axis=-1)
    return jnp.sum(match, axis=-1)
