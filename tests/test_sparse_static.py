"""paddle.sparse (BCOO/BCSR core ops) + paddle.static (Program/Executor
feed-fetch) + ERNIE-4.5 MoE config-point tests (SURVEY C31/C32)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import sparse, static


def _coo_example():
    dense = np.array([[0., 2., 0.], [3., 0., 4.]], np.float32)
    idx = np.array([[0, 1, 1], [1, 0, 2]])
    vals = np.array([2., 3., 4.], np.float32)
    return dense, idx, vals


class TestSparse:
    def test_coo_create_to_dense(self):
        dense, idx, vals = _coo_example()
        s = sparse.sparse_coo_tensor(idx, vals, dense.shape)
        assert s.nnz == 3 and s.shape == (2, 3)
        np.testing.assert_array_equal(np.asarray(s.to_dense()), dense)
        np.testing.assert_array_equal(np.asarray(s.indices), idx)

    def test_csr_create_and_convert(self):
        dense, _, _ = _coo_example()
        c = sparse.sparse_csr_tensor([0, 1, 3], [1, 0, 2], [2., 3., 4.],
                                     (2, 3))
        np.testing.assert_array_equal(np.asarray(c.to_dense()), dense)
        coo = c.to_sparse_coo()
        np.testing.assert_array_equal(np.asarray(coo.to_dense()), dense)
        back = coo.to_sparse_csr()
        np.testing.assert_array_equal(np.asarray(back.crows), [0, 1, 3])

    def test_elementwise_and_activations(self):
        dense, idx, vals = _coo_example()
        s = sparse.sparse_coo_tensor(idx, vals, dense.shape)
        np.testing.assert_allclose(
            np.asarray(sparse.add(s, s).to_dense()), dense * 2)
        np.testing.assert_allclose(
            np.asarray(sparse.multiply(s, 3.0).to_dense()), dense * 3)
        neg = sparse.neg(s)
        np.testing.assert_allclose(
            np.asarray(sparse.relu(neg).to_dense()), np.zeros_like(dense))
        np.testing.assert_allclose(
            np.asarray(sparse.tanh(s).to_dense()), np.tanh(dense), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(sparse.pow(s, 2).to_dense()), dense ** 2)

    def test_matmul_and_grad(self):
        dense, idx, vals = _coo_example()
        s = sparse.sparse_coo_tensor(idx, vals, dense.shape)
        y = jnp.asarray(np.random.RandomState(0).randn(3, 4), jnp.float32)
        out = sparse.matmul(s, y)
        np.testing.assert_allclose(np.asarray(out), dense @ np.asarray(y),
                                   rtol=1e-6)
        # grads flow through the sparse matmul to the dense operand
        g = jax.grad(lambda yy: sparse.matmul(s, yy).sum())(y)
        np.testing.assert_allclose(np.asarray(g),
                                   dense.T @ np.ones((2, 4), np.float32),
                                   rtol=1e-6)

    def test_masked_matmul(self):
        rs = np.random.RandomState(1)
        x = rs.randn(4, 8).astype(np.float32)
        y = rs.randn(8, 4).astype(np.float32)
        mask_idx = np.array([[0, 1, 3], [2, 0, 3]])
        mask = sparse.sparse_coo_tensor(mask_idx, np.ones(3, np.float32),
                                        (4, 4))
        out = sparse.masked_matmul(x, y, mask)
        full = x @ y
        want = np.zeros((4, 4), np.float32)
        for r, c in zip(*mask_idx):
            want[r, c] = full[r, c]
        np.testing.assert_allclose(np.asarray(out.to_dense()), want,
                                   rtol=1e-5)

    def test_csr_format_preserved(self):
        c = sparse.sparse_csr_tensor([0, 1, 3], [1, 0, 2], [2., -3., 4.],
                                     (2, 3))
        r = sparse.relu(c)
        assert isinstance(r, sparse.SparseCsrTensor)
        assert hasattr(r, "crows")
        np.testing.assert_allclose(
            np.asarray(r.to_dense()),
            np.array([[0., 2., 0.], [0., 0., 4.]], np.float32))

    def test_subtract_dense_and_mismatch(self):
        dense, idx, vals = _coo_example()
        s = sparse.sparse_coo_tensor(idx, vals, dense.shape)
        out = sparse.subtract(s, jnp.ones((2, 3), jnp.float32))
        np.testing.assert_allclose(np.asarray(out), dense - 1.0)
        np.testing.assert_allclose(
            np.asarray(sparse.subtract(s, s).to_dense()),
            np.zeros_like(dense))
        bigger = sparse.sparse_coo_tensor([[0], [0]], [1.0], (4, 4))
        with pytest.raises(ValueError, match="shape mismatch"):
            sparse.add(s, bigger)

    def test_transpose_cast(self):
        dense, idx, vals = _coo_example()
        s = sparse.sparse_coo_tensor(idx, vals, dense.shape)
        t = sparse.transpose(s, [1, 0])
        np.testing.assert_array_equal(np.asarray(t.to_dense()), dense.T)
        c = sparse.cast(s, value_dtype=jnp.float16)
        assert c.dtype == jnp.float16


class TestStatic:
    def test_program_executor_feed_fetch(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [-1, 4], "float32")
            w = static.data("w", [4, 2], "float32")
            static.build_program(lambda x, w: (x @ w, (x @ w).sum()))
        exe = static.Executor(static.device_places()[0])
        xv = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        wv = np.random.RandomState(1).randn(4, 2).astype(np.float32)
        out, total = exe.run(prog, feed={"x": xv, "w": wv},
                             fetch_list=[0, 1])
        np.testing.assert_allclose(out, xv @ wv, rtol=1e-5)
        np.testing.assert_allclose(total, (xv @ wv).sum(), rtol=1e-5)
        # variable batch: leading -1 admits a different batch size
        out2, _ = exe.run(prog, feed={"x": xv[:2], "w": wv},
                          fetch_list=[0, 1])
        assert out2.shape == (2, 2)

    def test_fetch_list_selects_subset(self):
        prog = static.Program.from_callable(
            lambda x: (x * 2, x.sum()),
            [static.InputSpec("x", (3,), "float32")])
        exe = static.Executor()
        xv = np.arange(3, dtype=np.float32)
        (total,) = exe.run(prog, feed={"x": xv}, fetch_list=[1])
        np.testing.assert_allclose(total, 3.0)
        with pytest.raises(ValueError, match="out of range"):
            exe.run(prog, feed={"x": xv}, fetch_list=[2])

    def test_save_load_inference_model_dynamic_batch(self, tmp_path):
        import os
        prog = static.Program()
        with static.program_guard(prog):
            static.data("x", [-1, 4], "float32")
            static.build_program(lambda x: x @ jnp.ones((4, 2)))
        path = os.path.join(str(tmp_path), "served")
        static.save_inference_model(path, None, None, None, program=prog)
        fn = static.load_inference_model(path)
        # the -1 dim exported symbolically: both batch sizes work
        assert np.asarray(fn(np.zeros((2, 4), np.float32))).shape == (2, 2)
        assert np.asarray(fn(np.zeros((5, 4), np.float32))).shape == (5, 2)

    def test_shape_mismatch_rejected(self):
        prog = static.Program()
        with static.program_guard(prog):
            static.data("x", [2, 3], "float32")
            static.build_program(lambda x: x * 2)
        with pytest.raises(ValueError, match="shape"):
            static.Executor().run(prog, feed={"x": np.zeros((2, 4),
                                                            np.float32)})

    def test_program_without_callable_errors(self):
        prog = static.Program()
        with static.program_guard(prog):
            static.data("x", [1], "float32")
        with pytest.raises(RuntimeError, match="from_callable"):
            static.Executor().run(prog, feed={"x": np.zeros(1, np.float32)})

    def test_concrete_program_jaxpr(self):
        prog = static.Program.from_callable(
            lambda a: a + 1, [static.InputSpec("a", (2,), "float32")])
        jaxpr = prog.concrete_program({"a": np.zeros(2, np.float32)})
        assert "add" in str(jaxpr)

    def test_default_program_and_scope(self):
        assert static.default_main_program() is not None
        sc = static.global_scope()
        sc.set_var("k", 7)
        assert sc.find_var("k") == 7


class TestErnie45Moe:
    def test_forward_loss_and_grad(self):
        from paddle_tpu.models import (Ernie45MoeForCausalLM, ernie45_moe_tiny,
                                       moe_lm_loss)
        pt.seed(0)
        model = Ernie45MoeForCausalLM(ernie45_moe_tiny())
        # layer 0 dense (first_k_dense_replace=1), layer 1 MoE
        from paddle_tpu.parallel.moe import MoEMLP
        kinds = [type(l.mlp).__name__ for l in model.model.layers]
        assert kinds[0] != "MoEMLP" and kinds[1] == "MoEMLP"
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 12)))
        fn, params = model.functional()

        def loss(p):
            logits, aux = fn(p, ids, return_aux=True)
            return moe_lm_loss(logits, aux, ids)

        l, g = jax.value_and_grad(loss)(dict(params))
        assert np.isfinite(float(l))
        gsum = sum(float(jnp.abs(v).sum()) for v in g.values())
        assert np.isfinite(gsum) and gsum > 0

    def test_generate(self):
        from paddle_tpu.models import Ernie45MoeForCausalLM, ernie45_moe_tiny
        pt.seed(0)
        model = Ernie45MoeForCausalLM(ernie45_moe_tiny())
        ids = jnp.asarray(np.random.RandomState(1).randint(0, 256, (2, 8)))
        out = model.generate(ids, max_new_tokens=4, temperature=0.0)
        assert out.shape == (2, 12)
