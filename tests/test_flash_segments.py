"""Segment-aware flash attention (packed sequences on the flash path):
kernel fwd/bwd vs the dense segment-masked reference in interpret mode,
GQA included, plus the model-level segment_ids dispatch."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

os.environ.setdefault("PADDLE_TPU_PALLAS_INTERPRET", "1")

import paddle_tpu as pt  # noqa: E402
from paddle_tpu.ops.attention import dense_attention, segment_mask  # noqa: E402
from paddle_tpu.ops.pallas.flash_attention import (  # noqa: E402
    flash_attention_bshd)


def _inputs(b=2, s=256, h=4, kv=2, d=64, seed=0):
    rs = np.random.RandomState(seed)
    q = jnp.asarray(rs.randn(b, s, h, d), jnp.float32) * 0.3
    k = jnp.asarray(rs.randn(b, s, kv, d), jnp.float32) * 0.3
    v = jnp.asarray(rs.randn(b, s, kv, d), jnp.float32) * 0.3
    # 3 packed segments + trailing pad (seg 0) per row
    seg = np.zeros((b, s), np.int32)
    for i in range(b):
        cuts = sorted(rs.choice(np.arange(16, s - 16), 2, replace=False))
        seg[i, :cuts[0]] = 1
        seg[i, cuts[0]:cuts[1]] = 2
        seg[i, cuts[1]:s - 8] = 3   # last 8 positions stay pad
    return q, k, v, jnp.asarray(seg)


def _dense_ref(q, k, v, seg, causal=True):
    return dense_attention(q, k, v, causal=causal,
                           attn_mask=segment_mask(seg))


class TestSegmentedFlashKernel:
    def test_forward_matches_dense(self):
        q, k, v, seg = _inputs()
        out = flash_attention_bshd(q, k, v, causal=True, segment_ids=seg,
                                   block_q=128, block_k=128)
        ref = _dense_ref(q, k, v, seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_forward_non_causal(self):
        q, k, v, seg = _inputs(seed=1)
        out = flash_attention_bshd(q, k, v, causal=False, segment_ids=seg,
                                   block_q=128, block_k=128)
        ref = _dense_ref(q, k, v, seg, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_gradients_match_dense(self):
        q, k, v, seg = _inputs(s=128, seed=2)

        def loss_flash(q, k, v):
            out = flash_attention_bshd(q, k, v, causal=True,
                                       segment_ids=seg,
                                       block_q=128, block_k=128)
            return (out * out).sum()

        def loss_dense(q, k, v):
            out = _dense_ref(q, k, v, seg)
            return (out * out).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gd, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=1e-3, err_msg=name)

    def test_no_cross_segment_leakage(self):
        """Perturbing segment 2's values must not change segment 1's out."""
        q, k, v, seg = _inputs(s=128, seed=3)
        seg = jnp.asarray(
            np.concatenate([np.full((2, 64), 1), np.full((2, 64), 2)],
                           axis=1))
        out1 = flash_attention_bshd(q, k, v, causal=True, segment_ids=seg,
                                    block_q=128, block_k=128)
        v2 = v.at[:, 64:].add(10.0)
        out2 = flash_attention_bshd(q, k, v2, causal=True, segment_ids=seg,
                                    block_q=128, block_k=128)
        np.testing.assert_array_equal(np.asarray(out1[:, :64]),
                                      np.asarray(out2[:, :64]))
        assert not np.allclose(np.asarray(out1[:, 64:]),
                               np.asarray(out2[:, 64:]))


class TestModelSegmentDispatch:
    def test_llama_segment_ids_matches_dense_mask(self):
        """Model forward with segment_ids == forward with the equivalent
        dense block-causal mask (the old packed path)."""
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny
        from paddle_tpu.trl import packed_sft_inputs

        pt.seed(0)
        model = LlamaForCausalLM(llama_tiny())
        fn, params = model.functional()
        rs = np.random.RandomState(4)
        ids = np.zeros((2, 32), np.int64)
        seg = np.zeros((2, 32), np.int64)
        ids[:, :20] = rs.randint(1, 256, (2, 20))
        seg[:, :12], seg[:, 12:20] = 1, 2
        seg_j = jnp.asarray(seg)
        positions, attn = packed_sft_inputs(seg_j)
        got = fn(dict(params), jnp.asarray(ids), positions=positions,
                 segment_ids=seg_j)
        want = fn(dict(params), jnp.asarray(ids), positions=positions,
                  attn_mask=attn)
        # real positions must agree exactly (pad rows differ by design:
        # segment semantics let pads attend earlier pads)
        np.testing.assert_allclose(np.asarray(got[:, :20]),
                                   np.asarray(want[:, :20]), atol=2e-5)
