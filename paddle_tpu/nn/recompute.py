"""Activation recompute (reference: paddle.distributed.fleet.utils.recompute,
python/paddle/distributed/fleet/recompute/recompute.py).

TPU-native: `jax.checkpoint` (rematerialization) — XLA re-executes the
forward inside the backward instead of saving activations, trading FLOPs
for HBM. Policies map paddle's selective-recompute lists onto jax's
checkpoint_policies (e.g. keep matmul outputs = dots_saveable).
"""
from __future__ import annotations

import jax

POLICIES = {
    "full": None,  # save nothing extra, recompute everything
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims_saveable": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
    "everything_saveable": jax.checkpoint_policies.everything_saveable,
}


def recompute(function, *args, policy=None, **kwargs):
    """paddle-style call-site recompute: runs `function(*args)` under
    jax.checkpoint. Unlike paddle there is no RNG-state juggling: dropout
    keys are explicit so replaying the forward is deterministic by
    construction."""
    pol = POLICIES.get(policy, policy) if isinstance(policy, str) else policy
    fn = jax.checkpoint(function, policy=pol)
    return fn(*args, **kwargs)


def checkpoint_wrapper(layer_or_fn, policy=None):
    """Wrap a Layer (or fn) so every call is rematerialized."""
    pol = POLICIES.get(policy, policy) if isinstance(policy, str) else policy
    if callable(layer_or_fn) and not hasattr(layer_or_fn, "forward"):
        return jax.checkpoint(layer_or_fn, policy=pol)

    layer = layer_or_fn
    orig_forward = layer.forward

    def wrapped(*args, **kwargs):
        return jax.checkpoint(orig_forward, policy=pol)(*args, **kwargs)
    object.__setattr__(layer, "forward", wrapped)
    return layer
