"""Tensor API numerics vs numpy (SURVEY.md §4: numerics vs reference
semantics)."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt


def test_creation():
    assert pt.zeros((2, 3)).shape == (2, 3)
    # x64 stays disabled (TPU-first): int64 requests canonicalize to int32
    assert pt.ones((2,), dtype="int64").dtype in (pt.int64, pt.int32)
    assert np.allclose(pt.numpy(pt.arange(5)), np.arange(5))
    assert pt.full((2, 2), 7.0)[0, 0] == 7.0
    assert pt.eye(3)[1, 1] == 1.0


def test_manipulation():
    x = pt.to_tensor(np.arange(24).reshape(2, 3, 4).astype(np.float32))
    assert pt.reshape(x, (6, 4)).shape == (6, 4)
    assert pt.transpose(x, (2, 0, 1)).shape == (4, 2, 3)
    assert pt.concat([x, x], axis=0).shape == (4, 3, 4)
    assert pt.stack([x, x]).shape == (2, 2, 3, 4)
    parts = pt.split(x, [1, 2], axis=1)
    assert parts[0].shape == (2, 1, 4) and parts[1].shape == (2, 2, 4)
    parts = pt.split(x, [1, -1], axis=1)
    assert parts[1].shape == (2, 2, 4)
    assert pt.squeeze(pt.unsqueeze(x, 0), 0).shape == x.shape
    assert pt.flatten(x, 1).shape == (2, 12)
    assert pt.tile(x, (2, 1, 1)).shape == (4, 3, 4)
    assert pt.expand(pt.ones((1, 3)), (5, 3)).shape == (5, 3)


def test_math_matches_numpy():
    a = np.random.randn(4, 5).astype(np.float32)
    b = np.random.randn(5, 6).astype(np.float32)
    assert np.allclose(pt.numpy(pt.matmul(pt.to_tensor(a), pt.to_tensor(b))),
                       a @ b, atol=1e-5)
    assert np.allclose(pt.numpy(pt.matmul(pt.to_tensor(a), pt.to_tensor(a),
                                          transpose_y=True)), a @ a.T, atol=1e-5)
    x = np.abs(np.random.randn(3, 4)).astype(np.float32) + 0.1
    for name in ["exp", "log", "sqrt", "abs", "tanh", "floor", "ceil"]:
        got = pt.numpy(getattr(pt, name)(pt.to_tensor(x)))
        want = getattr(np, name)(x)
        assert np.allclose(got, want, atol=1e-5), name
    assert np.allclose(pt.numpy(pt.rsqrt(pt.to_tensor(x))), 1 / np.sqrt(x), atol=1e-5)


def test_reductions():
    x = np.random.randn(3, 4, 5).astype(np.float32)
    t = pt.to_tensor(x)
    assert np.allclose(pt.numpy(pt.sum(t, axis=1)), x.sum(1), atol=1e-5)
    assert np.allclose(pt.numpy(pt.mean(t, axis=(0, 2))), x.mean((0, 2)), atol=1e-5)
    assert np.allclose(pt.numpy(pt.max(t, axis=-1, keepdim=True)),
                       x.max(-1, keepdims=True))
    assert np.allclose(pt.numpy(pt.std(t)), x.std(ddof=1), atol=1e-5)
    assert np.allclose(pt.numpy(pt.logsumexp(t, axis=1)),
                       np.log(np.exp(x).sum(1)), atol=1e-4)


def test_search_ops():
    x = np.random.randn(4, 10).astype(np.float32)
    t = pt.to_tensor(x)
    v, i = pt.topk(t, 3)
    want = np.sort(x, axis=-1)[:, ::-1][:, :3]
    assert np.allclose(pt.numpy(v), want, atol=1e-6)
    assert np.allclose(pt.numpy(pt.argmax(t, axis=1)), x.argmax(1))
    assert np.allclose(pt.numpy(pt.sort(t, axis=1)), np.sort(x, axis=1))


def test_indexing():
    x = pt.to_tensor(np.arange(20).reshape(4, 5).astype(np.float32))
    idx = pt.to_tensor(np.array([0, 2]))
    assert pt.gather(x, idx, axis=0).shape == (2, 5)
    out = pt.scatter(pt.zeros((4, 5)), idx, pt.ones((2, 5)))
    assert pt.numpy(out).sum() == 10
    mask = x > 10
    assert np.allclose(pt.numpy(pt.masked_fill(x, mask, 0.0)).max(), 10)


def test_logic():
    a = pt.to_tensor([1.0, 2.0, 3.0])
    b = pt.to_tensor([1.0, 2.0, 4.0])
    assert not bool(pt.equal_all(a, b))
    assert bool(pt.allclose(a, a))
    assert pt.numpy(pt.equal(a, b)).tolist() == [True, True, False]


def test_autograd_functional():
    def f(x):
        return pt.sum(pt.square(x))
    g = pt.grad(f)(pt.to_tensor([1.0, 2.0, 3.0]))
    assert np.allclose(pt.numpy(g), [2.0, 4.0, 6.0])


def test_einsum_norm():
    a = np.random.randn(3, 4).astype(np.float32)
    assert np.allclose(pt.numpy(pt.einsum("ij->ji", pt.to_tensor(a))), a.T)
    assert np.allclose(pt.numpy(pt.norm(pt.to_tensor(a))),
                       np.linalg.norm(a), atol=1e-5)


def test_round3_flat_ops():
    """diff/trapezoid/index_add/index_fill/masked_scatter/diag_embed/
    as_strided/view/unflatten/moveaxis/renorm/cdist/block_diag/rot90/
    nanmedian (reference: paddle/tensor/manipulation.py + math.py)."""
    x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_allclose(np.asarray(pt.diff(x)), np.diff(np.asarray(x)))
    assert float(pt.trapezoid(jnp.asarray([1., 2., 3.]))) == 4.0
    out = pt.index_add(x, jnp.asarray([0, 2]), 0, jnp.ones((2, 4)))
    assert float(out[0, 0]) == 1.0 and float(out[1, 0]) == 4.0
    assert float(pt.index_fill(x, jnp.asarray([1]), 0, -1.0)[1, 0]) == -1.0
    ms = pt.masked_scatter(x, x > 5, jnp.full((12,), 9.0))
    assert float(ms[2, 3]) == 9.0 and float(ms[0, 0]) == 0.0
    np.testing.assert_allclose(
        np.asarray(pt.diag_embed(jnp.asarray([1., 2., 3.]))),
        np.diag([1., 2., 3.]))
    np.testing.assert_allclose(
        np.asarray(pt.diag_embed(jnp.asarray([1., 2.]), offset=1)),
        np.diag([1., 2.], k=1))
    v = pt.as_strided(jnp.arange(10.), (3, 3), (3, 1))
    np.testing.assert_allclose(
        np.asarray(v),
        np.lib.stride_tricks.as_strided(np.arange(10.), (3, 3), (24, 8)))
    assert pt.view(jnp.asarray([1.0]), "int32").dtype == jnp.int32
    assert pt.view(x, [4, 3]).shape == (4, 3)
    r = pt.renorm(x, 2, 0, 1.0)
    assert float(jnp.linalg.norm(r[2])) <= 1.0001
    c = pt.cdist(x[:2], x)
    ref = np.sqrt(((np.asarray(x[:2])[:, None] - np.asarray(x)[None]) ** 2
                   ).sum(-1))
    np.testing.assert_allclose(np.asarray(c), ref, rtol=1e-5)
    assert pt.block_diag([jnp.eye(2), jnp.ones((1, 1))]).shape == (3, 3)
    assert pt.unflatten(x, 1, (2, 2)).shape == (3, 2, 2)
    assert pt.moveaxis(x, 0, 1).shape == (4, 3)
    assert pt.rot90(x).shape == (4, 3)
    assert float(pt.nanmedian(jnp.asarray([1.0, float("nan"), 3.0]))) == 2.0


def test_view_dtype_rescales_last_dim():
    """paddle.view(dtype): last dim scales by the width ratio."""
    x = jnp.zeros((2, 4), jnp.float32)
    assert pt.view(x, "float16").shape == (2, 8)
    assert pt.view(x, "int32").shape == (2, 4)
    # widening uses int16 -> int32 (x64 dtypes are disabled, TPU-first)
    assert pt.view(jnp.zeros((2, 4), jnp.int16), "int32").shape == (2, 2)
    with pytest.raises(ValueError, match="divisible"):
        pt.view(jnp.zeros((2, 3), jnp.int16), "int32")


def test_cdist_inf_and_zero_norms():
    a = jnp.asarray([[0.0, 0.0], [1.0, 5.0]])
    b = jnp.asarray([[3.0, 4.0]])
    np.testing.assert_allclose(np.asarray(pt.cdist(a, b, p=float("inf"))),
                               [[4.0], [2.0]])
    np.testing.assert_allclose(np.asarray(pt.cdist(a, b, p=0)),
                               [[2.0], [2.0]])


def test_histogram_weight_density():
    x = jnp.asarray([0.1, 0.2, 0.8])
    h = pt.histogram(x, bins=2, min=0.0, max=1.0,
                     weight=jnp.asarray([1.0, 2.0, 4.0]))
    np.testing.assert_allclose(np.asarray(h), [3.0, 4.0])
