"""paddle.incubate.nn.functional fused-op facade (C36): each fused entry
point must match its unfused composition exactly."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu.incubate.nn import functional as IF
from paddle_tpu.nn import functional as F

rs = np.random.RandomState(0)


def _x(*shape):
    return jnp.asarray(rs.randn(*shape), jnp.float32)


class TestFusedOps:
    def test_rms_and_layer_norm(self):
        x, w, b = _x(2, 8, 16), _x(16), _x(16)
        np.testing.assert_allclose(
            np.asarray(IF.fused_rms_norm(x, w)),
            np.asarray(F.rms_norm(x, weight=w)), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(IF.fused_layer_norm(x, w, b)),
            np.asarray(F.layer_norm(x, (16,), weight=w, bias=b)), rtol=1e-6)

    def test_linear_variants(self):
        x, w, b = _x(4, 8), _x(8, 12), _x(12)
        np.testing.assert_allclose(np.asarray(IF.fused_linear(x, w, b)),
                                   np.asarray(x @ w + b), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(IF.fused_linear(x, w.T, b, transpose_weight=True)),
            np.asarray(x @ w + b), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(IF.fused_linear_activation(x, w, b,
                                                  activation="gelu")),
            np.asarray(F.gelu(x @ w + b)), rtol=1e-5)

    def test_swiglu(self):
        x, y = _x(3, 8), _x(3, 8)
        np.testing.assert_allclose(np.asarray(IF.swiglu(x, y)),
                                   np.asarray(F.silu(x) * y), rtol=1e-6)
        xy = jnp.concatenate([x, y], axis=-1)
        np.testing.assert_allclose(np.asarray(IF.swiglu(xy)),
                                   np.asarray(F.silu(x) * y), rtol=1e-6)

    def test_rope_matches_model_rope(self):
        from paddle_tpu.models.llama import apply_rotary, rotary_cos_sin
        q, k = _x(2, 6, 4, 8), _x(2, 6, 2, 8)
        qr, kr, _ = IF.fused_rotary_position_embedding(q, k)
        pos = jnp.broadcast_to(jnp.arange(6)[None], (2, 6))
        cos, sin = rotary_cos_sin(pos, 8, 10000.0, q.dtype)
        np.testing.assert_allclose(np.asarray(qr),
                                   np.asarray(apply_rotary(q, cos, sin)),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(kr),
                                   np.asarray(apply_rotary(k, cos, sin)),
                                   rtol=1e-5)

    def test_fused_attention_matches_dense(self):
        from paddle_tpu.ops.attention import dense_attention
        q, k, v = _x(2, 16, 4, 8), _x(2, 16, 4, 8), _x(2, 16, 4, 8)
        out = IF.fused_dot_product_attention(q, k, v, is_causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(dense_attention(q, k, v, causal=True)),
            atol=2e-5)

    def test_fused_feedforward(self):
        x = _x(2, 4, 8)
        w1, w2 = _x(8, 16), _x(16, 8)
        g, b = _x(8), _x(8)
        out = IF.fused_feedforward(x, w1, w2, activation="gelu",
                                   ln1_scale=g, ln1_bias=b,
                                   pre_layer_norm=True, training=False)
        ln = F.layer_norm(x, (8,), weight=g, bias=b)
        want = x + F.gelu(ln @ w1) @ w2
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-5)

    def test_interleaved_rope_differs_and_pairs(self):
        q = _x(1, 4, 2, 8)
        neox = IF.fused_rotary_position_embedding(q)
        inter = IF.fused_rotary_position_embedding(
            q, use_neox_rotary_style=False)
        assert not np.allclose(np.asarray(neox), np.asarray(inter))
        # position 0 rotates by angle 0 in both styles -> identity
        np.testing.assert_allclose(np.asarray(inter[:, 0]),
                                   np.asarray(q[:, 0]), rtol=1e-6)

    def test_rope_tables_gather_position_ids(self):
        """Provided cos/sin tables must be gathered at position_ids, so a
        left-padded row rotates by logical position."""
        from paddle_tpu.models.llama import rotary_cos_sin
        q = _x(1, 4, 2, 8)
        # full-dim tables at theta=10000, max_pos=16
        pos_all = jnp.arange(16)[None]
        cos_h, sin_h = rotary_cos_sin(pos_all, 8, 10000.0, jnp.float32)
        cos_t = jnp.repeat(cos_h[0, :, 0], 2, axis=-1)  # [16, 8] full-dim
        sin_t = jnp.repeat(sin_h[0, :, 0], 2, axis=-1)
        pos = jnp.asarray([[0, 0, 1, 2]])  # left-padded style
        got = IF.fused_rotary_position_embedding(
            q, sin=sin_t, cos=cos_t, position_ids=pos)
        want = IF.fused_rotary_position_embedding(q, position_ids=pos)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_ffn_downscale_in_infer(self):
        x = _x(2, 4, 8)
        w1, w2 = _x(8, 16), _x(16, 8)
        g, b = _x(8), _x(8)
        out = IF.fused_feedforward(x, w1, w2, ln1_scale=g, ln1_bias=b,
                                   dropout1_rate=0.5, dropout2_rate=0.0,
                                   pre_layer_norm=True, training=False,
                                   mode="downscale_in_infer")
        ln = F.layer_norm(x, (8,), weight=g, bias=b)
        want = x + (F.relu(ln @ w1) * 0.5) @ w2  # (1-p) inference scaling
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-5)

    def test_causal_composes_with_mask(self):
        from paddle_tpu.ops.attention import dense_attention
        q, k, v = _x(1, 8, 2, 8), _x(1, 8, 2, 8), _x(1, 8, 2, 8)
        # padding mask blocking the last two keys, PLUS causality
        pad = (jnp.arange(8) < 6)[None, None, None, :]
        out = IF.fused_dot_product_attention(q, k, v, attn_mask=pad,
                                             is_causal=True)
        want = dense_attention(q, k, v, causal=True, attn_mask=pad)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=1e-6)
        # rows attending a future-masked-out region must differ from the
        # bidirectional result
        bidir = dense_attention(q, k, v, causal=False, attn_mask=pad)
        assert not np.allclose(np.asarray(out), np.asarray(bidir))

    def test_begin_norm_axis(self):
        x = _x(2, 3, 4)
        w = _x(12)
        out = IF.fused_layer_norm(x, w, None, begin_norm_axis=1)
        want = F.layer_norm(x.reshape(2, 12), (12,), weight=w).reshape(
            2, 3, 4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-5)

    def test_dropout_add_eval_is_identity_add(self):
        x, y = _x(3, 5), _x(3, 5)
        np.testing.assert_allclose(
            np.asarray(IF.fused_dropout_add(x, y, p=0.5, training=False)),
            np.asarray(x + y), rtol=1e-6)
