"""Round-4 flat-namespace ops vs numpy/torch semantics (SURVEY C1)."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt

torch = pytest.importorskip("torch")


def _r(*s, seed=0):
    return np.random.RandomState(seed).randn(*s).astype("float32")


def test_elementwise_batch():
    x = _r(3, 4) * 2
    y = _r(3, 4, seed=1) * 2 + 0.1
    for name in ("acosh", "asinh", "atanh", "deg2rad", "rad2deg",
                 "digamma", "lgamma", "frac", "signbit"):
        arg = np.abs(x) + 1.5 if name == "acosh" else \
            np.clip(x, -0.9, 0.9) if name == "atanh" else np.abs(x) + 0.5
        got = np.asarray(getattr(pt, name)(jnp.asarray(arg)))
        ref = getattr(torch, name)(torch.tensor(arg)).numpy()
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5,
                                   err_msg=name)
    for name in ("hypot", "logaddexp", "fmax", "fmin", "nextafter"):
        got = np.asarray(getattr(pt, name)(jnp.asarray(x), jnp.asarray(y)))
        ref = getattr(torch, name)(torch.tensor(x),
                                   torch.tensor(y)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-6, err_msg=name)


def test_cummax_cummin_match_torch():
    x = np.random.RandomState(2).randint(0, 5, (4, 7)).astype("float32")
    for name in ("cummax", "cummin"):
        gv, gi = getattr(pt, name)(jnp.asarray(x), axis=1)
        rv, ri = getattr(torch, name)(torch.tensor(x), dim=1)
        np.testing.assert_array_equal(np.asarray(gv), rv.numpy(),
                                      err_msg=name)
        np.testing.assert_array_equal(np.asarray(gi), ri.numpy(),
                                      err_msg=name + " indices")


def test_mode_matches_torch():
    x = np.random.RandomState(3).randint(0, 4, (5, 9)).astype("float32")
    gv, gi = pt.mode(jnp.asarray(x), axis=-1)
    rv, _ = torch.mode(torch.tensor(x), dim=-1)
    # torch.mode picks the SMALLEST most-frequent value; paddle the
    # largest — compare counts, not raw equality, plus paddle semantics
    for r in range(x.shape[0]):
        row = x[r]
        c_got = (row == float(gv[r])).sum()
        c_ref = (row == float(rv[r])).sum()
        assert c_got == c_ref, (r, float(gv[r]), float(rv[r]))
        assert row[int(gi[r])] == float(gv[r])


def test_gather_scatter_family():
    x = _r(4, 6)
    idx = np.random.RandomState(4).randint(0, 6, (4, 3))
    np.testing.assert_array_equal(
        np.asarray(pt.index_sample(jnp.asarray(x), jnp.asarray(idx))),
        np.take_along_axis(x, idx, axis=1))
    # scatter_nd accumulates
    index = np.array([[1], [1], [3]])
    ups = np.array([1.0, 2.0, 4.0], "float32")
    out = np.asarray(pt.scatter_nd(jnp.asarray(index), jnp.asarray(ups),
                                   (5,)))
    np.testing.assert_allclose(out, [0, 3, 0, 4, 0])
    # index_put with accumulate
    base = jnp.zeros((3, 3))
    got = pt.index_put(base, (jnp.asarray([0, 0]), jnp.asarray([1, 1])),
                       jnp.asarray([1.0, 2.0]), accumulate=True)
    assert float(got[0, 1]) == 3.0
    # take modes
    flat = jnp.asarray(np.arange(6.0))
    np.testing.assert_allclose(
        np.asarray(pt.take(flat, jnp.asarray([7, -1]), mode="wrap")),
        [1.0, 5.0])
    np.testing.assert_allclose(
        np.asarray(pt.take(flat, jnp.asarray([7]), mode="clip")), [5.0])


def test_linalg_and_shapes():
    x = _r(3, 3)
    np.testing.assert_allclose(np.asarray(pt.inverse(jnp.asarray(x))),
                               np.linalg.inv(x), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(pt.trace(jnp.asarray(x))),
                               np.trace(x), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(pt.mv(jnp.asarray(x), jnp.asarray(x[0]))), x @ x[0],
        rtol=1e-5)
    np.testing.assert_array_equal(
        np.asarray(pt.t(jnp.asarray(x))), x.T)
    np.testing.assert_array_equal(
        np.asarray(pt.permute(jnp.asarray(_r(2, 3, 4)), 2, 0, 1)),
        _r(2, 3, 4).transpose(2, 0, 1))
    parts = pt.unstack(jnp.asarray(x), axis=0)
    assert len(parts) == 3 and parts[0].shape == (3,)
    np.testing.assert_array_equal(
        np.asarray(pt.vander(jnp.asarray(np.array([1.0, 2, 3])), n=3)),
        np.vander([1.0, 2, 3], 3))
    assert int(pt.rank(jnp.zeros((2, 3)))) == 2


def test_unfold_matches_torch():
    x = _r(2, 10)
    got = np.asarray(pt.unfold(jnp.asarray(x), axis=1, size=4, step=3))
    ref = torch.tensor(x).unfold(1, 4, 3).numpy()
    np.testing.assert_array_equal(got, ref)


def test_unique_consecutive_matches_torch():
    x = np.array([1, 1, 2, 2, 3, 1, 1, 2], "int32")
    out, inv, counts = pt.unique_consecutive(jnp.asarray(x),
                                             return_inverse=True,
                                             return_counts=True)
    ro, ri, rc = torch.unique_consecutive(torch.tensor(x),
                                          return_inverse=True,
                                          return_counts=True)
    np.testing.assert_array_equal(np.asarray(out), ro.numpy())
    np.testing.assert_array_equal(np.asarray(inv), ri.numpy())
    np.testing.assert_array_equal(np.asarray(counts), rc.numpy())


def test_misc_numerics():
    x = _r(8)
    y = _r(8, seed=5)
    np.testing.assert_allclose(
        np.asarray(pt.dist(jnp.asarray(x), jnp.asarray(y), p=2)),
        np.linalg.norm(x - y), rtol=1e-5)
    p = np.clip(np.abs(x), 0.01, 0.99)
    np.testing.assert_allclose(
        np.asarray(pt.logit(jnp.asarray(p))),
        torch.logit(torch.tensor(p)).numpy(), rtol=2e-5, atol=1e-6)
    z = np.asarray(pt.polar(jnp.asarray(np.abs(x)), jnp.asarray(y)))
    ref = torch.polar(torch.tensor(np.abs(x)), torch.tensor(y)).numpy()
    np.testing.assert_allclose(z, ref, rtol=1e-5, atol=1e-6)
    a = np.array([4, 6, 9]); b = np.array([6, 4, 6])
    np.testing.assert_array_equal(
        np.asarray(pt.gcd(jnp.asarray(a), jnp.asarray(b))), [2, 2, 3])
    np.testing.assert_array_equal(
        np.asarray(pt.lcm(jnp.asarray(a), jnp.asarray(b))), [12, 12, 18])
    np.testing.assert_array_equal(
        np.asarray(pt.shard_index(jnp.asarray(np.array([0, 5, 9, 15])),
                                  16, 4, 1)), [-1, 1, -1, -1])
    got = np.asarray(pt.kron(jnp.asarray(np.eye(2)),
                             jnp.asarray(np.ones((2, 2)))))
    np.testing.assert_array_equal(got, np.kron(np.eye(2), np.ones((2, 2))))
