"""BERT family (reference: PaddleNLP paddlenlp/transformers/bert/
modeling.py — BertModel/BertEmbeddings/BertPooler, BertForPretraining with
masked-LM + next-sentence heads, BertForSequenceClassification).

TPU-native design: bidirectional encoder of post-LN blocks; attention/MLP
are Column/RowParallelLinear so GSPMD shards over ``tp``; the padding mask
is an additive bias broadcast into the attention logits (static shapes —
no dynamic-length branches under jit). MLM decoder ties to the word
embedding table via a vocab-parallel matmul.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer, Parameter
from ..ops.attention import dense_attention
from ..parallel.layers import (ColumnParallelLinear, RowParallelLinear,
                               VocabParallelEmbedding, parallel_matmul)
from ..parallel.sharding import constraint
from ..utils.rng import next_key


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    hidden_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def bert_tiny(**overrides) -> BertConfig:
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                max_position_embeddings=64, dtype=jnp.float32)
    base.update(overrides)
    return BertConfig(**base)


def padding_bias(attention_mask, dtype):
    """[b, s] 1/0 mask -> additive [b, 1, 1, s] bias (-inf on pads)."""
    bias = (1.0 - attention_mask.astype(jnp.float32)) * -1e9
    return bias[:, None, None, :].astype(dtype)


class BertEmbeddings(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        init = I.Normal(std=config.initializer_range)
        self.word_embeddings = VocabParallelEmbedding(config.vocab_size,
                                                      config.hidden_size)
        self.position_embeddings = Parameter(
            init(next_key(), (config.max_position_embeddings,
                              config.hidden_size)))
        self.token_type_embeddings = Parameter(
            init(next_key(), (config.type_vocab_size, config.hidden_size)))
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, positions=None,
                extra_embeds=None):
        b, s = input_ids.shape
        if positions is None:
            positions = jnp.arange(s)[None, :].repeat(b, axis=0)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings[positions]
             + self.token_type_embeddings[token_type_ids])
        if extra_embeds is not None:
            # e.g. ERNIE's task-type stream: summed BEFORE LayerNorm
            # (reference ErnieEmbeddings ordering)
            x = x + extra_embeds
        return self.dropout(self.layer_norm(x))


class BertSelfAttention(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        self.qkv_proj = ColumnParallelLinear(h, 3 * h, has_bias=True,
                                             gather_output=False)
        self.out_proj = RowParallelLinear(h, h, has_bias=True,
                                          input_is_parallel=True)

    def forward(self, x, attn_bias=None):
        cfg = self.config
        b, s, _ = x.shape
        nh, d = cfg.num_attention_heads, cfg.head_dim
        qkv = self.qkv_proj(x).reshape(b, s, 3, nh, d)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = constraint(q, None, None, "tp", None)
        k = constraint(k, None, None, "tp", None)
        v = constraint(v, None, None, "tp", None)
        out = dense_attention(q, k, v, causal=False, attn_mask=attn_bias)
        return self.out_proj(out.reshape(b, s, nh * d))


class BertLayer(Layer):
    """Post-LN transformer block (original BERT residual ordering)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        eps = config.layer_norm_eps
        self.attention = BertSelfAttention(config)
        self.attn_norm = nn.LayerNorm(config.hidden_size, epsilon=eps)
        self.fc_in = ColumnParallelLinear(config.hidden_size,
                                          config.intermediate_size,
                                          has_bias=True, gather_output=False)
        self.fc_out = RowParallelLinear(config.intermediate_size,
                                        config.hidden_size, has_bias=True,
                                        input_is_parallel=True)
        self.out_norm = nn.LayerNorm(config.hidden_size, epsilon=eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x, attn_bias=None):
        x = self.attn_norm(x + self.dropout(self.attention(x, attn_bias)))
        h = self.fc_out(F.gelu(self.fc_in(x)))
        x = self.out_norm(x + self.dropout(h))
        return constraint(x, ("dp", "fsdp"), None, None)


class BertPooler(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, x):
        return jnp.tanh(self.dense(x[:, 0]))


class BertModel(Layer):
    def __init__(self, config: BertConfig, with_pooler: bool = True):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.layers = nn.LayerList(
            [BertLayer(config) for _ in range(config.num_hidden_layers)])
        self.pooler = BertPooler(config) if with_pooler else None
        if config.dtype != jnp.float32:
            self.to(dtype=config.dtype)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                positions=None, extra_embeds=None):
        x = self.embeddings(input_ids, token_type_ids, positions,
                            extra_embeds=extra_embeds)
        x = constraint(x, ("dp", "fsdp"), None, None)
        bias = (padding_bias(attention_mask, x.dtype)
                if attention_mask is not None else None)
        for layer in self.layers:
            x = layer(x, attn_bias=bias)
        pooled = self.pooler(x) if self.pooler is not None else None
        return x, pooled


class TiedMLMHead(Layer):
    """Transform + LayerNorm + vocab-tied decoder matmul, shared by BERT
    and ERNIE pretraining heads (reference: BertLMPredictionHead). The
    whole head runs in config.dtype so the [b,s,h]x[h,V] decoder matmul
    stays on the bf16 MXU path; only the final logits are fp32."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.transform_norm = nn.LayerNorm(config.hidden_size,
                                           epsilon=config.layer_norm_eps)
        self.mlm_bias = Parameter(jnp.zeros((config.vocab_size,)))
        if config.dtype != jnp.float32:
            self.transform.to(dtype=config.dtype)
            self.transform_norm.to(dtype=config.dtype)

    def forward(self, seq, word_embedding_weight):
        h = self.transform_norm(F.gelu(self.transform(seq)))
        logits = parallel_matmul(h, word_embedding_weight, transpose_y=True)
        return logits.astype(jnp.float32) + self.mlm_bias


class BertForPretraining(Layer):
    """Masked-LM (tied decoder) + next-sentence-prediction heads."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        self.mlm_head = TiedMLMHead(config)
        self.nsp_head = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        mlm_logits = self.mlm_head(
            seq, self.bert.embeddings.word_embeddings.weight)
        nsp_logits = self.nsp_head(pooled).astype(jnp.float32)
        return mlm_logits, nsp_logits


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled)).astype(jnp.float32)


def pretraining_loss(mlm_logits, mlm_labels, nsp_logits=None, nsp_labels=None,
                     ignore_index: int = -100):
    loss = F.cross_entropy(mlm_logits, mlm_labels, ignore_index=ignore_index,
                           reduction="mean")
    if nsp_logits is not None and nsp_labels is not None:
        loss = loss + F.cross_entropy(nsp_logits, nsp_labels,
                                      reduction="mean")
    return loss
