"""Exponential moving average of weights (reference:
paddle.incubate.ExponentialMovingAverage — shadow weights with
apply()/restore() swap for eval).

TPU-native: the shadow tree is an ordinary pytree updated inside the
jitted train step (`ema_update` is pure), so EMA costs one fused
multiply-add over the parameters with no extra host sync. The
`ExponentialMovingAverage` class is the stateful facade for eager use.
"""
from __future__ import annotations

import jax


def ema_init(params):
    """Shadow = copy of params (fp32 recommended for long averages)."""
    return jax.tree.map(lambda p: p, params)


def ema_update(shadow, params, decay: float = 0.999, step=None):
    """One EMA step; with `step`, applies the reference's warmup
    min(decay, (1+t)/(10+t)) so early training isn't dominated by init."""
    if step is not None:
        import jax.numpy as jnp
        d = jnp.minimum(decay, (1.0 + step) / (10.0 + step))
    else:
        d = decay
    return jax.tree.map(lambda s, p: d * s + (1.0 - d) * p.astype(s.dtype),
                        shadow, params)


class ExponentialMovingAverage:
    """Stateful facade: track a Layer (or params dict), swap shadows in
    for eval with apply()/restore()."""

    def __init__(self, layer_or_params, decay: float = 0.999,
                 use_warmup: bool = False):
        self.decay = decay
        self.use_warmup = use_warmup
        self._layer = None
        if hasattr(layer_or_params, "trainable_parameters"):
            self._layer = layer_or_params
            params = dict(layer_or_params.trainable_parameters())
        else:
            params = dict(layer_or_params)
        self.shadow = ema_init(params)
        self._backup = None
        self._step = 0

    def update(self, params=None):
        if params is None:
            assert self._layer is not None, "pass params or track a Layer"
            params = dict(self._layer.trainable_parameters())
        step = self._step if self.use_warmup else None
        self.shadow = ema_update(self.shadow, params, self.decay, step)
        self._step += 1
        return self.shadow

    def apply(self):
        """Swap shadow weights into the tracked layer (for eval)."""
        assert self._layer is not None
        self._backup = {k: self._layer._get_by_path(k) for k in self.shadow}
        self._layer.bind({k: v.astype(self._backup[k].dtype)
                          for k, v in self.shadow.items()})

    def restore(self):
        assert self._backup is not None, "apply() first"
        self._layer.bind(self._backup)
        self._backup = None
