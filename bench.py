#!/usr/bin/env python
"""Headline bench (SURVEY.md §6): Llama train-step tokens/sec/chip + MFU on
the local chip. Prints ONE JSON line; vs_baseline = achieved MFU / 0.40
(the reference's Llama-3 pretraining MFU target in BASELINE.json)."""
import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
import paddle_tpu as pt  # noqa: E402
from paddle_tpu.models import LlamaForCausalLM, LlamaConfig, causal_lm_loss  # noqa: E402

# peak bf16 FLOP/s per chip by device kind
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # trillium
}

BATCH, SEQ = 8, 2048


def bench_config() -> LlamaConfig:
    """~470M-param Llama shaped to saturate a single v5e (16G HBM) with
    remat; same code path as the 8B recipe."""
    return LlamaConfig(
        vocab_size=32768, hidden_size=2048, intermediate_size=5632,
        num_hidden_layers=8, num_attention_heads=16, num_key_value_heads=8,
        max_position_embeddings=SEQ, rope_theta=500000.0,
        recompute=True, dtype=jnp.bfloat16)


def main():
    dev = jax.devices()[0]
    peak = PEAK_FLOPS.get(dev.device_kind, 197e12)
    pt.seed(0)
    cfg = bench_config()
    model = LlamaForCausalLM(cfg)
    fn, params = model.functional()
    n_params = sum(int(np.prod(v.shape)) for v in params.values())

    opt = pt.optimizer.AdamW(learning_rate=1e-4, multi_precision=True,
                             grad_clip=pt.optimizer.ClipGradByGlobalNorm(1.0))
    state = opt.init(params)
    ids = jnp.asarray(np.random.randint(0, cfg.vocab_size, (BATCH, SEQ)))

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, state, step, ids):
        def loss_fn(p):
            return causal_lm_loss(fn(p, ids), ids)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = opt.apply(params, grads, state, step)
        return params, state, loss

    # warmup/compile (float() forces a device->host transfer: on the axon
    # tunnel block_until_ready alone returns before execution completes)
    params, state, loss = train_step(params, state, jnp.int32(0), ids)
    float(loss)

    steps = 10
    t0 = time.perf_counter()
    for i in range(1, steps + 1):
        params, state, loss = train_step(params, state, jnp.int32(i), ids)
    float(loss)
    dt = (time.perf_counter() - t0) / steps

    tokens_per_sec = BATCH * SEQ / dt
    # fwd+bwd matmul flops 6N/token + causal attention 6*L*s*h/token
    flops_per_token = 6 * n_params + 6 * cfg.num_hidden_layers * SEQ * cfg.hidden_size
    mfu = flops_per_token * tokens_per_sec / peak
    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
        "mfu": round(mfu, 4),
        "params": n_params,
        "step_ms": round(dt * 1e3, 2),
        "device": dev.device_kind,
        "loss": round(float(loss), 4),
    }))


if __name__ == "__main__":
    main()
