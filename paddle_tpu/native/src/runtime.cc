// paddle_tpu native runtime (reference: Paddle's C++ data pipeline —
// paddle/fluid/framework/blocking_queue.h, DataLoader worker pool, and the
// pinned-memory staging allocator paddle/fluid/memory/allocation/
// pinned_allocator.cc).
//
// TPU-native role: the accelerator is fed from host RAM, so the pieces
// worth doing in C++ are the ones that move bytes while Python holds no
// locks: a pthread worker pool, page-aligned staging arenas (jax
// device_put DMA-copies from them), parallel gather/stack batch assembly
// (the hot half of collate), a blocking MPMC ring for prefetch handoff,
// and a trie tokenizer. Exposed as a C ABI for ctypes.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <deque>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#define PT_API extern "C" __attribute__((visibility("default")))

// ----------------------------------------------------------------- arena
// Bump allocator over one page-aligned slab. Batches are assembled here and
// handed to jax.device_put; reset() recycles the slab every step, so steady
// state does zero mallocs.
struct PtArena {
  uint8_t* base = nullptr;
  size_t cap = 0;
  std::atomic<size_t> off{0};
};

PT_API PtArena* pt_arena_create(size_t cap) {
  auto* a = new PtArena();
  // 4096: page alignment so the host->device DMA path never splits a page
  if (posix_memalign(reinterpret_cast<void**>(&a->base), 4096, cap) != 0) {
    delete a;
    return nullptr;
  }
  a->cap = cap;
  return a;
}

PT_API void* pt_arena_alloc(PtArena* a, size_t size) {
  size_t aligned = (size + 63) & ~size_t(63);  // 64B: cacheline/vector align
  size_t prev = a->off.fetch_add(aligned, std::memory_order_relaxed);
  if (prev + aligned > a->cap) {
    a->off.fetch_sub(aligned, std::memory_order_relaxed);
    return nullptr;
  }
  return a->base + prev;
}

PT_API void pt_arena_reset(PtArena* a) { a->off.store(0); }
PT_API size_t pt_arena_used(PtArena* a) { return a->off.load(); }
PT_API void pt_arena_destroy(PtArena* a) {
  if (a) { free(a->base); delete a; }
}

// ------------------------------------------------------------ thread pool
struct PtPool {
  std::vector<std::thread> threads;
  std::deque<std::function<void()>> tasks;
  std::mutex mu;
  std::condition_variable cv;
  std::condition_variable done_cv;
  size_t inflight = 0;
  bool stop = false;

  explicit PtPool(int n) {
    for (int i = 0; i < n; ++i) {
      threads.emplace_back([this] {
        for (;;) {
          std::function<void()> task;
          {
            std::unique_lock<std::mutex> lk(mu);
            cv.wait(lk, [this] { return stop || !tasks.empty(); });
            if (stop && tasks.empty()) return;
            task = std::move(tasks.front());
            tasks.pop_front();
          }
          task();
          {
            std::lock_guard<std::mutex> lk(mu);
            if (--inflight == 0) done_cv.notify_all();
          }
        }
      });
    }
  }

  void submit(std::function<void()> f) {
    {
      std::lock_guard<std::mutex> lk(mu);
      ++inflight;
      tasks.push_back(std::move(f));
    }
    cv.notify_one();
  }

  void wait() {
    std::unique_lock<std::mutex> lk(mu);
    done_cv.wait(lk, [this] { return inflight == 0; });
  }

  ~PtPool() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv.notify_all();
    for (auto& t : threads) t.join();
  }
};

PT_API PtPool* pt_pool_create(int n_threads) {
  return new PtPool(n_threads > 0 ? n_threads : 1);
}
PT_API void pt_pool_destroy(PtPool* p) { delete p; }
PT_API int pt_pool_size(PtPool* p) { return (int)p->threads.size(); }

// ------------------------------------------------------- batch assembly
// Parallel "np.stack": copy n same-sized items into one contiguous batch.
// The Python caller releases the GIL across this call (ctypes does), so
// collate overlaps with interpreter work in other threads.
PT_API void pt_gather_stack(PtPool* pool, const void** srcs, size_t n,
                            size_t item_bytes, void* dst) {
  const size_t kMinPerTask = 1 << 16;  // don't spawn tasks for tiny copies
  size_t per_task = item_bytes < kMinPerTask && n > 1
                        ? (kMinPerTask + item_bytes - 1) / item_bytes
                        : 1;
  for (size_t i = 0; i < n; i += per_task) {
    size_t hi = i + per_task < n ? i + per_task : n;
    pool->submit([=] {
      for (size_t j = i; j < hi; ++j) {
        memcpy(static_cast<uint8_t*>(dst) + j * item_bytes, srcs[j],
               item_bytes);
      }
    });
  }
  pool->wait();
}

// Ragged token sequences -> padded [n, max_len] batch (the LLM collate hot
// path). elem = element byte width; pad is the raw element bit pattern.
PT_API void pt_gather_pad(PtPool* pool, const void** srcs,
                          const size_t* lens, size_t n, size_t max_len,
                          size_t elem, const void* pad, void* dst) {
  for (size_t i = 0; i < n; ++i) {
    pool->submit([=] {
      auto* row = static_cast<uint8_t*>(dst) + i * max_len * elem;
      size_t len = lens[i] < max_len ? lens[i] : max_len;
      memcpy(row, srcs[i], len * elem);
      for (size_t j = len; j < max_len; ++j)
        memcpy(row + j * elem, pad, elem);
    });
  }
  pool->wait();
}

// --------------------------------------------------------------- ring
// Blocking MPMC ring of opaque u64 handles: the prefetch handoff between
// producer (collate) threads and the consumer (train loop). Close() wakes
// everyone; pop on a closed+empty ring returns 0.
struct PtRing {
  std::vector<uint64_t> buf;
  size_t head = 0, tail = 0, count = 0;
  bool closed = false;
  std::mutex mu;
  std::condition_variable not_full, not_empty;

  explicit PtRing(size_t cap) : buf(cap) {}
};

PT_API PtRing* pt_ring_create(size_t capacity) {
  return new PtRing(capacity ? capacity : 1);
}
PT_API void pt_ring_destroy(PtRing* r) { delete r; }

// returns 1 on success, 0 if closed, -1 on timeout
PT_API int pt_ring_push(PtRing* r, uint64_t value, int timeout_ms) {
  std::unique_lock<std::mutex> lk(r->mu);
  auto pred = [r] { return r->closed || r->count < r->buf.size(); };
  if (timeout_ms < 0) {
    r->not_full.wait(lk, pred);
  } else if (!r->not_full.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   pred)) {
    return -1;
  }
  if (r->closed) return 0;
  r->buf[r->tail] = value;
  r->tail = (r->tail + 1) % r->buf.size();
  ++r->count;
  r->not_empty.notify_one();
  return 1;
}

// returns 1 with *out set, 0 if closed and drained, -1 on timeout
PT_API int pt_ring_pop(PtRing* r, uint64_t* out, int timeout_ms) {
  std::unique_lock<std::mutex> lk(r->mu);
  auto pred = [r] { return r->closed || r->count > 0; };
  if (timeout_ms < 0) {
    r->not_empty.wait(lk, pred);
  } else if (!r->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                    pred)) {
    return -1;
  }
  if (r->count == 0) return 0;  // closed and drained
  *out = r->buf[r->head];
  r->head = (r->head + 1) % r->buf.size();
  --r->count;
  r->not_full.notify_one();
  return 1;
}

PT_API void pt_ring_close(PtRing* r) {
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->closed = true;
  }
  r->not_full.notify_all();
  r->not_empty.notify_all();
}

PT_API size_t pt_ring_size(PtRing* r) {
  std::lock_guard<std::mutex> lk(r->mu);
  return r->count;
}

// ------------------------------------------------------------ tokenizer
// Greedy longest-match trie tokenizer ("tokenizer-lite"): covers BPE-style
// vocabs for data prep without a Python inner loop. Vocab = id-ordered
// newline-separated byte strings; unknown bytes emit unk_id.
struct TrieNode {
  std::unordered_map<uint8_t, TrieNode*> next;
  int32_t id = -1;
  ~TrieNode() {
    for (auto& kv : next) delete kv.second;
  }
};

struct PtTokenizer {
  TrieNode root;
  int32_t unk_id = 0;
  size_t vocab_size = 0;
};

PT_API PtTokenizer* pt_tok_create(const char* vocab, size_t vocab_bytes,
                                  int32_t unk_id) {
  auto* t = new PtTokenizer();
  t->unk_id = unk_id;
  int32_t id = 0;
  size_t start = 0;
  for (size_t i = 0; i <= vocab_bytes; ++i) {
    if (i == vocab_bytes || vocab[i] == '\n') {
      if (i > start) {
        TrieNode* node = &t->root;
        for (size_t j = start; j < i; ++j) {
          uint8_t c = (uint8_t)vocab[j];
          auto it = node->next.find(c);
          if (it == node->next.end()) {
            node = node->next[c] = new TrieNode();
          } else {
            node = it->second;
          }
        }
        node->id = id;
      }
      ++id;
      start = i + 1;
    }
  }
  t->vocab_size = (size_t)id;
  return t;
}

PT_API void pt_tok_destroy(PtTokenizer* t) { delete t; }
PT_API size_t pt_tok_vocab_size(PtTokenizer* t) { return t->vocab_size; }

// Greedy longest match; returns number of ids written (<= max_out).
PT_API size_t pt_tok_encode(PtTokenizer* t, const char* text, size_t len,
                            int32_t* out, size_t max_out) {
  size_t n = 0, i = 0;
  while (i < len && n < max_out) {
    TrieNode* node = &t->root;
    int32_t best = -1;
    size_t best_len = 0;
    for (size_t j = i; j < len; ++j) {
      auto it = node->next.find((uint8_t)text[j]);
      if (it == node->next.end()) break;
      node = it->second;
      if (node->id >= 0) {
        best = node->id;
        best_len = j - i + 1;
      }
    }
    if (best >= 0) {
      out[n++] = best;
      i += best_len;
    } else {
      out[n++] = t->unk_id;
      i += 1;
    }
  }
  return n;
}

// Batch encode on the pool: texts are concatenated; offsets[i] delimits
// text i. Output is padded to max_out per row; out_lens gets true lengths.
PT_API void pt_tok_encode_batch(PtTokenizer* t, PtPool* pool,
                                const char* blob, const size_t* offsets,
                                size_t n, int32_t* out, size_t max_out,
                                int32_t pad_id, size_t* out_lens) {
  for (size_t i = 0; i < n; ++i) {
    pool->submit([=] {
      const char* text = blob + offsets[i];
      size_t len = offsets[i + 1] - offsets[i];
      int32_t* row = out + i * max_out;
      size_t m = pt_tok_encode(t, text, len, row, max_out);
      for (size_t j = m; j < max_out; ++j) row[j] = pad_id;
      out_lens[i] = m;
    });
  }
  pool->wait();
}
