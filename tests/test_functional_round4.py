"""Round-4 nn.functional surface vs torch semantics (SURVEY C4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.nn.functional as F

torch = pytest.importorskip("torch")
TF = torch.nn.functional


def _r(*s, seed=0):
    return np.random.RandomState(seed).randn(*s).astype("float32")


def test_pad_modes():
    x = _r(2, 3, 4, 5)
    for mode in ("constant", "reflect", "replicate", "circular"):
        got = np.asarray(F.pad(jnp.asarray(x), [1, 2, 2, 1], mode=mode))
        ref = TF.pad(torch.tensor(x), [1, 2, 2, 1], mode=mode).numpy()
        np.testing.assert_array_equal(got, ref, err_msg=mode)
    got = np.asarray(F.zeropad2d(jnp.asarray(x), (1, 2, 3, 4)))
    ref = TF.pad(torch.tensor(x), [1, 2, 3, 4]).numpy()
    np.testing.assert_array_equal(got, ref)


def test_pool_1d_3d():
    x1 = _r(2, 3, 12)
    np.testing.assert_allclose(
        np.asarray(F.max_pool1d(jnp.asarray(x1), 3, 2, 1)),
        TF.max_pool1d(torch.tensor(x1), 3, 2, 1).numpy(), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(F.avg_pool1d(jnp.asarray(x1), 2, 2)),
        TF.avg_pool1d(torch.tensor(x1), 2, 2).numpy(), rtol=1e-6)
    x3 = _r(1, 2, 6, 6, 6)
    np.testing.assert_allclose(
        np.asarray(F.max_pool3d(jnp.asarray(x3), 2, 2)),
        TF.max_pool3d(torch.tensor(x3), 2, 2).numpy(), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(F.adaptive_avg_pool1d(jnp.asarray(x1), 4)),
        TF.adaptive_avg_pool1d(torch.tensor(x1), 4).numpy(), rtol=1e-6)


def test_unpool_roundtrip():
    x = _r(1, 2, 8, 8)
    tx = torch.tensor(x)
    pooled, idx = TF.max_pool2d(tx, 2, 2, return_indices=True)
    got = np.asarray(F.max_unpool2d(jnp.asarray(pooled.numpy()),
                                    jnp.asarray(idx.numpy()), 2, 2))
    ref = TF.max_unpool2d(pooled, idx, 2, 2).numpy()
    np.testing.assert_array_equal(got, ref)


def test_fold_unfold_roundtrip():
    x = _r(2, 3, 8, 8)
    cols = F.unfold(jnp.asarray(x), 3, stride=2, padding=1)
    ref_cols = TF.unfold(torch.tensor(x), 3, stride=2, padding=1)
    np.testing.assert_allclose(np.asarray(cols), ref_cols.numpy(),
                               rtol=1e-6)
    back = F.fold(cols, (8, 8), 3, strides=2, paddings=1)
    ref_back = TF.fold(ref_cols, (8, 8), 3, stride=2, padding=1)
    np.testing.assert_allclose(np.asarray(back), ref_back.numpy(),
                               rtol=1e-6)


def test_grid_sample_and_affine_grid():
    x = _r(2, 3, 6, 7)
    theta = np.asarray([[[0.8, 0.1, 0.05], [-0.1, 0.9, -0.02]]] * 2,
                       dtype="float32")
    for ac in (True, False):
        grid = F.affine_grid(jnp.asarray(theta), (2, 3, 5, 6),
                             align_corners=ac)
        rgrid = TF.affine_grid(torch.tensor(theta), (2, 3, 5, 6),
                               align_corners=ac)
        np.testing.assert_allclose(np.asarray(grid), rgrid.numpy(),
                                   rtol=1e-5, atol=1e-6)
        got = np.asarray(F.grid_sample(jnp.asarray(x), grid,
                                       align_corners=ac))
        ref = TF.grid_sample(torch.tensor(x), rgrid,
                             align_corners=ac).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5,
                                   err_msg=f"ac={ac}")


def test_shuffles_and_norm():
    x = _r(2, 8, 4, 4)
    np.testing.assert_array_equal(
        np.asarray(F.channel_shuffle(jnp.asarray(x), 4)),
        TF.channel_shuffle(torch.tensor(x), 4).numpy())
    np.testing.assert_array_equal(
        np.asarray(F.pixel_unshuffle(jnp.asarray(x), 2)),
        TF.pixel_unshuffle(torch.tensor(x), 2).numpy())
    np.testing.assert_allclose(
        np.asarray(F.local_response_norm(jnp.asarray(x), 3)),
        TF.local_response_norm(torch.tensor(x), 3).numpy(),
        rtol=1e-5)


def test_round4_losses_match_torch():
    a, b = _r(4, 6), _r(4, 6, seed=1)
    lab = np.sign(_r(4, seed=2)).astype("float32")
    cases = [
        (F.margin_ranking_loss(jnp.asarray(a[:, 0]), jnp.asarray(b[:, 0]),
                               jnp.asarray(lab), margin=0.3),
         TF.margin_ranking_loss(torch.tensor(a[:, 0]),
                                torch.tensor(b[:, 0]),
                                torch.tensor(lab), margin=0.3)),
        (F.soft_margin_loss(jnp.asarray(a), jnp.asarray(np.sign(b))),
         TF.soft_margin_loss(torch.tensor(a),
                             torch.tensor(np.sign(b)))),
        (F.hinge_embedding_loss(jnp.asarray(a), jnp.asarray(np.sign(b))),
         TF.hinge_embedding_loss(torch.tensor(a),
                                 torch.tensor(np.sign(b)))),
        (F.cosine_embedding_loss(jnp.asarray(a), jnp.asarray(b),
                                 jnp.asarray(lab)),
         TF.cosine_embedding_loss(torch.tensor(a), torch.tensor(b),
                                  torch.tensor(lab))),
        (F.triplet_margin_loss(jnp.asarray(a), jnp.asarray(b),
                               jnp.asarray(_r(4, 6, seed=3))),
         TF.triplet_margin_loss(torch.tensor(a), torch.tensor(b),
                                torch.tensor(_r(4, 6, seed=3)))),
        (F.poisson_nll_loss(jnp.asarray(a), jnp.asarray(np.abs(b))),
         TF.poisson_nll_loss(torch.tensor(a), torch.tensor(np.abs(b)))),
        (F.multi_label_soft_margin_loss(
            jnp.asarray(a), jnp.asarray((b > 0).astype("float32"))),
         TF.multilabel_soft_margin_loss(
             torch.tensor(a), torch.tensor((b > 0).astype("float32")))),
    ]
    for i, (got, ref) in enumerate(cases):
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-4,
                                   err_msg=str(i))
    np.testing.assert_allclose(
        np.asarray(F.pairwise_distance(jnp.asarray(a), jnp.asarray(b))),
        TF.pairwise_distance(torch.tensor(a), torch.tensor(b)).numpy(),
        rtol=1e-4)


def test_misc_activations_and_utils():
    x = _r(3, 8)
    np.testing.assert_allclose(
        np.asarray(F.thresholded_relu(jnp.asarray(x), 0.5)),
        TF.threshold(torch.tensor(x), 0.5, 0.0).numpy())
    np.testing.assert_allclose(
        np.asarray(F.maxout(jnp.asarray(x), 2)),
        np.max(x.reshape(3, 4, 2), axis=2))
    m = np.asarray(F.sequence_mask(jnp.asarray([1, 3, 2]), maxlen=4))
    np.testing.assert_array_equal(
        m, [[1, 0, 0, 0], [1, 1, 1, 0], [1, 1, 0, 0]])
    w = _r(5, 6, 7, seed=9)
    got = np.asarray(F.bilinear(jnp.asarray(x[:, :6]),
                                jnp.asarray(_r(3, 7, seed=8)),
                                jnp.asarray(w)))
    ref = TF.bilinear(torch.tensor(x[:, :6]),
                      torch.tensor(_r(3, 7, seed=8)),
                      torch.tensor(w)).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    # rrelu eval mode is deterministic
    np.testing.assert_allclose(
        np.asarray(F.rrelu(jnp.asarray(x), training=False)),
        TF.rrelu(torch.tensor(x), training=False).numpy(), rtol=1e-6)


def test_focal_and_dice():
    logit = _r(4, 3)
    lab = (np.abs(_r(4, 3, seed=5)) > 0.5).astype("float32")
    got = float(F.sigmoid_focal_loss(jnp.asarray(logit),
                                     jnp.asarray(lab)))
    # torchvision is absent: check against the formula directly
    p_ = 1.0 / (1.0 + np.exp(-logit))
    ce = -(lab * np.log(p_) + (1 - lab) * np.log(1 - p_))
    pt_ = lab * p_ + (1 - lab) * (1 - p_)
    a = lab * 0.25 + (1 - lab) * 0.75
    ref = float(np.sum(a * (1 - pt_) ** 2.0 * ce))
    np.testing.assert_allclose(got, ref, rtol=1e-4)


def test_dice_loss_perfect_prediction_is_zero():
    lab = np.array([[0], [1], [2]], "int64")[:, :]
    probs = np.eye(3, dtype="float32")[lab.squeeze(-1)]
    loss = float(F.dice_loss(jnp.asarray(probs),
                             jnp.asarray(lab)))
    assert loss < 1e-4


def test_hsigmoid_raises_with_guidance():
    with pytest.raises(NotImplementedError, match="margin_cross_entropy"):
        F.hsigmoid_loss()


def test_margin_cross_entropy_reduces_to_ce_at_zero_margins():
    feats = _r(4, 8)
    cos = feats / np.linalg.norm(feats, axis=1, keepdims=True)
    lab = np.array([0, 1, 2, 3])
    got = float(F.margin_cross_entropy(jnp.asarray(cos), jnp.asarray(lab),
                                       margin1=1.0, margin2=0.0,
                                       margin3=0.0, scale=10.0))
    ref = float(TF.cross_entropy(torch.tensor(cos * 10.0),
                                 torch.tensor(lab)))
    np.testing.assert_allclose(got, ref, rtol=1e-4)


def test_pad_full_length_leading_dims():
    """Full spec (2*ndim entries) pads from dim 0 (paddle convention)."""
    x = jnp.zeros((2, 3, 4, 5))
    out = F.pad(x, [1, 1, 0, 0, 0, 0, 0, 0])
    assert out.shape == (4, 3, 4, 5)
    with pytest.raises(NotImplementedError, match="channels-last"):
        F.pad(x, [1, 1], data_format="NHWC")


def test_avg_pool1d_exclusive_padding():
    """Padded positions don't count toward the average (paddle
    exclusive=True), matching avg_pool2d and torch
    count_include_pad=False."""
    x = jnp.ones((1, 1, 4))
    got = np.asarray(F.avg_pool1d(x, 2, 2, padding=1))
    np.testing.assert_allclose(got, [[[1.0, 1.0, 1.0]]])
    ref = TF.avg_pool1d(torch.ones(1, 1, 4), 2, 2, padding=1,
                        count_include_pad=False).numpy()
    np.testing.assert_allclose(got, ref)


def test_grid_sample_rejects_reflection():
    x = jnp.zeros((1, 1, 4, 4))
    grid = jnp.zeros((1, 2, 2, 2))
    with pytest.raises(NotImplementedError, match="padding_mode"):
        F.grid_sample(x, grid, padding_mode="reflection")


def test_new_nn_classes_smoke_and_gaussian_nll():
    """Layer-class wrappers over the round-4 functional surface."""
    import paddle_tpu as pt
    from paddle_tpu import nn
    pt.seed(0)
    x = jnp.asarray(_r(2, 8, 6, 6))
    x1d = jnp.asarray(_r(2, 4, 12, seed=1))
    assert nn.MaxPool1D(2)(x1d).shape == (2, 4, 6)
    assert nn.Fold((6, 6), 3, paddings=1)(
        nn.Unfold(3, paddings=1)(x)).shape == x.shape
    assert nn.Maxout(2)(x).shape == (2, 4, 6, 6)
    assert nn.UpsamplingBilinear2D(scale_factor=2)(x).shape == \
        (2, 8, 12, 12)
    got = nn.GaussianNLLLoss()(x[:, 0], x[:, 1], jnp.abs(x[:, 2]) + 0.1)
    ref = torch.nn.GaussianNLLLoss(eps=1e-6)(
        torch.tensor(np.asarray(x[:, 0])),
        torch.tensor(np.asarray(x[:, 1])),
        torch.tensor(np.abs(np.asarray(x[:, 2])) + 0.1))
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
    loss = nn.TripletMarginLoss(margin=0.5)(
        x[:, 0, 0], x[:, 1, 0], x[:, 2, 0])
    assert np.isfinite(float(loss))
