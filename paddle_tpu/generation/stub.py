"""Negligible-compute reference CausalLM for the paged-serving tick
machinery (ISSUE 9): embed -> paged KV write -> paged attention ->
vocab projection, one layer, one head. Engine/gateway benchmarks and
tests that drive it measure scheduling, dispatch and transport — not
model FLOPs. Shared by ``tools/serve_loadgen.py --model stub`` and
``tests/test_gateway.py`` so the paged-cache calling convention lives
in ONE place (the multi-chunk global-positions contract below was
once fixed in two copies at once; see CHANGES PR 7).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .paged import (paged_chunk_attention, paged_decode_attention,
                    paged_decode_write, paged_prefill_write)

__all__ = ["TickStubConfig", "TickStubModel"]


class TickStubConfig:
    vocab_size = 128
    num_hidden_layers = 1
    num_key_value_heads = 1
    head_dim = 8
    dtype = jnp.float32


class TickStubModel:
    """Minimal CausalLM contract (``config`` + ``functional()``). The
    returned fn is a PURE closure over its own params — unlike
    ``Layer.functional()`` it never binds onto a shared layer tree, so
    replicas sharing one instance may tick concurrently."""

    config = TickStubConfig()

    def functional(self):
        d, V = self.config.head_dim, self.config.vocab_size
        k = jax.random.PRNGKey(0)
        params = dict(emb=jax.random.normal(k, (V, d)),
                      out=jax.random.normal(k, (d, V)))

        def fn(params, tokens, kv_caches=None, positions=None,
               paged_chunk=False, paged_decode=False):
            x = params["emb"][tokens]              # [R, s, d]
            kv = x[:, :, None, :]                  # [R, s, 1, d]
            pk = kv_caches[0]
            if paged_decode or tokens.shape[1] == 1:
                # decode tick — including the speculative multi-query
                # verify (paged_decode=True, [R, k+1]): the paged
                # write/attention helpers handle T >= 1 natively
                pk = paged_decode_write(pk, kv, kv)
                o = paged_decode_attention(x[:, :, None, :], pk)[:, :, 0]
            else:                                  # (chunk) prefill
                # chunk K/V lands at its GLOBAL positions — a chunk at
                # start > 0 written at 0..s-1 reads stale data later
                pk = paged_prefill_write(pk, kv, kv,
                                         positions=positions[0])
                o = paged_chunk_attention(x[:1, :, None, :], pk,
                                          positions)[:, :, 0]
            return o @ params["out"], [pk]

        return fn, params
