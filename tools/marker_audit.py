#!/usr/bin/env python
"""Tier-budget marker audit (ISSUE 6 satellite; sibling of
``fault_sites.py --check``).

The tier-1 verify runs ``pytest -m 'not slow'`` against a hard 870s
wall clock that currently has only ~duration-of-one-sweep headroom, so
a single dropped ``@pytest.mark.slow`` on a bench or sweep test can
blow the whole budget. ``--check`` collects the suite twice with
``pytest --collect-only`` (once ``-m slow``, once ``-m 'not slow'``)
and fails if:

- any MUST_BE_SLOW pattern (wall-clock benches, sweep-style parity
  matrices, multi-subprocess e2e) matches a test in the tier-1
  collection, or
- a pattern matches nothing at all (stale policy entry — the test was
  renamed or deleted and the guard is no longer guarding anything).

Run without flags for the marker census only.
"""
import os
import re
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Patterns (regex, matched against pytest node ids) that must stay OUT
# of the tier-1 run. Keep in sync with tests/conftest.py's _SLOW list
# and per-test @pytest.mark.slow decorations.
MUST_BE_SLOW = (
    # ISSUE 6: wall-clock micro-bench + sweep matrices + the 14s
    # full-batch interpret parity (each keeps a tier-1 representative)
    r"test_fused_tick\.py.*microbench",
    r"test_fused_tick\.py.*parity_sweep",
    r"test_fused_tick\.py.*full_batch",
    # ISSUE 7: spec k/ngram + multi-query kernel sweeps and the
    # tokens-per-forward micro-bench (bitwise k=4/g=2 cases, the
    # boundary-lens kernel case, and the dispatch pins stay tier-1)
    r"test_paged_spec\.py.*sweep",
    r"test_paged_spec\.py.*microbench",
    # PR 2: multi-subprocess preemption/elastic e2e (conftest _SLOW)
    r"test_kill_mid_run_then_resume_continues_trajectory",
    r"test_hang_checkpoints_exits_and_supervisor_finishes",
    r"test_nan_window_rolls_back_and_converges",
    # ISSUE 9: open-loop gateway rate sweeps + the subprocess loadgen
    # CLI e2e (each keeps a tier-1 in-process representative:
    # test_loadgen_inprocess_smoke + the single-shot gateway e2e tests)
    r"test_gateway\.py.*open_loop",
    r"test_gateway\.py.*loadgen_cli",
    # ISSUE 10: the many-request trace retention/attribution sweep
    # (tier-1 keeps the single-shot propagation + retention pins)
    r"test_reqtrace\.py.*sweep",
    # ISSUE 7 sweep: the 4-worker speedup wall-clock bench was tier-1's
    # one pre-policy bench (flipped at 2.56x/3.0 under full-suite load;
    # the rest of test_dataloader_mp.py keeps the correctness coverage)
    r"test_dataloader_mp\.py.*speedup",
)


def _collect(marker_expr):
    cmd = [sys.executable, "-m", "pytest", "tests/", "--collect-only",
           "-q", "-m", marker_expr, "-p", "no:cacheprovider",
           "--continue-on-collection-errors"]
    out = subprocess.run(cmd, cwd=ROOT, capture_output=True, text=True,
                         timeout=300,
                         env={**os.environ, "JAX_PLATFORMS": "cpu"})
    nodes = [ln.strip() for ln in out.stdout.splitlines()
             if "::" in ln and not ln.startswith(("=", "<", " "))]
    return nodes


def check() -> int:
    slow = _collect("slow")
    tier1 = _collect("not slow")
    bad, stale = [], []
    for pat in MUST_BE_SLOW:
        rx = re.compile(pat)
        leaked = [n for n in tier1 if rx.search(n)]
        if leaked:
            bad.extend(f"{pat}: IN TIER-1 -> {n}" for n in leaked[:3])
        elif not any(rx.search(n) for n in slow):
            stale.append(pat)
    census = (f"tier-1 {len(tier1)} tests, slow {len(slow)} "
              f"(cap 870s; see ROADMAP 'Tier-1 verify')")
    if bad or stale:
        print("marker audit FAILED:", file=sys.stderr)
        for line in bad:
            print(f"  budget leak  {line}", file=sys.stderr)
        for pat in stale:
            print(f"  stale policy {pat}: matches no collected test",
                  file=sys.stderr)
        print(census, file=sys.stderr)
        return 1
    print(f"marker audit OK: {census}; "
          f"{len(MUST_BE_SLOW)} slow-policy patterns enforced")
    return 0


if __name__ == "__main__":
    sys.exit(check())
