"""ISSUE 14: persistent decode program — in-program slot transitions
with delta mirror patches.

Contracts, each pinned against the full-rebuild reference
(``delta_transitions=False``, the pre-ISSUE-14 path kept verbatim):

- STREAM PARITY: greedy and seeded-sampled token/logprob streams are
  BITWISE identical between delta mode and the rebuild reference
  across every transition kind — admit, finish, chunked-prefill
  advance, preempt, cancel, block growth — with the ring on and off.
- SCOPED DRAIN: an out-of-band transition (cancel/expiry) consumes
  only the affected slot's pending ring entries; untouched siblings'
  pending tokens survive and land at the next step()'s normal drain.
- UPLOAD ACCOUNTING: steady churn runs 0 full-state rebuilds in delta
  mode (one-row patches carry every transition) and the byte counter
  — the ISSUE 14 small-fix satellite — shows the patch path moving
  far fewer H2D bytes than the rebuild path for the same workload.
- FAILOVER: ``export_resumable()`` descriptors, read off host mirrors
  that now advance via scoped drains, stay equal across modes, and a
  resume from them continues the stream bitwise.
"""
import numpy as np
import pytest

from paddle_tpu.generation.paged import PagedEngine
from paddle_tpu.generation.stub import TickStubModel


def _cyc(n, start=0):
    return (np.arange(n) % 5 + 1 + start)[None]


def _engine(**kw):
    base = dict(max_slots=4, num_blocks=32, block_size=8,
                max_blocks_per_seq=8, prefill_buckets=(16,))
    base.update(kw)
    return PagedEngine(TickStubModel(), **base)


def _drain(eng, submits):
    for rid, ids, skw in submits:
        eng.submit(rid, ids, **skw)
    res = eng.run()
    return res, dict(eng.logprobs)


# mixed greedy/sampled workload exercising admit, finish, eos, stops
# and block growth (prompts + budgets cross the 8-token block grid)
MIXED_SUBS = [
    ("g", _cyc(6), dict(max_new_tokens=20)),
    ("s", _cyc(8, 2), dict(max_new_tokens=14, temperature=0.8,
                           top_k=20, seed=5)),
    ("st", _cyc(9, 1), dict(max_new_tokens=24, stop_sequences=[[3, 4]])),
    ("e", _cyc(5, 3), dict(max_new_tokens=16, eos_token_id=2)),
]


class TestDeltaParity:
    @pytest.mark.parametrize("ring", [True, False])
    def test_transition_matrix_bitwise(self, ring):
        """Admit/finish/growth/stop/eos churn + a mid-run second wave
        (admits into slots whose previous tenants finished): delta and
        rebuild modes agree on every token and every logprob float."""
        def run(delta):
            eng = _engine(ring_mode=ring, delta_transitions=delta)
            res, lps = _drain(eng, MIXED_SUBS)
            # second wave: readmits into released rows (the ring
            # cursors continue where the previous tenant stopped)
            res2, lps2 = _drain(eng, [
                ("w1", _cyc(4, 1), dict(max_new_tokens=9)),
                ("w2", _cyc(7, 2), dict(max_new_tokens=11,
                                        temperature=0.6, seed=9)),
            ])
            res.update(res2)
            lps.update(lps2)
            return eng, res, lps

        er, rr, lr = run(delta=False)
        ed, rd, ld = run(delta=True)
        assert rr == rd
        assert lr == ld
        assert er.full_rebuilds > 1          # reference churned rebuilds
        assert ed.full_rebuilds == 1         # delta paid the first only
        assert ed.delta_patches > 0

    @pytest.mark.parametrize("ring", [True, False])
    def test_midstream_admit_interleave_exact(self, ring):
        """A submit() landing mid-decode rides a one-row patch; the
        per-request emission interleave matches the rebuild reference
        exactly (same ring mode on both sides)."""
        def run(delta):
            eng = _engine(ring_mode=ring, delta_transitions=delta)
            eng.submit("r0", _cyc(6), max_new_tokens=18)
            out = []
            for n, pair in enumerate(eng.stream()):
                out.append(pair)
                if n == 4:
                    eng.submit("r1", _cyc(10, 3), max_new_tokens=12,
                               temperature=0.8, seed=3)
            return out, dict(eng.results), dict(eng.logprobs)

        sr, rr, lr = run(delta=False)
        sd, rd, ld = run(delta=True)
        assert sr == sd          # emission order too, not just results
        assert rr == rd and lr == ld

    def test_chunked_prefill_and_prefix_cache_parity(self):
        """Chunk advances are lens-only patches until the final chunk
        activates the row; prefix-cache adoption (a table-row patch
        pointing at shared physical blocks) stays bitwise too."""
        sys_p = list(range(1, 17))

        def run(delta):
            eng = _engine(max_slots=2, chunk_prefill_tokens=8,
                          enable_prefix_cache=True,
                          prefill_buckets=(8,),
                          delta_transitions=delta)
            r1, l1 = _drain(eng, [
                ("x", np.asarray(sys_p + [20, 21])[None],
                 dict(max_new_tokens=10)),
            ])
            # second request adopts x's registered prefix blocks
            r2, l2 = _drain(eng, [
                ("y", np.asarray(sys_p + [30])[None],
                 dict(max_new_tokens=8, temperature=0.5, seed=7)),
            ])
            r1.update(r2)
            l1.update(l2)
            return eng, r1, l1

        er, rr, lr = run(False)
        ed, rd, ld = run(True)
        assert rr == rd and lr == ld
        assert ed.stats["prefix_hit_tokens"] == \
            er.stats["prefix_hit_tokens"] > 0
        assert ed.full_rebuilds == 1

    def test_preemption_parity(self):
        """Block-pool pressure forces recompute-mode preemption (a
        release patch + a requeue) mid-run; streams and preemption
        counts match the rebuild reference, sampled victim included."""
        kw = dict(max_slots=2, num_blocks=6, block_size=8,
                  max_blocks_per_seq=4, prefill_buckets=(16,))
        subs = [("p", _cyc(8), dict(max_new_tokens=14)),
                ("q", _cyc(11, 2), dict(max_new_tokens=14,
                                        temperature=0.9, seed=5))]
        er, rr, lr = (lambda e: (e, *_drain(e, subs)))(
            _engine(delta_transitions=False, **kw))
        ed, rd, ld = (lambda e: (e, *_drain(e, subs)))(
            _engine(**kw))
        assert rr == rd and lr == ld
        assert er.stats["preemptions"] == ed.stats["preemptions"] > 0

    def test_cancel_race_parity(self):
        """cancel() between steps (in-flight dispatch in ring mode):
        the survivor's stream matches the rebuild-mode run token for
        token, and the cancel lands identically."""
        def run(delta):
            eng = _engine(delta_transitions=delta)
            eng.submit("keep", _cyc(6), max_new_tokens=20)
            eng.submit("kill", _cyc(9, 3), max_new_tokens=20)
            for _ in range(4):
                eng.step()
            assert eng.cancel("kill")
            res = eng.run()
            return eng, res, dict(eng.logprobs)

        er, rr, lr = run(False)
        ed, rd, ld = run(True)
        assert rr == rd and lr == ld
        assert er.cancelled == ed.cancelled == {"kill": "cancelled"}
        assert len(ed.free_blocks) == ed.P - 1

    def test_spec_greedy_parity(self):
        """Speculative ticks: the descriptor carries the committed-
        token row, accept EMA and probe counter, so greedy spec
        streams (draft-invariant by the argmax-prefix rule) stay
        bitwise across modes through admit/finish churn."""
        def run(delta):
            eng = _engine(prefill_buckets=(8,), spec_tokens=3,
                          delta_transitions=delta)
            res, lps = _drain(eng, [
                ("g", _cyc(6), dict(max_new_tokens=15)),
                ("h", _cyc(8, 2), dict(max_new_tokens=10)),
            ])
            res2, lps2 = _drain(eng, [
                ("i", _cyc(5, 1), dict(max_new_tokens=12))])
            res.update(res2)
            lps.update(lps2)
            return eng, res, lps

        er, rr, lr = run(False)
        ed, rd, ld = run(True)
        assert rr == rd and lr == ld
        assert ed.full_rebuilds == 1 and ed.delta_patches > 0

    def test_delta_requires_fused_tick(self):
        with pytest.raises(ValueError):
            _engine(fused_tick=False, delta_transitions=True)


class TestScopedDrain:
    def test_sibling_pending_tokens_survive(self):
        """A cancel's scoped drain consumes ONLY the cancelled row's
        pending entries; the sibling's in-flight tokens stay pending
        and land at the next step() — none lost, none duplicated."""
        eng = _engine()
        eng.submit("keep", _cyc(6), max_new_tokens=20)
        eng.submit("kill", _cyc(9, 3), max_new_tokens=20)
        for _ in range(4):
            eng.step()
        assert eng._pending is not None
        keep_slot = next(s for s in eng.slots
                         if s is not None and s.request_id == "keep")
        n_keep = len(keep_slot.tokens)
        assert eng.cancel("kill")
        # the survivor's entries were NOT consumed by the cancel
        assert eng._pending is not None
        assert len(keep_slot.tokens) == n_keep
        assert eng.ring_scoped_drains == 1
        res = eng.run()
        ref = _engine(ring_mode=False, delta_transitions=False)
        ref.submit("keep", _cyc(6), max_new_tokens=20)
        assert res["keep"] == ref.run()["keep"]

    def test_scoped_drain_on_spec_engine(self):
        """The scoped drain's spec branch (per-row kprop/macc counters
        + EMA mirror) composes with a cancel racing an in-flight
        speculative dispatch; the survivor stays bitwise."""
        kw = dict(prefill_buckets=(8,), spec_tokens=3)
        eng = _engine(**kw)
        eng.submit("keep", _cyc(6), max_new_tokens=20)
        eng.submit("kill", _cyc(9, 3), max_new_tokens=20)
        for _ in range(4):
            eng.step()
        assert eng._pending is not None
        assert eng.cancel("kill")
        assert eng.ring_scoped_drains == 1
        res = eng.run()
        ref = _engine(ring_mode=False, delta_transitions=False, **kw)
        ref.submit("keep", _cyc(6), max_new_tokens=20)
        assert res["keep"] == ref.run()["keep"]

    def test_expire_scopes_to_deadline_slot(self):
        """A running-request deadline expiry on the SUBMIT path (the
        bounded-queue reap, which used to force a global drain) drains
        only the expiring slot: the sibling's pending tokens stay
        pending and its stream is unaffected (bitwise vs a run without
        the expiring tenant, by batch-composition independence)."""
        eng = _engine(max_queue=8)
        eng.submit("keep", _cyc(6), max_new_tokens=16)
        eng.submit("doomed", _cyc(7, 2), max_new_tokens=50)
        for _ in range(4):
            eng.step()
        assert eng._pending is not None
        doomed = next(s for s in eng.slots
                      if s is not None and s.request_id == "doomed")
        doomed.deadline = 0.0      # already past on the monotonic clock
        sc0 = eng.ring_scoped_drains
        # the bounded-queue submit runs _expire against the in-flight
        # dispatch — scoped to the doomed row, sibling left pending
        eng.submit("late", _cyc(4), max_new_tokens=4)
        assert eng.cancelled.get("doomed") == "timeout"
        assert eng.ring_scoped_drains == sc0 + 1
        assert eng._pending is not None
        res = eng.run()
        assert eng.cancelled.get("doomed") == "timeout"
        ref = _engine(ring_mode=False, delta_transitions=False)
        ref.submit("keep", _cyc(6), max_new_tokens=16)
        assert res["keep"] == ref.run()["keep"]


class TestUploadAccounting:
    def test_zero_rebuilds_steady_churn(self):
        """THE ISSUE 14 acceptance counter: a churny stream (short
        requests, a finish + admit every few ticks) runs ZERO
        full-state rebuilds after the first dispatch in delta mode —
        every transition rides a one-row patch — while the rebuild
        reference pays one full rebuild per churn tick."""
        def churn(delta):
            eng = _engine(delta_transitions=delta)
            eng.submit("w", _cyc(4), max_new_tokens=2)
            eng.run()                       # compile + first rebuild
            fr0, dp0 = eng.full_rebuilds, eng.delta_patches
            b0 = eng.h2d_upload_bytes
            for i in range(12):
                eng.submit(i, _cyc(4 + i % 3), max_new_tokens=4)
            eng.run()
            return (eng, eng.full_rebuilds - fr0,
                    eng.delta_patches - dp0, eng.h2d_upload_bytes - b0)

        _, fr_d, dp_d, bytes_d = churn(True)
        _, fr_r, dp_r, bytes_r = churn(False)
        assert fr_d == 0 and dp_d > 0       # steady churn: patches only
        assert fr_r >= 6 and dp_r == 0      # reference: rebuild storm
        # the small-fix satellite: bytes weigh what events hide
        assert 0 < bytes_d < bytes_r

    def test_steady_ticks_no_patches_no_bytes(self):
        """Between transitions nothing is uploaded at all: the
        1-dispatch/0-upload steady pins extend to the byte counter and
        the patch counter."""
        eng = _engine(block_size=64, max_blocks_per_seq=2)
        for i in range(4):
            eng.submit(f"r{i}", _cyc(6), max_new_tokens=100)
        for _ in range(6):
            eng.step()
        d0, u0 = eng.dispatch_count, eng.h2d_uploads
        b0, p0 = eng.h2d_upload_bytes, eng.delta_patches
        for _ in range(20):
            eng.step()
        assert eng.dispatch_count - d0 == 20
        assert eng.h2d_uploads - u0 == 0
        assert eng.h2d_upload_bytes - b0 == 0
        assert eng.delta_patches - p0 == 0

    def test_counters_flow_to_stats_health_and_snapshot(self):
        """full_rebuilds / delta_patches / h2d_upload_bytes reach the
        registry-backed stats (and so health() and a /metrics scrape)
        and the debug_snapshot transitions block, equal to the plain
        attributes the tests and tools read."""
        eng = _engine()
        eng.submit("a", _cyc(5), max_new_tokens=6)
        eng.run()
        st = eng.stats
        assert st["full_rebuilds"] == eng.full_rebuilds == 1
        assert st["delta_patches"] == eng.delta_patches
        assert st["h2d_upload_bytes"] == eng.h2d_upload_bytes > 0
        snap = eng.debug_snapshot()["transitions"]
        assert snap["delta_enabled"] is True
        assert snap["full_rebuilds"] == eng.full_rebuilds
        assert snap["delta_patches"] == eng.delta_patches
        assert snap["h2d_upload_bytes"] == eng.h2d_upload_bytes
        # the final finish's release patch coalesces until the next
        # dispatch would flush it — visible here as the pending row
        assert snap["pending_patch_rows"] == [0]
        h = eng.health()
        assert h["full_rebuilds"] == eng.full_rebuilds


class TestFailoverParity:
    def test_export_resumable_parity_and_bitwise_resume(self):
        """Mirrors advanced by (scoped) drains export the same resume
        descriptors as the rebuild reference, and a resume from them
        continues the stream bitwise (the ISSUE 12/13 failover gate
        with delta mode default-on)."""
        def partial(delta):
            eng = _engine(max_slots=2, delta_transitions=delta)
            eng.submit("r1", _cyc(6), max_new_tokens=30)
            eng.submit("r2", _cyc(7, 1), max_new_tokens=30,
                       temperature=0.7, seed=2)
            for _ in range(9):
                eng.step()
            return eng.export_resumable()

        exp_d = partial(True)
        assert exp_d == partial(False)
        # greedy resume on a fresh delta engine == uninterrupted run
        d = exp_d["r1"]
        fresh = _engine(max_slots=2)
        fresh.submit("r1", np.asarray(d["prompt"])[None],
                     max_new_tokens=d["remaining"],
                     resume_tokens=d["committed"],
                     resume_lps=d["committed_lps"])
        resumed = fresh.run()["r1"]
        ref = _engine(max_slots=2)
        ref.submit("r1", _cyc(6), max_new_tokens=30)
        assert resumed == ref.run()["r1"]


@pytest.mark.slow
class TestDeltaSweep:
    @pytest.mark.parametrize("ring", [True, False])
    @pytest.mark.parametrize("chunk", [None, 8])
    @pytest.mark.parametrize("spec", [0, 3])
    def test_parity_sweep(self, ring, chunk, spec):
        """Heavy matrix: ring x chunked-prefill x speculative, longer
        budgets, staggered second wave — delta vs rebuild bitwise.
        (Tier-1 keeps the single-combination pins above.)"""
        if spec and chunk:
            kw = dict(chunk_prefill_tokens=chunk, spec_tokens=spec,
                      prefill_buckets=(8,))
        elif chunk:
            kw = dict(chunk_prefill_tokens=chunk, prefill_buckets=(8,))
        elif spec:
            kw = dict(spec_tokens=spec, prefill_buckets=(8,))
        else:
            kw = {}
        # sampled rows join only the non-spec combos: sampled + spec
        # across modes is distribution-preserving, not bitwise (the
        # drafts read the uncommitted buffer tail, which rebuilds zero
        # and patches preserve — documented in PERFORMANCE.md)
        subs = [(f"r{j}", _cyc(5 + j % 4, j), dict(
            max_new_tokens=10 + 3 * (j % 3),
            **({} if (j % 2 == 0 or spec) else
               dict(temperature=0.7, seed=j, top_k=12))))
            for j in range(6)]

        def run(delta):
            eng = _engine(ring_mode=ring, delta_transitions=delta, **kw)
            res, lps = _drain(eng, subs[:4])
            res2, lps2 = _drain(eng, subs[4:])
            res.update(res2)
            lps.update(lps2)
            return res, lps

        rr, lr = run(False)
        rd, ld = run(True)
        assert rr == rd
        assert lr == ld
