"""LLM fine-tuning losses and trainers (reference: PaddleNLP
paddlenlp/trl — SFTTrainer/DPOTrainer and llm/ alignment recipes).

TPU-native stance: both recipes are ordinary jitted train steps over the
existing Trainer; what this module adds is the loss algebra and the batch
conventions:

- SFT: causal LM cross-entropy masked to the RESPONSE tokens only
  (prompt tokens contribute no gradient). Batches are dicts of static-
  shape arrays (``input_ids`` [b, s], ``loss_mask`` [b, s]) — right-
  padded, so one compiled step serves every batch.
- DPO: the Bradley-Terry preference loss on (chosen, rejected) pairs.
  Reference log-probs are PRECOMPUTED (``compute_sequence_logps`` with
  the frozen reference params) and carried in the batch — the jitted
  policy step then needs no second model in the program, which on TPU
  means no duplicated weights in HBM and no constant-folding a whole
  reference model into the executable.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .trainer import Trainer, TrainingArguments

__all__ = [
    "sft_loss", "sequence_logps", "compute_sequence_logps", "dpo_loss",
    "DataCollatorForSFT", "packed_sft_inputs", "SFTTrainer",
    "make_dpo_loss_fn", "DPOTrainer",
]


def _token_logps(logits, input_ids, loss_mask):
    """Shifted next-token log-probs at the masked positions: [b, s-1]."""
    lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(lp, input_ids[:, 1:, None], axis=-1)[..., 0]
    return tgt * loss_mask[:, 1:].astype(jnp.float32)


def sft_loss(logits, input_ids, loss_mask, segment_ids=None):
    """Next-token CE on positions where loss_mask[t+1] == 1 (the response;
    reference: PaddleNLP SFT recipes' masked cross-entropy). With packed
    ``segment_ids``, targets whose CONTEXT token lies in a different
    segment are dropped — the shifted loss must never train segment k's
    last token to predict segment k+1's unrelated first token."""
    mask = loss_mask
    if segment_ids is not None:
        same = jnp.concatenate(
            [jnp.ones_like(segment_ids[:, :1], dtype=bool),
             segment_ids[:, 1:] == segment_ids[:, :-1]], axis=1)
        mask = mask * same
    tok = _token_logps(logits, input_ids, mask)
    n = jnp.maximum(mask[:, 1:].sum().astype(jnp.float32), 1.0)
    return -tok.sum() / n


def sequence_logps(logits, input_ids, loss_mask):
    """Per-sequence sum log-prob of the masked (response) tokens."""
    return _token_logps(logits, input_ids, loss_mask).sum(axis=-1)


def compute_sequence_logps(model, input_ids, loss_mask, batch_size: int = 8):
    """Run a (frozen reference) model over sequences and return summed
    response log-probs — the precompute step of the DPO recipe. The model
    is traced in EVAL mode (dropout off): a reference model in train mode
    would either crash on an un-keyed next_key() under tracing or bias
    the reference logps with dropout noise."""
    was_training = model.training
    model.eval()
    try:
        fn, params = model.functional()
        jf = getattr(model, "_seq_logps_jit", None)
        if jf is None:
            jf = jax.jit(
                lambda p, ids, m: sequence_logps(fn(p, ids), ids, m))
            model._seq_logps_jit = jf
        outs = []
        for i in range(0, input_ids.shape[0], batch_size):
            outs.append(jf(params, input_ids[i:i + batch_size],
                           loss_mask[i:i + batch_size]))
    finally:
        if was_training:
            model.train()
    return jnp.concatenate(outs)


def dpo_loss(policy_chosen_logps, policy_rejected_logps,
             reference_chosen_logps, reference_rejected_logps,
             beta: float = 0.1, label_smoothing: float = 0.0):
    """Direct Preference Optimization (reference: PaddleNLP DPOTrainer;
    Rafailov et al. 2023). Returns (loss, chosen_rewards, rejected_rewards)
    — the rewards are the implicit ones, for logging margin/accuracy."""
    chosen_rel = policy_chosen_logps - reference_chosen_logps
    rejected_rel = policy_rejected_logps - reference_rejected_logps
    logits = beta * (chosen_rel - rejected_rel)
    loss = (-jax.nn.log_sigmoid(logits) * (1 - label_smoothing)
            - jax.nn.log_sigmoid(-logits) * label_smoothing).mean()
    return loss, beta * chosen_rel, beta * rejected_rel


class DataCollatorForSFT:
    """prompt/response token lists -> right-padded static-shape batches
    {"input_ids": [b, max_len], "loss_mask": [b, max_len]} (reference:
    PaddleNLP llm/ SFT data pipeline). Static shapes = one compile.

    ``packing=True`` (reference: PaddleNLP's "intokens"/ZeroPadding
    packing) greedily packs several examples into each row and adds
    ``segment_ids`` [b, max_len] (0 = pad, 1..k = example): attention is
    then block-causal per segment and positions restart per example (see
    ``packed_sft_inputs``). Packing removes pad waste, the difference
    between ~50% and ~95% useful FLOPs on short-example SFT corpora.
    Pass ``pack_rows`` to FIX the packed row count (padding with empty
    rows, erroring on overflow) so every batch keeps one static shape —
    without it the row count follows the content and each new count
    retraces the jitted step."""

    def __init__(self, max_length: int, pad_token_id: int = 0,
                 mask_prompt: bool = True, packing: bool = False,
                 pack_rows: Optional[int] = None):
        self.max_length = max_length
        self.pad_token_id = pad_token_id
        self.mask_prompt = mask_prompt
        self.packing = packing
        self.pack_rows = pack_rows

    def _fit(self, ex):
        prompt = list(ex["prompt_ids"])
        resp = list(ex["response_ids"])
        seq = (prompt + resp)[:self.max_length]
        lstart = min(len(prompt), self.max_length) if self.mask_prompt else 0
        return seq, lstart

    def __call__(self, examples) -> Dict[str, jnp.ndarray]:
        L = self.max_length
        if not self.packing:
            ids = np.full((len(examples), L), self.pad_token_id, np.int32)
            mask = np.zeros((len(examples), L), np.int32)
            for i, ex in enumerate(examples):
                seq, lstart = self._fit(ex)
                ids[i, :len(seq)] = seq
                mask[i, lstart:len(seq)] = 1
            return {"input_ids": jnp.asarray(ids),
                    "loss_mask": jnp.asarray(mask)}

        # greedy first-fit packing into rows of max_length
        rows = []  # each: {"ids": [...], "mask": [...], "seg": [...], "n": k}
        for ex in examples:
            seq, lstart = self._fit(ex)
            for row in rows:
                if len(row["ids"]) + len(seq) <= L:
                    break
            else:
                row = {"ids": [], "mask": [], "seg": [], "n": 0}
                rows.append(row)
            row["n"] += 1
            row["ids"].extend(seq)
            row["mask"].extend([0] * lstart + [1] * (len(seq) - lstart))
            row["seg"].extend([row["n"]] * len(seq))

        n_rows = len(rows)
        if self.pack_rows is not None:
            if n_rows > self.pack_rows:
                raise ValueError(
                    f"packing needed {n_rows} rows > pack_rows="
                    f"{self.pack_rows}; raise pack_rows or max_length")
            n_rows = self.pack_rows
        ids = np.full((n_rows, L), self.pad_token_id, np.int32)
        mask = np.zeros((n_rows, L), np.int32)
        segs = np.zeros((n_rows, L), np.int32)
        for i, row in enumerate(rows):
            ids[i, :len(row["ids"])] = row["ids"]
            mask[i, :len(row["mask"])] = row["mask"]
            segs[i, :len(row["seg"])] = row["seg"]
        return {"input_ids": jnp.asarray(ids), "loss_mask": jnp.asarray(mask),
                "segment_ids": jnp.asarray(segs)}


def packed_sft_inputs(segment_ids, with_mask: bool = True):
    """segment_ids [b, s] -> (positions [b, s], attn_mask [b, 1, s, s]).

    Attention is causal AND segment-diagonal (tokens never attend across
    packed examples — the correctness requirement of packing), and RoPE
    positions restart at each example's first token. Pure jnp: runs
    inside the jitted step, so the collator ships only one extra [b, s]
    int array. ``with_mask=False`` skips the O(s^2) mask and returns
    (positions, None) — the path used when the model takes segment_ids
    directly (segment-aware flash kernel)."""
    seg = segment_ids
    s = seg.shape[-1]
    idx = jnp.arange(s)
    # position = index - index_of_segment_start, computed via the running
    # max index where the segment id changes
    change = jnp.concatenate(
        [jnp.ones_like(seg[:, :1]), (seg[:, 1:] != seg[:, :-1])], axis=1)
    start_idx = jax.lax.cummax(jnp.where(change, idx[None, :], 0), axis=1)
    positions = idx[None, :] - start_idx
    if not with_mask:
        return positions, None
    causal = (idx[None, :, None] >= idx[None, None, :])
    same_seg = (seg[:, :, None] == seg[:, None, :]) & (seg[:, :, None] > 0)
    # pad rows (seg 0) attend only themselves: an all-masked softmax row
    # would be NaN and pollute real rows downstream (cf. serving path)
    self_only = idx[:, None] == idx[None, :]
    attn = jnp.where(seg[:, :, None] > 0, causal & same_seg,
                     self_only[None])
    return positions, attn[:, None]


def _model_takes_segment_ids(model) -> bool:
    import inspect
    try:
        return "segment_ids" in inspect.signature(
            type(model).forward).parameters
    except (TypeError, ValueError):
        return False


def _make_sft_loss(supports_seg: bool):
    def loss_fn(fn, p, batch):
        ids = batch["input_ids"]
        if "segment_ids" in batch:  # packed rows: block-causal + RoPE reset
            seg = batch["segment_ids"]
            if supports_seg:
                # segment_ids (not a dense [s, s] mask) so attention takes
                # the segment-aware FLASH path on TPU when shapes qualify;
                # the dense fallback builds the same mask internally
                positions, _ = packed_sft_inputs(seg, with_mask=False)
                logits = fn(p, ids, positions=positions, segment_ids=seg)
            else:
                # model forward without a segment_ids parameter (e.g.
                # GPT): the explicit block-causal mask
                positions, attn = packed_sft_inputs(seg)
                logits = fn(p, ids, positions=positions, attn_mask=attn)
            return sft_loss(logits, ids, batch["loss_mask"],
                            segment_ids=seg)
        return sft_loss(fn(p, ids), ids, batch["loss_mask"])
    return loss_fn


class SFTTrainer(Trainer):
    """Trainer preconfigured with the masked SFT loss over dict batches
    (reference: paddlenlp.trl.SFTTrainer); handles both padded and
    packed (segment_ids) collator outputs."""

    def __init__(self, model, optimizer, args: Optional[TrainingArguments]
                 = None, **kw):
        # capability dispatch by signature, not try/except around the
        # whole trace — a genuine TypeError inside a segment-aware model
        # must surface, not silently reroute to the dense path
        kw.setdefault("loss_fn", _make_sft_loss(
            _model_takes_segment_ids(model)))
        super().__init__(model, optimizer, args, **kw)


def make_dpo_loss_fn(beta: float = 0.1, label_smoothing: float = 0.0
                     ) -> Callable:
    """Trainer loss_fn for DPO batches: {"chosen_ids", "chosen_mask",
    "rejected_ids", "rejected_mask", "ref_chosen_logps",
    "ref_rejected_logps"} (reference logps precomputed)."""

    def loss_fn(fn, p, batch):
        # concatenated forward (the standard DPO trick): one [2b, s] pass
        # instead of two [b, s] passes — same math, better TPU utilization
        b = batch["chosen_ids"].shape[0]
        ids = jnp.concatenate([batch["chosen_ids"], batch["rejected_ids"]])
        mask = jnp.concatenate([batch["chosen_mask"],
                                batch["rejected_mask"]])
        logps = sequence_logps(fn(p, ids), ids, mask)
        loss, _, _ = dpo_loss(logps[:b], logps[b:],
                              batch["ref_chosen_logps"],
                              batch["ref_rejected_logps"], beta,
                              label_smoothing)
        return loss

    return loss_fn


class DPOTrainer(Trainer):
    """Trainer preconfigured with the DPO preference loss (reference:
    paddlenlp.trl.DPOTrainer). Precompute the reference logps with
    ``compute_sequence_logps(ref_model, ...)`` into the batches."""

    def __init__(self, model, optimizer, args: Optional[TrainingArguments]
                 = None, beta: float = 0.1, label_smoothing: float = 0.0,
                 **kw):
        kw.setdefault("loss_fn", make_dpo_loss_fn(beta, label_smoothing))
        super().__init__(model, optimizer, args, **kw)
