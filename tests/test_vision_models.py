"""Vision/multimodal model zoo tests (C23): shapes, grads, jit, losses.

Mirrors the reference's unit-test style (PaddleClas/PaddleMIX/PaddleOCR
test suites): forward shape checks, loss finiteness, gradient flow, and a
numerics check for CTC against torch.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.models import (AutoencoderKL, CLIPModel, DBNet, DiT, MMDiT,
                               ResNet, SVTRNet, ViTForImageClassification,
                               clip_contrastive_loss, clip_tiny,
                               ctc_greedy_decode, ctc_rec_loss, db_loss,
                               dbnet_tiny, dit_tiny, mmdit_tiny, resnet_tiny,
                               svtr_tiny, vae_loss, vae_tiny, vit_tiny)


def _train_step_loss(model, loss_fn, *args):
    """Grad-flow helper: returns (loss, grad_l2) through the functional
    bridge."""
    fn, params = model.functional()

    def loss_of(p):
        return loss_fn(fn(p, *args))

    loss, grads = jax.value_and_grad(loss_of)(params)
    gnorm = sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in grads.values())
    return loss, jnp.sqrt(gnorm)


class TestResNet:
    def test_forward_and_grad(self):
        model = ResNet(resnet_tiny())
        x = jnp.ones((2, 3, 32, 32))
        logits = model(x)
        assert logits.shape == (2, 10)
        labels = jnp.array([1, 2])
        loss, gnorm = _train_step_loss(
            model, lambda out: nn.functional.cross_entropy(out, labels), x)
        assert jnp.isfinite(loss) and gnorm > 0

    def test_feature_pyramid(self):
        model = ResNet(resnet_tiny())
        feats = model(jnp.ones((1, 3, 32, 32)), return_feats=True)
        assert len(feats) == 4
        # strides 4, 8, 16, 32
        assert [f.shape[-1] for f in feats] == [8, 4, 2, 1]

    def test_bottleneck_variant_d(self):
        from paddle_tpu.models import ResNetConfig
        model = ResNet(ResNetConfig(depth=50, variant="d", stem_width=8,
                                    layers=[1, 1, 1, 1], num_classes=4))
        out = model(jnp.ones((1, 3, 64, 64)))
        assert out.shape == (1, 4)


class TestViT:
    def test_forward_jit(self):
        model = ViTForImageClassification(vit_tiny())
        fn, params = model.functional()
        out = jax.jit(fn)(params, jnp.ones((2, 3, 32, 32)))
        assert out.shape == (2, 10)
        assert jnp.all(jnp.isfinite(out))

    def test_token_count(self):
        cfg = vit_tiny()
        model = ViTForImageClassification(cfg)
        seq = model.vit(jnp.ones((1, 3, 32, 32)))
        assert seq.shape == (1, cfg.num_patches + 1, cfg.hidden_size)

    def test_global_pool(self):
        model = ViTForImageClassification(vit_tiny(global_pool=True))
        assert model(jnp.ones((1, 3, 32, 32))).shape == (1, 10)


class TestCLIP:
    def test_contrastive_roundtrip(self):
        model = CLIPModel(clip_tiny())
        ids = jnp.arange(8).reshape(2, 4) + 1
        px = jnp.ones((2, 3, 16, 16))
        li, lt = model(ids, px)
        assert li.shape == (2, 2) and lt.shape == (2, 2)
        loss = clip_contrastive_loss(li, lt)
        assert jnp.isfinite(loss)

    def test_grad_through_both_towers(self):
        model = CLIPModel(clip_tiny())
        ids = jnp.arange(8).reshape(2, 4) + 1
        px = jnp.ones((2, 3, 16, 16))
        fn, params = model.functional()

        def loss_of(p):
            li, lt = fn(p, ids, px)
            return clip_contrastive_loss(li, lt)

        grads = jax.grad(loss_of)(params)
        text_g = sum(float(jnp.abs(g).sum()) for k, g in grads.items()
                     if k.startswith("text_model"))
        vis_g = sum(float(jnp.abs(g).sum()) for k, g in grads.items()
                    if k.startswith("vision_model"))
        assert text_g > 0 and vis_g > 0


class TestDiT:
    def test_dit_shape_and_zero_init(self):
        cfg = dit_tiny()
        model = DiT(cfg)
        x = jnp.ones((2, 4, 8, 8))
        t = jnp.array([1, 5])
        y = jnp.array([0, 3])
        out = model(x, t, y)
        assert out.shape == (2, cfg.out_channels, 8, 8)
        # adaLN-Zero: output head initialised to zero → output == 0
        assert float(jnp.abs(out).max()) == 0.0

    def test_dit_cfg_dropout(self):
        model = DiT(dit_tiny())
        x = jnp.ones((2, 4, 8, 8))
        out = model(x, jnp.array([1, 1]), jnp.array([0, 1]),
                    drop_mask=jnp.array([True, False]))
        assert out.shape[0] == 2

    def test_mmdit_joint_stream(self):
        cfg = mmdit_tiny()
        model = MMDiT(cfg)
        lat = jnp.ones((2, 4, 8, 8))
        ctx = jnp.ones((2, 6, cfg.context_dim))
        pooled = jnp.ones((2, cfg.pooled_dim))
        out = model(lat, jnp.array([3, 7]), ctx, pooled)
        assert out.shape == (2, cfg.out_channels, 8, 8)

    def test_dit_grad(self):
        model = DiT(dit_tiny())
        x = jnp.ones((1, 4, 8, 8))
        loss, gnorm = _train_step_loss(
            model, lambda out: jnp.mean(out ** 2),
            x, jnp.array([2]), jnp.array([1]))
        assert jnp.isfinite(loss)


class TestVAE:
    def test_roundtrip_shapes(self):
        cfg = vae_tiny()
        model = AutoencoderKL(cfg)
        x = jnp.ones((2, 3, 16, 16))
        post = model.encode(x)
        assert post.mean.shape == (2, 4, 8, 8)   # one downsample stage
        recon = model.decode(post.mode())
        assert recon.shape == x.shape

    def test_kl_and_loss(self):
        model = AutoencoderKL(vae_tiny())
        x = jnp.ones((1, 3, 16, 16)) * 0.5
        recon, post = model(x, key=jax.random.PRNGKey(0))
        loss = vae_loss(recon, x, post)
        assert jnp.isfinite(loss) and loss > 0
        assert jnp.all(post.kl() >= 0)

    def test_sample_stochastic(self):
        model = AutoencoderKL(vae_tiny())
        post = model.encode(jnp.ones((1, 3, 16, 16)))
        z1 = post.sample(jax.random.PRNGKey(0))
        z2 = post.sample(jax.random.PRNGKey(1))
        assert not jnp.allclose(z1, z2)


class TestPPOCR:
    def test_dbnet_maps(self):
        model = DBNet(dbnet_tiny())
        out = model(jnp.ones((1, 3, 64, 64)))
        # prob/thresh/binary maps at full input resolution
        assert out["maps"].shape == (1, 3, 64, 64)
        maps = out["maps"]
        assert float(maps.min()) >= 0.0 and float(maps.max()) <= 1.0

    def test_db_loss(self):
        model = DBNet(dbnet_tiny())
        pred = model(jnp.ones((1, 3, 32, 32)))
        key = jax.random.PRNGKey(0)
        shrink = (jax.random.uniform(key, (1, 32, 32)) > 0.8).astype(jnp.float32)
        mask = jnp.ones((1, 32, 32))
        loss = db_loss(pred, shrink, mask, shrink * 0.5, mask)
        assert jnp.isfinite(loss) and loss > 0

    def test_svtr_ctc(self):
        cfg = svtr_tiny()
        model = SVTRNet(cfg)
        logits = model(jnp.ones((2, 3, 16, 32)))
        assert logits.shape == (2, 8, cfg.num_classes)  # W/4 time steps
        labels = jnp.array([[1, 2, 3], [4, 5, 0]])
        lens = jnp.array([3, 2])
        loss = ctc_rec_loss(logits, labels, lens)
        assert jnp.isfinite(loss) and loss > 0

    def test_ctc_decode(self):
        # path b,b,blank,c,c → "bc"
        logits = jnp.full((1, 5, 4), -10.0)
        path = [2, 2, 0, 3, 3]
        logits = logits.at[0, jnp.arange(5), jnp.array(path)].set(10.0)
        ids, keep = ctc_greedy_decode(logits)
        decoded = np.asarray(ids[0])[np.asarray(keep[0])]
        assert decoded.tolist() == [2, 3]


class TestCTCvsTorch:
    def test_ctc_matches_torch(self):
        torch = pytest.importorskip("torch")
        rng = np.random.default_rng(1)
        B, T, C, L = 2, 10, 6, 3
        logits = rng.normal(size=(B, T, C)).astype(np.float32)
        labels = rng.integers(1, C, size=(B, L)).astype(np.int32)
        in_lens = np.array([10, 7], np.int32)
        lab_lens = np.array([3, 2], np.int32)
        ours = nn.functional.ctc_loss(
            jnp.asarray(logits), jnp.asarray(labels), jnp.asarray(in_lens),
            jnp.asarray(lab_lens), reduction="none")
        t_lp = torch.log_softmax(torch.tensor(logits), -1).transpose(0, 1)
        ref = torch.nn.functional.ctc_loss(
            t_lp, torch.tensor(labels.astype(np.int64)),
            torch.tensor(in_lens.astype(np.int64)),
            torch.tensor(lab_lens.astype(np.int64)),
            blank=0, reduction="none")
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(), atol=1e-4)
