"""GPTQ + AWQ error-compensating PTQ (VERDICT r3 missing #6): both must
beat plain RTN blockwise quantization on calibration-shaped data, and
the model passes must swap layers in place and keep the model usable."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import LlamaForCausalLM
from paddle_tpu.models.llama import llama_tiny
from paddle_tpu.quant.gptq_awq import (awq_quantize_model,
                                       awq_search_scale,
                                       capture_linear_inputs,
                                       gptq_quantize_model,
                                       gptq_quantize_weight)
from paddle_tpu.quant.weight_only import (dequantize_weight,
                                          quantize_blockwise)


def _calib_problem():
    rs = np.random.RandomState(0)
    din, dout, n = 128, 64, 256
    base = rs.randn(n, 16) @ rs.randn(16, din)   # correlated features
    x = base + 0.1 * rs.randn(n, din)
    x[:, :4] *= 30.0                             # salient channels
    w = rs.randn(din, dout).astype(np.float32) * 0.05
    return x, w


def _recon_err(x, w, deq, s=None):
    ref = x @ np.asarray(w, np.float64)
    xq = x / np.asarray(s) if s is not None else x
    return float(np.mean((ref - xq @ np.asarray(deq, np.float64)) ** 2))


def test_gptq_beats_rtn_int4():
    x, w = _calib_problem()
    q0, s0 = quantize_blockwise(jnp.asarray(w), bits=4, block_size=32)
    e_rtn = _recon_err(x, w, dequantize_weight(q0, s0, 4, 32, jnp.float32))
    qg, sg = gptq_quantize_weight(w, x, bits=4, block_size=32)
    e_gptq = _recon_err(x, w,
                        dequantize_weight(qg, sg, 4, 32, jnp.float32))
    assert e_gptq < e_rtn * 0.5, (e_gptq, e_rtn)


def test_awq_beats_rtn_int4():
    x, w = _calib_problem()
    q0, s0 = quantize_blockwise(jnp.asarray(w), bits=4, block_size=32)
    e_rtn = _recon_err(x, w, dequantize_weight(q0, s0, 4, 32, jnp.float32))
    s = awq_search_scale(jnp.asarray(w), x, bits=4, block_size=32)
    qa, sa = quantize_blockwise(
        jnp.asarray(w * np.asarray(s)[:, None]), 4, 32)
    e_awq = _recon_err(x, w,
                       dequantize_weight(qa, sa, 4, 32, jnp.float32), s=s)
    assert e_awq < e_rtn * 0.7, (e_awq, e_rtn)


@pytest.mark.parametrize("pass_fn", [gptq_quantize_model,
                                     awq_quantize_model])
def test_model_pass_swaps_and_generates(pass_fn):
    pt.seed(0)
    m = LlamaForCausalLM(llama_tiny(hidden_size=64, intermediate_size=128))
    rs = np.random.RandomState(1)
    batches = [jnp.asarray(rs.randint(0, 256, (2, 16))) for _ in range(2)]
    ids = batches[0]
    ref = np.asarray(m(ids))
    n = pass_fn(m, batches, bits=8, block_size=32,
                skip=["lm_head", "embed"])
    assert n > 0
    got = np.asarray(m(ids))
    # int8 weight-only on a tiny model: logits stay close
    assert np.mean(np.abs(got - ref)) < 0.1, np.mean(np.abs(got - ref))
    out = m.generate(ids[:1], max_new_tokens=8, temperature=0.0)
    assert out.shape == (1, 24)


def test_capture_hooks_removed():
    pt.seed(2)
    m = LlamaForCausalLM(llama_tiny())
    ids = jnp.asarray(np.random.RandomState(3).randint(0, 256, (1, 8)))
    calib = capture_linear_inputs(m, [ids], max_tokens=64)
    assert calib and all(v.shape[0] <= 64 for v in calib.values())
    assert all(not s._forward_pre_hooks
               for _, s in m.named_sublayers(include_self=False))


def test_gptq_act_order_int4():
    """VERDICT-r4 missing #5: act-order (descending diag(H) visit order)
    must emit the same blockwise layout and reconstruct at least as well
    as natural order on activation-salient data."""
    x, w = _calib_problem()
    qn, sn = gptq_quantize_weight(w, x, bits=4, block_size=32)
    e_nat = _recon_err(x, w,
                       dequantize_weight(qn, sn, 4, 32, jnp.float32))
    qa, sa = gptq_quantize_weight(w, x, bits=4, block_size=32,
                                  act_order=True)
    assert qa.shape == qn.shape and sa.shape == sn.shape  # same layout
    e_act = _recon_err(x, w,
                       dequantize_weight(qa, sa, 4, 32, jnp.float32))
    assert e_act <= e_nat * 1.001, (e_act, e_nat)
    # and still far better than RTN
    q0, s0 = quantize_blockwise(jnp.asarray(w), bits=4, block_size=32)
    e_rtn = _recon_err(x, w, dequantize_weight(q0, s0, 4, 32, jnp.float32))
    assert e_act < e_rtn * 0.5, (e_act, e_rtn)
