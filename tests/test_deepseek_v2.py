"""DeepSeek-V2 MLA (C22 flagship-family addition): torch logits parity,
absorbed-decode == expanded-prefill consistency, cache compression."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import paddle_tpu as pt  # noqa: E402
from paddle_tpu.models import (DeepseekV2ForCausalLM, deepseek_v2_tiny,  # noqa: E402
                               from_pretrained)


def _hf_cfg(**kw):
    base = dict(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        q_lora_rank=32, kv_lora_rank=32, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16, head_dim=24,
        n_routed_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        n_shared_experts=1, first_k_dense_replace=1, moe_layer_freq=1,
        topk_method="greedy", n_group=1, topk_group=1,
        routed_scaling_factor=1.0, norm_topk_prob=False,
        aux_loss_alpha=0.0, seq_aux=False,
        max_position_embeddings=128, rope_theta=10000.0,
        tie_word_embeddings=False, torch_dtype="float32",
        attn_implementation="eager")
    base.update(kw)
    return transformers.DeepseekV2Config(**base)


class TestDeepseekV2Parity:
    def test_logits_match_torch(self, tmp_path):
        torch.manual_seed(0)
        hf = transformers.DeepseekV2ForCausalLM(_hf_cfg())
        hf.eval()
        d = str(tmp_path)
        hf.save_pretrained(d, safe_serialization=True)
        model = from_pretrained(d)
        for layer in model.model.layers:
            if hasattr(layer.mlp, "capacity_factor"):
                layer.mlp.capacity_factor = 2.0  # E/k: dropless
        ids = np.random.RandomState(0).randint(0, 128, (2, 16))
        with torch.no_grad():
            ref = hf(torch.tensor(ids)).logits.numpy()
        got = np.asarray(model(jnp.asarray(ids)))
        np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


class TestYarnParity:
    def test_yarn_logits_match_torch(self, tmp_path):
        """Real DeepSeek-V2 checkpoints all ship yarn rope_scaling; the
        frequency remap + attention factor must match transformers."""
        torch.manual_seed(0)
        hf = transformers.DeepseekV2ForCausalLM(_hf_cfg(
            rope_scaling={"rope_type": "yarn", "factor": 4.0,
                          "mscale": 0.707, "mscale_all_dim": 0.707,
                          "beta_fast": 32, "beta_slow": 1,
                          "original_max_position_embeddings": 32}))
        hf.eval()
        d = str(tmp_path)
        hf.save_pretrained(d, safe_serialization=True)
        model = from_pretrained(d)
        # yarn params actually engaged
        attn = model.model.layers[0].self_attn
        assert attn._inv_freq is not None
        for layer in model.model.layers:
            if hasattr(layer.mlp, "capacity_factor"):
                layer.mlp.capacity_factor = 2.0
        ids = np.random.RandomState(3).randint(0, 128, (2, 48))
        with torch.no_grad():
            ref = hf(torch.tensor(ids)).logits.numpy()
        got = np.asarray(model(jnp.asarray(ids)))
        np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


class TestMLADecode:
    def test_absorbed_decode_matches_prefill(self):
        """The absorbed latent-space decode must produce the same logits
        as the expanded training-path forward at every position."""
        pt.seed(0)
        model = DeepseekV2ForCausalLM(deepseek_v2_tiny())
        for layer in model.model.layers:
            if hasattr(layer.mlp, "capacity_factor"):
                # dropless (E/k): GShard capacity depends on the token
                # count, so prefill-vs-full comparisons need no drops
                layer.mlp.capacity_factor = 2.0
        fn, params = model.functional()
        ids = jnp.asarray(np.random.RandomState(1).randint(0, 256, (2, 10)))
        full = fn(dict(params), ids)                     # expanded path
        caches = model.init_kv_caches(2, 16)
        # prefill 8 through the absorbed/cache path, then 2 decode steps
        # (step 8 proves decode-over-prefill-cache, step 9 proves
        # decode-over-decode-cache — more steps re-run the same program)
        logits, caches = fn(dict(params), ids[:, :8], kv_caches=caches,
                            cache_index=0)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, :8]),
                                   atol=2e-4, rtol=2e-4)
        for t in range(8, 10):
            step, caches = fn(dict(params), ids[:, t:t + 1],
                              kv_caches=caches, cache_index=t)
            np.testing.assert_allclose(np.asarray(step[:, 0]),
                                       np.asarray(full[:, t]),
                                       atol=2e-4, rtol=2e-4, err_msg=str(t))

    def test_cache_is_compressed(self):
        """The MLA cache stores kv_lora_rank + rope_d per token — here
        40 floats vs 2*4*24=192 for an equivalent dense KV cache."""
        cfg = deepseek_v2_tiny()
        model = DeepseekV2ForCausalLM(cfg)
        caches = model.init_kv_caches(2, 32)
        c, kpe = caches[0]
        per_tok = c.shape[-1] + kpe.shape[-1]
        assert per_tok == cfg.kv_lora_rank + cfg.qk_rope_head_dim == 40
        dense = 2 * cfg.num_attention_heads * cfg.qk_head_dim
        assert per_tok < dense / 4

    def test_generate_runs(self):
        pt.seed(0)
        model = DeepseekV2ForCausalLM(deepseek_v2_tiny())
        ids = jnp.asarray(np.random.RandomState(2).randint(0, 256, (1, 8)))
        out = model.generate(ids, max_new_tokens=6, temperature=0.0)
        assert out.shape == (1, 14)


class TestGroupLimitedRouting:
    def test_group_limited_logits_match_torch(self, tmp_path):
        """DeepSeek-V2 (non-Lite) routing: only the top groups' experts
        are eligible; parity vs transformers."""
        torch.manual_seed(1)
        hf = transformers.DeepseekV2ForCausalLM(_hf_cfg(
            topk_method="group_limited_greedy", n_group=2, topk_group=1))
        hf.eval()
        d = str(tmp_path)
        hf.save_pretrained(d, safe_serialization=True)
        model = from_pretrained(d)
        assert model.model.layers[1].mlp.n_group == 2
        for layer in model.model.layers:
            if hasattr(layer.mlp, "capacity_factor"):
                layer.mlp.capacity_factor = 2.0
        ids = np.random.RandomState(4).randint(0, 128, (2, 16))
        with torch.no_grad():
            ref = hf(torch.tensor(ids)).logits.numpy()
        got = np.asarray(model(jnp.asarray(ids)))
        np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


class TestDeepseekV3:
    def test_v3_logits_match_torch(self, tmp_path):
        """DeepSeek-V3/R1 architecture: sigmoid router with bias-corrected
        top-2-sum group selection, applied top-k normalization, and yarn
        with mscale^2 folded into the softmax scale."""
        cfg = transformers.DeepseekV3Config(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=4, q_lora_rank=32, kv_lora_rank=32,
            qk_nope_head_dim=16, qk_rope_head_dim=8, v_head_dim=16,
            head_dim=8, n_routed_experts=8, num_experts_per_tok=2,
            moe_intermediate_size=32, n_shared_experts=1,
            first_k_dense_replace=1, n_group=2, topk_group=1,
            routed_scaling_factor=2.5, norm_topk_prob=True,
            rope_scaling={"rope_type": "yarn", "factor": 8.0,
                          "mscale": 1.0, "mscale_all_dim": 1.0,
                          "original_max_position_embeddings": 16},
            max_position_embeddings=128, rope_theta=10000.0,
            rope_interleave=True, tie_word_embeddings=False,
            torch_dtype="float32", attn_implementation="eager")
        torch.manual_seed(2)
        hf = transformers.DeepseekV3ForCausalLM(cfg)
        hf.eval()
        # give the aux-free bias real values so the selection correction
        # is exercised (checkpoints ship trained biases)
        with torch.no_grad():
            for layer in hf.model.layers[1:]:
                layer.mlp.gate.e_score_correction_bias.uniform_(-0.2, 0.2)
        d = str(tmp_path)
        hf.save_pretrained(d, safe_serialization=True)
        model = from_pretrained(d)
        mlp = model.model.layers[1].mlp
        assert mlp.scoring == "sigmoid" and mlp.group_score_mode == "top2_sum"
        assert float(np.abs(np.asarray(
            model.model.layers[1].mlp.expert_bias)).sum()) > 0
        for layer in model.model.layers:
            if hasattr(layer.mlp, "capacity_factor"):
                layer.mlp.capacity_factor = 4.0  # E/k: dropless
        ids = np.random.RandomState(5).randint(0, 128, (2, 24))
        with torch.no_grad():
            ref = hf(torch.tensor(ids)).logits.numpy()
        got = np.asarray(model(jnp.asarray(ids)))
        np.testing.assert_allclose(got, ref, atol=5e-4, rtol=5e-4)


class TestMTP:
    """DeepSeek-V3 multi-token prediction (VERDICT r3 item 9)."""

    def _model(self, D=1):
        import paddle_tpu as pt
        from paddle_tpu.models.deepseek_v2 import (DeepseekV2ForCausalLM,
                                                   deepseek_v2_tiny)
        pt.seed(0)
        return DeepseekV2ForCausalLM(deepseek_v2_tiny(
            num_nextn_predict_layers=D, scoring="sigmoid",
            group_score_mode="top2sum"))

    def test_mtp_shapes_and_main_parity(self):
        """MTP depth k logits have length s-1-k; adding the MTP module
        must NOT change the main head's logits."""
        import paddle_tpu as pt
        from paddle_tpu.models.deepseek_v2 import (DeepseekV2ForCausalLM,
                                                   deepseek_v2_tiny)
        model = self._model(D=2)
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 16)))
        logits, mtp = model(ids, return_mtp=True)
        assert [m.shape for m in mtp] == [(2, 15, 256), (2, 14, 256)]
        # the plain forward (no MTP) yields the SAME main-head logits
        np.testing.assert_array_equal(np.asarray(model(ids)),
                                      np.asarray(logits))

    def test_mtp_module_does_not_shift_trunk_init(self):
        """Same seed with and without MTP heads: the trunk parameters
        (and main logits) must be identical — the MTP LayerList is
        constructed AFTER the trunk so it cannot consume trunk RNG."""
        import paddle_tpu as pt
        from paddle_tpu.models.deepseek_v2 import (DeepseekV2ForCausalLM,
                                                   deepseek_v2_tiny)
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 256, (1, 8)))
        model = self._model(D=1)
        pt.seed(0)
        base = DeepseekV2ForCausalLM(deepseek_v2_tiny(
            scoring="sigmoid", group_score_mode="top2sum"))
        np.testing.assert_allclose(np.asarray(base(ids)),
                                   np.asarray(model(ids)), rtol=1e-6)

    def test_mtp_training_decreases_both_losses(self):
        """V3 recipe: one jitted step on CE + lambda*MTP; both the main
        CE and the MTP CE must fall when overfitting one batch."""
        import paddle_tpu as pt
        from paddle_tpu.models.deepseek_v2 import (causal_lm_loss,
                                                   deepseek_mtp_loss)
        model = self._model(D=1)
        fn, params = model.functional()
        ids = jnp.asarray(np.random.RandomState(1).randint(0, 256, (2, 16)))
        opt = pt.optimizer.AdamW(learning_rate=3e-3)
        state = opt.init(params)

        @jax.jit
        def step(params, state, i):
            def loss_fn(p):
                logits, mtp = fn(p, ids, return_mtp=True)
                main = causal_lm_loss(logits, ids)
                total = deepseek_mtp_loss(logits, mtp, ids, weight=0.1)
                return total, (main, total - main)
            (_, (main, mtp_part)), g = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            params, state = opt.apply(params, g, state, i)
            return params, state, main, mtp_part

        mains, mtps = [], []
        for i in range(30):
            params, state, main, mtp_part = step(params, state, i)
            mains.append(float(main)); mtps.append(float(mtp_part))
        assert mains[-1] < mains[0] * 0.7, (mains[0], mains[-1])
        assert mtps[-1] < mtps[0] * 0.7, (mtps[0], mtps[-1])
