"""Failure detection (reference: paddle's elastic/fault-tolerant training —
paddle.distributed.elastic, and the NaN/Inf checks in
paddle.amp.debugging / check_numerics).

TPU analogue: jit programs either run or raise — the failure modes that
matter are (1) numeric divergence (NaN/Inf loss or grads) and (2) a hung
step (stuck host callback / preempted TPU). `StepWatchdog` covers both:
a NaN ring-buffer with a divergence threshold, and a wall-clock heartbeat
a monitor thread checks. Auto-resume = Trainer reloads the
latest-complete checkpoint (checkpoint.distributed_ckpt) on restart."""
from __future__ import annotations

import math
import threading
import time
from typing import Callable, Optional


class DivergenceError(RuntimeError):
    pass


class StepWatchdog:
    def __init__(self, nan_patience: int = 3,
                 hang_timeout_s: Optional[float] = None,
                 on_hang: Optional[Callable[[], None]] = None):
        """nan_patience: consecutive non-finite losses tolerated before
        raising DivergenceError (transient fp16 spikes are normal with a
        GradScaler; persistent NaN is divergence)."""
        self.nan_patience = nan_patience
        self._nan_streak = 0
        self._last_beat = time.monotonic()
        self._hang_timeout = hang_timeout_s
        self._on_hang = on_hang
        # hang detection arms on the FIRST beat (= first completed step):
        # the initial step includes jit compilation, which legitimately
        # dwarfs any sane per-step timeout
        self._armed = False
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if hang_timeout_s is not None:
            self._monitor = threading.Thread(target=self._watch, daemon=True)
            self._monitor.start()

    # ------------------------------------------------------------- numeric
    def check_loss(self, loss_value: float, step: int):
        if math.isfinite(loss_value):
            self._nan_streak = 0
        else:
            self._nan_streak += 1
            if self._nan_streak >= self.nan_patience:
                raise DivergenceError(
                    f"loss non-finite for {self._nan_streak} consecutive "
                    f"steps (last step {step}) — stopping; resume from the "
                    f"latest checkpoint with a lower lr / loss scale")
        self.beat()

    def reset_nan(self):
        """Clear the non-finite-loss streak (divergence recovery: the
        Trainer rolled back to a finite checkpoint, so the streak must
        restart from zero, not re-trip on the next spike)."""
        self._nan_streak = 0

    # ------------------------------------------------------------ heartbeat
    def beat(self):
        self._armed = True
        self._last_beat = time.monotonic()

    def seconds_since_beat(self) -> float:
        return time.monotonic() - self._last_beat

    def _watch(self):
        while not self._stop.wait(min(self._hang_timeout / 4, 30.0)):
            if self._armed and self.seconds_since_beat() > self._hang_timeout:
                if self._on_hang is not None:
                    self._on_hang()
                self._last_beat = time.monotonic()  # fire once per hang

    def close(self):
        self._stop.set()
