"""ISSUE 18: cross-replica KV transfer (``serving/kvxfer.py``).

Contracts pinned here:

- WIRE: ``encode_span``/``decode_span`` round-trip the self-describing
  ``KVX1`` record (digest, token count, geometry, crc32 banked before
  the bytes touch the wire) and count ``kv_xfer_{spans,bytes}_total``
  per gateway label.
- LADDER: every decode rung — truncation (short record, cut header,
  payload/nbytes mismatch), unparseable header, geometry skew, crc32
  mismatch — raises :class:`XferError` naming its rung and NEVER
  returns bytes; the checksum rung also counts
  ``kv_xfer_checksum_failures_total``.
- FAULTS: the ``xfer_corrupt`` / ``xfer_trunc`` chaos sites damage the
  record AFTER the crc is banked, exactly like wire bit rot — the
  decode ladder catches both.
- ARENA SEAM: ``export_span`` lifts a record out of one arena,
  ``inject_span`` lands it in a peer's (counted as a hit) where
  ``take`` serves it verbatim; an over-capacity receiver or a
  corrupted blob is a counted fallback that leaves the arena clean.
- MIGRATION: ``spill_live`` + wire + cross-arena restore is bitwise
  (tokens AND logprobs) vs the re-prefill control, token-exact vs the
  uninterrupted stream, and raises ``prefix_hit_tokens`` over the
  control — the survivor restored, it didn't recompute.
- CORRUPTION: a span corrupted in transit never lands and never
  emits — the survivor falls back to re-prefill with the stream still
  exact. A corrupted transfer may cost a prefill, never a token.
- FLEET DRAIN: a mid-stream ``drain(migrate=True)`` on the origin
  ends the proxied stream with a terminal ``migrated`` event the
  frontend INTERCEPTS — no failover charged — and resumes on the
  survivor via ``resume_kv`` with ``spill_restores`` advancing; the
  client sees one uninterrupted greedy stream.
- CHAOS (slow): the ``serve_loadgen --chaos --spill on --migrate on``
  harness — seeded mid-run kills plus the two-gateway drain-migration
  A/B probe — finishes with zero corrupted streams, bitwise A/B
  parity, and a recompute-amplification ratio >= the ISSUE 18 floor
  of 10x (``tools/marker_audit.py`` chaos patterns).
"""
import asyncio

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.generation.paged import PagedEngine
from paddle_tpu.models import LlamaForCausalLM
from paddle_tpu.models.llama import llama_tiny
from paddle_tpu.serving import Gateway
from paddle_tpu.serving.fleet import FleetFrontend, RemoteReplica
from paddle_tpu.serving.kvspill import KVSpillArena
from paddle_tpu.serving import kvxfer
from paddle_tpu.utils import faults

from test_gateway import _engine as _stub_engine
from test_gateway import _load_loadgen, _poll, _sse
from test_kvspill import _chaos_spill_ns


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    return LlamaForCausalLM(llama_tiny())


def _engine(model, arena=None, **kw):
    base = dict(max_slots=2, num_blocks=16, block_size=8,
                max_blocks_per_seq=8, prefill_buckets=(16, 32),
                chunk_prefill_tokens=16, enable_prefix_cache=True)
    base.update(kw)
    eng = PagedEngine(model, **base)
    if arena is not None:
        eng.attach_spill(arena)
    return eng


# =================================================================== wire
GEO = (2, 8, 1, 4, "float32", 16)   # (L, B, kvh, d, dtype, chunk)


def _payload(n_blocks, fill=7.0):
    L, B, kvh, d = GEO[0], GEO[1], GEO[2], GEO[3]
    return np.full((2 * L, n_blocks, B, kvh, d), fill,
                   np.float32).tobytes()


class TestWire:
    def test_roundtrip_and_counters(self):
        pay = _payload(2)
        before = kvxfer.counters_snapshot("u_rt")
        blob = kvxfer.encode_span("ab" * 32, 16, GEO, pay,
                                  gateway="u_rt")
        assert kvxfer.decode_span(blob, GEO) == ("ab" * 32, 16, pay)
        after = kvxfer.counters_snapshot("u_rt")
        assert after["kv_xfer_spans_total"] \
            == before["kv_xfer_spans_total"] + 1
        assert after["kv_xfer_bytes_total"] \
            == before["kv_xfer_bytes_total"] + len(blob)

    def test_decode_ladder_names_every_rung(self):
        pay = _payload(2)
        blob = kvxfer.encode_span("ab" * 32, 16, GEO, pay)
        # short / unmagical record
        with pytest.raises(kvxfer.XferError) as e:
            kvxfer.decode_span(blob[:10], GEO)
        assert e.value.rung == "truncated"
        # record cut inside its header
        with pytest.raises(kvxfer.XferError) as e:
            kvxfer.decode_span(blob[:len(kvxfer.MAGIC) + 6], GEO)
        assert e.value.rung == "truncated"
        # unparseable header json
        bad_hdr = kvxfer.MAGIC + kvxfer._HEAD.pack(5) + b"notjs"
        with pytest.raises(kvxfer.XferError) as e:
            kvxfer.decode_span(bad_hdr, GEO)
        assert e.value.rung == "header"
        # geometry skew (receiver's geometry wins, refused pre-arena)
        with pytest.raises(kvxfer.XferError) as e:
            kvxfer.decode_span(blob, (9,) + GEO[1:])
        assert e.value.rung == "geometry"
        # payload shorter than the header declared
        with pytest.raises(kvxfer.XferError) as e:
            kvxfer.decode_span(blob[:-2], GEO)
        assert e.value.rung == "truncated"
        # one flipped payload byte -> crc32, and the counter advances
        before = kvxfer.counters_snapshot("u_crc")
        flipped = (blob[:-3] + bytes([blob[-3] ^ 0xFF]) + blob[-2:])
        with pytest.raises(kvxfer.XferError) as e:
            kvxfer.decode_span(flipped, GEO, gateway="u_crc")
        assert e.value.rung == "checksum"
        after = kvxfer.counters_snapshot("u_crc")
        assert after["kv_xfer_checksum_failures_total"] \
            == before["kv_xfer_checksum_failures_total"] + 1

    def test_fault_sites_damage_after_crc_banked(self):
        pay = _payload(2)
        with faults.scoped("xfer_corrupt"):
            corrupt = kvxfer.encode_span("cd" * 32, 16, GEO, pay)
        with pytest.raises(kvxfer.XferError) as e:
            kvxfer.decode_span(corrupt, GEO)
        assert e.value.rung == "checksum"
        with faults.scoped("xfer_trunc"):
            cut = kvxfer.encode_span("ef" * 32, 16, GEO, pay)
        with pytest.raises(kvxfer.XferError) as e:
            kvxfer.decode_span(cut, GEO)
        assert e.value.rung == "truncated"


# ============================================================= arena seam
class TestArenaSeam:
    def test_export_inject_peer_roundtrip(self):
        pay = _payload(2)
        a1 = KVSpillArena(1 << 20, name="x_src")
        a2 = KVSpillArena(1 << 20, name="x_dst")
        assert a1.spill([(b"d" * 32, (1, 2))], lambda e: pay, GEO) == 1
        before = kvxfer.counters_snapshot("u_peer")
        blob = kvxfer.export_span(a1, (b"d" * 32).hex(), GEO,
                                  gateway="u_peer")
        assert blob is not None
        got = kvxfer.inject_span(a2, blob, GEO, gateway="u_peer")
        assert got == ((b"d" * 32).hex(), 16)
        assert a2.take(b"d" * 32, GEO) == (pay, 16)
        after = kvxfer.counters_snapshot("u_peer")
        assert after["kv_xfer_hits_total"] \
            == before["kv_xfer_hits_total"] + 1

    def test_export_unknown_digest_is_counted_fallback(self):
        a1 = KVSpillArena(1 << 20, name="x_miss")
        before = kvxfer.counters_snapshot("u_miss")
        assert kvxfer.export_span(a1, "00" * 32, GEO,
                                  gateway="u_miss") is None
        assert kvxfer.export_span(a1, "not-hex", GEO,
                                  gateway="u_miss") is None
        after = kvxfer.counters_snapshot("u_miss")
        assert after["kv_xfer_fallbacks_total"] \
            == before["kv_xfer_fallbacks_total"] + 1

    def test_inject_refusals_leave_arena_clean(self):
        pay = _payload(2)
        a1 = KVSpillArena(1 << 20, name="x_ok")
        assert a1.spill([(b"d" * 32, (1, 2))], lambda e: pay, GEO) == 1
        blob = kvxfer.export_span(a1, (b"d" * 32).hex(), GEO,
                                  gateway="u_ref")
        # over-capacity receiver: counted fallback, nothing stored
        tiny = KVSpillArena(8, name="x_tiny")
        before = kvxfer.counters_snapshot("u_ref")
        assert kvxfer.inject_span(tiny, blob, GEO,
                                  gateway="u_ref") is None
        assert len(tiny) == 0
        # corrupted-in-transit blob: ladder catches it pre-arena
        a2 = KVSpillArena(1 << 20, name="x_dirty")
        flipped = (blob[:-3] + bytes([blob[-3] ^ 0xFF]) + blob[-2:])
        assert kvxfer.inject_span(a2, flipped, GEO,
                                  gateway="u_ref") is None
        assert len(a2) == 0
        after = kvxfer.counters_snapshot("u_ref")
        assert after["kv_xfer_fallbacks_total"] \
            == before["kv_xfer_fallbacks_total"] + 2


# ============================================================== migration
@pytest.fixture(scope="module")
def mig(model):
    """One partial run, spilled live and shipped to a peer arena —
    shared by the parity and corruption pins. ``take`` is
    non-destructive so both tests can export the same record."""
    arena = KVSpillArena(64 << 20, name="mig_src")
    e0 = _engine(model, arena, num_blocks=32)
    rs = np.random.RandomState(7)
    prompt = np.asarray([rs.randint(1, 256, 40)])
    eref = _engine(model, num_blocks=32)
    eref.submit("r", prompt, max_new_tokens=8)
    ref = np.asarray(eref.run()["r"])
    ref_lps = np.asarray(eref.logprobs["r"])
    e0.submit("a", prompt, max_new_tokens=8)
    for _ in range(6):
        e0.step()
    desc = e0.export_resumable()["a"]
    assert e0.spill_live() > 0
    ids = [int(t) for t in desc["prompt"]]
    chain = e0._chunk_digests(ids, len(ids) - 1)
    resident = [c for c in chain if arena.probe(c) is not None]
    assert resident, "spill_live banked no chain digest"
    return dict(arena=arena, geo=e0._spill_geometry(), ids=ids,
                desc=desc, digest=resident[-1].hex(), ref=ref,
                ref_lps=ref_lps)


def _survivor(model, arena, mig):
    """Resume ``mig``'s stream on a fresh engine (the survivor): with
    an arena holding the transferred span it restores, without one it
    re-prefills — the A/B twin."""
    e = _engine(model, arena, num_blocks=32)
    h0 = e.stats.get("prefix_hit_tokens", 0)
    desc = mig["desc"]
    e.submit("b", np.asarray([mig["ids"]]),
             max_new_tokens=desc["remaining"],
             resume_tokens=list(desc["committed"]),
             resume_lps=list(desc["committed_lps"]))
    out = e.run()
    return (e, np.asarray(out["b"]), np.asarray(e.logprobs["b"]),
            e.stats["prefix_hit_tokens"] - h0)


class TestMigration:
    def test_live_span_migrates_bitwise_vs_reprefill_control(
            self, model, mig):
        blob = kvxfer.export_span(mig["arena"], mig["digest"],
                                  mig["geo"], gateway="mig_par")
        assert blob is not None
        peer = KVSpillArena(64 << 20, name="mig_peer")
        assert kvxfer.inject_span(peer, blob, mig["geo"],
                                  gateway="mig_par") is not None
        e_on, on, on_lps, hit_on = _survivor(model, peer, mig)
        e_off, off, off_lps, hit_off = _survivor(model, None, mig)
        # migration-on vs re-prefill control: bitwise, tokens AND lps
        np.testing.assert_array_equal(on, off)
        np.testing.assert_allclose(on_lps, off_lps, rtol=0, atol=0)
        # vs the uninterrupted stream: token-exact, lps to float tol
        # (prefill- vs decode-computed KV differ in the last ulp —
        # the existing resume contract)
        np.testing.assert_array_equal(on, mig["ref"])
        assert np.allclose(on_lps, mig["ref_lps"],
                           rtol=1e-5, atol=1e-6)
        # and the parity came from a RESTORE, not a quiet re-prefill
        assert e_on.stats["spill_restores"] >= 1
        assert e_off.stats["spill_restores"] == 0
        assert hit_on > hit_off

    def test_corrupted_transfer_never_lands_never_emits(
            self, model, mig):
        with faults.scoped("xfer_corrupt"):
            blob = kvxfer.export_span(mig["arena"], mig["digest"],
                                      mig["geo"], gateway="mig_cor")
        assert blob is not None
        peer = KVSpillArena(64 << 20, name="mig_cor_peer")
        assert kvxfer.inject_span(peer, blob, mig["geo"],
                                  gateway="mig_cor") is None
        assert len(peer) == 0
        # the survivor re-prefills off the clean arena and the stream
        # is still exact: a corrupted transfer cost a prefill, never
        # a token
        e, toks, lps, _hits = _survivor(model, peer, mig)
        np.testing.assert_array_equal(toks, mig["ref"])
        assert np.allclose(lps, mig["ref_lps"], rtol=1e-5, atol=1e-6)
        assert e.stats["spill_restores"] == 0
        snap = kvxfer.counters_snapshot("mig_cor")
        assert snap["kv_xfer_fallbacks_total"] >= 1


# ============================================================ fleet drain
def test_fleet_drain_migrates_stream_without_failover():
    """Mid-stream ``drain(migrate=True)`` on the origin: the frontend
    intercepts the terminal ``migrated`` event (no failover charged,
    no breaker), fetches the span over ``/kvz`` inside the drain
    grace, and resumes on the survivor via ``resume_kv`` — the client
    sees one uninterrupted greedy stream and the survivor's engine
    counts a spill restore, not a re-prefill."""
    prompt = list(range(1, 20))
    max_new = 24
    eng = _stub_engine()
    eng.submit("ref", [prompt], max_new_tokens=max_new,
               temperature=0.0)
    eng.run()
    ref_toks = eng.results["ref"]
    ref_lps = eng.logprobs["ref"]

    async def run():
        gws = [Gateway(_stub_engine(), name=f"t-xmg{j}",
                       spill_arena=KVSpillArena(64 << 20,
                                                name=f"xmg{j}"),
                       migrate_on_drain=True)
               for j in range(2)]
        for gw in gws:
            await gw.start()
        reps = [RemoteReplica(gw.name, "127.0.0.1", gw.port,
                              probe_interval_s=0.05) for gw in gws]
        fe = FleetFrontend(reps, chunk_tokens=8, name="t-xmg-fe",
                           migrate=True, breaker_backoff_s=60.0)
        await fe.start()
        assert await _poll(lambda: all(r.healthy() for r in reps), 10)
        drain = {}

        async def on_first():
            target = next(g for g in gws
                          if any(w._live for w in g._workers))
            drain["gw"] = target
            drain["t"] = asyncio.ensure_future(
                target.drain(migrate=True))

        status, _hdr, toks, fin = await _sse(
            fe.port, {"prompt": prompt, "max_new_tokens": max_new,
                      "temperature": 0.0}, on_first=on_first)
        assert status == 200 and drain, "drain never triggered"
        await drain["t"]
        hz = fe.healthz()
        survivor = next(g for g in gws if g is not drain["gw"])
        restores = survivor._workers[0].engine.stats.get(
            "spill_restores", 0)
        xfer = kvxfer.counters_snapshot(drain["gw"].name)
        await fe.drain()
        for gw in gws:
            await gw.drain()
        return toks, fin, hz, restores, xfer

    toks, fin, hz, restores, xfer = asyncio.run(run())
    assert toks == ref_toks
    assert fin["finish_reason"] == "stop"
    assert fin["tokens"] == ref_toks
    assert np.allclose(fin["logprobs"], ref_lps, rtol=1e-5, atol=1e-6)
    assert hz.get("migrated_requests", 0) >= 1
    assert hz["peer_failovers"] == 0, "migration must not count failover"
    assert restores >= 1, "survivor re-prefilled instead of restoring"
    assert xfer["kv_xfer_spans_total"] >= 1
    assert xfer["kv_xfer_checksum_failures_total"] == 0


# ================================================================== chaos
@pytest.mark.slow
@pytest.mark.chaos
def test_migrate_chaos_kill_and_probe_replay_clean():
    """The ISSUE 18 acceptance run: the ISSUE 17 chaos config (3
    replicas, 3 seeded mid-run kills, shared arena) with ``--migrate
    on``, which additionally runs the two-gateway drain-migration A/B
    probe. Gates: chaos replay clean (zero corrupted streams), probe
    bitwise parity migrate vs re-prefill control with zero errors, at
    least one real migration, and the recompute-amplification bound —
    re-prefill burns >= 10x the prefill tokens migration does."""
    slg = _load_loadgen()
    ns = _chaos_spill_ns(migrate="on", migrate_requests=6)
    rung = asyncio.run(slg.run_loadgen(ns))
    ch = rung["chaos"]
    assert ch["corrupted_streams"] == 0, ch
    assert ch["errors_5xx"] == 0, ch
    assert ch["ok"], ch
    assert rung["kv_xfer"]["kv_xfer_checksum_failures_total"] == 0
    mp = rung["migrate_probe"]
    assert mp["ok"], mp
    assert mp["parity_ok"], mp
    assert mp["lps_max_abs_diff"] < 1e-5, mp
    on, off = mp["modes"]["on"], mp["modes"]["off"]
    assert on["migrated"] >= 1, on
    assert on["corrupted_streams"] == 0 and off["corrupted_streams"] == 0
    assert on["restored_tokens"] > 0, on
    assert rung["kv_xfer_hit_frac"] > 0, rung
    assert rung["recompute_tokens_saved"] > 0, rung
    assert rung["recompute_amplification"] >= 10.0, rung
