"""ISSUE 10: request-scoped tracing & SLO attribution across the
serving stack.

Contracts pinned here:

- PROPAGATION: the trace id minted at the gateway (honoring an inbound
  ``X-Request-Id`` header) is the SAME id on the HTTP response, in the
  engine's ring entry (and its ``slot_take``/``engine_finish`` events)
  and on the metric exemplars — one id traverses the whole stack.
- ZERO-COST DEFAULT: tracing-on vs tracing-off gateway streams are
  bitwise identical, and at the engine level a trace sink changes
  neither tokens/logprobs nor the ``dispatch_count``/``h2d_uploads``
  pins — the whole path is host-side bookkeeping.
- TAIL RETENTION: full timelines are kept exactly for slow (ttft >
  slow_ttft_ms, strict), shed, expired, cancelled, disconnected or
  errored requests — a deterministic threshold, not sampling.
- ATTRIBUTION: ``ttft = queue_wait + prefill + first_tick`` (+ the
  accept->enqueue residual), exported as ``request_phase_ms`` labeled
  histograms with exemplar request-ids.
- INTROSPECTION: ``GET /debugz`` exposes the slot map, block pool,
  prefix digests, scheduler queue + tenant debt and ring summaries;
  ``tools/trace_report.py`` joins ring dumps with the loadgen's
  client JSONL.

Heavy many-request sweeps ride behind ``slow``
(``tools/marker_audit.py``).
"""
import asyncio
import importlib.util
import json
import os
import time
import types

import numpy as np
import pytest

from paddle_tpu.generation.paged import PagedEngine
from paddle_tpu.generation.stub import TickStubModel
from paddle_tpu.serving import Gateway, PrefixAffinityRouter
from paddle_tpu.serving.reqtrace import (RequestTrace, RequestTraceRing,
                                         attribution, validate_ring_doc)
from paddle_tpu.utils import observability as obs


def _engine(**kw):
    base = dict(max_slots=4, num_blocks=64, block_size=8,
                max_blocks_per_seq=8, prefill_buckets=(16,),
                chunk_prefill_tokens=8, enable_prefix_cache=True)
    base.update(kw)
    return PagedEngine(TickStubModel(), **base)


# ------------------------------------------------------------- HTTP client
async def _http(port, method, path, body=b"", headers=None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    try:
        h = "".join(f"{k}: {v}\r\n"
                    for k, v in (headers or {}).items())
        writer.write((f"{method} {path} HTTP/1.1\r\nHost: t\r\n{h}"
                      f"Content-Length: {len(body)}\r\n\r\n").encode()
                     + body)
        await writer.drain()
        status = int((await reader.readline()).split()[1])
        hdrs = {}
        while True:
            ln = await reader.readline()
            if ln in (b"\r\n", b"\n", b""):
                break
            k, _, v = ln.decode().partition(":")
            hdrs[k.strip().lower()] = v.strip()
        n = int(hdrs.get("content-length", "0") or 0)
        payload = await reader.readexactly(n) if n else b""
        return status, hdrs, payload
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass


async def _sse(port, payload, headers=None, break_after=None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    body = json.dumps(payload).encode()
    try:
        h = "".join(f"{k}: {v}\r\n"
                    for k, v in (headers or {}).items())
        writer.write((f"POST /v1/generate HTTP/1.1\r\nHost: t\r\n{h}"
                      f"Content-Length: {len(body)}\r\n\r\n").encode()
                     + body)
        await writer.drain()
        status = int((await reader.readline()).split()[1])
        hdrs = {}
        while True:
            ln = await reader.readline()
            if ln in (b"\r\n", b"\n", b""):
                break
            k, _, v = ln.decode().partition(":")
            hdrs[k.strip().lower()] = v.strip()
        if status != 200:
            n = int(hdrs.get("content-length", "0") or 0)
            extra = await reader.readexactly(n) if n else b""
            return status, [], (json.loads(extra) if extra else None)
        toks, final = [], None
        while True:
            ln = await reader.readline()
            if not ln:
                break
            ln = ln.strip()
            if not ln.startswith(b"data: "):
                continue
            ev = json.loads(ln[6:])
            if ev.get("done"):
                final = ev
                break
            toks.append(ev["token"])
            if break_after is not None and len(toks) >= break_after:
                break
        return status, toks, final
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass


async def _poll(cond, timeout=10.0, every=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        await asyncio.sleep(every)
    return False


def _mk_trace(rid, marks, slo="interactive", tenant="t"):
    """Synthetic trace with deterministic event times (ms)."""
    tr = RequestTrace(rid, tenant=tenant, slo=slo)
    for t, kind in marks:
        tr.ev(kind, t_ms=t)
    return tr


# =========================================================== buckets/units
def test_serving_buckets_log_spaced_and_exemplars():
    """Satellite: explicit 1-2-5 log-spaced serving buckets; exemplar
    rides the covering bucket and surfaces as p99_exemplar."""
    b = obs.SERVING_MS_BUCKETS
    assert b == tuple(sorted(b)) and len(set(b)) == len(b)
    # 1-2-5 per decade: every bucket is 2x or 2.5x its predecessor
    for lo, hi in zip(b, b[1:]):
        assert hi / lo in (2.0, 2.5), (lo, hi)
    h = obs.Histogram(buckets=b)
    for _ in range(98):
        h.observe(3.0, exemplar="fast")
    for _ in range(2):
        h.observe(4000.0, exemplar="slowreq")
    s = h.stats()
    assert s["p99_exemplar"] == "slowreq"
    assert s["p50"] == pytest.approx(3.0, abs=2.0)
    # the exposition path is untouched by exemplars
    reg = obs.MetricsRegistry()
    reg.histogram("t_ms", buckets=b, who="x").observe(7.0,
                                                     exemplar="r1")
    text = reg.prometheus_text()
    assert 't_ms_bucket{who="x",le="10"} 1' in text
    assert "r1" not in text       # exemplars stay in-process


def test_ring_tail_retention_deterministic():
    """Tentpole: retention is a deterministic threshold — slow/shed/
    expired/cancelled keep full timelines, fast healthy requests keep
    only the summary. Strictly-greater: ttft == threshold is NOT
    slow."""
    obs.reset()
    ring = RequestTraceRing(capacity=8, slow_ttft_ms=50.0,
                            labels={"gateway": "t-ret",
                                    "replica": "r0"})
    base = [(0.0, "accept"), (0.2, "queue_enter"), (1.0, "slot_take"),
            (2.0, "prefill_done")]
    fast = _mk_trace("fast", base + [(10.0, "first_token")])
    at_thresh = _mk_trace("edge", base + [(50.0, "first_token")])
    slow = _mk_trace("slow", base + [(50.1, "first_token")])
    shed = _mk_trace("shed", [(0.0, "accept"), (0.1, "shed")])
    exp = _mk_trace("exp", [(0.0, "accept"), (0.2, "queue_enter"),
                            (99.0, "queue_expire")])
    ring.finish(fast, "stop", tokens=4)
    ring.finish(at_thresh, "stop", tokens=4)
    ring.finish(slow, "stop", tokens=4)
    ring.finish(shed, "shed")
    ring.finish(exp, "expired")
    by_id = {e["request_id"]: e for e in ring.snapshot()}
    assert not by_id["fast"]["retained"] and not by_id["fast"]["events"]
    assert not by_id["edge"]["retained"]
    assert by_id["slow"]["retained"] and by_id["slow"]["events"]
    assert by_id["shed"]["retained"]
    assert by_id["exp"]["retained"]
    assert by_id["exp"]["queue_wait_ms"] is None   # never took a slot
    s = ring.summary()
    assert s["traced"] == 5 and s["retained"] == 3
    # idempotent: a second finisher (disconnect racing a tick finish)
    # neither double-counts nor appends twice
    assert ring.finish(slow, "disconnect") is None
    assert ring.summary()["traced"] == 5
    obs.reset()


def test_ring_attribution_and_histogram_export():
    """The decomposition is exact on the marks, and lands in labeled
    registry histograms with the request id as the p99 exemplar."""
    obs.reset()
    ring = RequestTraceRing(capacity=8, slow_ttft_ms=1e9,
                            labels={"gateway": "t-att",
                                    "replica": "r0"})
    tr = _mk_trace("rid-1", [(0.0, "accept"), (0.5, "queue_enter"),
                             (10.5, "slot_take"), (40.5, "prefill_done"),
                             (45.5, "first_token")])
    e = ring.finish(tr, "stop", tokens=8, tpot_ms=1.25)
    assert e["queue_wait_ms"] == 10.0
    assert e["prefill_ms"] == 30.0
    assert e["first_tick_ms"] == 5.0
    assert e["ttft_ms"] == 45.5
    assert e["tpot_ms"] == 1.25
    # components telescope: ttft - sum == accept->enqueue residual
    assert e["ttft_ms"] - (e["queue_wait_ms"] + e["prefill_ms"]
                           + e["first_tick_ms"]) == pytest.approx(0.5)
    text = obs.registry().prometheus_text()
    assert 'request_ttft_ms_bucket{gateway="t-att"' in text
    assert 'phase="queue_wait"' in text and 'phase="prefill"' in text \
        and 'phase="first_tick"' in text
    h = obs.registry().histogram("request_ttft_ms", slo="interactive",
                                 gateway="t-att", replica="r0")
    assert h.stats()["p99_exemplar"] == "rid-1"
    obs.reset()


def test_validate_ring_doc_catches_drift(tmp_path):
    obs.reset()
    ring = RequestTraceRing(capacity=4, slow_ttft_ms=0.0,
                            labels={"gateway": "t-val",
                                    "replica": "r0"})
    ring.finish(_mk_trace("a", [(0.0, "accept"),
                                (5.0, "first_token")]), "stop")
    path = ring.dump(str(tmp_path / "reqtrace_t_r0.json"))
    doc = json.load(open(path))
    assert validate_ring_doc(doc) == []
    bad = json.loads(json.dumps(doc))
    bad["entries"][0]["outcome"] = "vanished"
    bad["entries"][0]["events"].append([1.0, "not_a_kind", {}])
    problems = validate_ring_doc(bad)
    assert any("outcome" in p for p in problems)
    assert any("not_a_kind" in p for p in problems)
    assert validate_ring_doc({"schema": "??"})  # wrong schema flagged
    obs.reset()


def test_scheduler_router_trace_events():
    """Unit: queue_enter/leave (with promotion) and route verdicts
    land on the trace."""
    from paddle_tpu.serving import ServeRequest, SLOScheduler
    s = SLOScheduler(max_queue=8, promote_after_ms=10.0,
                     labels={"gateway": "t-sch-ev"})
    tr = RequestTrace("b1", slo="batch")
    req = ServeRequest("b1", [1, 2, 3], {}, slo="batch", trace=tr)
    s.enqueue(req)
    time.sleep(0.03)                       # past promotion age
    pick = s.pop()
    kinds = [k for _, k, _ in tr.events]
    assert kinds == ["queue_enter", "queue_leave"]
    leave = tr.events[1][2]
    assert leave["promoted"] is True and leave["wait_ms"] > 0
    # expiry event
    tr2 = RequestTrace("b2")
    s.enqueue(ServeRequest("b2", [1], {}, trace=tr2,
                           deadline=time.monotonic() - 1))
    assert [r.request_id for r in s.reap()] == ["b2"]
    assert [k for _, k, _ in tr2.events][-1] == "queue_expire"

    class _Rep:
        def __init__(self, name, warm=(), load=0):
            self.name, self._warm, self._load = name, set(warm), load

        def healthy(self):
            return True

        def has_prefix(self, d):
            return d in self._warm

        def load(self):
            return self._load

    r = PrefixAffinityRouter([_Rep("a", warm={"d1"}, load=1),
                              _Rep("b")],
                             labels={"gateway": "t-rt-ev"})
    t_warm = RequestTrace("w")
    assert r.route("d1", trace=t_warm).name == "a"
    assert t_warm.events[0][1] == "route"
    assert t_warm.events[0][2]["verdict"] == "warm"
    assert t_warm.events[0][2]["replica"] == "a"
    t_miss = RequestTrace("m")
    r.route("d9", trace=t_miss)
    assert t_miss.events[0][2]["verdict"] == "miss"


# ============================================================= propagation
def test_request_id_header_propagates_to_engine_ring():
    """Tentpole pin: the client-minted X-Request-Id IS the gateway
    response id AND the engine ring id, and the engine-side events
    (slot_take, engine_finish) recorded under it."""
    async def run():
        gw = Gateway(_engine(), name="t-rid", slow_ttft_ms=0.0)
        await gw.start()
        try:
            body = json.dumps(dict(prompt=list(range(1, 13)),
                                   max_new_tokens=5,
                                   stream=False)).encode()
            st, _, payload = await _http(
                gw.port, "POST", "/v1/generate", body,
                headers={"X-Request-Id": "cli-42"})
        finally:
            await gw.drain()
        return st, json.loads(payload), gw._workers[0].ring.snapshot()

    st, resp, entries = asyncio.run(run())
    assert st == 200 and resp["request_id"] == "cli-42"
    assert [e["request_id"] for e in entries] == ["cli-42"]
    e = entries[0]
    assert e["outcome"] == "stop" and e["retained"]   # slow_ttft 0.0
    kinds = [k for _, k, _ in e["events"]]
    for want in ("accept", "route", "queue_enter", "queue_leave",
                 "engine_queue", "slot_take", "prefill_chunk",
                 "prefill_done", "first_token", "tick",
                 "stream_write", "finish"):
        assert want in kinds, f"missing {want}: {kinds}"
    # lifecycle order (same-thread events)
    assert kinds.index("queue_enter") < kinds.index("slot_take") \
        < kinds.index("prefill_done") < kinds.index("first_token") \
        < kinds.index("finish")
    # attribution: components are non-negative and telescope into ttft
    # (the residual is the gateway's accept->enqueue parse/route time)
    comps = (e["queue_wait_ms"], e["prefill_ms"], e["first_tick_ms"])
    assert all(c is not None and c >= 0 for c in comps)
    resid = e["ttft_ms"] - sum(comps)
    assert 0 <= resid < 1000
    # slot_take carried the prefix-hit count (cold cache: 0)
    st_ev = next(f for _, k, f in e["events"] if k == "slot_take")
    assert st_ev["prefix_hit_tokens"] == 0


def test_tracing_on_off_streams_bit_identical():
    """Acceptance: default-on tracing changes nothing a client can
    see — SSE streams bitwise equal with trace=True vs trace=False."""
    reqs = [dict(prompt=list(range(1, 13)), max_new_tokens=8),
            dict(prompt=[5, 9, 2, 7, 7, 1, 3, 8, 4],
                 max_new_tokens=10, temperature=0.9, top_k=20, seed=7),
            dict(prompt=list(range(40, 52)), max_new_tokens=12,
                 stop=[[0]])]

    async def serve(trace, name):
        gw = Gateway(_engine(), name=name, trace=trace)
        await gw.start()
        try:
            outs = []
            for r in reqs:              # sequential: deterministic
                outs.append(await _sse(gw.port, dict(r, stream=True)))
        finally:
            await gw.drain()
        return outs

    on = asyncio.run(serve(True, "t-tron"))
    off = asyncio.run(serve(False, "t-troff"))
    for (st1, t1, f1), (st2, t2, f2) in zip(on, off):
        assert st1 == st2 == 200
        assert t1 == t2
        assert f1["tokens"] == f2["tokens"]
        assert f1["logprobs"] == f2["logprobs"]


def test_engine_trace_sink_parity_and_dispatch_pin():
    """Engine-level pin: a trace sink changes neither the streams nor
    the steady-tick dispatch/upload counters — tracing is free."""
    def drive(eng):
        eng.submit("a", np.asarray([list(range(1, 13))], np.int32),
                   max_new_tokens=6)
        eng.submit("b", np.asarray([[5, 9, 2, 7, 7, 1, 3]], np.int32),
                   max_new_tokens=8, temperature=0.8, seed=3)
        eng.submit("c", np.asarray([list(range(30, 39))], np.int32),
                   max_new_tokens=5, stop_sequences=[[0]])
        res = eng.run()
        return res, dict(eng.logprobs)

    plain = _engine()
    res0, lps0 = drive(plain)
    events = []
    traced = _engine()
    traced.trace_sink = lambda rid, kind, **f: events.append(
        (rid, kind, f))
    res1, lps1 = drive(traced)
    assert res0 == res1 and lps0 == lps1
    assert traced.dispatch_count == plain.dispatch_count
    assert traced.h2d_uploads == plain.h2d_uploads
    kinds_by_rid = {}
    for rid, kind, _ in events:
        kinds_by_rid.setdefault(rid, []).append(kind)
    for rid in ("a", "b", "c"):
        ks = kinds_by_rid[rid]
        assert "engine_queue" in ks and "slot_take" in ks
        assert "prefill_done" in ks and "engine_finish" in ks
    # per-request tick token counts reconcile with the emitted stream
    # (the first token comes from the prefill, the rest from ticks;
    # "a" has no stop/eos so nothing was trimmed)
    ticks_a = sum(f["n"] for rid, k, f in events
                  if rid == "a" and k == "tick")
    assert ticks_a == len(res1["a"]) - 1


def test_spec_tick_events_carry_proposed_accepted():
    """Speculative ticks report their proposed/accepted split on the
    per-tick event (the ISSUE 10 event-catalog requirement)."""
    events = []
    eng = _engine(spec_tokens=2)
    eng.trace_sink = lambda rid, kind, **f: events.append((kind, f))
    prompt = [1, 2, 3, 4] * 4            # repetitive: drafts accept
    eng.submit("s", np.asarray([prompt], np.int32), max_new_tokens=8)
    res = eng.run()
    ticks = [f for k, f in events if k == "tick"]
    assert ticks and all("proposed" in f and "accepted" in f
                         for f in ticks)
    assert sum(f["n"] for f in ticks) == len(res["s"]) - 1


# ================================================================ outcomes
def test_shed_and_queue_expiry_outcomes_recorded():
    async def run():
        eng = _engine(max_slots=1)
        gw = Gateway(eng, name="t-out", slow_ttft_ms=1e9)
        await gw.start()
        try:
            long = asyncio.ensure_future(_sse(
                gw.port, dict(prompt=list(range(1, 10)),
                              max_new_tokens=50)))
            await _poll(lambda: eng.health()["active_slots"] == 1)
            body = json.dumps(dict(prompt=[4, 5, 6], max_new_tokens=4,
                                   timeout_s=0.05,
                                   stream=False)).encode()
            st, _, payload = await _http(
                gw.port, "POST", "/v1/generate", body,
                headers={"X-Request-Id": "cli-exp"})
            st_long, _, _ = await long
            assert st == 504 and st_long == 200
        finally:
            await gw.drain()
        return gw._workers[0].ring.snapshot()

    entries = asyncio.run(run())
    by_id = {e["request_id"]: e for e in entries}
    exp = by_id["cli-exp"]
    assert exp["outcome"] == "expired" and exp["retained"]
    assert "queue_expire" in [k for _, k, _ in exp["events"]]
    assert exp["queue_wait_ms"] is None    # never reached a slot
    # the long request completed healthily under the huge threshold:
    # summary kept, timeline dropped
    stop = next(e for e in entries if e["outcome"] == "stop")
    assert not stop["retained"] and not stop["events"]

    async def run_shed():
        gw = Gateway(_engine(), name="t-shed", max_queue=0)
        await gw.start()
        try:
            st, _, body = await _sse(
                gw.port, dict(prompt=list(range(1, 10)),
                              max_new_tokens=4, request_id="cli-shed"))
            assert st == 429
        finally:
            await gw.drain()
        return gw._workers[0].ring.snapshot()

    entries = asyncio.run(run_shed())
    shed = {e["request_id"]: e for e in entries}["cli-shed"]
    assert shed["outcome"] == "shed" and shed["retained"]
    assert "shed" in [k for _, k, _ in shed["events"]]


def test_disconnect_outcome_records_engine_abort():
    async def run():
        eng = _engine(max_slots=2)
        gw = Gateway(eng, name="t-dct", slow_ttft_ms=1e9)
        await gw.start()
        try:
            st, toks, _ = await _sse(
                gw.port, dict(prompt=list(range(1, 10)),
                              max_new_tokens=50,
                              request_id="cli-gone"), break_after=2)
            assert st == 200 and len(toks) == 2
            freed = await _poll(
                lambda: eng.health()["active_slots"] == 0)
            assert freed
        finally:
            await gw.drain()
        return gw._workers[0].ring.snapshot()

    entries = asyncio.run(run())
    e = {x["request_id"]: x for x in entries}["cli-gone"]
    assert e["outcome"] == "disconnect" and e["retained"]
    aborts = [f for _, k, f in e["events"] if k == "engine_abort"]
    assert aborts and aborts[0]["reason"] == "cancelled"


# =============================================================== debugz
def test_debugz_schema_and_live_slot_map():
    async def run():
        eng = _engine()
        gw = Gateway(eng, name="t-dbg", slow_ttft_ms=0.0)
        await gw.start()
        try:
            long = asyncio.ensure_future(_sse(
                gw.port, dict(prompt=list(range(1, 10)),
                              max_new_tokens=40,
                              request_id="cli-live")))
            await _poll(lambda: eng.health()["active_slots"] == 1)
            st, _, payload = await _http(gw.port, "GET", "/debugz")
            live = json.loads(payload)
            await long
            # the trace closes on the tick thread a moment after the
            # client sees the done event — wait for it
            await _poll(
                lambda: gw._workers[0].ring.summary()["traced"] == 1)
            st2, _, payload2 = await _http(gw.port, "GET", "/debugz")
            done = json.loads(payload2)
        finally:
            await gw.drain()
        return st, live, st2, done

    st, live, st2, done = asyncio.run(run())
    assert st == 200 and st2 == 200
    for top in ("gateway", "draining", "slow_ttft_ms", "router",
                "replicas"):
        assert top in live
    rep = live["replicas"]["r0"]
    for k in ("healthy", "alive", "load", "engine", "scheduler",
              "trace_ring"):
        assert k in rep
    slot = next(s for s in rep["engine"]["slots"] if s is not None)
    assert slot["request_id"] == "cli-live"
    assert slot["remaining_budget"] <= 40 and slot["blocks"] >= 1
    bp = rep["engine"]["block_pool"]
    assert bp["total"] == 63
    assert bp["free"] + bp["cached_free"] + bp["live"] == bp["total"]
    assert 0 < bp["occupancy_frac"] <= 1
    assert "tenant_debt" in rep["scheduler"]
    assert "queue" in rep["scheduler"]
    assert rep["trace_ring"]["capacity"] == 512
    # after completion the ring summary shows the finished request
    rec = done["replicas"]["r0"]["trace_ring"]["recent"]
    assert any(r["request_id"] == "cli-live" for r in rec)
    assert done["replicas"]["r0"]["engine"]["prefix_cache"]["entries"] \
        >= 1


def test_autoscaler_gauges_scrapeable():
    """Satellite (ROADMAP 2c): engine_free_slots / block_pool_free_frac
    / gateway_queue_depth / gateway_goodput_frac all come from the one
    registry a /metrics scrape serves."""
    async def run():
        eng = _engine()
        gw = Gateway(eng, name="t-scale", slow_ttft_ms=1e9)
        await gw.start()
        try:
            st, _, fin = await _sse(
                gw.port, dict(prompt=list(range(1, 10)),
                              max_new_tokens=6))
            assert st == 200
            # the gauges refresh around ticks: wait for the post-finish
            # tick-loop pass before scraping
            await _poll(lambda: obs.registry().gauge(
                "engine_free_slots", gateway="t-scale",
                replica="r0").value == 4.0)
            _, _, prom = await _http(gw.port, "GET", "/metrics")
        finally:
            await gw.drain()
        return prom.decode()

    prom = asyncio.run(run())

    def val(prefix):
        line = next(ln for ln in prom.splitlines()
                    if ln.startswith(prefix))
        return float(line.split()[-1])

    assert val('engine_free_slots{gateway="t-scale"') == 4.0  # idle
    frac = val('block_pool_free_frac{gateway="t-scale"')
    assert 0.0 < frac <= 1.0
    assert val('gateway_queue_depth{gateway="t-scale"') == 0.0
    assert val('gateway_goodput_frac{gateway="t-scale"') == 1.0
    assert val('gateway_good_tokens_total{gateway="t-scale"') == 6.0
    assert val('request_traces_total{gateway="t-scale"') == 1.0


# ============================================================ trace_report
def _load_tool(name):
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_report_smoke_on_recorded_ring(tmp_path, capsys):
    """Acceptance: trace_report decomposes TTFT per component and per
    SLO class from a recorded ring, and joins the client JSONL."""
    async def run():
        gw = Gateway(_engine(), name="t-rep", slow_ttft_ms=0.0)
        await gw.start()
        try:
            for i, slo in enumerate(("interactive", "interactive",
                                     "batch")):
                st, _, fin = await _sse(
                    gw.port, dict(prompt=list(range(1, 13)),
                                  max_new_tokens=4, slo=slo),
                    headers={"X-Request-Id": f"cli-{i}"})
                assert st == 200 and fin["finish_reason"] == "stop"
        finally:
            # drain first: the tick threads exit only after every
            # in-flight finish (and its trace close) has run
            await gw.drain()
        gw.dump_traces(str(tmp_path))

    asyncio.run(run())
    jsonl = tmp_path / "lg.jsonl"
    with open(jsonl, "w") as f:
        for i, slo in enumerate(("interactive", "interactive",
                                 "batch")):
            f.write(json.dumps({"request_id": f"cli-{i}", "slo": slo,
                                "ttft_ms": 100.0 + i,
                                "outcome": "stop"}) + "\n")
        f.write(json.dumps({"request_id": "cli-lost",
                            "outcome": "conn_error"}) + "\n")
    tr = _load_tool("trace_report")
    docs = tr.load_rings([str(tmp_path)])
    assert len(docs) == 1
    s = tr.summarize(docs, client=tr.load_client_jsonl(str(jsonl)))
    assert s["requests"] == 3 and s["retained"] == 3
    inter = s["classes"]["interactive"]["components"]
    assert inter["ttft_ms"]["n"] == 2
    for comp in ("queue_wait_ms", "prefill_ms", "first_tick_ms"):
        assert inter[comp]["p99"] >= 0 and inter[comp]["n"] == 2
    assert inter["ttft_ms"]["p99_request_id"] in ("cli-0", "cli-1")
    assert "batch" in s["classes"]
    cj = s["client_join"]
    assert cj["matched"] == 3 and cj["client_only"] == 1
    out = tr.render(s)
    assert "class interactive" in out and "queue_wait_ms" in out
    # the CLI end of it
    assert tr.main([str(tmp_path), "--jsonl", str(jsonl),
                    "--json"]) == 0
    got = json.loads(capsys.readouterr().out)
    assert got["client_join"]["matched"] == 3


def _loadgen_ns(**kw):
    base = dict(requests=5, rate=100.0, share_frac=0.5, sys_tokens=8,
                tail_tokens=4, max_new=6, interactive_frac=0.6,
                ttft_slo_ms=5000.0, timeout_s=60.0, tenants=2,
                replicas=1, policy="prefix", max_queue=256,
                model="stub", seed=0, url=None, out="", jsonl="",
                trace_dir="")
    base.update(kw)
    return types.SimpleNamespace(**base)


def test_loadgen_jsonl_joins_server_rings(tmp_path):
    """Acceptance e2e (CPU loadgen run): client JSONL + server rings →
    trace_report matches every completed request and decomposes its
    TTFT."""
    slg = _load_tool("serve_loadgen")
    jsonl = str(tmp_path / "lg.jsonl")
    rings = str(tmp_path / "rings")
    rung = asyncio.run(slg.run_loadgen(_loadgen_ns(
        jsonl=jsonl, trace_dir=rings)))
    assert rung["completed"] == 5 and rung["jsonl"] == jsonl
    recs = [json.loads(ln) for ln in open(jsonl)]
    assert len(recs) == 5
    assert all(r["request_id"].startswith("lg0-") for r in recs)
    assert all(r["slo"] in ("interactive", "batch") for r in recs)
    assert sum(r["outcome"] == "stop" for r in recs) == 5
    tr = _load_tool("trace_report")
    docs = tr.load_rings([rings])
    assert docs, "loadgen wrote no trace rings"
    s = tr.summarize(docs, client=tr.load_client_jsonl(jsonl))
    assert s["client_join"]["matched"] == 5
    for cls in s["classes"].values():
        c = cls["components"]
        assert c["ttft_ms"]["n"] == cls["requests"]
        # server-side ttft telescopes into the three components
        assert c["queue_wait_ms"]["n"] == cls["requests"]
        assert c["prefill_ms"]["n"] == cls["requests"]
        assert c["first_tick_ms"]["n"] == cls["requests"]


@pytest.mark.slow
def test_trace_retention_rate_sweep(tmp_path):
    """Sweep (slow tier): a bounded ring under many requests keeps at
    most ``capacity`` entries, retention stays deterministic (every
    non-stop outcome retained), and the report still joins."""
    slg = _load_tool("serve_loadgen")
    tr = _load_tool("trace_report")
    for rate in (8.0, 200.0):
        obs.reset()
        jsonl = str(tmp_path / f"lg_{rate}.jsonl")
        rings = str(tmp_path / f"rings_{rate}")
        rung = asyncio.run(slg.run_loadgen(_loadgen_ns(
            requests=24, rate=rate, jsonl=jsonl, trace_dir=rings)))
        docs = tr.load_rings([rings])
        entries = [e for d in docs for e in d["entries"]]
        assert len(entries) <= 512
        # 24 measured + the loadgen's untimed warmup request
        assert len(entries) == 25
        for e in entries:
            if e["outcome"] != "stop":
                assert e["retained"], e
            if not e["retained"]:
                assert not e["events"]
        s = tr.summarize(docs, client=tr.load_client_jsonl(jsonl))
        assert s["client_join"]["matched"] == 24
        assert rung["completed"] + rung["shed"] + rung["timeouts"] \
            + rung["conn_errors"] == 24
