"""Closed-loop fleet autoscaling (ISSUE 13 tentpole; reference: the
goodput-per-chip cost framing of the TPU-serving comparison paper in
PAPERS.md — replicas cost chip-seconds whether or not they serve, so
the controller's objective is goodput per replica-second, not raw
queue draining).

:class:`FleetAutoscaler` closes the loop the PR-8 gauges were exported
for: it reads the signal quartet — ``gateway_queue_depth``,
``engine_free_slots``, ``block_pool_free_frac``,
``gateway_goodput_frac`` — off each peer's cached probe snapshot
(:meth:`~.remote.RemoteReplica.signals`; one ``/healthz`` fetch per
peer per probe interval, no new wire protocol) and drives a replica
COUNT through a manager's ``scale_up()``/``scale_down()``:

- **Scale up** when queue depth per replica, slot saturation, block
  pressure, or a sagging goodput fraction stays over threshold for
  ``hold_s`` — sustained pressure, not a one-poll blip.
- **Scale down** when the fleet is demonstrably idle (no queue, load
  under ``down_load_frac``) for ``hold_down_s``.
- **Hysteresis + cooldown** — the up and down thresholds leave a dead
  band between them, both conditions must HOLD for their window, and
  any action opens a ``cooldown_s`` lockout: a diurnal load trace
  scales up the ramp and back down the far side instead of flapping
  at the crest. Spawns in flight count toward the target (a slow
  cold-start must not trigger a second spawn).
- **Windowed signals** (ISSUE 15) — ``signal_mode="windowed"``
  (default) compares thresholds against each pressure signal's MEAN
  over the last ``signal_window_s`` seconds instead of the latest
  probe sample: one noisy tick can neither open a hold window nor
  reset a legitimately-running one, so a spiky trace produces
  strictly fewer scale events (pinned by test) while steady traffic
  decides identically to ``"instant"``, the A/B reference.

Replica processes come and go under the existing SIGTERM-drain
semantics: the manager's ``scale_down`` SIGTERMs a gateway process,
whose ``run_until_shutdown`` latches draining (503 new work, finish
in-flight, flush, exit) — the autoscaler never drops a live stream.

The controller is deliberately synchronous and clock-injectable:
``step(now)`` makes one decision and is what unit tests drive;
``start()`` wraps it in a daemon thread for real fleets. Accounting
(``replica_seconds``, the goodput-per-replica denominator) rides the
same loop.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ...utils import observability as obs

__all__ = ["FleetAutoscaler"]


class FleetAutoscaler:
    """Replica-count controller over a manager.

    ``manager`` duck type: ``replicas()`` -> list of objects with
    ``signals()`` (:class:`~.remote.RemoteReplica` or a test fake),
    ``pending()`` -> spawns in flight, ``scale_up()``,
    ``scale_down()``. The local-process implementation is
    :class:`~.manager.LocalProcessManager`."""

    def __init__(self, manager, *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 up_queue_depth: float = 2.0,
                 up_free_slot_frac: float = 0.125,
                 up_block_free_frac: float = 0.10,
                 goodput_floor: Optional[float] = None,
                 down_load_frac: float = 0.25,
                 hold_s: float = 1.0, hold_down_s: float = 3.0,
                 cooldown_s: float = 5.0,
                 interval_s: float = 0.25,
                 signal_mode: str = "windowed",
                 signal_window_s: float = 2.0,
                 outage_freeze_frac: float = 0.5,
                 migrate_on_scale_down: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        """``signal_mode`` (ISSUE 15): ``"windowed"`` (default) bases
        every pressure comparison on the MEAN of each signal over the
        last ``signal_window_s`` seconds of ``step()`` samples —
        one noisy probe tick can no longer open (or reset) a hold
        window, so a spiky trace scales strictly less than it did on
        instantaneous gauges. ``"instant"`` keeps the single-sample
        decision as the A/B reference (decision parity on steady
        traffic is pinned by test: constant signals make the windowed
        mean equal the instant value). Capacity facts (live/pending
        replica counts, slot totals) always read instant — a scale
        decision must see the fleet it is actually scaling."""
        if signal_mode not in ("windowed", "instant"):
            raise ValueError(f"unknown signal_mode {signal_mode!r}")
        self.signal_mode = signal_mode
        self.signal_window_s = float(signal_window_s)
        self._sig_hist: deque = deque(maxlen=4096)
        self.manager = manager
        self.min_replicas = max(int(min_replicas), 1)
        self.max_replicas = max(int(max_replicas), self.min_replicas)
        self.up_queue_depth = float(up_queue_depth)
        self.up_free_slot_frac = float(up_free_slot_frac)
        self.up_block_free_frac = float(up_block_free_frac)
        self.goodput_floor = goodput_floor
        self.down_load_frac = float(down_load_frac)
        self.hold_s = float(hold_s)
        self.hold_down_s = float(hold_down_s)
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        # correlated mass-outage guard (ISSUE 16): when live peers
        # drop to <= replicas * (1 - frac) the loop FREEZES instead of
        # acting — survivors' low aggregate load during an outage is
        # an artifact of excluded stale signals, and scaling down on
        # it is the classic SRE failure. <= 0 disables the guard.
        self.outage_freeze_frac = float(outage_freeze_frac)
        # planned scale-down is the BEST-case migration trigger
        # (ISSUE 18): the retiring replica is healthy and has the
        # whole drain window to cut live requests over — a manager
        # whose scale_down accepts migrate= gets the flag, others
        # keep their SIGTERM semantics (the gateway's own
        # migrate_on_drain still decides what SIGTERM does)
        self.migrate_on_scale_down = bool(migrate_on_scale_down)
        self._frozen = False
        self._clock = clock
        self._up_since: Optional[float] = None
        self._down_since: Optional[float] = None
        self._last_action: Optional[float] = None
        self._last_t: Optional[float] = None
        self.replica_seconds = 0.0
        self.events: List[Dict[str, Any]] = []
        self._halt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        labels = {"fleet": getattr(manager, "name", "fleet")}
        reg = obs.registry()
        self._g_replicas = reg.gauge("fleet_autoscale_replicas",
                                     **labels)
        self._c_up = reg.counter("fleet_scale_ups_total", **labels)
        self._c_down = reg.counter("fleet_scale_downs_total", **labels)
        self._c_freeze = reg.counter("fleet_autoscale_freezes_total",
                                     **labels)

    # ------------------------------------------------------------- signals
    def aggregate(self) -> Dict[str, Any]:
        """Fold the per-peer signal quartet into the fleet view the
        decision reads. Only HEALTHY peers contribute load numbers —
        a dead peer's stale queue must not hold replicas up."""
        sigs = [r.signals() for r in self.manager.replicas()]
        live = [s for s in sigs if s.get("healthy")]
        n = len(live)
        qd = sum(s["queue_depth"] for s in live)
        free = sum(s["free_slots"] for s in live)
        total = sum(s["total_slots"] for s in live)
        return {
            "replicas": len(sigs),
            "live": n,
            "stale": sum(1 for s in sigs if s.get("stale")),
            "pending": int(self.manager.pending()),
            "queue_depth": qd,
            "queue_depth_per_replica": qd / max(n, 1),
            "free_slots": free,
            "total_slots": total,
            "free_slot_frac": free / total if total else 1.0,
            "load_frac": 1.0 - (free / total) if total else 0.0,
            "block_pool_free_frac": min(
                (s["block_pool_free_frac"] for s in live),
                default=1.0),
            "goodput_frac": min((s["goodput_frac"] for s in live),
                                default=1.0),
        }

    # the pressure signals the windowed mode smooths; capacity facts
    # (replicas/live/pending/free_slots/total_slots) stay instant
    _WINDOWED_FIELDS = ("queue_depth", "queue_depth_per_replica",
                        "free_slot_frac", "load_frac",
                        "block_pool_free_frac", "goodput_frac")

    def _effective(self, agg: Dict[str, Any],
                   now: float) -> Dict[str, Any]:
        """Fold this tick's aggregate into the signal history and
        return the view the decision reads: the instant aggregate in
        ``instant`` mode, the per-field window MEAN in ``windowed``
        mode (ISSUE 15 — the same trajectory-not-point shift the
        /metricsz plane makes, applied to the control loop)."""
        self._sig_hist.append(
            (now, {k: agg[k] for k in self._WINDOWED_FIELDS}))
        lo = now - self.signal_window_s
        while self._sig_hist and self._sig_hist[0][0] < lo:
            self._sig_hist.popleft()
        if self.signal_mode == "instant":
            return agg
        eff = dict(agg)
        n = len(self._sig_hist)
        for k in self._WINDOWED_FIELDS:
            eff[k] = sum(s[1][k] for s in self._sig_hist) / n
        return eff

    # ------------------------------------------------------------ decision
    def step(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One control decision. Returns the (mode-effective)
        aggregate it saw plus the action taken
        (``"up"``/``"down"``/``None``)."""
        now = self._clock() if now is None else now
        agg = self._effective(self.aggregate(), now)
        # replica-seconds accounting: the goodput-per-replica
        # denominator (chip cost proxy — a pending spawn is already
        # paying its cold start, count it)
        if self._last_t is not None:
            self.replica_seconds += \
                (agg["live"] + agg["pending"]) * max(
                    now - self._last_t, 0.0)
        self._last_t = now
        n_eff = agg["live"] + agg["pending"]
        self._g_replicas.set(n_eff)
        action = None
        # mass-outage freeze (ISSUE 16): a majority of peers stale at
        # once is an OUTAGE, not low demand — the survivors' aggregate
        # (stale peers excluded) would read as idle and trigger the
        # classic scale-down-during-the-incident. Freeze every action,
        # fire the alert event, and let recovery (or the operator)
        # thaw the loop; hold windows reset so post-thaw decisions
        # start from honest signals.
        frozen = (self.outage_freeze_frac > 0.0
                  and agg["replicas"] >= 2
                  and agg["live"] <= agg["replicas"]
                  * (1.0 - self.outage_freeze_frac))
        if frozen != self._frozen:
            self._frozen = frozen
            ev = {"t": round(now, 3),
                  "action": "freeze" if frozen else "thaw",
                  "replicas_before": n_eff,
                  "replicas": agg["replicas"], "live": agg["live"],
                  "stale": agg.get("stale", 0)}
            self.events.append(ev)
            obs.record_event("fleet_autoscale_freeze", **ev)
            if frozen:
                self._c_freeze.inc()
        if frozen:
            self._up_since = self._down_since = None
            return dict(agg, action=None, frozen=True)
        pressure_up = (
            agg["live"] > 0
            and (agg["queue_depth_per_replica"] > self.up_queue_depth
                 or agg["free_slot_frac"] <= self.up_free_slot_frac
                 or agg["block_pool_free_frac"]
                 <= self.up_block_free_frac
                 or (self.goodput_floor is not None
                     and agg["goodput_frac"] < self.goodput_floor)))
        pressure_down = (
            agg["queue_depth"] == 0
            and agg["load_frac"] <= self.down_load_frac)
        # hold windows: sustained pressure only (hysteresis lives in
        # the dead band between up_* and down_* thresholds, plus the
        # separate hold windows). Explicit None checks: t=0.0 is a
        # legitimate window-open timestamp under an injected clock.
        if pressure_up:
            if self._up_since is None:
                self._up_since = now
        else:
            self._up_since = None
        if pressure_down:
            if self._down_since is None:
                self._down_since = now
        else:
            self._down_since = None
        cooled = self._last_action is None \
            or now - self._last_action >= self.cooldown_s
        if (self._up_since is not None
                and now - self._up_since >= self.hold_s
                and cooled and n_eff < self.max_replicas):
            self.manager.scale_up()
            self._c_up.inc()
            action = "up"
        elif (self._down_since is not None
                and now - self._down_since >= self.hold_down_s
                and cooled and agg["pending"] == 0
                and agg["live"] > self.min_replicas):
            try:
                self.manager.scale_down(
                    migrate=self.migrate_on_scale_down)
            except TypeError:
                # pre-ISSUE-18 manager duck type: no migrate kwarg
                self.manager.scale_down()
            self._c_down.inc()
            action = "down"
        if action is not None:
            self._last_action = now
            self._up_since = self._down_since = None
            ev = {"t": round(now, 3), "action": action,
                  "replicas_before": n_eff,
                  "signal_mode": self.signal_mode,
                  "migrate": (self.migrate_on_scale_down
                              if action == "down" else None),
                  "queue_depth_per_replica":
                      round(agg["queue_depth_per_replica"], 2),
                  "free_slot_frac": round(agg["free_slot_frac"], 3),
                  "goodput_frac": round(agg["goodput_frac"], 3)}
            self.events.append(ev)
            obs.record_event("fleet_autoscale", **ev)
        return dict(agg, action=action)

    # ------------------------------------------------------------- thread
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._halt.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="fleet-autoscaler")
        self._thread.start()

    def stop(self, timeout: float = 2.0):
        self._halt.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)

    def _loop(self):
        while not self._halt.wait(self.interval_s):
            try:
                self.step()
            except Exception as e:  # control must outlive any bug
                obs.record_event("fleet_autoscale_error", err=repr(e))

    # ------------------------------------------------------------- exports
    def snapshot(self) -> Dict[str, Any]:
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "scale_ups": int(self._c_up.value),
            "scale_downs": int(self._c_down.value),
            "freezes": int(self._c_freeze.value),
            "frozen": self._frozen,
            "outage_freeze_frac": self.outage_freeze_frac,
            "replica_seconds": round(self.replica_seconds, 3),
            "cooldown_s": self.cooldown_s,
            "signal_mode": self.signal_mode,
            "signal_window_s": self.signal_window_s,
            "events": list(self.events[-32:]),
            "aggregate": self.aggregate(),
        }
