"""Samplers (reference: python/paddle/io/dataloader/sampler.py,
batch_sampler.py). DistributedBatchSampler shards the *index space* per dp
rank; on a single-controller TPU runtime the loader usually feeds the global
batch and GSPMD shards it, but per-host sharding is needed for multi-host
input pipelines."""
from __future__ import annotations

import math
from typing import Iterator, Optional, Sequence

import numpy as np


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self) -> Iterator[int]:
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    """In-order indices, with a resumable in-epoch cursor (preemption
    safety: ``state_dict``/``load_state_dict`` restore the exact
    position in O(1) instead of replaying consumed samples)."""

    def __init__(self, data_source=None):
        super().__init__(data_source)
        self._cursor = 0
        self._resume_cursor = 0

    def __iter__(self):
        start, self._resume_cursor = self._resume_cursor, 0
        self._cursor = start
        for i in range(start, len(self.data_source)):
            self._cursor = i + 1
            yield i
        self._cursor = 0

    def state_dict(self):
        return {"cursor": self._cursor}

    def load_state_dict(self, state):
        self._resume_cursor = int(state.get("cursor", 0))
        self._cursor = self._resume_cursor


class RandomSampler(Sampler):
    """Shuffled indices. The per-epoch permutation is a pure function of
    (generator seed, epoch counter): a supplied ``generator`` seed keeps
    the run reproducible while every epoch still gets a *different*
    shuffle (the epoch counter is folded into the seed — a fixed seed
    alone would replay the identical permutation each epoch), and a
    resumed run can rebuild the exact permutation it was preempted in
    from ``state_dict()``'s (epoch, cursor) in O(1)."""

    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator
        self.epoch = 0
        self._active_epoch = None
        self._cursor = 0
        self._resume_cursor = 0

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def _seed_base(self) -> Optional[int]:
        """Int seed base, or None when ``generator`` is a Generator
        OBJECT (torch/paddle-style) whose permutations cannot be
        rebuilt from (seed, epoch)."""
        if self.generator is None:
            return 0
        try:
            return int(self.generator)
        except (TypeError, ValueError):
            return None

    def _epoch_indices(self, epoch: int) -> np.ndarray:
        n = len(self.data_source)
        base = self._seed_base()
        # base None: epochs differ by advancing the generator object's
        # state; pass an int seed instead for exact (epoch,cursor) resume
        rng = np.random.default_rng(self.generator if base is None
                                    else base + epoch)
        if self.replacement:
            return rng.integers(0, n, size=self.num_samples)
        return rng.permutation(n)[:self.num_samples]

    def __iter__(self):
        e = self.epoch
        self.epoch = e + 1          # a fresh __iter__ reshuffles (legacy)
        self._active_epoch = e
        idx = self._epoch_indices(e)
        start, self._resume_cursor = self._resume_cursor, 0
        self._cursor = start
        for i in range(start, len(idx)):
            # advance BEFORE yielding: a state_dict() taken between
            # batches counts the just-delivered sample as consumed
            self._cursor = i + 1
            yield int(idx[i])
        # reset the cursor BEFORE leaving the active epoch: a state_dict
        # snapshot from another thread (prefetch producer) between the
        # two writes must never pair the next epoch with a stale cursor
        self._cursor = 0
        self._active_epoch = None

    def state_dict(self):
        """(epoch, in-epoch cursor) — enough to rebuild the exact
        permutation and position after a preemption. The fallback
        branch returns the live ``_cursor`` (not 0) so a restored-but-
        not-yet-resumed position survives a second preemption that
        lands before the first batch."""
        if self._active_epoch is not None:
            return {"epoch": self._active_epoch, "cursor": self._cursor}
        return {"epoch": self.epoch, "cursor": self._cursor}

    def load_state_dict(self, state):
        self.epoch = int(state.get("epoch", 0))
        cursor = int(state.get("cursor", 0))
        if self._seed_base() is None:
            # the checkpointed permutation is NOT reconstructible from a
            # generator object: resuming mid-permutation would silently
            # skip never-seen samples of a fresh shuffle — restart the
            # epoch instead (full coverage beats exact position)
            cursor = 0
        self._resume_cursor = cursor
        self._active_epoch = None
        self._cursor = self._resume_cursor

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices: Sequence[int], generator=None):
        super().__init__(indices)
        self.indices = list(indices)
        self.generator = generator

    def __iter__(self):
        rng = np.random.default_rng(self.generator)
        yield from (self.indices[i] for i in rng.permutation(len(self.indices)))

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights: Sequence[float], num_samples: int,
                 replacement=True, generator=None):
        super().__init__(None)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement
        self.generator = generator

    def __iter__(self):
        rng = np.random.default_rng(self.generator)
        p = self.weights / self.weights.sum()
        yield from rng.choice(len(self.weights), size=self.num_samples,
                              replace=self.replacement, p=p).tolist()

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler: Optional[Sampler] = None,
                 shuffle=False, batch_size=1, drop_last=False):
        super().__init__(dataset)
        if sampler is None:
            assert dataset is not None
            sampler = RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return math.ceil(n / self.batch_size)

    # -------------------------------------------------- resumable state
    def state_dict(self):
        """Delegates to the wrapped sampler (sample-level cursor; the
        Trainer checkpoints at step == batch boundaries, so the cursor
        is batch-aligned in practice)."""
        if hasattr(self.sampler, "state_dict"):
            return {"sampler": self.sampler.state_dict()}
        return {}

    def load_state_dict(self, state):
        inner = state.get("sampler")
        if inner is not None and hasattr(self.sampler, "load_state_dict"):
            self.sampler.load_state_dict(inner)


class DistributedBatchSampler(BatchSampler):
    """Index-sharded batch sampler (reference:
    python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        import jax
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else jax.process_count()
        self.local_rank = rank if rank is not None else jax.process_index()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks
        # global samples consumed in the current epoch (across ALL ranks;
        # ranks advance in lockstep under SPMD, so local batches * nranks)
        self._consumed = 0
        self._resume_consumed = 0
        self._resume_nranks = self.nranks

    def _epoch_indices(self, nranks: Optional[int] = None):
        """The epoch's GLOBAL index order, padded to an even shard for
        ``nranks`` — identical on every rank and a pure function of the
        epoch seed, so any rank (under any topology) can rebuild the
        stream another topology was consuming."""
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        nranks = self.nranks if nranks is None else nranks
        total = int(math.ceil(n / nranks)) * nranks
        indices += indices[: (total - n)]  # pad to even shards
        return indices

    def __iter__(self):
        consumed, self._resume_consumed = self._resume_consumed, 0
        self._consumed = consumed
        # Resuming mid-epoch (possibly under a DIFFERENT rank count than
        # the checkpoint's): rebuild the stream AS THE SAVING TOPOLOGY
        # PADDED IT, drop the globally-consumed prefix, then re-shard
        # the REMAINING index space over the current ranks (re-padding
        # from the remainder itself, never from consumed samples).
        # Rank-strided sharding makes "consumed" topology-independent —
        # after each lockstep batch the consumed set is exactly a prefix
        # of the global order — so the new shards are non-overlapping
        # and cover precisely the unseen remainder.
        rest = self._epoch_indices(self._resume_nranks
                                   if consumed else None)[consumed:]
        self._resume_nranks = self.nranks
        if rest and len(rest) % self.nranks:
            # cycle the remainder until it divides evenly — the unseen
            # rest can be SMALLER than the pad (epoch-tail resume onto
            # many ranks), and uneven shards would break SPMD lockstep
            pad = self.nranks - len(rest) % self.nranks
            rest = rest + (rest * (-(-pad // len(rest))))[:pad]
        local = rest[self.local_rank::self.nranks]
        batch = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                # advance BEFORE yielding: a state_dict() taken between
                # batches counts the delivered batch as consumed
                consumed += self.batch_size * self.nranks
                self._consumed = consumed
                yield batch
                batch = []
        if batch and not self.drop_last:
            consumed += len(batch) * self.nranks
            self._consumed = consumed
            yield batch
        # epoch completed: advance so the next wrap reshuffles (same
        # identical-shuffle-per-epoch fix as RandomSampler — nothing in
        # the Trainer calls set_epoch, which still overrides explicitly).
        # Reset consumed FIRST: a state_dict snapshot between the two
        # writes must never pair the next epoch with a full-epoch count.
        self._consumed = 0
        self.epoch += 1

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return math.ceil(self.num_samples / self.batch_size)

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        self._consumed = 0
        self._resume_consumed = 0

    # -------------------------------------------------- resumable state
    def state_dict(self):
        """Topology-portable position: (epoch, globally consumed
        samples, saving rank count). ``nranks`` is LOAD-BEARING: the
        saving topology's padding defined the stream the consumed
        counter was measured against, and load_state_dict rebuilds
        exactly that stream before re-sharding the remainder. While a
        restored position is still pending (no __iter__ yet), the
        counter is still measured against the ORIGINAL saving
        topology's stream — report that nranks, not the live one."""
        return {"epoch": self.epoch, "consumed": self._consumed,
                "nranks": self._resume_nranks if self._resume_consumed
                else self.nranks}

    def load_state_dict(self, state):
        self.epoch = int(state.get("epoch", 0))
        self._resume_consumed = int(state.get("consumed", 0))
        self._consumed = self._resume_consumed
        # the SAVING topology's rank count: its padding defined the
        # stream the consumed counter was measured against
        self._resume_nranks = int(state.get("nranks", self.nranks))
