"""Text generation + serving: greedy/sampling decode over the static KV
cache, then the batched serving pipeline.

  python examples/generate.py
  python examples/generate.py --hf /path/to/llama-checkpoint  # real weights
"""
import argparse

import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu.models import LlamaForCausalLM, from_pretrained, llama_tiny


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hf", default=None,
                    help="HF/safetensors checkpoint dir (Llama/Qwen2 family)")
    args = ap.parse_args()

    pt.seed(0)
    if args.hf:
        model = from_pretrained(args.hf)  # real weights + config
    else:
        model = LlamaForCausalLM(llama_tiny(vocab_size=512))

    prompts = jnp.asarray(
        np.random.RandomState(0).randint(0, 256, (2, 16)))
    out = model.generate(prompts, max_new_tokens=32, temperature=0.8,
                         top_p=0.95)
    print("sampled:", np.asarray(out)[:, -8:])

    greedy = model.generate(prompts, max_new_tokens=32, temperature=0.0)
    print("greedy: ", np.asarray(greedy)[:, -8:])

    # speculative decoding: a small draft proposes, the target verifies —
    # identical output to greedy, fewer target forwards. (On a random-init
    # toy model the logits are near-uniform and float-epsilon differences
    # between the decode and verify paths can flip an argmax, so the
    # example reports rather than asserts; tests/test_speculative.py
    # checks exactness on decisive logits.)
    from paddle_tpu.generation import speculative_generate
    pt.seed(1)
    draft = LlamaForCausalLM(llama_tiny(
        vocab_size=model.config.vocab_size, hidden_size=32,
        intermediate_size=64, num_hidden_layers=1))
    out, stats = speculative_generate(model, draft, prompts[:1],
                                      max_new_tokens=32,
                                      num_draft_tokens=4, return_stats=True)
    match = np.array_equal(np.asarray(out), np.asarray(greedy[:1]))
    print(f"speculative: match={match}, {stats['target_forwards']} target "
          f"forwards for 32 tokens")


if __name__ == "__main__":
    main()
