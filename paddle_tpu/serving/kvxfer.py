"""Cross-replica KV transfer wire format (ISSUE 18; ROADMAP item 2b —
reference: Mooncake-style KV movement between serving processes, where
shipping checksummed cache bytes, not recompute, is the cheap currency
— restated over the ISSUE 17 spill arena's integrity contract).

One span on the wire is one self-describing record::

    b"KVX1" | u32 header_len | header json | payload bytes

The header carries the span's chunk-chain digest (the SAME key the
device ``prefix_cache`` and the host :class:`~.kvspill.KVSpillArena`
file it under), its token count, the producing engine's geometry tuple
``(layers, block_size, kv_heads, head_dim, dtype, chunk)``, the
payload byte count and a crc32 banked BEFORE the bytes touch the wire.
The payload is the spill serializer's packed ``(2L, n, B, kvh, d)``
buffer verbatim — :func:`export_span` lifts it straight out of an
arena record and :func:`inject_span` lands it into the receiver's
arena, so a transferred span restores through ``_arena_restore``'s one
batched H2D scatter exactly like a locally spilled one.

**The integrity ladder is the contract** (PR 17's, extended over the
wire). Decode re-walks every rung — magic/truncation, header parse,
geometry skew, byte-count mismatch, crc32 — and ANY failure raises
:class:`XferError`; every caller's handler is the same: count the
fallback and re-prefill. A corrupted transfer may cost a prefill,
never a token: greedy streams are pinned bitwise identical
migration-on vs migration-off on every path.

Chaos sites (``utils/faults.py``): ``xfer_corrupt`` flips one payload
byte AFTER the header crc is banked (wire bit rot — the decode-side
crc must catch it), ``xfer_trunc`` cuts the encoded record short
(severed transfer mid-body). ``xfer_slow`` lives in the gateway's
``/kvz`` handler (the serving side of this module), bounded by the
fetcher's ``xfer_timeout_s``.

Counters (one set per ``gateway`` label, exported like every other
registry metric through ``/metrics`` and ``/metricsz``):
``kv_xfer_{spans,bytes,hits,fallbacks,checksum_failures}_total``.
"""
from __future__ import annotations

import json
import struct
import threading
from typing import Any, Dict, Optional, Tuple

from ..utils import faults
from ..utils import observability as obs

__all__ = ["XferError", "encode_span", "decode_span", "export_span",
           "inject_span", "counters_snapshot"]

MAGIC = b"KVX1"
_HEAD = struct.Struct("<I")

_COUNTER_NAMES = ("spans", "bytes", "hits", "fallbacks",
                  "checksum_failures")
_counters_lock = threading.Lock()
_counters: Dict[tuple, Dict[str, Any]] = {}


def _ctr(gateway: str) -> Dict[str, Any]:
    """The per-gateway ``kv_xfer_*_total`` counter set (memoized —
    the registry dedupes by (name, labels) anyway, this just skips
    the lookup on the hot path)."""
    key = (gateway,)
    with _counters_lock:
        got = _counters.get(key)
        if got is None:
            reg = obs.registry()
            got = {n: reg.counter(f"kv_xfer_{n}_total",
                                  gateway=gateway)
                   for n in _COUNTER_NAMES}
            _counters[key] = got
        return got


def counters_snapshot(gateway: str) -> Dict[str, int]:
    """Current ``kv_xfer_*`` values for one gateway label (what the
    loadgen banks into the serving rung)."""
    return {f"kv_xfer_{n}_total": int(c.value)
            for n, c in _ctr(gateway).items()}


class XferError(ValueError):
    """One failed rung of the wire-decode integrity ladder. ``rung``
    names which: ``truncated`` / ``header`` / ``geometry`` /
    ``checksum``. The only correct handling is the fallback the
    ladder promises — count it and re-prefill."""

    def __init__(self, rung: str, msg: str):
        super().__init__(msg)
        self.rung = rung


def encode_span(digest_hex: str, tokens: int, geometry: tuple,
                payload: bytes, *, gateway: str = "xfer") -> bytes:
    """Pack one span for the wire. The crc is banked over the TRUE
    payload before the chaos sites run, so an injected ``xfer_corrupt``
    flip or ``xfer_trunc`` cut is exactly what silent wire damage looks
    like to the receiver: a record whose ladder fails."""
    payload = bytes(payload)
    import zlib
    hdr = json.dumps({
        "digest": str(digest_hex), "tokens": int(tokens),
        "nbytes": len(payload), "crc": zlib.crc32(payload),
        "geometry": list(geometry),
    }).encode()
    blob = MAGIC + _HEAD.pack(len(hdr)) + hdr + payload
    c = _ctr(gateway)
    c["spans"].inc()
    c["bytes"].inc(len(blob))
    if faults.inject("xfer_corrupt", gateway=gateway,
                     digest=str(digest_hex)[:12]):
        # one payload byte flipped AFTER the crc banked: the decode
        # side must catch it, drop the span, and re-prefill
        pos = len(blob) - max(len(payload) // 2, 1)
        blob = blob[:pos] + bytes([blob[pos] ^ 0xFF]) + blob[pos + 1:]
    if faults.inject("xfer_trunc", gateway=gateway,
                     digest=str(digest_hex)[:12]):
        blob = blob[:len(blob) // 2]     # severed mid-body
    return blob


def decode_span(blob: bytes, geometry: tuple, *,
                gateway: str = "xfer") -> Tuple[str, int, bytes]:
    """Walk the wire-decode ladder; returns ``(digest_hex, tokens,
    payload)`` or raises :class:`XferError` (checksum rungs also count
    ``kv_xfer_checksum_failures_total``). ``geometry`` is the
    RECEIVER's — a span from a skewed engine is refused here, before
    any bytes land in the arena."""
    import zlib
    blob = bytes(blob)
    if len(blob) < len(MAGIC) + _HEAD.size \
            or blob[:len(MAGIC)] != MAGIC:
        raise XferError("truncated", "short or unmagical record")
    (hlen,) = _HEAD.unpack_from(blob, len(MAGIC))
    body = len(MAGIC) + _HEAD.size
    if len(blob) < body + hlen:
        raise XferError("truncated", "record cut inside its header")
    try:
        hdr = json.loads(blob[body:body + hlen])
        digest = str(hdr["digest"])
        tokens = int(hdr["tokens"])
        nbytes = int(hdr["nbytes"])
        crc = int(hdr["crc"])
        geo = tuple(hdr["geometry"])
    except (ValueError, KeyError, TypeError):
        raise XferError("header", "unparseable span header")
    if geo != tuple(tuple(geometry)):
        raise XferError(
            "geometry",
            f"span geometry {geo} != engine geometry "
            f"{tuple(geometry)}")
    payload = blob[body + hlen:]
    if len(payload) != nbytes:
        raise XferError("truncated",
                        f"payload {len(payload)}B != declared "
                        f"{nbytes}B")
    if zlib.crc32(payload) != crc:
        _ctr(gateway)["checksum_failures"].inc()
        raise XferError("checksum", "payload crc32 mismatch")
    return digest, tokens, payload


def export_span(arena, digest_hex: str, geometry: tuple, *,
                gateway: str = "xfer") -> Optional[bytes]:
    """Lift one arena record onto the wire (the ``GET /kvz`` body).
    Rides the arena's own validated ``take`` — a locally bit-rotted
    record is dropped THERE and never shipped. ``None`` when the
    digest isn't restorable (the fetcher falls back to re-prefill)."""
    try:
        raw = bytes.fromhex(digest_hex)
    except ValueError:
        return None
    got = arena.take(raw, tuple(geometry))
    if got is None:
        _ctr(gateway)["fallbacks"].inc()
        return None
    payload, tokens = got
    return encode_span(digest_hex, tokens, geometry, payload,
                       gateway=gateway)


def inject_span(arena, blob: bytes, geometry: tuple, *,
                gateway: str = "xfer") -> Optional[Tuple[str, int]]:
    """Land a wire record in the receiving arena: decode ladder, then
    the arena's own capacity ladder (over-capacity refusal is a
    counted fallback too). Returns ``(digest_hex, tokens)`` on
    success — the span is now restorable by ``_arena_restore`` exactly
    like a local spill — or ``None`` after counting the fallback; the
    caller re-prefills and the stream stays bitwise identical."""
    c = _ctr(gateway)
    try:
        digest_hex, tokens, payload = decode_span(
            blob, geometry, gateway=gateway)
        raw = bytes.fromhex(digest_hex)
    except XferError:
        c["fallbacks"].inc()
        return None
    except ValueError:
        c["fallbacks"].inc()
        return None
    if not arena.put(raw, payload, tokens, tuple(geometry)):
        c["fallbacks"].inc()         # over-capacity refusal
        return None
    c["hits"].inc()
    return digest_hex, tokens
