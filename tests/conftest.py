"""Test config: force an 8-virtual-device CPU platform so mesh/sharding
tests run without TPU hardware (SURVEY.md §4)."""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    flags += " --xla_force_host_platform_device_count=8"
if "xla_backend_optimization_level" not in flags:
    # tests are compile-bound on this image's single CPU core; O0 cuts
    # XLA:CPU compile ~2-3x and every numerics tolerance still holds
    # (fast-math stays off). Production TPU compiles are untouched.
    flags += " --xla_backend_optimization_level=0"
os.environ["XLA_FLAGS"] = flags

import jax

# The axon sitecustomize force-selects the TPU backend via jax.config, so a
# plain JAX_PLATFORMS env var is not enough here.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: many test files compile byte-identical
# tiny-model programs in fresh closures; jit's in-process cache can't
# dedupe those (different callables), the HLO-keyed persistent cache can —
# both within one suite run and across runs/subprocess children.
jax.config.update("jax_compilation_cache_dir", "/tmp/paddle_tpu_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)

import numpy as np
import pytest

# ---------------------------------------------------------------- tiers
# The heavy tier (see pytest.ini): exhaustive variants whose subsystem
# keeps a fast representative in the default run. One central list, not
# per-file markers, so the split stays reviewable.
_HEAVY = (
    # pipeline 1F1B: the tp+dp composition test subsumes these grad-match
    # variants (same machinery, wider mesh)
    "test_1f1b_matches_sequential[4-2]",
    "test_1f1b_single_microbatch",
    "test_trainer_pp_path_runs_and_learns",
    # HF interop: llama logits parity + round-trip stay; the rest are
    # per-family repeats of the same converter machinery
    "test_hf_interop.py::test_llama_greedy_decode_matches",
    "test_hf_interop.py::test_qwen2_logits_match",
    "test_hf_interop.py::test_llama_tied_embeddings",
    "test_hf_interop.py::test_bert_hidden_states_match",
    "test_hf_interop.py::test_bert_pretraining_heads_load",
    "test_hf_interop.py::test_ernie_mlm_logits_match",
    "test_hf_interop.py::test_sharded_index_checkpoint",
    # ring flash: both composition variants are heavy since the round-5
    # pass (see below) — the default tier keeps the plain ring exactness
    # tests (segments/window vs dense) + the flash kernel suite
    "TestRingFlash::test_gradients_flow",
    # elastic: kill/resume (the r2 deliverable) stays; the hang path is a
    # second full subprocess cycle
    "test_hang_checkpoints_exits_and_supervisor_finishes",
    # dataloader: order/speedup/exception stay (each spawn pool costs
    # seconds); these exercise secondary pool semantics
    "test_get_worker_info_and_distribution",
    "test_worker_init_fn_controls_rng",
    "test_persistent_pool_reused",
    "test_consumer_early_break_then_reuse",
    "test_concurrent_iterators_rejected",
    # model zoo: one overfit + one kv-decode parity per backbone family
    # stays (gpt); qwen2/moe/bert/ernie reuse the identical Llama/Bert
    # machinery verified elsewhere
    "test_gpt_forward_and_overfit",
    "test_qwen2_kv_cache_decode_parity",
    "test_qwen2_moe_forward_aux_and_overfit",
    "test_qwen2_moe_kv_cache_decode",
    "test_bert_classifier_overfit",
    # vision/diffusion/pipelines: shape/math smoke stays; grads + image
    # pipelines are compile-heavy conv/attention repeats
    "TestResNet::test_forward_and_grad",
    "TestResNet::test_bottleneck_variant_d",
    "TestCLIP::test_grad_through_both_towers",
    "TestDiT::test_dit_grad",
    "TestDiT::test_mmdit_joint_stream",
    "TestVAE::test_roundtrip_shapes",
    "TestPPOCR::test_svtr_ctc",
    "TestPPOCR::test_dbnet_maps",
    "TestLoopAndLoss::test_diffusion_loss_with_dit",
    "TestDiTPipeline::test_vae_decode_stage",
    "TestDiTPipeline::test_guidance_changes_output",
    "TestSD3Pipeline::test_flow_sampling",
    "TestPredictor::test_quantized_predictor",
    # generation: beam internals stay via beam1==greedy; this reruns
    # the whole beam program (sampling e2e stays default)
    "test_beam_search_beats_greedy_logprob",
    # second-tier variants added after the first timing pass: each line's
    # subsystem keeps the named cheaper representative
    "test_1f1b_matches_sequential[2-1]",   # <- compose_with_tp_dp
    "test_dead_worker_raises_not_hangs",   # <- worker_exception_propagates
    "TestVAE::test_kl_and_loss",           # <- vae sample_stochastic
    "test_text_pipeline.py::test_pipeline_bucket_reuse",  # <- left_padded
    "test_text_pipeline.py::test_pipeline_single_and_batch",
    # decode kernels: keep a diagonal of the parametrized cross-product
    "test_decode_dispatch_matches_dense[5-",
    "test_decode_dispatch_matches_dense[127-",
    "test_decode_dispatch_matches_dense[200-",
    "test_pallas_decode_kernel_matches_dense[100-",
    # trainer/llama: exhaustive repeats of the jitted-step machinery
    "test_grad_accumulation_matches_big_batch",
    # interleaved pipeline: [3] (microbatches % pp != 0, the harder
    # schedule) stays default; [4] and the tp-composition variant rerun
    # the same table machinery the non-interleaved compose test covers
    "test_interleaved_vpp_matches_sequential[4]",
    "test_interleaved_vpp_composes_with_tp",
    # ernie45-moe: forward+grad (incl. dense/MoE layer split) stays; the
    # generate path is the same CausalLMBase while_loop as llama/qwen
    "TestErnie45Moe::test_generate",
    # deepseek-v2: torch parity + absorbed-decode proofs stay; generate
    # rides the shared while_loop machinery
    "TestMLADecode::test_generate_runs",
    # round-4 timing pass: subsystems keep the named cheaper/stronger
    # representative in the default tier
    "test_speedup_4_workers",            # <- order_matches_serial
    "TestCLIP::test_contrastive_roundtrip",  # <- interop clip parity
    "TestPPOCR::test_db_loss",           # <- heavy dbnet_maps/svtr
    "TestResNet::test_feature_pyramid",  # <- vit/resnet interop + heavy
    "test_custom_logits_loss_under_pp",  # <- compose_with_tp_dp (same
    # machinery; the logits_loss hook itself is 5 lines re-verified there)
    "TestDPO::test_sequence_logps_and_precompute",  # <- dpo_trainer test
    "test_packed_fallback_for_models_without_segment_ids",  # <- packing
    "test_round3_flat_ops",              # <- per-op coverage in test_nn
    "test_mtp_module_does_not_shift_trunk_init",  # <- shapes_and_parity
    # round-5 timing pass (suite was 540s standalone; VERDICT r4 item 9):
    # each demotion names the default-tier representative that exercises
    # the same machinery
    "test_interleaved_vpp_matches_sequential[3]",  # <- composes_with_ep_moe
    # (interleaved tables + harder ep composition in one test)
    "TestDeepseekV2Parity::test_logits_match_torch",  # <- v3_logits_match
    # (V3 parity is the superset: same converter/MLA plus sigmoid router)
    "TestRingFlash::test_matches_full_attention",  # <- plain ring
    # exactness tests (segments/window vs dense) + flash kernel suite
    "TestMTP::test_mtp_shapes_and_main_parity",  # <- mtp_training_decreases
    # + TestMTPSpeculative exactness (MTP modules e2e in decode)
    "test_vae_diffusers_roundtrip",     # <- dit/sd3 roundtrips (dispatch)
    "test_model_pass_swaps_and_generates[awq_quantize_model]",  # <- [gptq]
    "test_fuse_attention_only",         # <- full fuse + mesh exactness
)


# The slow tier: tier-1 verify runs `-m 'not slow'` (which, unlike the
# default addopts, INCLUDES heavy) against a hard wall-clock cap — these
# multi-subprocess e2e tests are its biggest line items (~80s combined)
# and each keeps a faster default-tier representative of the same
# machinery:
#   kill/resume e2e        <- test_preemption.py in-process preempt e2e
#                             (sampler-exact resume, a strict superset)
#   hang+supervisor e2e    <- test_supervise_uses_shared_backoff +
#                             preempt free-restart supervisor test
#   nan rollback converges <- test_rollbacks_bounded_then_reraise
_SLOW = (
    "test_kill_mid_run_then_resume_continues_trajectory",
    "test_hang_checkpoints_exits_and_supervisor_finishes",
    "test_nan_window_rolls_back_and_converges",
    # ISSUE 11 tier-budget pass: the tier-1 suite was within one sweep
    # of the 870s cap, so the top duration offenders (compile-bound
    # exhaustive variants, each already in _HEAVY with a named cheaper
    # tier-1 representative of the same machinery) move to the slow
    # tier. Representatives staying in tier-1:
    #   resnet fwd+grad / bottleneck  <- TestResNet::test_feature_pyramid
    #   deepseek-v2 torch parity      <- TestDeepseekV3::v3_logits_match
    #   ring-flash composition pair   <- plain ring exactness + flash suite
    #   clip tower grads              <- TestCLIP::contrastive_roundtrip
    #   mtp shapes+parity             <- mtp_training_decreases + spec e2e
    #   dit diffusion loss            <- TestLoopAndLoss flow/ddpm losses
    #   dataloader worker-info/rng    <- order_matches_serial + exceptions
    #   vae diffusers roundtrip       <- dit/sd3 pipeline roundtrips
    # Enforced by tools/marker_audit.py --check (pattern sync) and
    # --budget-log (per-test wall-clock ceilings).
    "TestResNet::test_forward_and_grad",
    "TestResNet::test_bottleneck_variant_d",
    "TestDeepseekV2Parity::test_logits_match_torch",
    "TestRingFlash::test_matches_full_attention",
    "TestRingFlash::test_gradients_flow",
    "TestCLIP::test_grad_through_both_towers",
    "TestMTP::test_mtp_shapes_and_main_parity",
    "TestLoopAndLoss::test_diffusion_loss_with_dit",
    "test_get_worker_info_and_distribution",
    "test_worker_init_fn_controls_rng",
    "test_vae_diffusers_roundtrip",
)


def pytest_collection_modifyitems(items):
    for item in items:
        if any(key in item.nodeid for key in _HEAVY):
            item.add_marker(pytest.mark.heavy)
        if any(key in item.nodeid for key in _SLOW):
            item.add_marker(pytest.mark.slow)


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as pt
    from paddle_tpu.distributed import env
    pt.seed(0)
    np.random.seed(0)
    yield
    env.clear_mesh()  # tests that install a mesh must not leak it
