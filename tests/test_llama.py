"""Llama end-to-end (SURVEY.md §4): tiny overfit, KV-cache decode parity,
TP-sharded train step on the 8-device mesh."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import env
from paddle_tpu.models import LlamaForCausalLM, causal_lm_loss, llama_tiny
from paddle_tpu.parallel.sharding import shard_layer


@pytest.fixture
def tiny():
    return LlamaForCausalLM(llama_tiny())


def test_forward_shapes(tiny):
    ids = jnp.asarray(np.random.randint(0, 256, (2, 16)))
    logits = tiny(ids)
    assert logits.shape == (2, 16, 256)
    assert logits.dtype == jnp.float32


def test_overfit_tiny(tiny):
    """Memorize one batch: loss must collapse (autograd + model wiring)."""
    ids = jnp.asarray(np.random.randint(0, 256, (4, 32)))
    fn, params = tiny.functional()
    opt = pt.optimizer.AdamW(learning_rate=3e-3)
    state = opt.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, state, n):
        def loss_fn(p):
            return causal_lm_loss(fn(p, ids), ids)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = opt.apply(params, grads, state, n)
        return params, state, loss

    losses = []
    for n in range(60):
        params, state, loss = step(params, state, n)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.3, losses[::10]


def test_kv_cache_decode_matches_full_forward(tiny):
    """Prefill+decode through the cache must reproduce the full-context
    logits (static shapes, lax-friendly)."""
    tiny.eval()
    ids = jnp.asarray(np.random.randint(0, 256, (1, 12)))
    full_logits = tiny(ids)  # [1, 12, v]

    caches = tiny.init_kv_caches(1, 16)
    # prefill first 8 tokens
    logits, caches = tiny(ids[:, :8], kv_caches=caches, cache_index=0)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full_logits[:, :8]),
                               rtol=2e-3, atol=2e-3)
    # decode tokens 8..11 one at a time
    for t in range(8, 12):
        logits, caches = tiny(ids[:, t:t + 1], kv_caches=caches, cache_index=t)
        np.testing.assert_allclose(np.asarray(logits[:, 0]),
                                   np.asarray(full_logits[:, t]),
                                   rtol=2e-3, atol=2e-3)


def test_tp_sharded_train_step():
    """Full train step with tp=4, dp=2: runs, loss finite, params sharded."""
    env.init_parallel_env({"tp": 4, "dp": 2})
    try:
        model = LlamaForCausalLM(llama_tiny())
        shardings = shard_layer(model)
        assert "tp" in str(shardings["model.layers.0.self_attn.q_proj.weight"].spec)
        fn, params = model.functional()
        opt = pt.optimizer.AdamW(learning_rate=1e-3)
        state = opt.init(params)
        ids = jnp.asarray(np.random.randint(0, 256, (4, 32)))

        @jax.jit
        def step(params, state, ids):
            def loss_fn(p):
                return causal_lm_loss(fn(p, ids), ids)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            params, state = opt.apply(params, grads, state, 0)
            return params, state, loss

        params, state, loss = step(params, state, ids)
        assert np.isfinite(float(loss))
        spec = str(params["model.layers.0.self_attn.q_proj.weight"].sharding.spec)
        assert "tp" in spec
    finally:
        env.init_parallel_env({})


def test_recompute_same_loss(tiny):
    ids = jnp.asarray(np.random.randint(0, 256, (2, 16)))
    fn, params = tiny.functional()
    loss_a = float(causal_lm_loss(jax.jit(fn)(params, ids), ids))
    model_r = LlamaForCausalLM(llama_tiny(recompute=True))
    fn_r, _ = model_r.functional()
    loss_b = float(causal_lm_loss(jax.jit(fn_r)(params, ids), ids))
    np.testing.assert_allclose(loss_a, loss_b, rtol=1e-5)


def test_sequence_parallel_matches_dense():
    """Llama with ring attention (sp=4) == same weights without sp."""
    env.init_parallel_env({"sp": 4, "dp": 2})
    try:
        pt.seed(3)
        model = LlamaForCausalLM(llama_tiny(sequence_parallel=True))
        ids = jnp.asarray(np.random.randint(0, 256, (2, 32)))
        fn, params = model.functional()
        out_sp = jax.jit(fn)(params, ids)
        model.config.sequence_parallel = False
        out_dense = jax.jit(fn)(params, ids)
        np.testing.assert_allclose(np.asarray(out_sp), np.asarray(out_dense),
                                   rtol=2e-3, atol=2e-3)
    finally:
        env.init_parallel_env({})


def test_sequence_parallel_packed_window_matches_dense():
    """Packed segments + sliding window now ride the ring path under sp
    (VERDICT r3 weak #4): same weights, sp on vs off, logits equal."""
    env.init_parallel_env({"sp": 4, "dp": 2})
    try:
        pt.seed(5)
        model = LlamaForCausalLM(llama_tiny(sequence_parallel=True,
                                            sliding_window=16))
        ids = jnp.asarray(np.random.randint(0, 256, (2, 32)))
        seg = jnp.asarray(
            np.repeat(np.array([[1, 2, 3, 0]]), 8, axis=1).reshape(1, 32)
            * np.ones((2, 1), np.int32))
        fn, params = model.functional()
        out_sp = jax.jit(fn)(params, ids, segment_ids=seg)
        model.config.sequence_parallel = False
        out_dense = jax.jit(fn)(params, ids, segment_ids=seg)
        np.testing.assert_allclose(np.asarray(out_sp),
                                   np.asarray(out_dense),
                                   rtol=2e-3, atol=2e-3)
    finally:
        env.init_parallel_env({})
