"""Pallas TPU decode attention (reference: PHI
``fusion/gpu/masked_multihead_attention_kernel.cu`` — the single-token
decode kernel; reimagined for TPU).

Autoregressive decode is HBM-bandwidth-bound: each step streams the whole
static KV cache once. The XLA dense path pays h/kv times that traffic for
GQA models because it materializes `jnp.repeat`-ed K/V; this kernel reads
each KV block exactly once per *kv head* and shares it across the whole
query-head group:

- grid (batch, kv_blocks); KV innermost so the fp32 accumulator scratch
  carries the online softmax across blocks. Each K/V block carries the
  FULL trailing (kv, d) dims (always Mosaic-legal, any GQA d) and the kv
  loop is unrolled inside the kernel.
- q is pre-reshaped to [b, kv, group, d] (group = h // kv, padded to the
  8-sublane minimum) — the group dim rides the matmul's M dimension.
- `cache_index` arrives via scalar prefetch: blocks fully past the valid
  length are predicated off with @pl.when (their compute never runs), the
  boundary block masks with an iota compare.

The non-TPU fallback (`ops.attention.decode_attention`) uses the same
grouped einsum layout, so GQA never materializes a repeat on any backend.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
DEFAULT_BLOCK_T = 512


from . import interpret_enabled as _interpret


def pick_block_t(total: int, preferred: int = DEFAULT_BLOCK_T) -> int:
    b = min(preferred, total)
    while b > 128 and total % b:
        b //= 2
    if total % b == 0:
        return b
    # halving can strand on a size that doesn't divide `total` when
    # `preferred` is not a power of two — e.g. the VMEM budget cap's 384
    # rows (kv*d in (1024,1365]: kv=10/d=128, kv=5/d=256, kv=20/d=64)
    # against T=2048 walks 384->192->96 and never hits a divisor. The
    # dispatch gate guarantees T % 128 == 0, so a 128-row tile is always
    # legal; fall back to it instead of reporting "no tile".
    return 128 if total % 128 == 0 else 0


def _decode_kernel(idx_ref, q_ref, k_ref, v_ref, o_ref, acc, m_scr, l_scr, *,
                   scale, block_t, nt, kv, gp, window=None):
    ti = pl.program_id(1)

    @pl.when(ti == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    valid = idx_ref[0] + 1  # positions [0, cache_index] are attendable
    run = ti * block_t < valid
    if window is not None:  # skip blocks fully before the window band
        run &= (ti + 1) * block_t > valid - window

    @pl.when(run)
    def _compute():
        k_ids = lax.broadcasted_iota(jnp.int32, (gp, block_t), 1) \
            + ti * block_t
        keep = k_ids < valid
        if window is not None:  # only the trailing `window` cache slots
            keep &= k_ids >= valid - window
        # static loop over kv heads: the whole [bt, kv, d] block is in
        # VMEM once, each head's group of gp query rows rides the MXU
        for ki in range(kv):
            q = q_ref[0, ki]                        # [gp, d]
            k = k_ref[0, :, ki, :]                  # [bt, d]
            v = v_ref[0, :, ki, :]
            s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
            s = jnp.where(keep, s, NEG_INF)
            m_prev = m_scr[ki, :, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_scr[ki, :, :1] = alpha * l_scr[ki, :, :1] \
                + jnp.sum(p, axis=-1, keepdims=True)
            acc[ki] = acc[ki] * alpha + lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[ki, :, :1] = m_new

    @pl.when(ti == nt - 1)
    def _finalize():
        for ki in range(kv):
            safe_l = jnp.maximum(l_scr[ki, :, :1], 1e-30)
            o_ref[0, ki] = (acc[ki] / safe_l).astype(o_ref.dtype)


def decode_attention_pallas(q, k_cache, v_cache, cache_index, scale,
                            block_t: int = DEFAULT_BLOCK_T, window=None):
    """q [b, h, d]; k/v_cache [b, T, kv, d]; cache_index: scalar int (the
    write position of the current token; positions <= it are valid).
    ``window`` keeps only the trailing window cache slots (sliding-window
    decode). Returns [b, h, d]."""
    b, h, d = q.shape
    _, T, kv, _ = k_cache.shape
    group = h // kv
    gp = max(8, -(-group // 8) * 8)  # round UP to 8-sublane alignment
    # each K/V block is [bt, kv, d] in VMEM: cap it at ~1 MB so MHA-sized
    # kv (32 heads x d=128) stays well inside the ~16 MB/core budget even
    # with Mosaic's double buffering (K + V + fp32 scratch)
    budget_rows = max(128, (1 << 20) // (2 * kv * d) // 128 * 128)
    bt = pick_block_t(T, min(block_t, budget_rows))
    assert bt, f"cache length {T} has no 128-multiple tile"
    nt = T // bt

    qg = q.reshape(b, kv, group, d)
    if gp != group:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - group), (0, 0)))

    idx = jnp.asarray(cache_index, jnp.int32).reshape(1)
    kernel = functools.partial(_decode_kernel, scale=scale, block_t=bt,
                               nt=nt, kv=kv, gp=gp, window=window)
    # Mosaic requires the last TWO block dims be (8,128)-tiled or equal to
    # the array's own dims. Blocking [b, T, kv, d] with FULL trailing
    # (kv, d) dims is therefore always legal (any kv, any d — including
    # d=64 GQA heads), and the T dim (rank -3) is unconstrained. The kv
    # loop moves inside the kernel: every cache element still enters VMEM
    # exactly once per step, shared across the head group.
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, nt),
            in_specs=[
                pl.BlockSpec((1, kv, gp, d), lambda bi, ti, idx: (bi, 0, 0, 0)),
                pl.BlockSpec((1, bt, kv, d), lambda bi, ti, idx: (bi, ti, 0, 0)),
                pl.BlockSpec((1, bt, kv, d), lambda bi, ti, idx: (bi, ti, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, kv, gp, d),
                                   lambda bi, ti, idx: (bi, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((kv, gp, d), jnp.float32),
                pltpu.VMEM((kv, gp, 128), jnp.float32),
                pltpu.VMEM((kv, gp, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kv, gp, d), q.dtype),
        interpret=_interpret(),
    )(idx, qg, k_cache, v_cache)
    return out[:, :, :group, :].reshape(b, h, d)
