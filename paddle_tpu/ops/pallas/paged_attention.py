"""Pallas TPU paged-attention decode kernel (reference: PaddleNLP
block-attention predictor's fused block_multihead_attention kernel;
tiling discipline follows jax's paged_attention_kernel — scalar-prefetched
block tables driving the BlockSpec index map).

The dense fallback in ``generation/paged.py`` gathers the ENTIRE block
table (``kp[block_tables]`` → [R, M, B, kvh, d]) and attends over all
M·B positions every step — O(max_ctx) HBM traffic per row per token
regardless of the actual context. This kernel streams ONLY each row's
live blocks:

- ``block_tables`` [R, M] and ``seq_lens`` [R] ride scalar prefetch
  (SMEM), so the K/V BlockSpec index maps — which run on the scalar core
  ahead of the pipeline — translate (row, logical block) → physical pool
  block per grid step.
- grid (R, kvh, M) with the logical-block dim innermost; the fp32
  accumulator scratch carries the online softmax across a row's blocks.
- steps past a row's live block count are predicated off with
  ``@pl.when`` AND their index map CLAMPS to the last live block: Mosaic
  skips the HBM→VMEM copy when the computed block index repeats, so dead
  blocks cost neither FLOPs nor bandwidth. Sliding windows clamp the
  front the same way.
- GQA rides the matmul M dim: q is viewed [R, kvh, group, d] (group
  padded to the 8-sublane minimum) and each KV block is read once per
  KV head, never per query head.

Pool layout note: the [P, B, kvh, d] pools are viewed [P, B, kvh*d]
(free reshape — contiguous) so the last-two block dims (B, d) satisfy
Mosaic's (8, 128) tiling with the column block selecting the kv head,
the same trick as ``decode_attention.py``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import interpret_enabled as _interpret

NEG_INF = -1e30


def _paged_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  acc, m_scr, l_scr, *, scale, bs, nm, gp, window):
    r = pl.program_id(0)
    ti = pl.program_id(2)

    @pl.when(ti == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    valid = len_ref[r] + 1          # tokens [0, seq_len] attendable
    run = ti * bs < valid
    if window is not None:          # skip blocks fully before the band
        run &= (ti + 1) * bs > valid - window

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0, :, :]                        # [gp, d]
        k = k_ref[0, :, :]                           # [bs, d]
        v = v_ref[0, :, :]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        k_ids = lax.broadcasted_iota(jnp.int32, (gp, bs), 1) + ti * bs
        keep = k_ids < valid
        if window is not None:
            keep &= k_ids >= valid - window
        s = jnp.where(keep, s, NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:, :1] = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1,
                                                      keepdims=True)
        acc[:] = acc[:] * alpha + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:, :1] = m_new

    @pl.when(ti == nm - 1)
    def _finalize():
        safe_l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0, :, :] = (acc[:] / safe_l).astype(o_ref.dtype)


def paged_attention_pallas(q, kp, vp, block_tables, seq_lens, scale,
                           window=None):
    """q [R, h, d]; kp/vp [P, B, kvh, d] physical pools;
    block_tables [R, M]; seq_lens [R] (position written this step —
    tokens 0..seq_lens[r] attend). Returns [R, h, d]."""
    R, h, d = q.shape
    P, B, kvh, _ = kp.shape
    M = block_tables.shape[1]
    group = h // kvh
    gp = max(8, -(-group // 8) * 8)

    qg = q.reshape(R, kvh, group, d)
    if gp != group:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - group), (0, 0)))

    tbl = jnp.asarray(block_tables, jnp.int32)
    lens = jnp.asarray(seq_lens, jnp.int32)

    def kv_index(r, ki, ti, tbl, lens):
        # clamp dead steps to the last live block (and pre-window steps
        # to the first in-band block): a repeated index skips the copy
        valid = lens[r] + 1
        last = jnp.maximum(lax.div(valid + B - 1, B) - 1, 0)
        lo = 0 if window is None else lax.div(
            jnp.maximum(valid - window, 0), B)
        i_eff = jnp.clip(ti, lo, last)
        return (tbl[r, i_eff], 0, ki)

    kernel = functools.partial(_paged_kernel, scale=scale, bs=B, nm=M,
                               gp=gp, window=window)
    kc = kp.reshape(P, B, kvh * d)
    vc = vp.reshape(P, B, kvh * d)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(R, kvh, M),
            in_specs=[
                pl.BlockSpec((1, 1, gp, d),
                             lambda r, ki, ti, tbl, lens: (r, ki, 0, 0)),
                pl.BlockSpec((1, B, d), kv_index),
                pl.BlockSpec((1, B, d), kv_index),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, gp, d), lambda r, ki, ti, tbl, lens: (r, ki, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((gp, d), jnp.float32),
                pltpu.VMEM((gp, 128), jnp.float32),
                pltpu.VMEM((gp, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((R, kvh, gp, d), q.dtype),
        interpret=_interpret(),
    )(tbl, lens, qg, kc, vc)
    return out[:, :, :group, :].reshape(R, h, d)


def use_paged_kernel(q, kp) -> bool:
    """Same gating policy as the other kernels: TPU backend (or interpret
    mode so CI drives the dispatch glue), MXU-friendly head_dim, whole
    query-head groups, 8-sublane-aligned block_size. ``s > 1`` (the
    speculative verify's multi-query rows, ISSUE 7) is gated the same
    way — only the ragged kernel serves it; the grid-per-row kernel
    stays single-query (its caller falls back to dense)."""
    from . import interpret_enabled, kernels_enabled
    R, s, h, d = q.shape
    B, kvh = kp.shape[1], kp.shape[2]
    if h % kvh:
        return False
    if not kernels_enabled():
        return False
    if interpret_enabled():
        return True
    return d in (64, 128, 256) and B % 8 == 0 and (
        d % 128 == 0 or kvh == 1)
