"""LLM fine-tuning losses and trainers (reference: PaddleNLP
paddlenlp/trl — SFTTrainer/DPOTrainer and llm/ alignment recipes).

TPU-native stance: both recipes are ordinary jitted train steps over the
existing Trainer; what this module adds is the loss algebra and the batch
conventions:

- SFT: causal LM cross-entropy masked to the RESPONSE tokens only
  (prompt tokens contribute no gradient). Batches are dicts of static-
  shape arrays (``input_ids`` [b, s], ``loss_mask`` [b, s]) — right-
  padded, so one compiled step serves every batch.
- DPO: the Bradley-Terry preference loss on (chosen, rejected) pairs.
  Reference log-probs are PRECOMPUTED (``compute_sequence_logps`` with
  the frozen reference params) and carried in the batch — the jitted
  policy step then needs no second model in the program, which on TPU
  means no duplicated weights in HBM and no constant-folding a whole
  reference model into the executable.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .trainer import Trainer, TrainingArguments

__all__ = [
    "sft_loss", "sequence_logps", "compute_sequence_logps", "dpo_loss",
    "DataCollatorForSFT", "SFTTrainer", "make_dpo_loss_fn", "DPOTrainer",
]


def _token_logps(logits, input_ids, loss_mask):
    """Shifted next-token log-probs at the masked positions: [b, s-1]."""
    lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(lp, input_ids[:, 1:, None], axis=-1)[..., 0]
    return tgt * loss_mask[:, 1:].astype(jnp.float32)


def sft_loss(logits, input_ids, loss_mask):
    """Next-token CE on positions where loss_mask[t+1] == 1 (the response;
    reference: PaddleNLP SFT recipes' masked cross-entropy)."""
    tok = _token_logps(logits, input_ids, loss_mask)
    n = jnp.maximum(loss_mask[:, 1:].sum().astype(jnp.float32), 1.0)
    return -tok.sum() / n


def sequence_logps(logits, input_ids, loss_mask):
    """Per-sequence sum log-prob of the masked (response) tokens."""
    return _token_logps(logits, input_ids, loss_mask).sum(axis=-1)


def compute_sequence_logps(model, input_ids, loss_mask, batch_size: int = 8):
    """Run a (frozen reference) model over sequences and return summed
    response log-probs — the precompute step of the DPO recipe. The model
    is traced in EVAL mode (dropout off): a reference model in train mode
    would either crash on an un-keyed next_key() under tracing or bias
    the reference logps with dropout noise."""
    was_training = model.training
    model.eval()
    try:
        fn, params = model.functional()
        jf = jax.jit(lambda p, ids, m: sequence_logps(fn(p, ids), ids, m))
        outs = []
        for i in range(0, input_ids.shape[0], batch_size):
            outs.append(jf(params, input_ids[i:i + batch_size],
                           loss_mask[i:i + batch_size]))
    finally:
        if was_training:
            model.train()
    return jnp.concatenate(outs)


def dpo_loss(policy_chosen_logps, policy_rejected_logps,
             reference_chosen_logps, reference_rejected_logps,
             beta: float = 0.1, label_smoothing: float = 0.0):
    """Direct Preference Optimization (reference: PaddleNLP DPOTrainer;
    Rafailov et al. 2023). Returns (loss, chosen_rewards, rejected_rewards)
    — the rewards are the implicit ones, for logging margin/accuracy."""
    chosen_rel = policy_chosen_logps - reference_chosen_logps
    rejected_rel = policy_rejected_logps - reference_rejected_logps
    logits = beta * (chosen_rel - rejected_rel)
    loss = (-jax.nn.log_sigmoid(logits) * (1 - label_smoothing)
            - jax.nn.log_sigmoid(-logits) * label_smoothing).mean()
    return loss, beta * chosen_rel, beta * rejected_rel


class DataCollatorForSFT:
    """prompt/response token lists -> right-padded static-shape batches
    {"input_ids": [b, max_len], "loss_mask": [b, max_len]} (reference:
    PaddleNLP llm/ SFT data pipeline). Static shapes = one compile."""

    def __init__(self, max_length: int, pad_token_id: int = 0,
                 mask_prompt: bool = True):
        self.max_length = max_length
        self.pad_token_id = pad_token_id
        self.mask_prompt = mask_prompt

    def __call__(self, examples) -> Dict[str, jnp.ndarray]:
        L = self.max_length
        ids = np.full((len(examples), L), self.pad_token_id, np.int32)
        mask = np.zeros((len(examples), L), np.int32)
        for i, ex in enumerate(examples):
            prompt = list(ex["prompt_ids"])
            resp = list(ex["response_ids"])
            seq = (prompt + resp)[:L]
            ids[i, :len(seq)] = seq
            start = min(len(prompt), L) if self.mask_prompt else 0
            mask[i, start:len(seq)] = 1
        return {"input_ids": jnp.asarray(ids), "loss_mask": jnp.asarray(mask)}


class SFTTrainer(Trainer):
    """Trainer preconfigured with the masked SFT loss over dict batches
    (reference: paddlenlp.trl.SFTTrainer)."""

    def __init__(self, model, optimizer, args: Optional[TrainingArguments]
                 = None, **kw):
        kw.setdefault("loss_fn", lambda fn, p, batch: sft_loss(
            fn(p, batch["input_ids"]), batch["input_ids"],
            batch["loss_mask"]))
        super().__init__(model, optimizer, args, **kw)


def make_dpo_loss_fn(beta: float = 0.1, label_smoothing: float = 0.0
                     ) -> Callable:
    """Trainer loss_fn for DPO batches: {"chosen_ids", "chosen_mask",
    "rejected_ids", "rejected_mask", "ref_chosen_logps",
    "ref_rejected_logps"} (reference logps precomputed)."""

    def loss_fn(fn, p, batch):
        # concatenated forward (the standard DPO trick): one [2b, s] pass
        # instead of two [b, s] passes — same math, better TPU utilization
        b = batch["chosen_ids"].shape[0]
        ids = jnp.concatenate([batch["chosen_ids"], batch["rejected_ids"]])
        mask = jnp.concatenate([batch["chosen_mask"],
                                batch["rejected_mask"]])
        logps = sequence_logps(fn(p, ids), ids, mask)
        loss, _, _ = dpo_loss(logps[:b], logps[b:],
                              batch["ref_chosen_logps"],
                              batch["ref_rejected_logps"], beta,
                              label_smoothing)
        return loss

    return loss_fn


class DPOTrainer(Trainer):
    """Trainer preconfigured with the DPO preference loss (reference:
    paddlenlp.trl.DPOTrainer). Precompute the reference logps with
    ``compute_sequence_logps(ref_model, ...)`` into the batches."""

    def __init__(self, model, optimizer, args: Optional[TrainingArguments]
                 = None, beta: float = 0.1, label_smoothing: float = 0.0,
                 **kw):
        kw.setdefault("loss_fn", make_dpo_loss_fn(beta, label_smoothing))
        super().__init__(model, optimizer, args, **kw)
