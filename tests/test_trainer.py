"""Trainer (C25) + distributed ckpt (C14) + watchdog (C20) + logging (C21)."""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.trainer import Trainer, TrainingArguments
from paddle_tpu.utils.watchdog import DivergenceError, StepWatchdog


def _loader(n_batches=8, b=4, s=16, vocab=256, seed=0):
    rng = np.random.RandomState(seed)
    data = [jnp.asarray(rng.randint(0, vocab, (b, s))) for _ in range(n_batches)]
    return data


def test_trainer_overfits(tmp_path):
    model = LlamaForCausalLM(llama_tiny())
    opt = pt.optimizer.AdamW(learning_rate=3e-3)
    args = TrainingArguments(output_dir=str(tmp_path), max_steps=40,
                             logging_steps=5, resume_from_checkpoint=False)
    batches = _loader(n_batches=1)  # memorize one batch
    tr = Trainer(model, opt, args, train_dataloader=batches)
    tr.train()
    hist = tr.logger.history["loss"]
    assert hist[-1][1] < hist[0][1] * 0.5
    # metrics jsonl written
    lines = open(tr.logger.path).read().strip().splitlines()
    assert all("tag" in json.loads(l) for l in lines)


def test_grad_accumulation_matches_big_batch(tmp_path):
    """accum=4 over micro-batches == one batch of 4x size (same grads)."""
    pt.seed(5)
    model = LlamaForCausalLM(llama_tiny())
    init_sd = {k: np.asarray(v) for k, v in model.state_dict().items()}
    batch = jnp.asarray(np.random.RandomState(1).randint(0, 256, (8, 16)))

    def run(accum):
        model.set_state_dict(init_sd)  # same starting point for both runs
        opt = pt.optimizer.SGD(learning_rate=0.1)
        args = TrainingArguments(output_dir=str(tmp_path), max_steps=1,
                                 gradient_accumulation_steps=accum,
                                 logging_steps=1, resume_from_checkpoint=False)
        tr = Trainer(model, opt, args, train_dataloader=[batch])
        tr.train()
        # snapshot: the next run donates (deletes) these buffers
        return {k: np.asarray(v) for k, v in tr._params.items()}

    p1 = run(1)
    p4 = run(4)
    for k in p1:
        np.testing.assert_allclose(p1[k], p4[k], rtol=1e-4, atol=1e-5)


def test_checkpoint_save_resume(tmp_path):
    model = LlamaForCausalLM(llama_tiny())
    opt = pt.optimizer.AdamW(learning_rate=1e-3)
    args = TrainingArguments(output_dir=str(tmp_path), max_steps=10,
                             save_steps=5, logging_steps=5)
    batches = _loader()
    tr = Trainer(model, opt, args, train_dataloader=batches)
    tr.train()
    tr.save_checkpoint(wait=True)
    params_end = {k: np.asarray(v) for k, v in tr._params.items()}

    # fresh trainer resumes from step 10
    model2 = LlamaForCausalLM(llama_tiny())
    tr2 = Trainer(model2, pt.optimizer.AdamW(learning_rate=1e-3), args,
                  train_dataloader=batches)
    tr2._opt_state = tr2.optimizer.init(tr2._params)
    tr2._try_resume()
    assert tr2.global_step == 10
    for k in params_end:
        np.testing.assert_array_equal(params_end[k], np.asarray(tr2._params[k]))


def test_watchdog_divergence():
    wd = StepWatchdog(nan_patience=2)
    wd.check_loss(1.0, 0)
    wd.check_loss(float("nan"), 1)
    with pytest.raises(DivergenceError):
        wd.check_loss(float("inf"), 2)
    # recovery resets the streak
    wd2 = StepWatchdog(nan_patience=2)
    wd2.check_loss(float("nan"), 0)
    wd2.check_loss(1.0, 1)
    wd2.check_loss(float("nan"), 2)  # streak 1 again: no raise


def test_step_timer_mfu():
    from paddle_tpu.utils.profiler import StepTimer
    t = StepTimer(flops_per_token=1e9, peak_flops=1e12)
    t.start()
    import time as _t
    _t.sleep(0.01)
    t.stop(tokens=1000)
    assert 0 < t.mfu < 120  # sanity: mfu = 1e12*tok_rate/1e12
    assert t.tokens_per_sec > 0


def test_launch_local_mode(tmp_path):
    """init_distributed on a single host is a no-op that still reports
    topology; launch() runs a script in-process with argv wired."""
    from paddle_tpu.distributed.launch import init_distributed, launch
    info = init_distributed()
    assert info["process_count"] == 1 and info["global_devices"] >= 1
    script = tmp_path / "train.py"
    script.write_text("import sys, json, pathlib\n"
                      "pathlib.Path(sys.argv[1]).write_text('ran')\n")
    out = tmp_path / "out.txt"
    assert launch([str(script), str(out)]) == 0
    assert out.read_text() == "ran"
