"""ISSUE 15: fleet telemetry plane — time-series sampler, SLO
burn-rate alerting, federated live metrics, windowed autoscaling.

Contracts pinned here:

- SAMPLER MATH: counter rates, gauge window means and TRUE windowed
  histogram quantiles derived from the sampled rings are pinned to
  exact values under an injected clock; rings obey the hard capacity
  bound; a sampler restart begins from zero; ``observability.reset()``
  stops the thread and flushes ``series_<name>.json``.
- OFF THE HOT PATH: greedy SSE streams are BITWISE identical with the
  sampler + alerting on vs off, and the steady-tick
  1-dispatch/0-upload/0-byte engine pins hold with a sampler thread
  running — the plane is provably pull-only.
- BURN-RATE RULES: fire requires BOTH windows over threshold, resolve
  takes hysteresis (no flap in the dead band), windows scale linearly
  with the knob, alerts land in the flight recorder and the
  ``slo_burn_rate{class=,window=}`` gauges.
- FEDERATION: a frontend folds N peers' cached ``/metricsz`` docs
  into one fleet view with per-replica sections and totals; a stale
  peer is excluded from totals (same bound routing uses).
- WINDOWED AUTOSCALING: decision parity with instant mode on steady
  traffic; strictly fewer scale events on a seeded noisy trace.

Sweeps (multi-window burn matrix), the multi-PROCESS federation e2e
and the chaos-alert loadgen e2e ride behind ``slow`` (see
``tools/marker_audit.py``).
"""
import asyncio
import importlib.util
import json
import os
import random
import threading
import time
import types

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.generation.paged import PagedEngine
from paddle_tpu.generation.stub import TickStubModel
from paddle_tpu.serving import BurnRateEngine, BurnRule, Gateway
from paddle_tpu.serving.fleet.autoscaler import FleetAutoscaler
from paddle_tpu.serving.fleet.remote import RemoteReplica
from paddle_tpu.utils import observability as obs


def _engine(**kw):
    base = dict(max_slots=4, num_blocks=64, block_size=8,
                max_blocks_per_seq=8, prefill_buckets=(16,),
                chunk_prefill_tokens=8, enable_prefix_cache=True)
    base.update(kw)
    return PagedEngine(TickStubModel(), **base)


# ------------------------------------------------------------- HTTP client
async def _http(port, method, path, body=b""):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    try:
        writer.write((f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
                      f"Content-Length: {len(body)}\r\n\r\n").encode()
                     + body)
        await writer.drain()
        status = int((await reader.readline()).split()[1])
        headers = {}
        while True:
            ln = await reader.readline()
            if ln in (b"\r\n", b"\n", b""):
                break
            k, _, v = ln.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        n = int(headers.get("content-length", "0") or 0)
        payload = await reader.readexactly(n) if n else b""
        return status, payload
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass


async def _sse_raw(port, payload):
    """One SSE request, returning the RAW response bytes (status line,
    headers, every event) — what the bitwise sampler-on/off pin
    compares."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    body = json.dumps(payload).encode()
    try:
        writer.write((f"POST /v1/generate HTTP/1.1\r\nHost: t\r\n"
                      f"Content-Length: {len(body)}\r\n\r\n").encode()
                     + body)
        await writer.drain()
        return await reader.read()
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass


# ================================================================ sampler
class TestTimeSeries:
    def test_ring_bound_kinds_and_restart_from_zero(self):
        reg = obs.MetricsRegistry()
        c = reg.counter("c_total")
        reg.gauge("g")
        reg.histogram("h_ms", buckets=(1, 2, 5))
        clk = [0.0]
        ts = obs.MetricsTimeSeries(name="t", registry=reg,
                                   capacity=4, clock=lambda: clk[0])
        for i in range(7):
            clk[0] = float(i)
            c.inc()
            ts.sample()
        assert ts.samples_taken == 7
        assert len(ts.series("c_total")) == 4       # hard ring bound
        assert sorted(ts.names()) == ["c_total", "g", "h_ms"]
        # histogram samples carry the cumulative bucket vector
        t, cnt, total, counts = ts.series("h_ms")[-1]
        assert cnt == 0 and len(counts) == 4        # 3 buckets + Inf
        # a restart begins from zero (the supervise() isolation
        # contract, mirrored)
        ts.start()
        assert ts.samples_taken == 0 and ts.names() == []
        ts.stop()

    def test_windowed_rates_means_and_quantiles_pinned(self):
        reg = obs.MetricsRegistry()
        c = reg.counter("toks_total")
        g = reg.gauge("queue")
        h = reg.histogram("lat_ms", buckets=(1, 2, 5))
        clk = [0.0]
        ts = obs.MetricsTimeSeries(name="t", registry=reg,
                                   capacity=64, clock=lambda: clk[0])
        for i in range(6):
            clk[0] = float(i)
            c.inc(5)
            g.set(i)
            # era split: old observations land in bucket (2, 5],
            # recent ones in (1, 2] — the windowed quantile must see
            # ONLY the recent era
            h.observe(4.0 if i < 3 else 1.5)
            ts.sample()
        # lo=2.5: baseline = the last sample before it (t=2, the 4.0
        # era's close), in-window samples t=3,4,5 — exactly the 1.5 era
        w = ts.window(2.5, now=5.0)
        # counter: (30-15)/(5-2) = 5/s exactly
        assert w["toks_total"]["rate_per_s"] == 5.0
        assert w["toks_total"]["delta"] == 15.0
        assert w["queue"]["mean"] == 4.0            # (3+4+5)/3
        assert w["queue"]["last"] == 5.0
        # histogram: 3 recent observations of 1.5 -> p50 interpolates
        # to exactly 1.5 inside the (1, 2] bucket; the old 4.0s are
        # OUTSIDE the window and must not leak in
        assert w["lat_ms"]["count"] == 3
        assert w["lat_ms"]["p50"] == 1.5
        assert w["lat_ms"]["mean"] == 1.5
        # whole-history window: the baseline is the FIRST sample, so
        # a delta-of-cumulative view integrates the 5 deltas after it
        # (two 4.0s + three 1.5s)
        w_all = ts.window(100.0, now=5.0)
        assert w_all["lat_ms"]["count"] == 5
        assert w_all["lat_ms"]["mean"] == pytest.approx(2.5)

    def test_sampler_thread_torn_read_safe(self):
        """A real sampler thread against concurrent observe(): every
        histogram sample's bucket vector must sum to its count (the
        one-lock export), and counter samples stay monotone."""
        obs.reset()
        h = obs.histogram("tt_ms", buckets=(1, 2, 5))
        c = obs.counter("tt_total")
        ts = obs.MetricsTimeSeries(name="tt", interval_s=0.002,
                                   capacity=512)
        halt = threading.Event()

        def hammer():
            while not halt.is_set():
                h.observe(1.5)
                c.inc()

        threads = [threading.Thread(target=hammer) for _ in range(3)]
        ts.start()
        for t in threads:
            t.start()
        time.sleep(0.15)
        halt.set()
        for t in threads:
            t.join()
        ts.stop()
        hs = ts.series("tt_ms")
        assert len(hs) >= 3
        for _, cnt, _, counts in hs:
            assert sum(counts) == cnt               # never torn
        cs = [v for _, v in ts.series("tt_total")]
        assert cs == sorted(cs)                     # monotone
        doc = ts.to_doc()
        assert obs.validate_series_doc(
            json.loads(json.dumps(doc))) == []
        obs.reset()

    def test_reset_stops_sampler_and_flushes_series(self, tmp_path):
        """ISSUE 15 small fix: reset() must stop tracked sampler
        threads and leave series_<name>.json in the run dir — a
        leaked thread would keep sampling the fresh registry."""
        obs.reset()
        obs.configure(str(tmp_path))
        obs.counter("x_total").inc(3)
        ts = obs.MetricsTimeSeries(name="gwX", interval_s=0.005)
        ts.start()
        time.sleep(0.05)
        thread = ts._thread
        obs.reset()
        assert not thread.is_alive()
        path = tmp_path / "series_gwX.json"
        assert path.exists()
        doc = json.loads(path.read_text())
        assert obs.validate_series_doc(doc) == []
        assert any(k.startswith("x_total")
                   for k in doc["metrics"])

    def test_validator_catches_drift(self):
        reg = obs.MetricsRegistry()
        reg.counter("c_total").inc()
        reg.histogram("h_ms", buckets=(1, 2))
        clk = [0.0]
        ts = obs.MetricsTimeSeries(name="v", registry=reg, capacity=4,
                                   clock=lambda: clk[0])
        for i in range(3):
            clk[0] = float(i)
            ts.sample()
        good = json.loads(json.dumps(ts.to_doc(alerts=[
            {"kind": "fire", "slo": "interactive", "rule": "page",
             "t": 1.0}])))
        assert obs.validate_series_doc(good) == []

        def broken(mut):
            d = json.loads(json.dumps(good))
            mut(d)
            return obs.validate_series_doc(d)

        assert broken(lambda d: d.update(schema="series/0"))
        assert broken(lambda d: d["metrics"]["c_total"]["samples"]
                      .__setitem__(0, [0.0]))          # malformed
        assert broken(lambda d: d["metrics"]["c_total"]
                      .update(samples=[[0.0, 5.0], [1.0, 1.0]]))
        assert broken(lambda d: d["metrics"]["h_ms"]["samples"][0]
                      .__setitem__(3, [0]))            # bucket vector
        assert broken(lambda d: d["alerts"][0].update(kind="page"))
        # ring bound: more samples than capacity claims
        assert broken(lambda d: d["metrics"]["c_total"].update(
            samples=[[float(i), float(i)] for i in range(9)]))


# ============================================================== burn rate
def _burn(**kw):
    base = dict(targets={"interactive": 0.9},
                rules=(BurnRule("page", 5.0, 20.0, 2.0),),
                clock=None)
    base.update(kw)
    clk = [0.0]
    if base["clock"] is None:
        base["clock"] = lambda: clk[0]
    eng = BurnRateEngine(**base)
    return eng, clk


class TestBurnRate:
    def test_fire_needs_both_windows_then_fires_once(self):
        eng, clk = _burn()
        # clean history fills the slow window
        for i in range(20):
            clk[0] = float(i)
            assert eng.observe("interactive", True) == []
        # a 2-sample bad blip: fast burn spikes but the SLOW window
        # stays under threshold -> no page (the SRE "is it real" gate)
        clk[0] = 20.0
        eng.observe("interactive", False)
        clk[0] = 20.5
        eng.observe("interactive", False)
        assert eng.burn_rate("interactive", 5.0) > 2.0
        assert eng.burn_rate("interactive", 20.0) < 2.0
        assert eng.active() == []
        # sustained burn: both windows over -> exactly one fire
        evs = []
        for i in range(6):
            clk[0] = 21.0 + i
            evs += eng.observe("interactive", False)
        fires = [e for e in evs if e["kind"] == "fire"]
        assert len(fires) == 1
        assert fires[0]["slo"] == "interactive"     # names the class
        assert fires[0]["rule"] == "page"
        assert fires[0]["burn_fast"] >= 2.0 \
            and fires[0]["burn_slow"] >= 2.0
        assert len(eng.active()) == 1
        assert eng.fires_total == 1

    def test_resolve_hysteresis_no_flap_in_dead_band(self):
        eng, clk = _burn(resolve_frac=0.5)
        for i in range(10):
            clk[0] = float(i)
            eng.observe("interactive", False)
        assert len(eng.active()) == 1
        # drift the fast burn into the dead band (threshold/2 ..
        # threshold): still active — no resolve, no second fire
        t = 10.0
        for i in range(12):
            t += 0.5
            clk[0] = t
            eng.observe("interactive", i % 4 == 0)   # mostly bad
        assert len(eng.active()) == 1
        assert eng.fires_total == 1
        # clean traffic pushes fast burn under threshold/2 -> resolve
        for i in range(30):
            t += 0.5
            clk[0] = t
            eng.observe("interactive", True)
        assert eng.active() == []
        kinds = [a["kind"] for a in eng.alerts]
        assert kinds == ["fire", "resolve"]          # no flap
        assert eng.alerts[-1]["fired_t"] == eng.alerts[0]["t"]

    def test_window_scale_knob_scales_fire_time(self):
        times = {}
        for scale in (1.0, 0.1):
            eng, clk = _burn(window_scale=scale)
            t, dt = 0.0, 0.1 * scale
            fired = None
            for i in range(600):
                t += dt
                clk[0] = t
                for e in eng.observe("interactive", False):
                    if e["kind"] == "fire" and fired is None:
                        fired = t
                if fired is not None:
                    break
            assert fired is not None
            times[scale] = fired
        # the same outcome pattern fires at 1/10 the wall time
        assert times[0.1] == pytest.approx(times[1.0] * 0.1,
                                           rel=0.05)

    def test_gauges_flight_events_and_evaluate_heartbeat(self):
        obs.reset()
        eng, clk = _burn(labels={"gateway": "gwT"})
        for i in range(10):
            clk[0] = float(i)
            eng.observe("interactive", False)
        snap = obs.registry().snapshot()
        key = ('slo_burn_rate{class="interactive",gateway="gwT",'
               'window="5s"}')
        assert key in snap and snap[key] > 2.0
        fires = [e for e in obs.recorder().snapshot()
                 if e["kind"] == "alert_fire"]
        assert fires and fires[0]["slo"] == "interactive"
        # traffic STOPS; the evaluate() heartbeat (the sampler hook)
        # still resolves the alert once the window empties
        clk[0] = 60.0
        evs = eng.evaluate()
        assert [e["kind"] for e in evs] == ["resolve"]
        assert eng.snapshot()["burn"]["interactive"]["5s"] == 0.0
        obs.reset()

    @pytest.mark.slow
    def test_multi_window_burn_sweep(self):
        """Sweep seeded outcome streams x window scales x thresholds:
        behavior is invariant to the scale knob (it stretches time,
        not decisions — pinned on the full fire/resolve transition
        sequence by event INDEX), and the first fire arrives monotone
        later as the threshold rises (hysteresis makes raw fire
        COUNTS non-monotone: a low threshold fires once and stays
        active where a mid one flaps — that's by design)."""
        for seed in range(4):
            rng = random.Random(seed)
            stream = [rng.random() < 0.7 for _ in range(400)]
            by_scale = {}
            # power-of-two scales + a binary-exact 0.25 step keep
            # every window-boundary comparison exactly scale-
            # equivariant (an accumulated 0.2*scale drifts in the
            # last ulp and flips boundary events between scales)
            for scale in (0.25, 1.0, 4.0):
                runs = []
                for thr in (1.0, 3.0, 9.0):
                    eng, clk = _burn(
                        rules=(BurnRule("r", 5.0, 15.0, thr),),
                        window_scale=scale)
                    t = 0.0
                    transitions = []
                    for i, ok in enumerate(stream):
                        t += 0.25 * scale
                        clk[0] = t
                        for e in eng.observe("interactive", ok):
                            transitions.append((i, e["kind"]))
                    runs.append(tuple(transitions))
                by_scale[scale] = runs
                first_fire = [
                    next((i for i, k in tr if k == "fire"),
                         len(stream))
                    for tr in runs]
                assert first_fire == sorted(first_fire), \
                    (seed, scale, first_fire)
            assert by_scale[0.25] == by_scale[1.0] == by_scale[4.0], \
                (seed, {s: [len(r) for r in v]
                        for s, v in by_scale.items()})


# ======================================================= gateway telemetry
def _run(coro):
    return asyncio.run(coro)


class TestGatewayTelemetry:
    def test_metricsz_endpoint_windowed_rates(self):
        pt.seed(0)
        eng = _engine()

        async def run():
            gw = Gateway(eng, sample_interval_s=0.02,
                         slo_window_scale=0.01)
            await gw.start()
            for i in range(6):
                st, _ = await _http(
                    gw.port, "POST", "/v1/generate",
                    json.dumps({"prompt": [1, 2, 3, 4, 5 + i],
                                "max_new_tokens": 4,
                                "stream": False}).encode())
                assert st == 200
            await asyncio.sleep(0.15)
            st, payload = await _http(gw.port, "GET",
                                      "/metricsz?window_s=30")
            assert st == 200
            doc = json.loads(payload)
            assert doc["enabled"] and doc["window_s"] == 30.0
            toks = [v for k, v in doc["metrics"].items()
                    if k.startswith("gateway_tokens_total")]
            assert toks and toks[0]["rate_per_s"] > 0
            assert toks[0]["delta"] == 24.0          # 6 req x 4 toks
            ttft = [v for k, v in doc["metrics"].items()
                    if k.startswith("gateway_ttft_ms")]
            assert ttft and ttft[0]["count"] == 6 \
                and ttft[0]["p99"] >= ttft[0]["p50"] > 0
            assert "slo" in doc and "burn" in doc["slo"]
            # debugz carries the telemetry block
            st, payload = await _http(gw.port, "GET", "/debugz")
            tz = json.loads(payload)["telemetry"]
            assert tz["sampler"]["running"] \
                and tz["sampler"]["samples_taken"] > 0
            await gw.drain()

        _run(run())

    def test_sampler_off_metricsz_disabled(self):
        pt.seed(0)
        eng = _engine()

        async def run():
            gw = Gateway(eng, sample_interval_s=None,
                         slo_alerting=False)
            await gw.start()
            st, payload = await _http(gw.port, "GET", "/metricsz")
            assert st == 200
            assert json.loads(payload) == {"gateway": gw.name,
                                           "enabled": False}
            assert gw.debugz()["telemetry"] == {"sampler": None,
                                                "slo": None}
            await gw.drain()

        _run(run())

    def test_sampler_on_off_sse_streams_bitwise(self):
        """THE off-the-hot-path pin: the full SSE byte stream (status
        line, headers, every event) is identical with the telemetry
        plane on vs off — sampling is pull-only and alerting is
        host-side bookkeeping."""
        payloads = [{"prompt": [1, 2, 3, 4, 5 + i],
                     "max_new_tokens": 5, "stream": True,
                     "request_id": f"bw-{i}"}
                    for i in range(5)]

        async def serve(telemetry):
            pt.seed(0)
            kw = dict(sample_interval_s=0.01,
                      slo_window_scale=0.01) if telemetry else \
                dict(sample_interval_s=None, slo_alerting=False)
            gw = Gateway(_engine(), **kw)
            await gw.start()
            out = []
            for p in payloads:
                out.append(await _sse_raw(gw.port, p))
            await gw.drain()
            return out

        on = _run(serve(True))
        off = _run(serve(False))
        assert on == off                              # bitwise

    def test_steady_tick_dispatch_upload_pins_with_sampler(self):
        """The ISSUE 6/14 steady-tick counters, re-pinned with a
        sampler thread running: N ticks = N dispatches, 0 uploads,
        0 bytes — the plane never touches the engine hot path."""
        obs.reset()
        # the test_fused_tick pin geometry: block_size 64 so no block-
        # growth transition lands inside the measured steady window
        eng = PagedEngine(TickStubModel(), max_slots=4,
                          num_blocks=256, block_size=64,
                          max_blocks_per_seq=8,
                          prefill_buckets=(16,))
        ts = obs.MetricsTimeSeries(name="pin", interval_s=0.001)
        ts.start()
        try:
            for i in range(4):
                eng.submit(f"r{i}", np.arange(1, 9)[None],
                           max_new_tokens=120)
            for _ in range(6):
                eng.step()
            d0, u0 = eng.dispatch_count, eng.h2d_uploads
            b0 = eng.h2d_upload_bytes
            n = 20
            for _ in range(n):
                eng.step()
            assert eng.dispatch_count - d0 == n
            assert eng.h2d_uploads - u0 == 0
            assert eng.h2d_upload_bytes - b0 == 0
            assert ts.samples_taken > 0               # it really ran
        finally:
            ts.stop()
            obs.reset()

    def test_slo_alert_fires_in_gateway_and_flight_recorder(self):
        """Deterministic alert e2e: slow_ttft_ms=0 makes every
        interactive request an SLO miss — the burn alert MUST fire,
        name the class, land in the flight recorder and ride the
        drained series file."""
        obs.reset()
        pt.seed(0)
        eng = _engine()

        async def run(tmp):
            obs.configure(tmp)
            gw = Gateway(eng, sample_interval_s=0.02,
                         slo_window_scale=0.01, slow_ttft_ms=0.0)
            await gw.start()
            for i in range(8):
                st, _ = await _http(
                    gw.port, "POST", "/v1/generate",
                    json.dumps({"prompt": [1, 2, 3, 4, 5 + i],
                                "max_new_tokens": 4,
                                "stream": False}).encode())
                assert st == 200
            await asyncio.sleep(0.2)
            snap = gw._slo.snapshot()
            assert snap["fires_total"] >= 1
            assert [a for a in snap["active"]
                    if a["slo"] == "interactive"]
            assert snap["peak_burn"]["interactive"] >= 10.0
            await gw.drain()
            return gw.name

        import tempfile
        with tempfile.TemporaryDirectory() as tmp:
            name = _run(run(tmp))
            fires = [e for e in obs.recorder().snapshot()
                     if e["kind"] == "alert_fire"]
            assert fires and fires[0]["slo"] == "interactive"
            series = os.path.join(tmp, f"series_{name}.json")
            assert os.path.exists(series)
            with open(series) as f:
                doc = json.load(f)
            assert obs.validate_series_doc(doc) == []
            assert any(a["kind"] == "fire" and
                       a["slo"] == "interactive"
                       for a in doc["alerts"])
            burn = [k for k in doc["metrics"]
                    if k.startswith("slo_burn_rate")]
            assert burn                                # trajectory too
        obs.reset()


# ============================================================== federation
class TestFederation:
    def test_frontend_federated_metricsz_and_staleness(self):
        """N real gateways -> RemoteReplica caches -> ONE federated
        /metricsz with per-replica sections + fleet totals; a peer
        whose cache goes stale drops out of the totals (the routing
        staleness bound, reused)."""
        from paddle_tpu.serving.fleet import FleetFrontend
        pt.seed(0)
        engines = [_engine(), _engine()]

        async def run():
            gws = [Gateway(engines[i], name=f"fgw{i}",
                           sample_interval_s=0.02,
                           slo_window_scale=0.01)
                   for i in range(2)]
            for gw in gws:
                await gw.start()
            for i, gw in enumerate(gws):
                for j in range(4):
                    st, _ = await _http(
                        gw.port, "POST", "/v1/generate",
                        json.dumps({"prompt": [1, 2, 3, 4,
                                               5 + i * 10 + j],
                                    "max_new_tokens": 4,
                                    "stream": False}).encode())
                    assert st == 200
            await asyncio.sleep(0.1)
            fake = [0.0]
            peers = [RemoteReplica(f"peer{i}", "127.0.0.1",
                                   gws[i].port, stale_after_s=2.0,
                                   clock=lambda: fake[0])
                     for i in range(2)]
            fe = FleetFrontend(peers, chunk_tokens=8, name="fedfe")
            for p in peers:
                p.stop()         # deterministic: manual refresh only
                # refresh probes the gateways over HTTP — run it off
                # the loop thread the gateways answer on
                assert await asyncio.to_thread(p.refresh)
            await fe.start()
            # the federated doc over HTTP, per-replica labeled
            st, payload = await _http(fe.port, "GET",
                                      "/metricsz?window_s=60")
            assert st == 200
            doc = json.loads(payload)
            assert set(doc["replicas"]) == {"peer0", "peer1"}
            assert doc["live_peers"] == 2
            for name, mz in doc["replicas"].items():
                assert not mz["stale"]
                assert mz["doc"]["enabled"]
                assert any(k.startswith("gateway_tokens_total")
                           for k in mz["doc"]["metrics"])
            # totals: both peers' token rates summed — counting ONLY
            # each peer's own gateway="<name>" variant. The two
            # gateways share this process's registry, so each sampler
            # carries the OTHER gateway's series too (pinned below);
            # folding every variant would double-count the fleet.
            assert doc["totals"]["tokens_per_sec"] > 0
            expect = 0.0
            for name, mz in doc["replicas"].items():
                own = mz["doc"]["gateway"]
                for full, view in mz["doc"]["metrics"].items():
                    if (full.startswith("gateway_tokens_total")
                            and f'gateway="{own}"' in full):
                        expect += view["rate_per_s"]
            assert doc["totals"]["tokens_per_sec"] == \
                pytest.approx(expect, abs=1e-3)
            assert any('gateway="fgw1"' in k for k in
                       doc["replicas"]["peer0"]["doc"]["metrics"])
            assert "burn_rate_max" in doc["totals"]
            # staleness: advance the peers' injected clock past the
            # bound WITHOUT refreshing — excluded from totals
            fake[0] = 10.0
            doc2 = fe.metricsz()
            assert doc2["live_peers"] == 0
            assert doc2["totals"]["tokens_per_sec"] == 0.0
            assert all(mz["stale"]
                       for mz in doc2["replicas"].values())
            # one refresh brings a peer back
            assert await asyncio.to_thread(peers[0].refresh)
            doc3 = fe.metricsz()
            assert doc3["live_peers"] == 1
            await fe.drain()
            for gw in gws:
                await gw.drain()

        _run(run())

    def test_remote_metricsz_failure_does_not_evict(self):
        """A peer without the endpoint (or with its sampler off) must
        stay healthy: live metrics are a lens, not a liveness
        signal."""
        pt.seed(0)

        async def run():
            gw = Gateway(_engine(), sample_interval_s=None,
                         slo_alerting=False)
            await gw.start()
            peer = RemoteReplica("p0", "127.0.0.1", gw.port)
            assert await asyncio.to_thread(peer.refresh)
            assert peer.healthy()
            mz = peer.metricsz()
            # cached doc exists but reports enabled=False
            assert mz["doc"] == {"gateway": gw.name,
                                 "enabled": False}
            await gw.drain()

        _run(run())

    @pytest.mark.slow
    def test_fleet_federation_multiproc_e2e(self, tmp_path):
        """Real replica SUBPROCESSES behind a frontend: the federated
        /metricsz shows every process's windowed metrics and the
        CI-scaled burn windows ride --slo-window-scale through
        replica_main; drained replicas leave series_<gw>.json in the
        run dir."""
        from paddle_tpu.serving.fleet import (FleetFrontend,
                                              LocalProcessManager)

        async def run():
            fe = FleetFrontend([], chunk_tokens=8, name="mpfe")
            manager = LocalProcessManager(
                fe, model="stub", chunk_tokens=8,
                probe_interval_s=0.1, stale_after_s=2.0,
                extra_args=["--run-dir", str(tmp_path),
                            "--slo-window-scale", "0.01"])
            try:
                for _ in range(2):
                    manager.spawn()
                await fe.start()
                for i in range(8):
                    st, _ = await _http(
                        fe.port, "POST", "/v1/generate",
                        json.dumps({"prompt": [1, 2, 3, 4, 5 + i],
                                    "max_new_tokens": 4,
                                    "stream": False}).encode())
                    assert st == 200
                await asyncio.sleep(0.6)   # a probe round + samples
                st, payload = await _http(fe.port, "GET",
                                          "/metricsz?window_s=60")
                doc = json.loads(payload)
                assert st == 200 and doc["live_peers"] == 2
                assert doc["totals"]["tokens_per_sec"] > 0
                for mz in doc["replicas"].values():
                    assert mz["doc"]["enabled"]
                    assert mz["doc"]["slo"]["window_scale"] == 0.01
                await fe.drain()
            finally:
                manager.stop_all()

        _run(run())
        series = [p for p in os.listdir(tmp_path)
                  if p.startswith("series_")]
        assert len(series) >= 2           # one trajectory per process
        for p in series:
            with open(tmp_path / p) as f:
                assert obs.validate_series_doc(json.load(f)) == []


# ====================================================== windowed autoscale
class _FakePeer:
    def __init__(self):
        self.sig = {}

    def signals(self):
        return dict(self.sig)


class _FakeManager:
    name = "t"

    def __init__(self):
        self.peers = [_FakePeer()]
        self.ups = self.downs = 0

    def replicas(self):
        return self.peers

    def pending(self):
        return 0

    def scale_up(self):
        self.ups += 1

    def scale_down(self):
        self.downs += 1


def _drive(mode, trace, **kw):
    obs.reset()
    mgr = _FakeManager()
    base = dict(min_replicas=1, max_replicas=8, up_queue_depth=2.0,
                down_load_frac=0.25, hold_s=0.5, hold_down_s=0.5,
                cooldown_s=0.5, signal_mode=mode, signal_window_s=2.0,
                clock=lambda: 0.0)
    base.update(kw)
    sc = FleetAutoscaler(mgr, **base)
    actions = []
    for i, (qd, used) in enumerate(trace):
        mgr.peers[0].sig = {
            "healthy": True, "queue_depth": qd,
            "free_slots": 4 - used, "total_slots": 4,
            "block_pool_free_frac": 0.5, "goodput_frac": 1.0,
            "load": float(used)}
        actions.append(sc.step(now=i * 0.25)["action"])
    return mgr, actions, sc


class TestWindowedAutoscaler:
    def test_invalid_mode_raises(self):
        with pytest.raises(ValueError):
            FleetAutoscaler(_FakeManager(), signal_mode="psychic")

    def test_parity_with_instant_on_steady_traffic(self):
        """Constant signals make the window mean equal the instant
        sample — decision-for-decision identical action traces."""
        for qd, used in ((6, 4), (0, 0), (1, 2)):
            trace = [(qd, used)] * 16
            mi, ai, _ = _drive("instant", trace)
            mw, aw, _ = _drive("windowed", trace)
            assert ai == aw, (qd, used, ai, aw)
            assert (mi.ups, mi.downs) == (mw.ups, mw.downs)

    def test_strictly_fewer_scale_events_on_seeded_noisy_trace(self):
        """The flap demonstration: a seeded oscillating trace (1s hot
        with full slots + queue, 1s idle, jittered phase lengths)
        makes the instant controller ride every swing while the
        window mean sits in the hysteresis dead band."""
        rng = random.Random(3)
        trace = []
        for _ in range(15):
            trace += [(0, 0)] * (4 + rng.randrange(-1, 2))
            trace += [(3, 4)] * (4 + rng.randrange(-1, 2))
        mi, ai, _ = _drive("instant", trace)
        mw, aw, sc = _drive("windowed", trace)
        inst_events = mi.ups + mi.downs
        wind_events = mw.ups + mw.downs
        assert inst_events >= 5                  # instant flaps
        assert wind_events < inst_events         # strictly fewer
        assert sc.snapshot()["signal_mode"] == "windowed"
        obs.reset()

    def test_windowed_still_scales_on_sustained_pressure(self):
        """Smoothing must not deafen the controller: a genuine
        sustained overload scales up in BOTH modes."""
        trace = [(0, 0)] * 8 + [(6, 4)] * 24
        mi, _, _ = _drive("instant", trace)
        mw, _, _ = _drive("windowed", trace)
        assert mi.ups >= 1 and mw.ups >= 1
        obs.reset()


# ================================================================= loadgen
def _load_loadgen():
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "serve_loadgen.py")
    spec = importlib.util.spec_from_file_location("serve_loadgen2",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _loadgen_ns(**kw):
    base = dict(requests=8, rate=60.0, share_frac=0.5, sys_tokens=8,
                tail_tokens=4, max_new=6, interactive_frac=1.0,
                ttft_slo_ms=5000.0, timeout_s=60.0, tenants=2,
                replicas=1, policy="prefix", max_queue=256,
                model="stub", seed=0, url=None, out="",
                telemetry="on", slo_windows=0.02)
    base.update(kw)
    return types.SimpleNamespace(**base)


class TestLoadgenTelemetry:
    def test_rung_records_trajectory_and_burn_state(self):
        """ISSUE 15 satellite: the rung banks the windowed tok/s
        trajectory, the alert log and the peak burn rate — and
        --telemetry off reproduces the bare rung."""
        slg = _load_loadgen()
        rung = asyncio.run(slg.run_loadgen(_loadgen_ns()))
        assert rung["completed"] == 8
        assert rung["telemetry"] == "on" \
            and rung["slo_windows"] == 0.02
        traj = rung["tok_s_trajectory"]
        assert traj["points"] and traj["peak"] > 0
        assert traj["peak"] >= traj["mean"]
        assert isinstance(rung["alerts"], list)
        assert rung["peak_burn_rate"] >= 0.0
        off = asyncio.run(slg.run_loadgen(
            _loadgen_ns(telemetry="off")))
        assert off["completed"] == 8 and off["telemetry"] == "off"
        assert "tok_s_trajectory" not in off
        assert "alerts" not in off

    @pytest.mark.slow
    @pytest.mark.chaos
    def test_chaos_alert_loadgen_e2e(self):
        """THE ISSUE 15 acceptance run: a seeded chaos run (replica
        hang mid-run — the watchdog's dispatch-to-drain stall is the
        TTFT spike) deterministically fires a burn-rate alert naming
        the interactive class, the alert lands in the rung AND the
        flight recorder, the bitwise replay gate still passes, and
        the same seeds with the plane disabled reproduce a clean
        alert-free run."""
        slg = _load_loadgen()
        ns = _loadgen_ns(requests=24, rate=40.0, replicas=3,
                         max_new=6, interactive_frac=0.7,
                         chaos=True, chaos_kills=2,
                         chaos_mode="hang", failover_budget=2,
                         watchdog_timeout_s=0.5,
                         goodput_floor=0.95, slo_windows=0.02)
        obs.reset()
        rung = asyncio.run(slg.run_loadgen(ns))
        assert rung["chaos"]["ok"], rung["chaos"]
        fired = [a for a in rung["alerts"] if a["kind"] == "fire"]
        assert fired, "chaos hang did not fire a burn alert"
        assert any(a["slo"] == "interactive" for a in fired)
        assert rung["peak_burn_rate"] > 1.0
        flight = [e for e in obs.recorder().snapshot()
                  if e["kind"] == "alert_fire"]
        assert flight and flight[0]["slo"] == "interactive"
        # plane off: same seeds, same gate, no alert machinery
        obs.reset()
        off = asyncio.run(slg.run_loadgen(
            _loadgen_ns(requests=24, rate=40.0, replicas=3,
                        max_new=6, interactive_frac=0.7,
                        chaos=True, chaos_kills=2,
                        chaos_mode="hang", failover_budget=2,
                        watchdog_timeout_s=0.5,
                        goodput_floor=0.95, telemetry="off")))
        assert off["chaos"]["ok"]
        assert "alerts" not in off
        assert not [e for e in obs.recorder().snapshot()
                    if e["kind"].startswith("alert_")]
        obs.reset()


# ================================================================== dash
class TestFleetDash:
    def _load(self):
        path = os.path.join(os.path.dirname(__file__), os.pardir,
                            "tools", "fleet_dash.py")
        spec = importlib.util.spec_from_file_location("fleet_dash",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_render_series_doc_with_alert_markers(self):
        dash = self._load()
        reg = obs.MetricsRegistry()
        c = reg.counter("gateway_tokens_total", gateway="gwD")
        g = reg.gauge("gateway_queue_depth", gateway="gwD")
        b = reg.gauge("slo_burn_rate", **{"class": "interactive",
                                          "window": "5s"})
        clk = [0.0]
        ts = obs.MetricsTimeSeries(name="gwD", registry=reg,
                                   capacity=128,
                                   clock=lambda: clk[0])
        for i in range(20):
            clk[0] = float(i)
            c.inc(10 if i < 10 else 40)
            g.set(i % 4)
            b.set(0.0 if i < 15 else 12.0)
            ts.sample()
        doc = json.loads(json.dumps(ts.to_doc(alerts=[
            {"kind": "fire", "slo": "interactive", "rule": "page",
             "t": 15.0, "burn_fast": 12.0}])))
        docs = {"gwD": doc}
        out = dash.render(docs, dash.collect_events(docs, []),
                          width=40)
        assert "gwD" in out and "tok/s" in out and "burn" in out
        assert "alert_fire" in out and "!" in out
        # the rate series really derives: peak tok/s ~40/s
        pts = dash.counter_rate_points(
            doc["metrics"]['gateway_tokens_total{gateway="gwD"}']
            ["samples"])
        assert max(r for _, r in pts) == pytest.approx(40.0)

    def test_sparkline_and_resample(self):
        dash = self._load()
        assert len(dash.sparkline([1, 2, 3, None, 5])) == 5
        assert dash.sparkline([0, 0, 0]) == "▁▁▁"
        vals = dash.resample([(0.0, 1.0), (1.0, 3.0), (9.0, 5.0)],
                             0.0, 10.0, 5)
        assert vals[0] == 2.0 and vals[4] == 5.0
        assert vals[2] is None
