"""Datasets (reference: python/paddle/io/dataloader/dataset.py)."""
from __future__ import annotations

import bisect
from typing import Iterable, List, Sequence

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset is not indexable")

    def __len__(self):
        raise TypeError("IterableDataset has no length")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        lengths = {len(t) for t in tensors}
        assert len(lengths) == 1, "all tensors must share dim 0"
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ConcatDataset(Dataset):
    def __init__(self, datasets: Iterable[Dataset]):
        self.datasets = list(datasets)
        self.cumulative = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative, idx)
        prev = 0 if ds_idx == 0 else self.cumulative[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets: Iterable[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence[int], generator=None):
    total = len(dataset)
    assert sum(lengths) == total
    rng = np.random.default_rng(generator)
    perm = rng.permutation(total)
    out, offset = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset:offset + n].tolist()))
        offset += n
    return out
