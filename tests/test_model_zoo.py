"""LLM model zoo (SURVEY.md C22): GPT, BERT, ERNIE, Qwen2, Qwen2-MoE —
forward shapes, overfit sanity, KV-cache decode parity, MoE aux loss."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import (BertForPretraining,
                               BertForSequenceClassification,
                               ErnieForMaskedLM, GPTForCausalLM,
                               Qwen2ForCausalLM, Qwen2MoeForCausalLM,
                               bert_tiny, causal_lm_loss, deepseek_moe_tiny,
                               ernie_tiny, gpt_tiny, moe_lm_loss,
                               qwen2_moe_tiny, qwen2_tiny)


def _overfit(model, loss_of_params, steps=50, lr=3e-3, factor=0.5):
    fn, params = model.functional()
    opt = pt.optimizer.AdamW(learning_rate=lr)
    state = opt.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, state, n):
        loss, grads = jax.value_and_grad(
            lambda p: loss_of_params(fn, p))(params)
        params, state = opt.apply(params, grads, state, n)
        return params, state, loss

    losses = []
    for n in range(steps):
        params, state, loss = step(params, state, n)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * factor, losses[::10]
    return losses


# ------------------------------------------------------------------- GPT
def test_gpt_forward_and_overfit():
    model = GPTForCausalLM(gpt_tiny())
    ids = jnp.asarray(np.random.randint(0, 256, (4, 32)))
    logits = model(ids)
    assert logits.shape == (4, 32, 256) and logits.dtype == jnp.float32
    _overfit(model, lambda fn, p: causal_lm_loss(fn(p, ids), ids))


def test_gpt_kv_cache_decode_parity():
    model = GPTForCausalLM(gpt_tiny())
    ids = jnp.asarray(np.random.randint(0, 256, (2, 12)))
    full = model(ids)
    caches = model.init_kv_caches(2, 16)
    logits, caches = model(ids[:, :8], kv_caches=caches, cache_index=0)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, :8]),
                               rtol=2e-4, atol=2e-4)
    for t in range(8, 12):
        logits, caches = model(ids[:, t:t + 1], kv_caches=caches,
                               cache_index=t)
        np.testing.assert_allclose(np.asarray(logits[:, 0]),
                                   np.asarray(full[:, t]),
                                   rtol=2e-4, atol=2e-4)


# ------------------------------------------------------------------- BERT
def test_bert_pretraining_shapes_and_mask():
    model = BertForPretraining(bert_tiny())
    ids = jnp.asarray(np.random.randint(0, 256, (2, 16)))
    mask = jnp.ones((2, 16), jnp.int32).at[:, 12:].set(0)
    mlm, nsp = model(ids, attention_mask=mask)
    assert mlm.shape == (2, 16, 256) and nsp.shape == (2, 2)
    # masking out pad positions must not change non-pad logits' finiteness
    assert np.isfinite(np.asarray(mlm)).all()


def test_bert_classifier_overfit():
    model = BertForSequenceClassification(bert_tiny(), num_classes=2)
    ids = jnp.asarray(np.random.randint(0, 256, (8, 12)))
    labels = jnp.asarray(np.arange(8) % 2)

    def loss(fn, p):
        return pt.nn.functional.cross_entropy(fn(p, ids), labels,
                                              reduction="mean")
    _overfit(model, loss, steps=60)


# ------------------------------------------------------------------ ERNIE
def test_ernie_mlm_forward():
    model = ErnieForMaskedLM(ernie_tiny())
    ids = jnp.asarray(np.random.randint(0, 256, (2, 16)))
    task = jnp.zeros((2, 16), jnp.int32)
    logits = model(ids, task_type_ids=task)
    assert logits.shape == (2, 16, 256)
    # task-type stream participates: different task ids change the output
    logits2 = model(ids, task_type_ids=task + 1)
    assert not np.allclose(np.asarray(logits), np.asarray(logits2))


# ------------------------------------------------------------------ Qwen2
def test_qwen2_has_qkv_bias_and_overfits():
    model = Qwen2ForCausalLM(qwen2_tiny())
    assert model.model.layers[0].self_attn.q_proj.bias is not None
    ids = jnp.asarray(np.random.randint(0, 256, (4, 32)))
    _overfit(model, lambda fn, p: causal_lm_loss(fn(p, ids), ids))


def test_qwen2_kv_cache_decode_parity():
    model = Qwen2ForCausalLM(qwen2_tiny())
    ids = jnp.asarray(np.random.randint(0, 256, (2, 10)))
    full = model(ids)
    caches = model.init_kv_caches(2, 12)
    logits, caches = model(ids[:, :6], kv_caches=caches, cache_index=0)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, :6]),
                               rtol=2e-4, atol=2e-4)
    for t in range(6, 10):  # incremental decode must match full forward
        logits, caches = model(ids[:, t:t + 1], kv_caches=caches,
                               cache_index=t)
        np.testing.assert_allclose(np.asarray(logits[:, 0]),
                                   np.asarray(full[:, t]),
                                   rtol=2e-4, atol=2e-4)


# -------------------------------------------------------------- Qwen2-MoE
def test_qwen2_moe_forward_aux_and_overfit():
    model = Qwen2MoeForCausalLM(qwen2_moe_tiny())
    ids = jnp.asarray(np.random.randint(0, 256, (4, 32)))
    logits, aux = model(ids, return_aux=True)
    assert logits.shape == (4, 32, 256)
    assert float(aux) > 0.0  # switch aux loss is positive
    _overfit(model,
             lambda fn, p: moe_lm_loss(*fn(p, ids, return_aux=True), ids),
             factor=0.6)


def test_deepseek_moe_first_dense_layer():
    cfg = deepseek_moe_tiny()
    model = Qwen2MoeForCausalLM(cfg)
    assert model.model.layers[0].is_dense
    assert not model.model.layers[1].is_dense
    ids = jnp.asarray(np.random.randint(0, 256, (2, 16)))
    logits = model(ids)
    assert logits.shape == (2, 16, 256)


def test_qwen2_moe_kv_cache_decode():
    model = Qwen2MoeForCausalLM(qwen2_moe_tiny())
    ids = jnp.asarray(np.random.randint(0, 256, (2, 10)))
    full = model(ids)
    caches = model.init_kv_caches(2, 12)
    logits, caches = model(ids[:, :8], kv_caches=caches, cache_index=0)
    # MoE routing capacity differs between prefill widths, so compare with
    # loose tolerance (dropped-token sets can differ at bucket boundaries)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, :8]),
                               rtol=5e-2, atol=5e-2)
    for t in range(8, 10):
        step, caches = model(ids[:, t:t + 1], kv_caches=caches,
                             cache_index=t)
        np.testing.assert_allclose(np.asarray(step[:, 0]),
                                   np.asarray(full[:, t]),
                                   rtol=5e-2, atol=5e-2)
