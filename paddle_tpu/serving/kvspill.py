"""Checksummed host-RAM KV spill tier (ISSUE 17; ROADMAP item 2a —
reference: tiered KV caching in production LLM serving — vLLM's CPU
swap space, SGLang's hierarchical radix cache — restated over
PagedEngine's chunk-grid digest chain).

A :class:`KVSpillArena` is a bounded host-RAM store of prefix-cache
spans, keyed by the SAME SHA-256 chain digests the device-side
``prefix_cache`` files blocks under. Two producers feed it:

- **eviction spill** — when block pressure evicts a registered span
  out of ``cached_free`` (``PagedEngine._alloc_block``), the span's KV
  blocks are copied D2H into the arena first;
- **drain spill** — ``PagedEngine.spill_parked()`` at gateway drain
  (SIGTERM rolling restart) banks every still-parked span.

One consumer: a warm MISS in the device cache at admission
(``PagedEngine._arena_restore``) probes the arena and re-uploads the
span — one batched H2D scatter into freshly allocated blocks —
instead of re-prefilling it.

The arena deliberately lives OUTSIDE the engine: the gateway owns it
and re-attaches it to whatever engine ``_make_worker`` wires up, so a
supervisor rebuild (``engine_factory`` swap or ``hard_reset``) comes
back WARM — the crashed replica's spilled spans survive in host RAM.

**Integrity is the contract.** Every payload record carries a crc32
plus metadata (digest chain, token count, block geometry, the
producing engine's ``prefix_generation``). On the way back, any
checksum mismatch, truncated record, or geometry skew drops the
record, counts it (``kv_spill_checksum_failures_total`` /
``kv_spill_drops_total``), and the caller falls back to normal
re-prefill — a corrupted span may cost a prefill, never a token.
Because digests are content-addressed over the token chain and
chunk-grid recompute is bit-exact, a restored span's KV is
byte-identical to what re-prefill would have computed: greedy streams
are pinned bitwise identical spill-on vs spill-off across every path
(tests/test_kvspill.py).

Payloads are deduplicated along the digest chain: one record per
dying chain, keyed by the LONGEST digest; every shorter sub-span
digest becomes an index alias into the same payload (sub-span KV is a
block-prefix of the long span's). Capacity is bounded in bytes; LRU
payload records are evicted to make room, and a span that cannot fit
is refused and counted (``kv_spill_drops_total``) — the fallback
ladder again.

Chaos sites (``utils/faults.py``): ``spill_corrupt`` flips a stored
payload byte AFTER its crc is banked (silent bit rot — the take-side
checksum must catch it), ``spill_slow`` sleeps
``PADDLE_TPU_FAULT_SPILL_SLOW_S`` in the arena copy paths (host
memory-bandwidth contention), ``spill_drop`` refuses a store
(capacity pressure / allocation failure).
"""
from __future__ import annotations

import itertools
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import faults
from ..utils import observability as obs

__all__ = ["KVSpillArena", "DEFAULT_CAPACITY_BYTES"]

DEFAULT_CAPACITY_BYTES = 256 << 20

_arena_ids = itertools.count()


class _Record:
    """One payload record: the packed KV bytes of a chain's LONGEST
    span plus the integrity/provenance metadata the take-side
    validation ladder checks."""

    __slots__ = ("payload", "crc", "nbytes", "tokens", "geometry",
                 "prefix_generation", "aliases", "t_spilled")

    def __init__(self, payload: bytes, crc: int, tokens: int,
                 geometry: tuple, prefix_generation: int):
        self.payload = payload
        self.crc = crc
        self.nbytes = len(payload)
        self.tokens = int(tokens)
        self.geometry = tuple(geometry)
        self.prefix_generation = int(prefix_generation)
        self.aliases: List[bytes] = []   # sub-span digests indexed here
        self.t_spilled = time.monotonic()


class KVSpillArena:
    """Bounded, thread-safe, LRU host-RAM store of spilled prefix
    spans. All byte accounting is payload bytes (metadata overhead is
    negligible next to KV). ``geometry`` is the engine's
    ``(layers, block_size, kv_heads, head_dim, dtype, chunk)`` tuple —
    a record is only ever restored into an engine with the EXACT
    geometry that produced it (anything else is a counted drop)."""

    def __init__(self, capacity_bytes: int = DEFAULT_CAPACITY_BYTES,
                 *, name: Optional[str] = None,
                 labels: Optional[Dict[str, str]] = None):
        self.name = name or f"spill{next(_arena_ids)}"
        self.capacity_bytes = int(capacity_bytes)
        self._lock = threading.RLock()
        # digest -> record (LRU order; key is the chain's longest digest)
        self._records: "Dict[bytes, _Record]" = {}
        # EVERY known digest (records + aliases) -> (record key, tokens)
        self._index: Dict[bytes, Tuple[bytes, int]] = {}
        self._occupancy = 0
        # monotonic mutation counter for gossip (folded into the
        # gateway's /debugz/prefix generation so an if_gen poller sees
        # spill-tier changes too). Never reset.
        self._gen = 0
        self.lru_evictions = 0
        labels = dict(labels or {}, arena=self.name)
        reg = obs.registry()
        self._c_spans = reg.counter("kv_spill_spans_total", **labels)
        self._c_bytes = reg.counter("kv_spill_bytes_total", **labels)
        self._c_hits = reg.counter("kv_spill_hits_total", **labels)
        self._c_drops = reg.counter("kv_spill_drops_total", **labels)
        self._c_crc = reg.counter("kv_spill_checksum_failures_total",
                                  **labels)
        self._g_occ = reg.gauge("kv_spill_occupancy_bytes", **labels)
        self._g_spans = reg.gauge("kv_spill_resident_spans", **labels)

    # ------------------------------------------------------------ helpers
    @property
    def generation(self) -> int:
        with self._lock:
            return self._gen

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def _set_gauges(self):
        self._g_occ.set(float(self._occupancy))
        self._g_spans.set(float(len(self._records)))

    def _forget(self, key: bytes, rec: _Record):
        """Drop a record and every alias pointing at it (lock held)."""
        self._occupancy -= rec.nbytes
        self._index.pop(key, None)
        for a in rec.aliases:
            ent = self._index.get(a)
            if ent is not None and ent[0] == key:
                del self._index[a]
        self._gen += 1
        self._set_gauges()

    def _evict_record(self, key: bytes):
        rec = self._records.pop(key, None)
        if rec is not None:
            self._forget(key, rec)

    # -------------------------------------------------------------- spill
    def spill(self, spans, fetch: Callable[[tuple], bytes],
              geometry: tuple, prefix_generation: int = 0) -> int:
        """Store a batch of dying spans. ``spans`` is a list of
        ``(digest bytes, block-id tuple)`` pairs — the prefix-cache
        entries about to be evicted (or parked spans at drain);
        ``fetch(blocks)`` is the engine's D2H gather returning the
        packed payload bytes for those blocks. Spans whose entry is a
        block-prefix of a longer span stored in the SAME call become
        aliases of that record (one D2H copy per chain, not per span).
        Returns the number of payload records stored."""
        geometry = tuple(geometry)
        block_size = int(geometry[1])
        stored = 0
        with self._lock:
            ordered = sorted(
                ((bytes(k), tuple(e)) for k, e in spans),
                key=lambda kv: len(kv[1]), reverse=True)
            roots: List[Tuple[bytes, tuple]] = []
            for key, entry in ordered:
                tokens = len(entry) * block_size
                if key in self._index:
                    # already resident (content-addressed: same digest
                    # chain => byte-identical KV) — refresh LRU only
                    rk = self._index[key][0]
                    rec = self._records.pop(rk, None)
                    if rec is not None:
                        self._records[rk] = rec
                    continue
                root = next((rk for rk, re in roots
                             if re[:len(entry)] == entry), None)
                if root is not None:
                    self._index[key] = (root, tokens)
                    self._records[root].aliases.append(key)
                    self._gen += 1
                    continue
                if faults.inject("spill_drop", arena=self.name,
                                 digest=key.hex()[:12]):
                    self._c_drops.inc()
                    continue
                if faults.inject("spill_slow", arena=self.name,
                                 op="spill"):
                    time.sleep(faults.spill_slow_seconds())
                payload = bytes(fetch(entry))
                if len(payload) > self.capacity_bytes:
                    self._c_drops.inc()      # can never fit: refuse
                    continue
                while self._occupancy + len(payload) \
                        > self.capacity_bytes:
                    old = next(iter(self._records))
                    self._evict_record(old)
                    self.lru_evictions += 1
                crc = zlib.crc32(payload)
                if faults.inject("spill_corrupt", arena=self.name,
                                 digest=key.hex()[:12]):
                    # silent bit rot AFTER the checksum banked: the
                    # take-side crc must catch this, never a token
                    pos = len(payload) // 2
                    payload = (payload[:pos]
                               + bytes([payload[pos] ^ 0xFF])
                               + payload[pos + 1:])
                rec = _Record(payload, crc, tokens, geometry,
                              prefix_generation)
                self._records[key] = rec
                self._index[key] = (key, tokens)
                self._occupancy += rec.nbytes
                self._c_spans.inc()
                self._c_bytes.inc(rec.nbytes)
                self._gen += 1
                roots.append((key, entry))
                stored += 1
            self._set_gauges()
        return stored

    def put(self, digest: bytes, payload: bytes, tokens: int,
            geometry: tuple, prefix_generation: int = 0) -> bool:
        """Insert one payload already in hand — the wire-receive side
        of cross-replica transfer (``kvxfer.inject_span``). Mirrors
        ``spill()``'s capacity ladder: a payload that can never fit is
        refused (False — the caller counts the fallback and
        re-prefills), LRU records are evicted to make room, and the
        crc is banked over the bytes as received. A digest already
        resident just refreshes LRU (content-addressed: same digest
        => byte-identical KV)."""
        digest = bytes(digest)
        payload = bytes(payload)
        with self._lock:
            ent = self._index.get(digest)
            if ent is not None:
                rec = self._records.pop(ent[0], None)
                if rec is not None:
                    self._records[ent[0]] = rec
                return True
            if len(payload) > self.capacity_bytes:
                self._c_drops.inc()          # can never fit: refuse
                return False
            while self._occupancy + len(payload) \
                    > self.capacity_bytes:
                old = next(iter(self._records))
                self._evict_record(old)
                self.lru_evictions += 1
            rec = _Record(payload, zlib.crc32(payload), tokens,
                          tuple(geometry), prefix_generation)
            self._records[digest] = rec
            self._index[digest] = (digest, rec.tokens)
            self._occupancy += rec.nbytes
            self._c_spans.inc()
            self._c_bytes.inc(rec.nbytes)
            self._gen += 1
            self._set_gauges()
            return True

    # -------------------------------------------------------------- probe
    def probe(self, digest: bytes) -> Optional[int]:
        """Token count of the span stored under ``digest`` (record or
        alias), or None. Pure peek — no counters, no LRU touch."""
        with self._lock:
            ent = self._index.get(bytes(digest))
            return None if ent is None else ent[1]

    def take(self, digest: bytes,
             geometry: tuple) -> Optional[Tuple[bytes, int]]:
        """Validated fetch for restore: returns ``(payload bytes,
        record tokens)`` — ALWAYS the full record's bytes and token
        count, even for an alias take (the caller slices the leading
        blocks its shorter span needs) — or None after dropping the
        record on any integrity failure (checksum mismatch, truncated
        record, geometry skew). The caller's fallback is normal
        re-prefill."""
        if faults.inject("spill_slow", arena=self.name, op="take"):
            time.sleep(faults.spill_slow_seconds())
        digest = bytes(digest)
        with self._lock:
            ent = self._index.get(digest)
            if ent is None:
                return None
            rk, _ = ent
            rec = self._records.get(rk)
            if rec is None:                  # torn index: self-heal
                self._index.pop(digest, None)
                return None
            if rec.geometry != tuple(geometry):
                self._c_drops.inc()          # geometry skew
                self._evict_record(rk)
                return None
            if len(rec.payload) != rec.nbytes:
                self._c_drops.inc()          # truncated record
                self._evict_record(rk)
                return None
            if zlib.crc32(rec.payload) != rec.crc:
                self._c_crc.inc()            # bit rot caught
                self._evict_record(rk)
                return None
            rec2 = self._records.pop(rk)     # refresh LRU
            self._records[rk] = rec2
            self._c_hits.inc()
            return rec.payload, rec.tokens

    # ------------------------------------------------------------- gossip
    def digest_hexes(self) -> List[str]:
        """Every digest restorable from the arena (records + aliases),
        hex-encoded — the spilled tier ``/debugz/prefix`` advertises."""
        with self._lock:
            return sorted(k.hex() for k in self._index)

    # ------------------------------------------------------------ exports
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "arena": self.name,
                "capacity_bytes": self.capacity_bytes,
                "occupancy_bytes": self._occupancy,
                "occupancy_frac": round(
                    self._occupancy / max(self.capacity_bytes, 1), 4),
                "records": len(self._records),
                "digests": len(self._index),
                "generation": self._gen,
                "lru_evictions": self.lru_evictions,
                "spans": int(self._c_spans.value),
                "bytes": int(self._c_bytes.value),
                "hits": int(self._c_hits.value),
                "drops": int(self._c_drops.value),
                "checksum_failures": int(self._c_crc.value),
            }
