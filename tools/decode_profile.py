#!/usr/bin/env python
"""One-window decode-path profiler (round 5).

BENCH_SELF_r05 raised three decode puzzles the standard queue cannot
answer: the Pallas decode kernel timed 0.61x dense, fused projections
timed SLOWER than unfused, and int8 weight-only decode timed slower
than bf16. Each 'time' there was one whole generate() call over the
tunnel; this script separates compile/dispatch from steady-state
on-device time (long decode runs amortize the tunnel RTT) and times
each lever in isolation. Writes DECODE_PROFILE_r05.json.

Usage: timeout 2100 python tools/decode_profile.py
(budget covers ~20 cold generate compiles across base/fused/int8/int4
plus the attention and paged sections; every subsection banks as it
goes, so even a SIGTERM keeps what was measured)
"""
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "DECODE_PROFILE_r05.json")

report = {"started": time.strftime("%Y-%m-%d %H:%M:%S")}


def bank():
    with open(OUT, "w") as f:
        json.dump(report, f, indent=1)


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp

    report["device"] = str(jax.devices()[0].device_kind)
    bank()
    import paddle_tpu as pt
    from paddle_tpu.models import LlamaForCausalLM

    import bench

    rs = np.random.RandomState(0)

    # --- 1) raw decode-attention: new kv-folded kernel vs dense, several
    # shapes (the bench shape first). np.asarray forces full execution
    # through the tunnel; iters amortize RTT.
    from paddle_tpu.ops.attention import dense_attention
    from paddle_tpu.ops.pallas.decode_attention import decode_attention_pallas

    def time_it(jfn, *args, iters=100):
        np.asarray(jfn(*args))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jfn(*args)
        np.asarray(out)
        return round((time.perf_counter() - t0) / iters * 1e3, 4)

    attn = {}
    for (b, T, h, kv, d) in ((8, 2048, 16, 8, 128), (8, 2048, 8, 4, 64),
                             (1, 4096, 32, 8, 128)):
        ck = jnp.asarray(rs.randn(b, T, kv, d), jnp.bfloat16)
        cv = jnp.asarray(rs.randn(b, T, kv, d), jnp.bfloat16)
        q1 = jnp.asarray(rs.randn(b, h, d), jnp.bfloat16)
        idx = jnp.int32(T - 2)
        mask = (jnp.arange(T) <= T - 2)[None, None, None, :]
        jd = jax.jit(lambda q, k, v: dense_attention(
            q[:, None], k, v, attn_mask=mask)[:, 0])
        jp = jax.jit(lambda q, k, v: decode_attention_pallas(
            q, k, v, idx, d ** -0.5))
        err = float(jnp.max(jnp.abs(
            jd(q1, ck, cv).astype(jnp.float32)
            - jp(q1, ck, cv).astype(jnp.float32))))
        key = f"b{b}_T{T}_h{h}_kv{kv}_d{d}"
        attn[key] = {"dense_ms": time_it(jd, q1, ck, cv),
                     "pallas_ms": time_it(jp, q1, ck, cv),
                     "max_err": round(err, 4)}
        # HBM floor: read K+V once
        attn[key]["hbm_floor_ms"] = round(
            2 * b * T * kv * d * 2 / 819e9 * 1e3, 4)
        report["attn"] = attn
        bank()

    # --- 2) end-to-end generate: long decode to amortize dispatch.
    # 256 new tokens vs 64: slope = per-token cost, intercept = overhead.
    pt.seed(0)
    cfg = bench._bench_config("tiny")
    model = LlamaForCausalLM(cfg)
    gen = {}

    def time_generate(m, bs, n_new):
        ids = jnp.asarray(rs.randint(0, m.config.vocab_size, (bs, 32)))
        out = m.generate(ids, max_new_tokens=n_new, temperature=0.0)
        np.asarray(out)      # compile
        t0 = time.perf_counter()
        out = m.generate(ids, max_new_tokens=n_new, temperature=0.0)
        np.asarray(out)
        return time.perf_counter() - t0

    for bs in (1, 8):
        t64 = time_generate(model, bs, 64)
        t256 = time_generate(model, bs, 256)
        per_tok_ms = (t256 - t64) / 192 * 1e3
        gen[f"bs{bs}"] = {
            "t64_s": round(t64, 4), "t256_s": round(t256, 4),
            "per_token_ms": round(per_tok_ms, 4),
            "dispatch_overhead_ms": round(
                (t64 * 4 - t256) / 3 * 1e3, 2),
            "tokens_per_sec_steady": round(bs / per_tok_ms * 1e3, 1)}
        report["generate"] = gen
        bank()

    # weight-read floor for the tiny model: all params once per token
    n_params = sum(int(np.prod(v.shape))
                   for v in model.state_dict().values())
    report["weight_floor_ms_per_tok_bs1"] = round(
        n_params * 2 / 819e9 * 1e3, 4)
    bank()

    # --- 3) fused projections, steady-state
    from paddle_tpu.nn.fuse import fuse_projections
    pt.seed(0)
    fused = fuse_projections(LlamaForCausalLM(cfg))
    for bs in (1, 8):
        t64 = time_generate(fused, bs, 64)
        t256 = time_generate(fused, bs, 256)
        gen[f"fused_bs{bs}"] = {
            "per_token_ms": round((t256 - t64) / 192 * 1e3, 4)}
        report["generate"] = gen
        bank()

    # --- 4) int8/int4: kernel route vs forced-XLA-dequant route. Each
    # bits-width guarded on its own so an int4-specific compile failure
    # cannot cost the remaining rungs or section 5 (cf. bench.py).
    from paddle_tpu.quant import quantize_model
    for bits in (8, 4):
        try:
            for tag, disable in ((f"int{bits}_kernel", ""),
                                 (f"int{bits}_xla", "1")):
                os.environ["PADDLE_TPU_DISABLE_QUANT_KERNEL"] = disable
                pt.seed(0)
                qm = LlamaForCausalLM(cfg)
                quantize_model(qm, bits=bits, block_size=128,
                               skip=["lm_head", "embed"])
                for bs in (1, 8):
                    t64 = time_generate(qm, bs, 64)
                    t256 = time_generate(qm, bs, 256)
                    gen[f"{tag}_bs{bs}"] = {
                        "per_token_ms": round((t256 - t64) / 192 * 1e3, 4)}
                    report["generate"] = gen
                    bank()
        except Exception as e:
            gen[f"int{bits}_error"] = repr(e)[:200]
            report["generate"] = gen
            bank()
    os.environ.pop("PADDLE_TPU_DISABLE_QUANT_KERNEL", None)

    # --- 5) paged engine: per-tick decode cost with all slots busy
    from paddle_tpu.generation.paged import PagedEngine
    eng = PagedEngine(model, max_slots=8, num_blocks=64, block_size=32,
                      max_blocks_per_seq=8, prefill_buckets=(32,))
    rs2 = np.random.RandomState(1)
    for i in range(8):
        # 8 + 240 = 248 <= max_blocks_per_seq*block_size = 256; the 112
        # ticks stepped below never finish a request, so all 8 slots
        # stay busy for the whole timed window
        eng.submit(f"r{i}", rs2.randint(1, 255, (1, 8)),
                   max_new_tokens=240)
    for _ in range(12):   # admit everything + compile decode_step
        eng.step()
    t0 = time.perf_counter()
    n_ticks = 100
    for _ in range(n_ticks):
        eng.step()
    dt = time.perf_counter() - t0
    report["paged"] = {
        "tick_ms": round(dt / n_ticks * 1e3, 3),
        "tokens_per_sec": round(8 * n_ticks / dt, 1)}
    bank()
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # bank whatever we got plus the failure
        report["error"] = repr(e)[:400]
        bank()
        raise
