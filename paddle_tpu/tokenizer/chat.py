"""Chat templates (reference: PaddleNLP tokenizer ``apply_chat_template`` /
``chat_template.json`` — rendering a messages list into the model's
conversation format before tokenization).

The reference renders Jinja templates; here the three formats that cover
the supported model zoo (Llama-3, Qwen2/ChatML, ERNIE) are implemented
directly — a template is just a pure function str(messages) -> str, which
keeps the data pipeline dependency-free and trivially testable.
"""
from __future__ import annotations

from typing import Callable, Dict, List

__all__ = ["CHAT_TEMPLATES", "render_chat_template", "apply_chat_template"]

Message = Dict[str, str]  # {"role": "system|user|assistant", "content": ...}


def _llama3(messages: List[Message], add_generation_prompt: bool) -> str:
    out = ["<|begin_of_text|>"]
    for m in messages:
        out.append(f"<|start_header_id|>{m['role']}<|end_header_id|>\n\n"
                   f"{m['content']}<|eot_id|>")
    if add_generation_prompt:
        out.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
    return "".join(out)


def _chatml(messages: List[Message], add_generation_prompt: bool) -> str:
    """ChatML — Qwen2's format."""
    out = [f"<|im_start|>{m['role']}\n{m['content']}<|im_end|>\n"
           for m in messages]
    if add_generation_prompt:
        out.append("<|im_start|>assistant\n")
    return "".join(out)


def _ernie(messages: List[Message], add_generation_prompt: bool) -> str:
    out = []
    for m in messages:
        tag = {"system": "<|system|>", "user": "<|user|>",
               "assistant": "<|assistant|>"}.get(m["role"], "<|user|>")
        out.append(f"{tag}\n{m['content']}\n")
    if add_generation_prompt:
        out.append("<|assistant|>\n")
    return "".join(out)


CHAT_TEMPLATES: Dict[str, Callable] = {
    "llama3": _llama3,
    "chatml": _chatml,
    "qwen2": _chatml,
    "ernie": _ernie,
}


def render_chat_template(messages: List[Message], template: str = "llama3",
                         add_generation_prompt: bool = True) -> str:
    try:
        fn = CHAT_TEMPLATES[template]
    except KeyError:
        raise KeyError(f"unknown chat template {template!r}; have "
                       f"{sorted(CHAT_TEMPLATES)}") from None
    for m in messages:
        if "role" not in m or "content" not in m:
            raise ValueError(f"message missing role/content: {m}")
    return fn(list(messages), add_generation_prompt)


def apply_chat_template(tokenizer, messages: List[Message],
                        template: str = "llama3",
                        add_generation_prompt: bool = True,
                        tokenize: bool = True):
    """Render then (optionally) tokenize — the reference's tokenizer
    method, as a free function over any tokenizer with ``encode``."""
    text = render_chat_template(messages, template, add_generation_prompt)
    return tokenizer.encode(text) if tokenize else text
