"""Automatic mixed precision (reference: python/paddle/amp/*.py).

TPU-first AMP: bfloat16 has fp32's exponent range, so the default TPU
policy needs **no loss scaling** — `amp.auto_cast(dtype="bfloat16")` casts
layer compute to bf16 and keeps normalization/softmax/reductions in fp32
(our F.* norms already accumulate in fp32). GradScaler exists for fp16
parity and is an identity when scaling is unnecessary.

Levels (paddle parity):
- O1: per-op cast — matmul/conv inputs to low precision, fp32 elsewhere.
- O2: model weights in low precision + fp32 master weights in the optimizer
  (optimizer(multi_precision=True)).
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp

from ..dtypes import to_dtype

_amp_state = threading.local()


def _dtype():
    return getattr(_amp_state, "dtype", None)


@contextlib.contextmanager
def auto_cast(enable=True, dtype="bfloat16", level="O1", custom_white_list=None,
              custom_black_list=None):
    """Context that makes Linear/Conv/Attention cast inputs to `dtype`."""
    prev = _dtype()
    _amp_state.dtype = to_dtype(dtype) if enable else None
    _amp_state.level = level
    try:
        yield
    finally:
        _amp_state.dtype = prev


amp_guard = auto_cast


def amp_dtype():
    """Queried by compute layers; None when AMP is off."""
    return _dtype()


def maybe_cast(x):
    dt = _dtype()
    if dt is not None and hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
        return x.astype(dt)
    return x


def decorate(models=None, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None):
    """paddle.amp.decorate parity: cast model params to `dtype`; the
    optimizer keeps fp32 masters (multi_precision)."""
    dt = to_dtype(dtype)
    single = False
    if models is not None and not isinstance(models, (list, tuple)):
        models, single = [models], True
    for m in models or []:
        m.to(dtype=dt)
    if optimizers is not None:
        opts = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
        for o in opts:
            o.multi_precision = True if master_weight is None else master_weight
    if models is None:
        return optimizers
    out_models = models[0] if single else models
    if optimizers is None:
        return out_models
    return out_models, optimizers


class GradScaler:
    """Loss scaling for fp16 (reference: python/paddle/amp/grad_scaler.py).
    With bf16 (TPU default) scaling is unnecessary; enable=False makes all
    methods identity passthroughs.

    Functional usage inside ONE jitted step (no host sync anywhere):
        sstate = scaler.init_state()                       # outside jit
        scaled = scaler.scale(loss, sstate)
        ... grads of scaled loss ...
        grads, found_inf = scaler.unscale(grads, sstate)
        sstate = scaler.update_state(sstate, found_inf)    # pure, branchless
        params = scaler.select(found_inf, skipped=old, applied=new)

    The legacy mutating `update()` routes through `update_state` and then
    host-syncs to store — fine eagerly, never inside jit.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self.init_loss_scaling = init_loss_scaling
        self.incr_ratio = incr_ratio
        self.decr_ratio = decr_ratio
        self.incr_every_n_steps = incr_every_n_steps
        self.decr_every_n = decr_every_n_nan_or_inf
        self.dynamic = use_dynamic_loss_scaling
        self._scale = jnp.float32(init_loss_scaling if enable else 1.0)
        self._growth_tracker = jnp.int32(0)
        self._nan_tracker = jnp.int32(0)

    def is_enable(self):
        return self._enable

    # ------------------------------------------------- functional (jittable)
    def init_state(self):
        """Scaler state pytree — thread it through the jitted train step."""
        return {"scale": jnp.float32(self.init_loss_scaling if self._enable
                                     else 1.0),
                "growth_tracker": jnp.int32(0),
                "nan_tracker": jnp.int32(0)}

    def scale(self, loss, state=None):
        if not self._enable:
            return loss
        scale = self._scale if state is None else state["scale"]
        return loss * scale

    def unscale(self, grads, state=None):
        """Returns (unscaled_grads, found_inf[bool])."""
        if not self._enable:
            return grads, jnp.bool_(False)
        scale = self._scale if state is None else state["scale"]
        inv = 1.0 / scale
        unscaled = jax.tree.map(lambda g: g * inv, grads)
        found_inf = jnp.any(jnp.stack([
            jnp.any(~jnp.isfinite(g.astype(jnp.float32))) for g in jax.tree.leaves(unscaled)
        ]))
        return unscaled, found_inf

    def update_state(self, state, found_inf):
        """Pure, branchless paddle update_loss_scaling semantics: a bad step
        zeroes the good counter; scale shrinks only after decr_every_n
        accumulated bad steps; a good step zeroes the bad counter. Safe under
        jit — no data-dependent Python control flow."""
        if not (self._enable and self.dynamic):
            return state
        growth = jnp.where(found_inf, 0, state["growth_tracker"] + 1)
        nan = jnp.where(found_inf, state["nan_tracker"] + 1, 0)
        decr = nan >= self.decr_every_n
        incr = growth >= self.incr_every_n_steps
        scale = (state["scale"]
                 * jnp.where(decr, jnp.float32(self.decr_ratio), 1.0)
                 * jnp.where(incr, jnp.float32(self.incr_ratio), 1.0))
        return {"scale": scale,
                "growth_tracker": jnp.where(incr, 0, growth),
                "nan_tracker": jnp.where(decr, 0, nan)}

    @staticmethod
    def select(found_inf, skipped, applied):
        """Pick `skipped` (old) trees on an inf step, `applied` otherwise —
        the jittable form of 'skip the optimizer update'."""
        return jax.tree.map(
            lambda old, new: jnp.where(found_inf, old, new), skipped, applied)

    # --------------------------------------------------- eager (host-synced)
    def update(self, found_inf=None):
        """Mutating wrapper over update_state (eager use only)."""
        if not (self._enable and self.dynamic) or found_inf is None:
            return
        state = {"scale": self._scale, "growth_tracker": self._growth_tracker,
                 "nan_tracker": self._nan_tracker}
        state = self.update_state(state, jnp.bool_(found_inf))
        self._scale = state["scale"]
        self._growth_tracker = state["growth_tracker"]
        self._nan_tracker = state["nan_tracker"]

    # paddle flow: scaler.step(optimizer) + scaler.update()
    def step(self, optimizer, layer=None, grads=None):
        grads, found_inf = self.unscale(grads)
        if isinstance(found_inf, jax.core.Tracer):
            raise TypeError(
                "GradScaler.step() is the eager/host-synced path and "
                "cannot run under jit (bool(found_inf) would sync or "
                "fail). Inside a jitted train step use the functional "
                "API: init_state/update_state/select — see "
                "Trainer._build_step for the pattern.")
        if not bool(found_inf):
            optimizer.step(grads=grads, layer=layer)
        self.update(found_inf)

    def state_dict(self):
        return {"scale": self._scale, "growth_tracker": self._growth_tracker,
                "nan_tracker": self._nan_tracker}

    def load_state_dict(self, sd):
        self._scale = jnp.float32(sd["scale"])
        self._growth_tracker = jnp.int32(sd["growth_tracker"])
        self._nan_tracker = jnp.int32(sd.get("nan_tracker", 0))
