"""Regression tests for review findings: AMP O1 casting, GradScaler
counters, broadcast semantics, dropout infer modes, RNG determinism and
traced keys, BatchNorm buffer hygiene under jit."""
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import amp
from paddle_tpu.distributed import collective, env
from paddle_tpu.nn import functional as F
from paddle_tpu.utils import rng


def test_autocast_casts_linear_to_bf16():
    layer = pt.nn.Linear(8, 8)
    x = jnp.ones((2, 8), jnp.float32)
    assert layer(x).dtype == jnp.float32
    with amp.auto_cast(dtype="bfloat16"):
        assert layer(x).dtype == jnp.bfloat16
    assert layer(x).dtype == jnp.float32


def test_gradscaler_decr_every_n():
    s = amp.GradScaler(init_loss_scaling=1024.0, decr_every_n_nan_or_inf=2)
    s.update(jnp.bool_(True))
    assert float(s._scale) == 1024.0  # first bad step: counter only
    s.update(jnp.bool_(True))
    assert float(s._scale) == 512.0   # second consecutive: halve
    s.update(jnp.bool_(True))
    s.update(jnp.bool_(False))        # good step resets bad counter
    s.update(jnp.bool_(True))
    assert float(s._scale) == 512.0


def test_gradscaler_update_state_jittable():
    s = amp.GradScaler(init_loss_scaling=1024.0, decr_every_n_nan_or_inf=1,
                       incr_every_n_steps=2)
    state = s.init_state()
    upd = jax.jit(s.update_state)
    state = upd(state, jnp.bool_(True))       # inf: halve immediately
    assert float(state["scale"]) == 512.0
    state = upd(state, jnp.bool_(False))
    state = upd(state, jnp.bool_(False))      # 2 good steps: double
    assert float(state["scale"]) == 1024.0
    assert int(state["growth_tracker"]) == 0


def test_fp16_trainer_step_skips_on_inf_under_one_jit():
    """VERDICT r1 item 7: inf-grad step skips the update + halves the scale,
    scaler state threaded through the single jitted train step."""
    from paddle_tpu.trainer import Trainer, TrainingArguments

    model = pt.nn.Linear(4, 4, bias_attr=False)
    opt = pt.optimizer.SGD(learning_rate=0.1)
    scaler = amp.GradScaler(init_loss_scaling=256.0,
                            decr_every_n_nan_or_inf=1, incr_every_n_steps=3)
    tr = Trainer(model, opt,
                 TrainingArguments(output_dir="/tmp/pt_fp16_test",
                                   max_steps=1, donate_state=False),
                 loss_fn=lambda fn, p, b: jnp.sum(fn(p, b) ** 2),
                 scaler=scaler)
    # build the step manually to drive it with controlled batches
    tr._opt_state = opt.init(tr._params)
    step = tr._build_step()
    p0 = jax.tree.map(lambda x: np.asarray(x), dict(tr._params))
    # batch big enough that (xW)^2 overflows fp32 -> inf loss -> inf grads
    bad = jnp.full((2, 4), 1e20, jnp.float32)
    params, state, sstate, loss = step(
        dict(tr._params), tr._opt_state, tr._scaler_state, jnp.int32(0), bad)
    assert float(sstate["scale"]) == 128.0        # halved
    for k, v in params.items():                    # update skipped
        np.testing.assert_array_equal(np.asarray(v), p0[k])
    # a finite batch applies the update and keeps the scale
    good = jnp.ones((2, 4), jnp.float32)
    params2, _, sstate2, _ = step(dict(params), state, sstate,
                                  jnp.int32(1), good)
    assert float(sstate2["scale"]) == 128.0
    assert any(not np.array_equal(np.asarray(params2[k]), np.asarray(params[k]))
               for k in params2)


def test_eager_broadcast_correct():
    env.init_parallel_env({})  # dp over all 8
    n = env.get_world_size("dp")
    x = jnp.arange(n * 4, dtype=jnp.float32).reshape(n, 4)
    out = collective.eager_broadcast(x, src=2, group="dp")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x[2:3]))


def test_dropout_downscale_in_infer():
    x = jnp.ones((4, 4))
    out = F.dropout(x, p=0.5, training=False, mode="downscale_in_infer")
    np.testing.assert_allclose(np.asarray(out), 0.5)
    layer = pt.nn.Dropout(0.5, mode="downscale_in_infer")
    layer.eval()
    np.testing.assert_allclose(np.asarray(layer(x)), 0.5)
    # upscale mode unchanged at eval
    np.testing.assert_allclose(
        np.asarray(F.dropout(x, 0.5, training=False, mode="upscale_in_train")), 1.0)


def test_rng_stream_stable_and_local_distinct():
    assert rng._stream_seed("global") == rng._stream_seed("global")
    assert rng._stream_seed("global") != rng._stream_seed("local")


def test_key_context_traced_dropout_varies_by_key():
    model = pt.nn.Sequential(pt.nn.Linear(8, 8), pt.nn.Dropout(0.5))
    model.train()
    fn, params = model.functional()
    jitted = jax.jit(fn)
    x = jnp.ones((4, 8))
    o1 = jitted(params, x, rng=jax.random.key(1))
    o2 = jitted(params, x, rng=jax.random.key(2))
    o1b = jitted(params, x, rng=jax.random.key(1))
    assert not np.array_equal(np.asarray(o1), np.asarray(o2))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o1b))


def test_next_key_warns_under_trace_without_context():
    rng._ensure()
    rng._state.warned_const_key = False
    model = pt.nn.Sequential(pt.nn.Linear(8, 8), pt.nn.Dropout(0.5))
    model.train()
    fn, params = model.functional()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        jax.jit(fn)(params, jnp.ones((4, 8)))
        assert any("baked" in str(w.message) for w in rec)


def test_batchnorm_no_tracer_leak_under_jit():
    bn = pt.nn.BatchNorm2D(3)
    bn.train()
    fn, params = bn.functional()
    x = jnp.ones((2, 3, 4, 4))
    jax.jit(fn)(params, x)  # traced forward rebinds stats...
    mean = bn._buffers["_mean"]
    assert isinstance(mean, jax.Array)  # ...but the tracer must not leak
    bn.eval()
    bn(x)  # would raise UnexpectedTracerError before the fix
    # with_buffers path actually carries the stats update out
    fnb, (params, bufs) = bn.functional(with_buffers=True)
    bn.train()
    out, new_bufs = jax.jit(fnb)(params, bufs, x)
    assert not np.allclose(np.asarray(new_bufs["_mean"]), np.asarray(bufs["_mean"]))


def test_scheduler_driven_optimizer_lr():
    layer = pt.nn.Linear(4, 4)
    sched = pt.optimizer.lr.ExponentialDecay(learning_rate=0.1, gamma=0.5)
    opt = pt.optimizer.SGD(learning_rate=sched, parameters=layer)
    grads = {k: jnp.ones_like(v) for k, v in layer.named_parameters()}
    w0 = np.asarray(layer.weight)
    opt.step(grads=grads)
    w1 = np.asarray(layer.weight)
    np.testing.assert_allclose(w0 - w1, 0.1, rtol=1e-6)  # epoch 0: lr=0.1
    sched.step(); sched.step()  # epoch 2: lr=0.025
    opt.step(grads=grads)
    w2 = np.asarray(layer.weight)
    np.testing.assert_allclose(w1 - w2, 0.025, rtol=1e-6)


def test_scaler_step_rejects_tracers():
    """GradScaler.step is the eager path; under jit it must raise the
    documented TypeError instead of silently host-syncing (VERDICT r2
    weak#6)."""
    import jax
    import pytest
    from paddle_tpu.amp import GradScaler

    scaler = GradScaler(init_loss_scaling=2.0)

    class _Opt:
        def step(self, grads=None, layer=None):
            pass

    def inside_jit(g):
        with pytest.raises(TypeError, match="eager"):
            scaler.step(_Opt(), grads={"w": g})
        return g

    jax.jit(inside_jit)(jax.numpy.ones(2))
