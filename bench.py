#!/usr/bin/env python
"""Headline bench (SURVEY.md §6): Llama train-step tokens/sec/chip + MFU on
the local chip. Prints EXACTLY ONE JSON line on stdout, ALWAYS — success or
failure. vs_baseline = achieved MFU / 0.40 (the reference's Llama-3
pretraining MFU target in BASELINE.json).

Environment-proof redesign (VERDICT r2 item 1). The axon TPU tunnel has
HUNG during backend init in both prior rounds, so:

  (a) PROBE first: a subprocess that only calls ``jax.devices()`` under a
      75s timeout, twice max. If the backend is down we stop *before*
      building any model and emit a failure JSON with the probe evidence.
  (b) HARD TOTAL BUDGET: everything (probe + all attempts + retries) fits
      in PADDLE_TPU_BENCH_BUDGET seconds (default 450s < 8 min); each
      subprocess timeout is clamped to the remaining budget.
  (c) ALWAYS-EMIT JSON: every exit path prints one machine-readable line —
      on failure ``{"error":..., "probe":..., "attempts":N, ...}`` so the
      driver never records just a stderr tail again.
  (d) CONFIG LADDER: a tiny model first (compiles in seconds → a real
      tokens/s number is banked), then the ~470M headline config only if
      budget remains. The best successful rung wins.

Each rung runs in a fresh child process because a failed TPU init is
sticky within a jax process."""
import functools
import json
import os
import subprocess
import sys
import time

BATCH, SEQ = 8, 2048
TINY_BATCH, TINY_SEQ = 8, 1024

# peak bf16 FLOP/s per chip by device kind
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # trillium
}


# ---------------------------------------------------------------- children

def _force_platform():
    """PADDLE_TPU_BENCH_PLATFORM=cpu forces a backend in the children. The
    env var JAX_PLATFORMS alone is NOT enough in this image: the axon
    sitecustomize re-selects its platform via jax.config after env
    parsing, so only an in-process config.update wins."""
    plat = os.environ.get("PADDLE_TPU_BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)


def _child_probe():
    """Backend-reachability probe: jax.devices() and nothing else."""
    t0 = time.time()
    _force_platform()
    import jax
    devs = jax.devices()
    print(json.dumps({
        "probe_ok": True,
        "n_devices": len(devs),
        "device_kind": devs[0].device_kind,
        "platform": devs[0].platform,
        "probe_s": round(time.time() - t0, 1),
    }))


def _bench_config(rung):
    from paddle_tpu.models import LlamaConfig
    import jax.numpy as jnp
    if os.environ.get("PADDLE_TPU_BENCH_SMOKE"):
        # machinery self-test (probe -> ladder -> JSON) on any backend; the
        # numbers it yields are meaningless.
        return LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=256,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128, recompute=False, dtype=jnp.float32)
    if rung == "tiny":
        # ~67M params: compiles in seconds, still MXU-bound bf16 matmuls.
        return LlamaConfig(
            vocab_size=8192, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=4,
            max_position_embeddings=TINY_SEQ, rope_theta=500000.0,
            recompute=False, dtype=jnp.bfloat16)
    # headline: ~470M-param Llama shaped to saturate a single v5e (16G HBM)
    # with remat; same code path as the 8B recipe. The "_dots" variant
    # keeps weight-matmul outputs in HBM and reruns only elementwise
    # chains — fewer recompute FLOPs if the activations fit.
    policy = ("dots_with_no_batch_dims_saveable" if rung == "headline_dots"
              else "full")
    return LlamaConfig(
        vocab_size=32768, hidden_size=2048, intermediate_size=5632,
        num_hidden_layers=8, num_attention_heads=16, num_key_value_heads=8,
        max_position_embeddings=SEQ, rope_theta=500000.0,
        recompute=True, recompute_policy=policy, dtype=jnp.bfloat16)


def _child_bench(rung):
    _force_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np
    # persistent compilation cache: shared across rungs/attempts/processes.
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import paddle_tpu as pt
    from paddle_tpu.models import LlamaForCausalLM, causal_lm_loss

    batch, seq = (TINY_BATCH, TINY_SEQ) if rung == "tiny" else (BATCH, SEQ)
    if os.environ.get("PADDLE_TPU_BENCH_SMOKE"):
        batch, seq = 2, 128
    dev = jax.devices()[0]
    peak = PEAK_FLOPS.get(dev.device_kind, 197e12)
    pt.seed(0)
    cfg = _bench_config(rung)
    model = LlamaForCausalLM(cfg)
    fn, params = model.functional()
    n_params = sum(int(np.prod(v.shape)) for v in params.values())

    opt = pt.optimizer.AdamW(learning_rate=1e-4, multi_precision=True,
                             grad_clip=pt.optimizer.ClipGradByGlobalNorm(1.0))
    state = opt.init(params)
    ids = jnp.asarray(np.random.randint(0, cfg.vocab_size, (batch, seq)))

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, state, step, ids):
        def loss_fn(p):
            return causal_lm_loss(fn(p, ids), ids)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = opt.apply(params, grads, state, step)
        return params, state, loss

    # warmup/compile (float() forces a device->host transfer: on the axon
    # tunnel block_until_ready alone returns before execution completes)
    params, state, loss = train_step(params, state, jnp.int32(0), ids)
    float(loss)

    steps = 5 if rung == "tiny" else 10
    t0 = time.perf_counter()
    for i in range(1, steps + 1):
        params, state, loss = train_step(params, state, jnp.int32(i), ids)
    float(loss)
    dt = (time.perf_counter() - t0) / steps

    tokens_per_sec = batch * seq / dt
    # Honest 6N (VERDICT r1 weak#3): the input-embedding forward is a
    # gather, not a matmul, so its params don't belong in 6N; lm_head does
    # (it IS a matmul). mfu_legacy keeps round 1's all-params formula once
    # for continuity.
    embed_params = cfg.vocab_size * cfg.hidden_size
    matmul_params = n_params - embed_params
    attn_flops = 6 * cfg.num_hidden_layers * seq * cfg.hidden_size
    flops_per_token = 6 * matmul_params + attn_flops
    mfu = flops_per_token * tokens_per_sec / peak
    mfu_legacy = (6 * n_params + attn_flops) * tokens_per_sec / peak
    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
        "mfu": round(mfu, 4),
        "mfu_legacy": round(mfu_legacy, 4),
        "config": rung,
        "params": n_params,
        "step_ms": round(dt * 1e3, 2),
        "device": dev.device_kind,
        "loss": round(float(loss), 4),
    }))


def _child_decode():
    """Decode-path bench (VERDICT r2 item 5): per-step latency of the old
    masked-dense attention over the full cache vs the new GQA-native
    decode path (Pallas kernel on TPU), plus end-to-end generate()
    tokens/s at bs=1 and bs=8."""
    _force_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import paddle_tpu as pt
    from paddle_tpu.ops.attention import decode_attention, dense_attention
    from paddle_tpu.models import LlamaForCausalLM

    smoke = bool(os.environ.get("PADDLE_TPU_BENCH_SMOKE"))
    b, T, h, kv, d = (2, 256, 4, 2, 64) if smoke else (8, 2048, 16, 8, 128)
    rs = np.random.RandomState(0)
    dt = jnp.bfloat16
    q = jnp.asarray(rs.randn(b, 1, h, d), dt)
    ck = jnp.asarray(rs.randn(b, T, kv, d), dt)
    cv = jnp.asarray(rs.randn(b, T, kv, d), dt)
    idx = jnp.int32(T - 2)

    def dense_ref(q, ck, cv, idx):
        mask = (jnp.arange(T) <= idx)[None, None, None, :]
        return dense_attention(q, ck, cv, attn_mask=mask)

    def time_it(fn, *args, iters=50):
        jfn = jax.jit(fn)  # one wrapper: iterations hit the trace cache
        np.asarray(jfn(*args))  # compile + force full execution (axon:
        # block_until_ready returns early; only a D2H transfer waits)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jfn(*args)
        np.asarray(out)  # device queue is FIFO: last done => all done
        return (time.perf_counter() - t0) / iters * 1e3  # ms

    ms_dense = time_it(dense_ref, q, ck, cv, idx)
    ms_decode = time_it(decode_attention, q, ck, cv, idx)

    # end-to-end generate tokens/s (static cache, while_loop decode)
    pt.seed(0)
    model = LlamaForCausalLM(_bench_config("tiny"))
    gen = {}
    new_tok = 16 if smoke else 64

    def time_generate(m, bs, tag):
        ids = jnp.asarray(rs.randint(0, m.config.vocab_size, (bs, 32)))
        out = m.generate(ids, max_new_tokens=new_tok, temperature=0.0)
        np.asarray(out)  # compile + force execution (see time_it)
        t0 = time.perf_counter()
        out = m.generate(ids, max_new_tokens=new_tok, temperature=0.0)
        np.asarray(out)
        dt_s = time.perf_counter() - t0
        gen[tag] = round(bs * new_tok / dt_s, 1)

    for bs in (1, 8):
        time_generate(model, bs, f"generate_tokens_per_sec_bs{bs}")

    # fused q/k/v + gate/up projections (VERDICT r3 item 2: attack the
    # decode while_loop body) — same weights, fewer matmul launches
    try:
        from paddle_tpu.nn.fuse import fuse_projections
        pt.seed(0)
        fused = fuse_projections(LlamaForCausalLM(_bench_config("tiny")))
        for bs in (1, 8):
            time_generate(fused, bs,
                          f"generate_fused_tokens_per_sec_bs{bs}")
    except Exception as e:  # keep the rung's other numbers
        gen["fused_error"] = repr(e)[:120]

    # int8/int4 weight-only decode: half/quarter the HBM bytes per token
    # — the main lever for the memory-bound decode regime (the int4
    # nibble path cleared its hardware compile-check in round 5)
    for bits in (8, 4):
        try:
            from paddle_tpu.quant import quantize_model
            pt.seed(0)
            qmodel = LlamaForCausalLM(_bench_config("tiny"))
            n_swapped = quantize_model(qmodel, bits=bits, block_size=128,
                                       skip=["lm_head", "embed"])
            assert n_swapped > 0, "quantize_model swapped nothing"
            for bs in (1, 8):
                time_generate(qmodel, bs,
                              f"generate_int{bits}_tokens_per_sec_bs{bs}")
        except Exception as e:
            gen[f"int{bits}_error"] = repr(e)[:120]

    # speculative decoding with a 1-layer draft of the same family
    # (VERDICT r3 weak #5: a measured tokens/s comparison)
    try:
        from paddle_tpu.generation.speculative import speculative_generate
        pt.seed(0)
        cfg = _bench_config("tiny")
        cfg.num_hidden_layers = 1
        draft = LlamaForCausalLM(cfg)
        ids = jnp.asarray(rs.randint(0, model.config.vocab_size, (1, 32)))
        out = speculative_generate(model, draft, ids,
                                   max_new_tokens=new_tok,
                                   num_draft_tokens=4)
        np.asarray(out)
        t0 = time.perf_counter()
        out, stats = speculative_generate(model, draft, ids,
                                          max_new_tokens=new_tok,
                                          num_draft_tokens=4,
                                          return_stats=True)
        np.asarray(out)
        dt_s = time.perf_counter() - t0
        gen["speculative_tokens_per_sec_bs1"] = round(new_tok / dt_s, 1)
        gen["speculative_tokens_per_forward"] = round(
            stats["tokens_per_forward"], 2)

        # random-init drafts accept ~nothing (tokens_per_forward ~1), so
        # the rung above is the floor. The CEILING — what a well-trained
        # draft buys — is draft == target: every proposal accepted.
        out = speculative_generate(model, model, ids,
                                   max_new_tokens=new_tok,
                                   num_draft_tokens=4)
        np.asarray(out)
        t0 = time.perf_counter()
        out, stats = speculative_generate(model, model, ids,
                                          max_new_tokens=new_tok,
                                          num_draft_tokens=4,
                                          return_stats=True)
        np.asarray(out)
        dt_s = time.perf_counter() - t0
        gen["speculative_ceiling_tokens_per_sec_bs1"] = round(
            new_tok / dt_s, 1)
        gen["speculative_ceiling_tokens_per_forward"] = round(
            stats["tokens_per_forward"], 2)
    except Exception as e:  # keep the rung's other numbers
        gen["speculative_error"] = repr(e)[:120]

    # paged continuous batching: mixed-length stream throughput
    try:
        from paddle_tpu.generation.paged import PagedEngine
        eng = PagedEngine(model, max_slots=8, num_blocks=64,
                          block_size=32, max_blocks_per_seq=8,
                          prefill_buckets=(32,))
        rs2 = np.random.RandomState(1)
        # warmup: compile the prefill + decode executables untimed,
        # like every other number in this rung
        eng.submit("warm", rs2.randint(1, model.config.vocab_size,
                                       (1, 32)), max_new_tokens=2)
        eng.run()
        for i in range(16):
            eng.submit(i, rs2.randint(1, model.config.vocab_size,
                                      (1, 32)), max_new_tokens=new_tok)
        t0 = time.perf_counter()
        res = eng.run()
        dt_s = time.perf_counter() - t0
        n_tok = sum(len(v) for v in res.values())
        gen["paged_tokens_per_sec"] = round(n_tok / dt_s, 1)
        gen["paged_active_slot_frac"] = round(
            eng.stats["active_slot_steps"]
            / max(eng.stats["slot_steps"], 1), 3)
    except Exception as e:
        gen["paged_error"] = repr(e)[:120]

    # prefix caching (round 5): 16 requests sharing a 64-token system
    # prompt — the cached run should skip most prefill chunks
    try:
        from paddle_tpu.generation.paged import PagedEngine
        rs3 = np.random.RandomState(2)
        sysp = rs3.randint(1, model.config.vocab_size, 64).tolist()
        reqs = [np.asarray([sysp + rs3.randint(
            1, model.config.vocab_size, 8).tolist()]) for _ in range(16)]
        for tag, pc in (("prefix_cache_on", True),
                        ("prefix_cache_off", False)):
            eng = PagedEngine(model, max_slots=8, num_blocks=96,
                              block_size=32, max_blocks_per_seq=8,
                              prefill_buckets=(32,),
                              chunk_prefill_tokens=32,
                              enable_prefix_cache=pc)
            # compile BOTH the miss path and (cache on) the adoption
            # path before timing: warm2 shares warm's prefix, so its
            # admission exercises the seen-seed + adoption scatters
            eng.submit("warm", reqs[0], max_new_tokens=2)
            eng.run()
            eng.submit("warm2", np.asarray([sysp + [9, 9]]),
                       max_new_tokens=2)
            eng.run()
            warm_chunks = eng.stats["prefill_chunks"]
            t0 = time.perf_counter()
            for i, ids in enumerate(reqs):
                eng.submit(i, ids, max_new_tokens=16)
            res = eng.run()
            dt_s = time.perf_counter() - t0
            # count only the timed requests (results accumulate the
            # warmups too) and only the timed batch's chunks
            n_tok = sum(len(res[i]) for i in range(len(reqs)))
            gen[f"paged_{tag}_tokens_per_sec"] = round(n_tok / dt_s, 1)
            gen[f"paged_{tag}_prefill_chunks"] = \
                eng.stats["prefill_chunks"] - warm_chunks
    except Exception as e:
        gen["prefix_cache_error"] = repr(e)[:120]

    print(json.dumps({"decode": {
        "attn_ms_dense": round(ms_dense, 3),
        "attn_ms_decode_kernel": round(ms_decode, 3),
        "attn_speedup": round(ms_dense / ms_decode, 2),
        "shape": f"b{b} T{T} h{h} kv{kv} d{d}",
        **gen,
    }}))


# ------------------------------------------------------------------ parent

def _run_child(mode, timeout):
    """Run one child rung; return (rc, parsed_json_or_None, stderr_tail)."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env={**os.environ, "_PADDLE_TPU_BENCH_CHILD": mode},
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=timeout)
        rc, out = proc.returncode, proc.stdout.decode(errors="replace")
        err = proc.stderr.decode(errors="replace")
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"").decode(errors="replace")
        err = (e.stderr or b"").decode(errors="replace")
        rc = 124
    parsed = None
    for line in reversed(out.strip().splitlines()):
        try:
            parsed = json.loads(line)
            break
        except ValueError:
            continue
    return rc, parsed, err[-800:]


def _last_known_good():
    """Best previously-banked TPU numbers (BENCH_SELF_*.json): embedded
    in failure JSON so the driver artifact always carries the best
    available evidence even when the tunnel is down."""
    here = os.path.dirname(os.path.abspath(__file__))
    best = None
    import glob
    for f in sorted(glob.glob(os.path.join(here, "BENCH_SELF_*.json"))):
        try:
            with open(f) as fh:
                data = json.load(fh)
            # self-run files wrap the train-rung JSON under "train"
            data = data.get("train", data) if isinstance(data, dict) else {}
            if data.get("value"):
                if best is None or data.get("mfu", 0) >= best[1].get("mfu",
                                                                     0):
                    best = (os.path.basename(f), data)
        except Exception:
            continue  # one corrupt file must not discard the others
    if best is None:
        return None
    return {"file": best[0],
            **{k: best[1][k] for k in ("value", "unit", "mfu",
                                       "vs_baseline", "config", "device")
               if k in best[1]}}


def _ingest_rung(result, probe, filename, section_key, profile_field,
                 promote):
    """Fold one rung file (written by tools/decode_profile.py or
    tools/serve_loadgen.py next to this script) into the bench result:
    always annotate ``result["decode"][profile_field]`` with the full
    section + provenance; promote the keys in ``promote`` (first one
    required for the file to count at all) only under the same-device
    + <6h freshness gate. Missing/corrupt files are ignored."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        filename)
    try:
        with open(path) as f:
            pj = json.load(f)
        section = pj.get(section_key)
        if not section or promote[0] not in section:
            return
        result.setdefault("decode", {})
        result["decode"][profile_field] = dict(
            section, profile_device=pj.get("device"),
            profile_started=pj.get("started"))
        try:
            age_s = time.time() - time.mktime(time.strptime(
                pj["started"], "%Y-%m-%d %H:%M:%S"))
        except (KeyError, ValueError):
            age_s = float("inf")
        if pj.get("device") == probe.get("device_kind") \
                and age_s < 6 * 3600:
            for key in promote:
                if key in section:
                    result["decode"].setdefault(key, section[key])
    except (OSError, ValueError):
        pass


def main():
    budget = float(os.environ.get("PADDLE_TPU_BENCH_BUDGET", 450))
    t0 = time.monotonic()

    def remaining():
        return budget - (time.monotonic() - t0)

    failures = []
    attempts = 0

    # (a) probe: is the backend even reachable? The first attempt is
    # CHEAP (25s): when the tunnel hangs (its usual failure mode) the
    # whole probe phase burns ~100s instead of 150s of the budget.
    probe = None
    for probe_t in (25.0, 75.0):
        if remaining() < 20:
            break
        attempts += 1
        rc, parsed, err = _run_child(
            "probe", min(probe_t, max(remaining() - 10, 15)))
        if rc == 0 and parsed and parsed.get("probe_ok"):
            probe = parsed
            break
        failures.append({"stage": "probe", "rc": rc,
                         "stderr_tail": err[-300:]})
    if probe is None:
        print(json.dumps({
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s/chip", "vs_baseline": 0.0,
            "error": "backend unreachable: jax.devices() probe failed/hung",
            "last_known_good": _last_known_good(),
            "probe": failures, "attempts": attempts,
            "budget_s": budget, "elapsed_s": round(time.monotonic() - t0, 1),
        }))
        return 3

    # (b/d) ladder: bank a tiny number, then the headline config, then the
    # lighter-remat headline variant (kept only if it measures FASTER —
    # it can OOM or lose, in which case the plain headline stands).
    result = None
    for rung, max_t, min_t in (("tiny", 240.0, 45.0),
                               ("headline", 420.0, 150.0),
                               ("headline_dots", 300.0, 120.0)):
        if remaining() < min_t:
            break
        if rung == "headline_dots" and (result is None or
                                        result.get("config") != "headline"):
            continue  # only as an upgrade attempt over a banked headline
        attempts += 1
        rc, parsed, err = _run_child(rung, min(max_t, remaining() - 15))
        if rc == 0 and parsed and "value" in parsed:
            if rung == "headline_dots" and result is not None and \
                    parsed.get("mfu", 0) <= result.get("mfu", 0):
                continue  # not an improvement; keep the plain headline
            result = parsed
        else:
            failures.append({"stage": rung, "rc": rc,
                             "stderr_tail": err[-300:]})
            # one retry per rung if the failure looks transient and the
            # budget allows; a hang (rc=124) is NOT retried — it would
            # just burn the rest of the budget the same way.
            transient = rc != 124 and any(
                s in err for s in ("UNAVAILABLE", "DEADLINE_EXCEEDED",
                                   "failed to connect", "Socket closed"))
            if transient and remaining() > min_t + 30:
                attempts += 1
                rc, parsed, err = _run_child(rung, min(max_t, remaining() - 15))
                if rc == 0 and parsed and "value" in parsed:
                    result = parsed
                else:
                    failures.append({"stage": rung + "_retry", "rc": rc,
                                     "stderr_tail": err[-300:]})

    # decode-path bench rides along if a training number is banked and
    # budget remains (its JSON merges into the result).
    if result is not None and remaining() > 70:
        attempts += 1
        rc, parsed, err = _run_child("decode", min(200.0, remaining() - 15))
        if rc == 0 and parsed and "decode" in parsed:
            result["decode"] = parsed["decode"]
        else:
            failures.append({"stage": "decode", "rc": rc,
                             "stderr_tail": err[-300:]})

    # Profiler/loadgen rung ingestion — decode_profile (ISSUE 6) and
    # serve_loadgen (ISSUE 9) share one contract: annotate the banked
    # bench with the profile either way, but promote the headline keys
    # only when the file came from THIS window (same device kind,
    # started < 6h ago — a stale CPU-run file, or a week-old hardware
    # window's, must not masquerade as this run's number).
    if result is not None:
        _ingest_rung(result, probe, "DECODE_PROFILE_r06.json", "paged",
                     "paged_profile",
                     ("paged_tokens_per_sec",
                      "paged_spec_tokens_per_sec",
                      "paged_sampled_spec_tokens_per_sec",
                      "paged_churn_tokens_per_sec",
                      "paged_churn_fused_tokens_per_sec"))
        _ingest_rung(result, probe, "SERVE_LOADGEN_r07.json", "gateway",
                     "gateway_profile",
                     ("gateway_tokens_per_sec", "gateway_p99_ttft_ms",
                      "kv_spill_hit_frac", "kv_spill_restored_tokens",
                      "kv_xfer_hit_frac", "recompute_tokens_saved",
                      "phase_breakdown"))
        _ingest_rung(result, probe, "SERVE_FLEET_r13.json", "fleet",
                     "fleet_profile",
                     ("fleet_tokens_per_sec", "goodput_per_replica"))
        _ingest_rung(result, probe, "FLEET_SIM_r16.json", "fleet_sim",
                     "fleet_sim_profile",
                     ("sim_decisions_per_sec", "alert_precision",
                      "alert_recall"))

    # (c) always emit exactly one JSON line.
    if result is not None:
        result["probe"] = {k: probe[k] for k in
                           ("device_kind", "probe_s", "n_devices")}
        result["attempts"] = attempts
        print(json.dumps(result))
        return 0
    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": 0.0, "unit": "tokens/s/chip", "vs_baseline": 0.0,
        "error": "probe ok but all bench rungs failed",
        "last_known_good": _last_known_good(),
        "probe": probe, "failures": failures, "attempts": attempts,
        "budget_s": budget, "elapsed_s": round(time.monotonic() - t0, 1),
    }))
    return 4


if __name__ == "__main__":
    mode = os.environ.get("_PADDLE_TPU_BENCH_CHILD")
    if mode == "probe":
        _child_probe()
    elif mode == "decode":
        _child_decode()
    elif mode in ("tiny", "headline", "headline_dots"):
        _child_bench(mode)
    else:
        sys.exit(main())
