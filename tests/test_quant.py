"""Weight-only quantization tests (C17): roundtrip error bounds, packed
int4 correctness, model-tree swapping, QAT straight-through grads.
"""
import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import nn
from paddle_tpu.quant import (FakeQuantLinear, QuantizedLinear,
                              dequantize_weight, fake_quant,
                              quantize_blockwise, quantize_model,
                              weight_only_linear)


def _rand_w(din, dout, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), (din, dout)) * 0.05


class TestBlockwise:
    def test_int8_roundtrip_error(self):
        w = _rand_w(256, 64)
        q, s = quantize_blockwise(w, bits=8, block_size=128)
        assert q.dtype == jnp.int8 and q.shape == (256, 64)
        assert s.shape == (2, 64)
        deq = dequantize_weight(q, s, bits=8, block_size=128,
                                dtype=jnp.float32)
        # symmetric int8: rounding error ≤ scale/2, plus bf16 scale
        # storage adds ~2^-8 relative error on the weight magnitude
        max_scale = float(s.astype(jnp.float32).max())
        max_w = float(jnp.abs(w).max())
        assert float(jnp.abs(deq - w).max()) <= \
            max_scale * 0.51 + max_w * 2 ** -7

    def test_int4_pack_unpack_exact(self):
        """Quantize→pack→unpack must reproduce the unpacked int values."""
        w = _rand_w(128, 16, seed=1)
        q8, s = quantize_blockwise(w, bits=4, block_size=128)
        assert q8.shape == (64, 16)   # two rows per byte
        deq = dequantize_weight(q8, s, bits=4, block_size=128,
                                dtype=jnp.float32)
        # independently compute the unpacked reference
        wf = np.asarray(w, np.float32).reshape(1, 128, 16)
        scales = np.abs(wf).max(axis=1) / 7.0
        qref = np.clip(np.round(wf / scales[:, None]), -7, 7).reshape(128, 16)
        ref = (qref * np.asarray(s, np.float32).repeat(128, 0).reshape(128, 16))
        np.testing.assert_allclose(np.asarray(deq), ref, atol=1e-2)

    def test_int4_negative_values_sign_extend(self):
        w = jnp.ones((128, 4)) * -0.5   # all negative → all nibbles negative
        q, s = quantize_blockwise(w, bits=4, block_size=128)
        deq = dequantize_weight(q, s, bits=4, block_size=128,
                                dtype=jnp.float32)
        assert float(deq.max()) < 0, "sign extension broken"
        np.testing.assert_allclose(np.asarray(deq), np.asarray(w), rtol=0.01)

    def test_matmul_close_to_dense(self):
        w = _rand_w(256, 32, seed=2)
        x = jax.random.normal(jax.random.PRNGKey(3), (4, 256))
        dense = x @ w
        for bits, tol in ((8, 2e-2), (4, 2e-1)):
            q, s = quantize_blockwise(w, bits=bits)
            out = weight_only_linear(x, q, s, bits=bits)
            err = float(jnp.abs(out - dense).max()) / float(jnp.abs(dense).max())
            assert err < tol, f"bits={bits}: rel err {err}"


class TestQuantizedLinear:
    def test_from_linear_forward(self):
        lin = nn.Linear(128, 16)
        qlin = QuantizedLinear.from_linear(lin, bits=8)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 128))
        np.testing.assert_allclose(np.asarray(qlin(x)), np.asarray(lin(x)),
                                   atol=5e-2)

    def test_quantize_model_swaps_and_skips(self):
        model = nn.Sequential(nn.Linear(128, 64), nn.GELU(),
                              nn.Linear(64, 128))  # 64 not divisible by 128
        n = quantize_model(model, bits=8, block_size=128)
        assert n == 1   # second layer skipped (in_features=64)
        kinds = [type(l).__name__ for l in model.sublayers()]
        assert "QuantizedLinear" in kinds

    def test_quantize_model_skip_patterns(self):
        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.body = nn.Linear(128, 8)
                self.lm_head = nn.Linear(128, 8)
        m = M()
        n = quantize_model(m, skip=["lm_head"])
        assert n == 1
        assert type(m._sub_layers["lm_head"]).__name__ == "Linear"

    def test_jit_and_memory_dtype(self):
        lin = nn.Linear(256, 64)
        qlin = QuantizedLinear.from_linear(lin, bits=4)
        fn, params = qlin.functional()
        assert params["qweight"].dtype == jnp.int8
        out = jax.jit(fn)(params, jnp.ones((1, 256)))
        assert out.shape == (1, 64) and bool(jnp.all(jnp.isfinite(out)))


class TestQAT:
    def test_fake_quant_ste_gradient(self):
        x = jnp.linspace(-1, 1, 32)
        g = jax.grad(lambda v: jnp.sum(fake_quant(v) ** 2))(x)
        # STE: gradient flows as if identity → d/dx sum(q(x)^2) ≈ 2q(x)
        assert bool(jnp.all(jnp.isfinite(g)))
        assert float(jnp.abs(g).sum()) > 0

    def test_fake_quant_linear_trains(self):
        lin = nn.Linear(16, 4)
        fq = FakeQuantLinear(lin, bits=8)
        fn, params = fq.functional()
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 16))
        y = jnp.ones((8, 4))

        def loss(p):
            return jnp.mean((fn(p, x) - y) ** 2)

        grads = jax.grad(loss)(params)
        assert float(jnp.abs(grads["inner.weight"]).sum()) > 0

    def test_fake_quant_idempotent_scale(self):
        x = jnp.array([0.0, 0.0, 0.0])   # all-zero: scale guard
        out = fake_quant(x)
        assert bool(jnp.all(out == 0))


class TestParallelQuant:
    def test_partition_metadata_preserved(self):
        from paddle_tpu.parallel.layers import (ColumnParallelLinear,
                                                RowParallelLinear)
        col = ColumnParallelLinear(128, 64, gather_output=False)
        q = QuantizedLinear.from_linear(col, bits=8)
        meta = q.param_meta()
        assert meta["qweight"].partition == (None, "tp")
        assert meta["scales"].partition == (None, "tp")
        assert q.output_parallel_axis == "tp"

        row = RowParallelLinear(128, 64, input_is_parallel=True)
        qr = QuantizedLinear.from_linear(row, bits=4)
        assert qr.param_meta()["qweight"].partition == ("tp", None)
        assert qr.input_parallel_axis == "tp"

    def test_quantized_tp_matches_dense_on_mesh(self):
        """8-virtual-device mesh: quantized TP layer == same layer dense."""
        import jax
        from paddle_tpu.parallel.layers import ColumnParallelLinear
        from paddle_tpu.distributed import env
        from paddle_tpu.parallel.sharding import shard_layer
        col = ColumnParallelLinear(128, 64, gather_output=True)
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 128))
        q = QuantizedLinear.from_linear(col, bits=8)
        ref = np.asarray(q(x))
        env.init_parallel_env({"tp": 8})
        try:
            shard_layer(q)
            fn, params = q.functional()
            out = jax.jit(fn)(params, x)
            spec = params["qweight"].sharding.spec
            assert "tp" in str(spec), f"qweight not tp-sharded: {spec}"
        finally:
            env.init_parallel_env({})
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


class TestPTQ:
    """Activation-calibrated post-training quantization (C17 PTQ half:
    observers -> convert -> W8A8 forward)."""

    def _mlp(self):
        import paddle_tpu as pt
        pt.seed(0)
        return nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 8))

    def test_calibrate_convert_accuracy(self):
        from paddle_tpu.quant import PTQ, W8A8Linear
        model = self._mlp()
        rs = np.random.RandomState(0)
        calib = [jnp.asarray(rs.randn(8, 16), jnp.float32) for _ in range(4)]
        ref = np.asarray(model(calib[0]))
        ptq = PTQ(model)
        for b in calib:
            model(b)
        assert all(o.stat is not None for o in ptq.observers.values())
        ptq.convert()
        kinds = [type(l).__name__ for l in model.sublayers()]
        assert kinds.count("W8A8Linear") == 2 and "Linear" not in kinds
        got = np.asarray(model(calib[0]))
        # int8 weights + int8 activations: a few percent, not garbage
        rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
        assert rel < 0.08, rel

    def test_convert_without_calibration_raises(self):
        import pytest
        from paddle_tpu.quant import PTQ
        ptq = PTQ(self._mlp())
        with pytest.raises(RuntimeError, match="calibration"):
            ptq.convert()

    def test_observer_semantics(self):
        import pytest
        from paddle_tpu.quant import AbsMaxObserver
        o = AbsMaxObserver()
        o.update(jnp.asarray([1.0, -3.0]))
        o.update(jnp.asarray([2.0]))
        assert o.stat == 3.0 and o.scale() == pytest.approx(3.0 / 127)
        e = AbsMaxObserver(ema=0.9)
        e.update(jnp.asarray([10.0]))
        e.update(jnp.asarray([0.0]))
        assert e.stat == pytest.approx(9.0)

    def test_skip_patterns(self):
        from paddle_tpu.quant import PTQ
        model = self._mlp()
        ptq = PTQ(model, skip=["2"])  # skip the second Linear ("2")
        assert len(ptq.observers) == 1
