"""Direct Preference Optimization: precompute reference log-probs, then
train the policy with DPOTrainer.

  python examples/dpo.py
"""
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.trainer import TrainingArguments
from paddle_tpu.trl import DPOTrainer, compute_sequence_logps


def main():
    pt.seed(0)
    policy = LlamaForCausalLM(llama_tiny())

    rs = np.random.RandomState(0)
    chosen = jnp.asarray(rs.randint(1, 256, (8, 32)))
    rejected = jnp.asarray(rs.randint(1, 256, (8, 32)))
    mask = jnp.ones_like(chosen)

    # reference = frozen snapshot of the starting policy (eval mode)
    ref_c = compute_sequence_logps(policy, chosen, mask)
    ref_r = compute_sequence_logps(policy, rejected, mask)

    batch = {"chosen_ids": chosen, "chosen_mask": mask,
             "rejected_ids": rejected, "rejected_mask": mask,
             "ref_chosen_logps": ref_c, "ref_rejected_logps": ref_r}
    tr = DPOTrainer(policy, pt.optimizer.AdamW(learning_rate=5e-4),
                    TrainingArguments(output_dir="output/dpo", max_steps=20,
                                      logging_steps=5),
                    beta=0.1, train_dataloader=[batch])
    tr.train()


if __name__ == "__main__":
    main()
