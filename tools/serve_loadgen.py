#!/usr/bin/env python
"""Open-loop load generator for the serving gateway (ISSUE 9
satellite; reference: the open-loop methodology of the Gemma-on-TPU
serving comparison in PAPERS.md — arrivals keep coming at the offered
rate whether or not the server keeps up, so queueing delay shows up in
TTFT instead of being hidden by a closed loop).

Default mode self-hosts a gateway in-process (tiny-llama replicas with
chunked prefill + prefix caching; ``--model stub`` swaps in a
negligible-compute stub so CI measures the gateway machinery, not the
model). ``--url HOST:PORT`` attaches to an external gateway instead.

Workload: ``--share-frac`` of requests carry a shared, chunk-grid-
aligned system prompt (``--sys-tokens``) plus a short unique tail —
the prompt-sharing mix knob that makes prefix-affinity routing
measurable; the rest are fully random prompts. ``--interactive-frac``
splits the SLO-class mix.

Reports ONE ``LOADGEN_JSON`` line: p50/p99 TTFT + TPOT, total
tokens/s, goodput (tokens from requests whose TTFT met
``--ttft-slo-ms``, per second), shed/timeout counts and the
prefix-route hit split; and writes ``SERVE_LOADGEN_r07.json`` next to
bench.py, which auto-ingests the ``gateway_p99_ttft_ms`` /
``gateway_tokens_per_sec`` rung alongside ``paged_tokens_per_sec``
(same device + freshness gating as the decode-profile rung).

``--chaos`` (ISSUE 12) turns the run into the seeded fault-tolerance
acceptance harness: replicas are killed/hung mid-run at deterministic
points, every completed greedy stream is replayed BITWISE against a
fresh reference engine, and the run fails (nonzero exit) on any
corrupted stream, on 5xx counts beyond the retry-budget bound, or on
a completed fraction below ``--goodput-floor`` (docs/SERVING.md).

Telemetry (ISSUE 15): the self-hosted gateways run the time-series
sampler + SLO burn-rate alerting by default, and the rung banks the
fired-alert log, the peak burn rate per class and the windowed tok/s
trajectory summary — so bench.py trend lines capture SLO health, not
just end-of-run throughput. ``--slo-windows 0.01`` scales the burn
windows down so a CI-length run can fire (a chaos kill's TTFT spike
deterministically trips the interactive class); ``--telemetry off``
is the A/B reference reproducing the pre-plane gateway bitwise.

``--spill on`` (ISSUE 17) hands the self-hosted replicas one shared
host-RAM :class:`KVSpillArena`: spans evicted under block pressure
(and everything parked at drain) are checksummed D2H into the arena,
and a warm miss — including on a supervisor-REBUILT replica after a
chaos kill — restores them with one batched H2D scatter instead of
re-prefilling. The rung banks ``kv_spill_hit_frac`` (share of
prefix-hit tokens the host tier supplied) and
``kv_spill_restored_tokens`` (re-prefill tokens saved); ``--spill
off`` (default) is the A/B reference every greedy stream must match
bitwise. Composes with ``--chaos``: the replay gate must stay at
zero corrupted streams with the tier on.

``--churn`` (ISSUE 14) swaps in a transition-heavy mix — short,
staggered per-request budgets so replica slots finish and readmit
every few ticks — and the rung records ``full_rebuilds`` /
``delta_patches`` / ``h2d_upload_bytes`` from the engines;
``--delta off`` keeps the full-rebuild transition path as the A/B
reference (pair them to see what slot churn costs each way).
``--patch-fuse off`` (ISSUE 19) keeps the standalone-patch-dispatch
reference instead; the default fuses pending transition descriptors
into the next tick's program, and the rung's ``patches_fused`` /
``patch_queue_overflows`` / ``dispatches_per_tick`` fields show churn
riding one dispatch per tick fleet-wide.

Fleet mode (ISSUE 13): ``--url`` may repeat (client-side round-robin
over several fleet front doors), ``--diurnal`` replaces the flat
offered rate with a seeded sinusoid over the run (the autoscaler's
evaluation trace), and ``--fleet N`` self-hosts N SEPARATE gateway
processes behind an in-process :class:`FleetFrontend` (remote-replica
adapter routing + byte-for-byte SSE proxying). ``--fleet-kill K``
SIGKILLs K replica processes at seeded mid-run points (the remote
analogue of ``--chaos``: completed greedy streams replay bitwise, the
goodput floor applies); ``--autoscale`` runs the closed-loop
:class:`FleetAutoscaler` over the run and the rung reports
``fleet_tokens_per_sec`` plus goodput-per-replica (good tokens per
replica-second — the chip-cost framing of the TPU-serving comparison
paper). The fleet rung lands in ``SERVE_FLEET_r13.json``, which
bench.py auto-ingests beside the gateway rung.
"""
import argparse
import asyncio
import json
import math
import os
import random
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

OUT_DEFAULT = os.path.join(ROOT, "SERVE_LOADGEN_r07.json")
OUT_FLEET = os.path.join(ROOT, "SERVE_FLEET_r13.json")


def diurnal_rate(i: int, n_requests: int, base_rate: float,
                 amp: float = 0.8, cycles: float = 1.0,
                 phase: float = 0.0) -> float:
    """Seeded sinusoidal offered-rate trace (ISSUE 13): request ``i``
    of ``n_requests`` arrives at instantaneous rate
    ``base * (1 + amp * sin(2*pi*cycles*i/n + phase))`` — a compressed
    diurnal load curve the autoscaler must ride up AND back down.
    Floored at 5% of base so the open loop never stalls entirely.
    Deterministic in (i, n, base, amp, cycles, phase); the CLI derives
    ``phase`` from ``--seed``."""
    frac = i / max(n_requests - 1, 1)
    r = base_rate * (1.0 + amp * math.sin(
        2.0 * math.pi * cycles * frac + phase))
    return max(r, 0.05 * base_rate)


def _force_platform():
    """PADDLE_TPU_BENCH_PLATFORM=cpu forces a backend (the axon
    sitecustomize re-selects its platform via jax.config after env
    parsing, so only an in-process config.update wins — see bench.py)."""
    plat = os.environ.get("PADDLE_TPU_BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)


# ------------------------------------------------------------------ client
async def sse_generate(host: str, port: int, payload: dict,
                       timeout_s: float = 120.0,
                       request_id: str = None, skip: int = 0,
                       ha: bool = False, on_token=None):
    """One SSE request; returns a per-request record with wire-level
    TTFT/TPOT timings (measured at the CLIENT, queueing included).
    ``request_id`` (ISSUE 10) is the CLIENT-minted trace id, sent as
    the ``X-Request-Id`` header the gateway honors — the join key
    ``tools/trace_report.py`` matches client and server views on.

    ISSUE 16 HA: ``skip`` drops the first N token events (a resumed
    stream re-emits the committed prefix first — dedupe by count, the
    frontend's own rule one tier down); ``ha=True`` converts a
    MID-STREAM connection loss (frontend SIGKILL) into a returned
    record with ``finish_reason="severed"`` carrying the committed
    tokens/lps, instead of raising them away — the caller retries
    against a sibling with that prefix as ``resume_tokens``."""
    rec = {"status": 0, "tokens": [], "lps": [], "ttft_ms": None,
           "tpot_ms": None, "finish_reason": None,
           "retry_after": None, "request_id": request_id}
    t0 = time.perf_counter()
    reader, writer = await asyncio.open_connection(host, port)
    try:
        body = json.dumps(payload).encode()
        rid_hdr = (f"X-Request-Id: {request_id}\r\n"
                   if request_id else "")
        writer.write((f"POST /v1/generate HTTP/1.1\r\nHost: {host}\r\n"
                      f"{rid_hdr}"
                      f"Content-Length: {len(body)}\r\n\r\n").encode()
                     + body)
        await writer.drain()
        status = await asyncio.wait_for(reader.readline(), timeout_s)
        parts = status.split()
        if len(parts) < 2:
            # EOF before a status line (server mid-restart closed the
            # accepted connection): a per-request conn_error, not a
            # run-killing IndexError
            raise ConnectionError("connection closed before response")
        rec["status"] = int(parts[1])
        while True:   # headers
            ln = await asyncio.wait_for(reader.readline(), timeout_s)
            if ln in (b"\r\n", b"\n", b""):
                break
            if ln.lower().startswith(b"retry-after:"):
                rec["retry_after"] = ln.split(b":", 1)[1].strip().decode()
        if rec["status"] != 200:
            rec["finish_reason"] = "rejected"
            return rec
        t_first = t_last = None
        seen = 0
        try:
            while True:
                ln = await asyncio.wait_for(reader.readline(),
                                            timeout_s)
                if not ln:
                    if ha:
                        rec["finish_reason"] = "severed"
                    break
                ln = ln.strip()
                if not ln.startswith(b"data: "):
                    continue
                ev = json.loads(ln[6:])
                if ev.get("done"):
                    rec["finish_reason"] = ev.get(
                        "finish_reason",
                        "error" if "error" in ev else None)
                    if skip == 0:
                        rec["tokens"] = ev.get("tokens", rec["tokens"])
                    else:
                        # resumed stream: keep the streamed NEW tokens
                        # authoritative for the caller's merge; the
                        # server's full list rides along for the
                        # bitwise cross-check
                        rec["final_tokens"] = ev.get("tokens")
                    break
                seen += 1
                if seen <= skip:
                    continue    # committed-prefix replay: dedupe
                now = time.perf_counter()
                t_last = now
                if t_first is None:
                    t_first = now
                    rec["ttft_ms"] = (now - t0) * 1e3
                rec["tokens"].append(ev["token"])
                rec["lps"].append(ev.get("lp"))
                if on_token is not None:
                    # --migrate probe hook: lets the caller fire a
                    # mid-stream drain at a deterministic token count
                    on_token(seen)
        except (ConnectionError, OSError) as e:
            # mid-stream sever (the frontend died under us): the
            # committed prefix in rec is the client's resume state
            if not ha:
                raise
            rec["finish_reason"] = "severed"
            rec["error"] = repr(e)[:80]
        n = len(rec["tokens"])
        if t_first is not None and t_last is not None and n >= 2:
            rec["tpot_ms"] = (t_last - t_first) / (n - 1) * 1e3
        return rec
    finally:
        try:
            writer.close()
            await writer.wait_closed()
        except Exception:
            pass


async def sse_generate_ha(targets, start: int, payload: dict,
                          timeout_s: float = 120.0,
                          request_id: str = None, resumes: int = 2):
    """Leaderless-HA client (ISSUE 16): one logical request across up
    to ``resumes`` frontend failovers. A severed stream (frontend
    SIGKILL mid-flight) is retried against the NEXT frontend with the
    committed prefix as ``resume_tokens``/``resume_lps`` — the same
    resubmit the frontend itself performs one tier down when a PEER
    dies — so the client sees every token exactly once and a greedy
    stream stays bitwise the uninterrupted run's."""
    orig_prompt = list(payload["prompt"])
    orig_max = int(payload["max_new_tokens"])
    committed, lps = [], []
    first_ttft = None
    rec = None
    for attempt in range(resumes + 1):
        h, p = targets[(start + attempt) % len(targets)]
        if committed:
            spec = dict(payload,
                        prompt=orig_prompt + committed,
                        resume_tokens=list(committed),
                        resume_lps=list(lps),
                        max_new_tokens=orig_max - len(committed))
        else:
            spec = payload
        try:
            rec = await sse_generate(h, p, spec, timeout_s,
                                     request_id=request_id,
                                     skip=len(committed), ha=True)
        except (ConnectionError, OSError) as e:
            # refused/reset before any response (corpse still in the
            # client's rotation): nothing new committed, next sibling
            rec = {"status": 0, "tokens": [], "lps": [],
                   "ttft_ms": None, "tpot_ms": None,
                   "finish_reason": "severed", "retry_after": None,
                   "request_id": request_id, "error": repr(e)[:80]}
        if rec["ttft_ms"] is not None and first_ttft is None:
            first_ttft = rec["ttft_ms"]
        if rec["finish_reason"] == "severed":
            committed += rec["tokens"]
            lps += rec["lps"]
            continue
        # terminal (done / rejected / error): merge the resume chain
        rec["resumes"] = attempt
        if committed:
            full = committed + rec["tokens"]
            ft = rec.pop("final_tokens", None)
            if ft is not None and ft != full:
                # the server's authoritative list disagrees with the
                # client's merge: a real token was lost or duplicated
                # across the failover — surface it, don't paper over
                rec["resume_mismatch"] = {"client": len(full),
                                          "server": len(ft)}
            rec["tokens"] = full
            rec["lps"] = lps + rec["lps"]
            rec["ttft_ms"] = first_ttft
        return rec
    # every attempt severed: report the request as a conn_error with
    # whatever prefix was committed (the gate counts it against the
    # goodput floor)
    rec = dict(rec, tokens=committed + rec["tokens"],
               finish_reason="conn_error", resumes=resumes)
    return rec


# ----------------------------------------------------------------- fleet
def _build_gateway(ns):
    """Self-hosted replica fleet: chunked prefill + prefix caching on
    every engine so affinity routing has warm blocks to find. Returns
    ``(gateway, engines, engine_factory)`` — the factory is what
    ``--chaos`` hands the supervisor so killed replicas rebuild on
    fresh engines."""
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/paddle_tpu_loadgen_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.1)
    except Exception:
        pass
    import paddle_tpu as pt
    from paddle_tpu.generation.paged import PagedEngine
    from paddle_tpu.serving import Gateway

    pt.seed(0)
    if ns.model == "stub":
        engine_kw = dict(max_slots=4, num_blocks=128, block_size=8,
                         max_blocks_per_seq=16, prefill_buckets=(16,),
                         chunk_prefill_tokens=ns.sys_tokens or 8,
                         enable_prefix_cache=True)
        # non-chaos rung semantics unchanged: ONE shared stub (ticks
        # serialize on the per-model lock exactly as before). Under
        # --chaos each engine gets its own stub — a hung replica's
        # abandoned thread must never share a layer tree (or a tick
        # lock) with its replacement.
        shared_stub = None if getattr(ns, "chaos", False) \
            else _stub_model()

        def _model():
            return shared_stub if shared_stub is not None \
                else _stub_model()
    else:
        from paddle_tpu.models import LlamaForCausalLM
        from paddle_tpu.models.llama import llama_tiny
        model = LlamaForCausalLM(llama_tiny())
        engine_kw = dict(max_slots=4, num_blocks=128, block_size=16,
                         max_blocks_per_seq=16, prefill_buckets=(32,),
                         chunk_prefill_tokens=ns.sys_tokens or 32,
                         enable_prefix_cache=True)

        def _model():
            return model
    # --ring off: the synchronous-readback reference engines (ISSUE 11
    # A/B — same workload, same gateway, only the tick readback
    # architecture differs); --delta off likewise keeps the full-
    # rebuild transition reference (ISSUE 14 A/B)
    engine_kw["ring_mode"] = getattr(ns, "ring", "on") == "on"
    engine_kw["delta_transitions"] = \
        getattr(ns, "delta", "on") == "on"
    # --patch-fuse off: the standalone-patch-dispatch reference
    # (ISSUE 19 A/B — same descriptors, dispatched one tiny program
    # per transition instead of staged into the tick). Only the "off"
    # side is passed through: the default (None) lets the engine fuse
    # whenever delta transitions are on.
    if getattr(ns, "patch_fuse", "on") == "off" \
            and engine_kw["delta_transitions"]:
        engine_kw["patch_fuse"] = False
    # --tick-profile on: per-tick phase attribution (ISSUE 20) — the
    # rung banks phase_breakdown from the engines' phase totals
    engine_kw["tick_profile"] = \
        getattr(ns, "tick_profile", "off") == "on"

    chaos = bool(getattr(ns, "chaos", False))
    # host-RAM KV spill tier (ISSUE 17 A/B): --spill on hands every
    # replica (and every supervisor REBUILD) one shared arena, so
    # evicted/killed warm prefixes restore instead of re-prefilling;
    # --spill off (default) is the reference the bitwise gate and the
    # kv_spill_hit_frac rung compare against
    spill_arena = None
    migrate_on = getattr(ns, "migrate", "off") == "on"
    if getattr(ns, "spill", "off") == "on" or migrate_on:
        # --migrate on implies an arena: migration IS spill + wire
        # (export_resumable descriptors serialized D2H, ISSUE 18)
        from paddle_tpu.serving.kvspill import KVSpillArena
        spill_arena = KVSpillArena(
            int(getattr(ns, "spill_mb", 256)) << 20,
            name="loadgen")
    # telemetry plane (ISSUE 15): sampler + burn-rate alerting default
    # ON (host-side, pinned harmless); --telemetry off is the A/B
    # reference that reproduces the pre-plane gateway exactly.
    # --slo-windows scales the burn windows so a CI-length run can
    # fire (and resolve) real alerts.
    if getattr(ns, "telemetry", "on") == "on":
        gw_telemetry_kw = dict(
            slo_window_scale=getattr(ns, "slo_windows", 1.0))
    else:
        gw_telemetry_kw = dict(sample_interval_s=None,
                               slo_alerting=False)

    def engine_factory():
        eng = PagedEngine(_model(), **engine_kw)
        if chaos:
            # compile-before-traffic (what a real fleet's readiness
            # probe guarantees): a cold engine's FIRST step pays the
            # executable build/deserialize — far over the sub-second
            # chaos watchdog deadline — so warm every engine (and
            # every supervisor REBUILD, which runs this same factory)
            # before it can take traffic
            eng.submit("warmup", list(range(1, 5)), max_new_tokens=4)
            eng.run()
            eng.results.pop("warmup", None)
            eng.logprobs.pop("warmup", None)
        return eng

    engines = [engine_factory() for _ in range(ns.replicas)]
    gw_kw = dict(routing=ns.policy, max_queue=ns.max_queue,
                 spill_arena=spill_arena, **gw_telemetry_kw)
    if migrate_on:
        # live requests at drain time cut over (terminal migrated
        # events + resume_kv spans) instead of finishing here
        gw_kw.update(migrate_on_drain=True)
    if chaos:
        # fast-recovery supervision knobs sized for a short chaos run:
        # sub-second watchdog + breaker backoff so kills, failovers
        # AND rejoins all land inside the measured window
        gw_kw.update(engine_factory=engine_factory,
                     failover_budget=getattr(ns, "failover_budget", 2),
                     watchdog_timeout_s=getattr(
                         ns, "watchdog_timeout_s", 0.5),
                     watchdog_interval_s=0.02,
                     breaker_backoff_s=0.2)
    gw = Gateway(engines, **gw_kw)
    return gw, engines, engine_factory


def _stub_model():
    """Negligible-compute CausalLM: loadgen numbers then measure
    gateway + engine machinery, not model FLOPs (the shared reference
    stub in ``paddle_tpu/generation/stub.py``)."""
    from paddle_tpu.generation.stub import TickStubModel
    return TickStubModel()


def _build_fleet(ns):
    """Fleet mode (ISSUE 13): spawn ``--fleet`` SEPARATE gateway
    processes (``fleet/replica_main.py``, warmed before ready) and an
    in-process :class:`FleetFrontend` routing over their
    :class:`RemoteReplica` adapters. Returns
    ``(frontend, manager, autoscaler_or_None)`` — the frontend is NOT
    started yet (the caller awaits ``start()`` on its loop)."""
    _force_platform()
    from paddle_tpu.serving.fleet import (FleetAutoscaler,
                                          FleetFrontend,
                                          LocalProcessManager,
                                          link_frontends)
    chunk = ns.sys_tokens or 8
    n_fe = max(int(getattr(ns, "frontends", 1) or 1), 1)
    fes = []
    for i in range(n_fe):
        # the single-frontend name stays "fleet" (metric labels and
        # rung fields downstream key on it); HA siblings are fleet0..
        name = "fleet" if n_fe == 1 else f"fleet{i}"
        fes.append(FleetFrontend(
            [], chunk_tokens=chunk, routing=ns.policy,
            failover_budget=getattr(ns, "failover_budget", 2),
            breaker_backoff_s=0.2, name=name))
    fe = fes[0]
    links = []
    if n_fe > 1:
        # leaderless HA (ISSUE 16): full-mesh gossip of prefix
        # digests, breaker states and sticky assignments — a fast
        # cadence so a CI-length run converges before the kill
        links = link_frontends(fes, interval_s=0.25,
                               seed=getattr(ns, "seed", 0))
    extra = []
    trace_dir = getattr(ns, "trace_dir", None)
    if trace_dir:
        # peer gateways dump their reqtrace rings here on SIGTERM
        # drain — the multi-run-dir input trace_report's fleet merge
        # joins with the frontend's own ring by request id (ISSUE 15:
        # their series_<gw>.json trajectories land beside them)
        extra += ["--run-dir", trace_dir]
    if getattr(ns, "telemetry", "on") == "on":
        # thread the CI-speed burn windows into the replica PROCESSES
        # so their engines can fire alerts inside a short run; the
        # frontend's federated /metricsz surfaces them (ISSUE 15)
        scale = getattr(ns, "slo_windows", 1.0)
        if scale != 1.0:
            extra += ["--slo-window-scale", str(scale)]
    else:
        extra += ["--telemetry", "off"]
    if getattr(ns, "spill", "off") == "on" \
            or getattr(ns, "migrate", "off") == "on":
        # each replica PROCESS gets its own arena (host RAM dies with
        # the process; migrated spans ship inline over /kvz during the
        # drain grace window, so cross-process cutover still restores)
        extra += ["--spill-mb", str(int(getattr(ns, "spill_mb", 256)))]
        if getattr(ns, "migrate", "off") == "on":
            extra += ["--migrate", "on"]
    manager = LocalProcessManager(
        fes, model=ns.model if ns.model in ("stub", "tiny")
        else "stub",
        chunk_tokens=chunk, extra_args=extra,
        probe_interval_s=0.1, stale_after_s=1.5)
    for _ in range(ns.fleet):
        manager.spawn()
    scaler = None
    if getattr(ns, "autoscale", False):
        scaler = FleetAutoscaler(
            manager,
            min_replicas=getattr(ns, "autoscale_min", 1),
            max_replicas=getattr(ns, "autoscale_max",
                                 max(ns.fleet, 2)),
            up_queue_depth=1.0, hold_s=0.3, hold_down_s=1.5,
            cooldown_s=getattr(ns, "autoscale_cooldown_s", 3.0),
            interval_s=0.1,
            signal_mode=getattr(ns, "autoscale_mode", "windowed"),
            signal_window_s=getattr(ns, "autoscale_window_s", 1.0))
        fe.attach_autoscaler(scaler)
    return fes, manager, scaler, links


# ---------------------------------------------------- migrate A/B probe
async def _migrate_probe(ns) -> dict:
    """Drain-migration A/B (ISSUE 18): a dedicated two-gateway mini
    fleet, SIGTERM-drained mid-stream, run twice — ``on`` resolves
    each migrated stream's ``resume_kv`` span so the survivor RESTORES
    the KV, ``off`` is the re-prefill control (identical cut-over, no
    transfer). The drain point is deterministic (fired by the client
    the moment every stream has its first token), prompts are UNIQUE
    (survivor prefix hits can only come from the transfer), so
    ``recompute = resubmitted prefill tokens - prefix-hit tokens`` is
    measured, not modeled. Retries with fresh gateway names if the
    race between drain and stream completion yields zero migrations.
    """
    import paddle_tpu as pt
    from paddle_tpu.generation.paged import PagedEngine
    from paddle_tpu.generation.stub import TickStubModel
    from paddle_tpu.serving import Gateway
    from paddle_tpu.serving import kvxfer
    from paddle_tpu.serving.fleet import FleetFrontend, RemoteReplica
    from paddle_tpu.serving.fleet.replica_main import stub_engine_kw
    from paddle_tpu.serving.kvspill import KVSpillArena
    from paddle_tpu.utils import observability as obs

    reqs = max(int(getattr(ns, "migrate_requests", 6)), 2)
    prompt_len, max_new = 64, 32
    rng = random.Random(ns.seed + 11)
    prompts = [[rng.randrange(1, 120) for _ in range(prompt_len)]
               for _ in range(reqs)]

    def _eng():
        eng = PagedEngine(TickStubModel(), **stub_engine_kw(8))
        eng.submit("warmup", list(range(1, 5)), max_new_tokens=4)
        eng.run()
        eng.results.pop("warmup", None)
        eng.logprobs.pop("warmup", None)
        return eng

    # uninterrupted single-engine reference: the bitwise truth both
    # modes (and every migrated stream) must reproduce
    ref = PagedEngine(TickStubModel(), **stub_engine_kw(8))
    for i in range(reqs):
        ref.submit(f"migprobe-{i:03d}", prompts[i],
                   max_new_tokens=max_new)
    expect = ref.run()

    async def _run_mode(mode: str, attempt: int):
        pt.seed(0)
        gws = []
        for j in range(2):
            # attempt-unique names: kvxfer counters key on the
            # gateway name, and a retry must not inherit stale counts
            name = f"migprobe{attempt}-{mode}{j}"
            gw = Gateway([_eng()], name=name,
                         spill_arena=KVSpillArena(64 << 20, name=name),
                         migrate_on_drain=True)
            await gw.start()
            gws.append(gw)
        fleet_name = f"migprobe{attempt}-{mode}"
        reps = [RemoteReplica(g.name, g.host, g.port,
                              probe_interval_s=0.05) for g in gws]
        fe = FleetFrontend(reps, chunk_tokens=8, name=fleet_name,
                           migrate=(mode == "on"),
                           breaker_backoff_s=60.0)
        await fe.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and not all(r.healthy() for r in reps):
            await asyncio.sleep(0.02)

        firsts = [False] * reqs
        fired = []

        def _on_token(i):
            firsts[i] = True
            if all(firsts) and not fired:
                # every stream is live: drain gateway 0 — its
                # in-flight requests cut over to gateway 1
                fired.append(asyncio.ensure_future(
                    gws[0].drain(migrate=True)))

        async def _one(i):
            rec = await sse_generate(
                fe.host, fe.port,
                {"prompt": prompts[i], "max_new_tokens": max_new,
                 "temperature": 0.0, "stream": True,
                 "timeout_s": 60.0},
                request_id=f"migprobe-{i:03d}",
                on_token=lambda n, i=i: _on_token(i))
            return i, rec

        done = await asyncio.gather(*[_one(i) for i in range(reqs)])
        if fired:
            await fired[0]
        hz = fe.healthz()
        mig_events = [e for e in obs.recorder().snapshot()
                      if e.get("kind") == "fleet_peer_migrated"
                      and e.get("fleet") == fleet_name]
        resubmit_prefill = sum(prompt_len + int(e.get("committed", 0))
                               for e in mig_events)
        engs = [w.engine for g in gws for w in g._workers]
        restored = sum(e.stats.get("spill_restored_tokens", 0)
                       for e in engs)
        hits = sum(e.stats.get("prefix_hit_tokens", 0) for e in engs)
        xfer = {}
        for g in gws:
            for k, v in kvxfer.counters_snapshot(g.name).items():
                xfer[k] = xfer.get(k, 0) + int(v)
        await fe.drain()
        for g in gws:
            await g.drain()
        toks = {i: list(r["tokens"]) for i, r in done}
        lps = {i: list(r.get("lps", ())) for i, r in done}
        res = {
            "migrated": int(hz.get("migrated_requests", 0)),
            "resubmit_prefill_tokens": resubmit_prefill,
            "prefix_hit_tokens": hits,
            "restored_tokens": restored,
            "recompute_tokens": max(resubmit_prefill - hits, 0),
            "errors": sum(1 for _, r in done
                          if r["finish_reason"] != "stop"),
            "corrupted_streams": sum(
                1 for i, r in done
                if r["finish_reason"] == "stop"
                and r["tokens"] != expect[f"migprobe-{i:03d}"]),
            "xfer": xfer,
        }
        return res, toks, lps

    probe = {"requests": reqs, "prompt_tokens": prompt_len,
             "max_new": max_new, "modes": {}}
    toks_m, lps_m = {}, {}
    for attempt in range(3):
        for mode in ("on", "off"):
            res, toks, lps = await _run_mode(mode, attempt)
            probe["modes"][mode] = res
            toks_m[mode], lps_m[mode] = toks, lps
        probe["attempts"] = attempt + 1
        if probe["modes"]["on"]["migrated"] >= 1:
            break
    on, off = probe["modes"]["on"], probe["modes"]["off"]
    probe["kv_xfer_hit_frac"] = round(
        on["restored_tokens"]
        / max(on["resubmit_prefill_tokens"], 1), 4)
    probe["recompute_tokens_saved"] = \
        off["recompute_tokens"] - on["recompute_tokens"]
    probe["recompute_amplification"] = round(
        off["recompute_tokens"] / max(on["recompute_tokens"], 1), 2)
    # bitwise A/B parity: migration must never change what a greedy
    # client observes — tokens exactly, logprobs to float tolerance
    # (prefill- vs decode-computed KV differ in the last ulp; the
    # existing resume contract)
    probe["parity_ok"] = all(
        toks_m["on"].get(i) == toks_m["off"].get(i)
        for i in range(reqs))
    diff = 0.0
    for i in range(reqs):
        for a, b in zip(lps_m["on"].get(i) or (),
                        lps_m["off"].get(i) or ()):
            if a is not None and b is not None:
                diff = max(diff, abs(float(a) - float(b)))
    probe["lps_max_abs_diff"] = round(diff, 9)
    probe["ok"] = bool(probe["parity_ok"]
                       and on["corrupted_streams"] == 0
                       and off["corrupted_streams"] == 0
                       and on["errors"] == 0 and off["errors"] == 0)
    return probe


# ------------------------------------------------------------------- run
def _tok_trajectory(sampler, base="gateway_tokens_total",
                    max_points=24):
    """Windowed tok/s trajectory summary (ISSUE 15 satellite): the
    sampled cumulative token counters (summed across label variants)
    differenced into a rate series, downsampled to <= max_points —
    the shape bench.py trend lines can carry so a rung records HOW
    the run served, not just its end-of-run mean."""
    import math as _math
    by_t = {}
    for name in sampler.names():
        if name.split("{", 1)[0] != base:
            continue
        for t, v in sampler.series(name):
            by_t[t] = by_t.get(t, 0.0) + v
    pts = sorted(by_t.items())
    rates = [(b[0], (b[1] - a[1]) / (b[0] - a[0]))
             for a, b in zip(pts, pts[1:]) if b[0] > a[0]]
    if not rates:
        return None
    t0 = pts[0][0]
    stride = max(1, _math.ceil(len(rates) / max_points))
    return {
        "points": [[round(t - t0, 2), round(r, 1)]
                   for t, r in rates[::stride]],
        "peak": round(max(r for _, r in rates), 1),
        "mean": round(sum(r for _, r in rates) / len(rates), 1),
        "samples": len(rates),
    }


def _pct(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return sorted_vals[i]


async def run_loadgen(ns) -> dict:
    rng = random.Random(ns.seed)
    gw = engines = engine_factory = None
    fe = manager = scaler = None
    fes, fe_links = [], []
    chaos = bool(getattr(ns, "chaos", False))
    fleet = int(getattr(ns, "fleet", 0) or 0)
    urls = ns.url if isinstance(ns.url, list) \
        else ([ns.url] if ns.url else [])
    if (urls or fleet) and getattr(ns, "delta", "on") == "off":
        # --fleet replica processes and external --url servers run
        # their own engine defaults (replica_main has no --delta);
        # silently recording "delta": "off" would mislabel a delta-on
        # run as the full-rebuild reference in the A/B rung
        raise SystemExit("--delta off requires in-process replicas "
                         "(no --fleet / --url): fleet peers and "
                         "external servers don't receive it")
    if (urls or fleet) and getattr(ns, "patch_fuse", "on") == "off":
        # same mislabeling hazard as --delta off: the knob only
        # reaches engines this process constructs
        raise SystemExit("--patch-fuse off requires in-process "
                         "replicas (no --fleet / --url)")
    if (urls or fleet) and getattr(ns, "tick_profile", "off") == "on":
        # phase_breakdown is summed from THIS process's engine
        # objects; fleet replica processes and external servers never
        # see the knob, so the rung would bank an empty breakdown
        raise SystemExit("--tick-profile on requires in-process "
                         "replicas (no --fleet / --url)")
    if int(getattr(ns, "frontends", 1) or 1) > 1 and not fleet:
        raise SystemExit("--frontends needs --fleet: sibling "
                         "frontends share one replica-process fleet")
    if urls:
        if chaos or fleet:
            raise SystemExit("--chaos/--fleet require self-hosted "
                             "mode (they inject faults into / spawn "
                             "their own fleet)")
        targets = []
        for u in urls:
            h, _, p = u.partition(":")
            targets.append((h, int(p)))
    elif fleet:
        if chaos:
            raise SystemExit("--chaos is the single-process harness; "
                             "the fleet analogue is --fleet-kill")
        fes, manager, scaler, fe_links = _build_fleet(ns)
        fe = fes[0]
        for f in fes:
            await f.start()
        targets = [(f.host, f.port) for f in fes]
    else:
        gw, engines, engine_factory = _build_gateway(ns)
        await gw.start()
        if gw.sampler is not None:
            # explicit t0 baseline: the sampler thread's first tick is
            # a full interval away, and a warm-cache CI run can finish
            # inside it — without this the tok/s trajectory would need
            # two timer ticks it never gets
            gw.sampler.sample()
        targets = [(gw.host, gw.port)]
    # fleet-mode trajectory (ISSUE 15): the frontend's own proxied-
    # token counter lives in THIS process's registry — a local sampler
    # over it yields the fleet tok/s series the rung banks (replica-
    # side series land in --trace-dir as series_<gw>.json on drain)
    local_sampler = None
    if fe is not None and getattr(ns, "telemetry", "on") == "on":
        from paddle_tpu.utils import observability as obs
        local_sampler = obs.MetricsTimeSeries(
            name="loadgen", interval_s=0.2, capacity=1024).start()
        local_sampler.sample()    # t0 baseline (see gateway twin)
    host, port = targets[0]
    # chaos schedule (ISSUE 12): seeded kill/hang points spread evenly
    # over the request stream — deterministic per (--seed,
    # --chaos-kills, --chaos-mode), replica picked by a seeded RNG
    chaos_plan = {}
    chaos_events = []
    if chaos:
        if ns.replicas < 2:
            raise SystemExit("--chaos needs --replicas >= 2: failover "
                             "requires a surviving replica, so a "
                             "single-replica chaos run can only fail")
        if getattr(ns, "chaos_mode", "mix") == "hang" \
                or getattr(ns, "chaos_mode", "mix") == "mix":
            # a finite injected hang: the abandoned thread wakes after
            # the watchdog already replaced it, sees the flag and exits
            os.environ.setdefault("PADDLE_TPU_FAULT_DISPATCH_HANG_S",
                                  "2")
        crng = random.Random(ns.seed + 1)
        kinds = {"kill": ("crash",), "hang": ("hang",),
                 "mix": ("crash", "hang")}[getattr(ns, "chaos_mode",
                                                   "mix")]
        kills = max(int(getattr(ns, "chaos_kills", 2)), 1)
        for j in range(kills):
            pt = max(1, round((j + 1) * ns.requests / (kills + 1)))
            while pt in chaos_plan and pt < ns.requests - 1:
                pt += 1
            if pt in chaos_plan:
                # more kills than schedulable request points: say so
                # instead of silently under-delivering fault coverage
                print(f"warning: only {len(chaos_plan)} of "
                      f"{kills} --chaos-kills fit before request "
                      f"{ns.requests}", file=sys.stderr)
                break
            chaos_plan[pt] = (kinds[j % len(kinds)],
                              crng.randrange(ns.replicas))
    # fleet process-kill schedule (ISSUE 13): seeded SIGKILL points —
    # the remote analogue of --chaos (no in-process hooks exist into a
    # separate gateway process; death arrives as dropped connections
    # and failed probes, which is exactly what the failover must eat)
    fleet_kill_plan = set()
    fleet_kill_events = []
    if fleet and int(getattr(ns, "fleet_kill", 0) or 0) > 0:
        kk = int(ns.fleet_kill)
        for j in range(kk):
            pt = max(1, round((j + 1) * ns.requests / (kk + 1)))
            while pt in fleet_kill_plan and pt < ns.requests - 1:
                pt += 1
            if pt in fleet_kill_plan:
                print(f"warning: only {len(fleet_kill_plan)} of {kk} "
                      f"--fleet-kill points fit", file=sys.stderr)
                break
            fleet_kill_plan.add(pt)
    # frontend SIGKILL schedule (ISSUE 16 HA): sever a FRONTEND
    # mid-run — the last single point of failure. Clients recover by
    # resuming against a surviving sibling; requires >= 2 frontends.
    fe_kill_plan = set()
    fe_kill_events = []
    fe_dead = set()
    n_fe_kills = int(getattr(ns, "frontend_kill", 0) or 0)
    if n_fe_kills > 0:
        if len(fes) < 2:
            raise SystemExit("--frontend-kill needs --frontends >= 2: "
                             "clients must have a survivor to resume "
                             "against")
        if n_fe_kills >= len(fes):
            raise SystemExit(f"--frontend-kill {n_fe_kills} would "
                             f"leave no survivor of {len(fes)} "
                             "frontends")
        for j in range(n_fe_kills):
            pt = max(1, round((j + 1) * ns.requests
                              / (n_fe_kills + 1)))
            while pt in fe_kill_plan and pt < ns.requests - 1:
                pt += 1
            fe_kill_plan.add(pt)
    krng = random.Random(ns.seed + 2)
    # seeded diurnal phase: the trace is deterministic per --seed
    phase = random.Random(ns.seed + 3).uniform(0, 2 * math.pi)
    vocab = 120
    sysp = [rng.randrange(1, vocab) for _ in range(ns.sys_tokens)]

    def _payload(i):
        shared = rng.random() < ns.share_frac
        tail = [rng.randrange(1, vocab) for _ in range(ns.tail_tokens)]
        prompt = (sysp + tail) if shared else \
            [rng.randrange(1, vocab)
             for _ in range(ns.sys_tokens + ns.tail_tokens)]
        slo = "interactive" if rng.random() < ns.interactive_frac \
            else "batch"
        # --churn (ISSUE 14): transition-heavy traffic — short,
        # STAGGERED budgets so a slot finishes (and an admit lands)
        # every few ticks per replica. Deterministic in i so the
        # chaos/fleet replay gates can rebuild the exact request.
        mn = 2 + (i % 6) if getattr(ns, "churn", False) else ns.max_new
        return {"prompt": prompt, "max_new_tokens": mn,
                "temperature": 0.0, "slo": slo,
                "tenant": f"t{i % ns.tenants}", "stream": True,
                "timeout_s": ns.timeout_s}, shared

    # warmup (compiles the prefill/decode executables untimed); a
    # failed warmup against a restarting --url gateway must not kill
    # the run the per-request guard below protects
    for wh, wp in targets:
        try:
            await sse_generate(wh, wp, _payload(0)[0])
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass

    records = []

    async def _one(i):
        payload, shared = _payload(i)
        rid = f"lg{ns.seed}-{i:05d}"     # client-minted trace id
        # client-side round-robin over the fleet front doors (ISSUE
        # 13 satellite: several --url targets, or the one frontend)
        th, tp = targets[i % len(targets)]
        try:
            if len(fes) > 1:
                # HA client (ISSUE 16): round-robin over the sibling
                # frontends, resuming a severed stream on the next
                # one with the committed prefix
                rec = await sse_generate_ha(
                    targets, i % len(targets), payload,
                    request_id=rid,
                    resumes=max(2, len(targets)))
            else:
                rec = await sse_generate(th, tp, payload,
                                         request_id=rid)
        except (ConnectionError, OSError, asyncio.TimeoutError) as e:
            # one dropped connection (external gateway restarting,
            # request timeout) must not discard the whole run's rung
            rec = {"status": 0, "tokens": [], "ttft_ms": None,
                   "tpot_ms": None, "finish_reason": "conn_error",
                   "retry_after": None, "request_id": rid,
                   "error": repr(e)[:80]}
        rec["shared"] = shared
        rec["tenant"] = payload["tenant"]
        rec["slo"] = payload["slo"]
        if chaos or fleet:
            rec["prompt"] = payload["prompt"]   # for the reference replay
            rec["max_new"] = payload["max_new_tokens"]
        records.append(rec)

    def _fire_chaos(i):
        kind, target = chaos_plan[i]
        workers = gw._workers
        w = workers[target % len(workers)]
        if w.failed or w.abandoned or not w.is_alive():
            w = next((x for x in workers
                      if x.is_alive() and not x.failed
                      and not x.abandoned), w)
        w.inject_fault(kind)
        chaos_events.append({"at_request": i, "kind": kind,
                             "replica": w.replica.name})

    def _fire_fleet_kill(i):
        names = sorted(manager.procs)
        if not names:
            return
        name = manager.kill(names[krng.randrange(len(names))])
        fleet_kill_events.append({"at_request": i, "peer": name})

    def _fire_frontend_kill(i):
        live = [j for j in range(len(fes)) if j not in fe_dead]
        if len(live) < 2:
            return               # never kill the last survivor
        victim = live[krng.randrange(len(live))]
        fe_dead.add(victim)
        fes[victim].kill()
        fe_kill_events.append({"at_request": i,
                               "frontend": fes[victim].name})
        print(f"# frontend kill: {fes[victim].name} at request {i}",
              file=sys.stderr)

    t0 = time.perf_counter()
    tasks = []
    for i in range(ns.requests):
        tasks.append(asyncio.ensure_future(_one(i)))
        if i in chaos_plan:
            _fire_chaos(i)
        if i in fleet_kill_plan:
            _fire_fleet_kill(i)
        if i in fe_kill_plan:
            _fire_frontend_kill(i)
        if i < ns.requests - 1:
            # open-loop Poisson arrivals: exponential gaps at the
            # offered rate, slept regardless of completions. --diurnal
            # modulates the instantaneous rate along the seeded
            # sinusoid (the autoscaler's evaluation trace).
            rate_i = ns.rate
            if getattr(ns, "diurnal", False):
                rate_i = diurnal_rate(
                    i, ns.requests, ns.rate,
                    amp=getattr(ns, "diurnal_amp", 0.8),
                    cycles=getattr(ns, "diurnal_cycles", 1.0),
                    phase=phase)
            await asyncio.sleep(rng.expovariate(rate_i))
    await asyncio.gather(*tasks)
    wall = time.perf_counter() - t0

    ok = [r for r in records if r["finish_reason"] == "stop"]
    shed = sum(r["status"] == 429 for r in records)
    timeouts = sum(r["finish_reason"] == "timeout" for r in records)
    ttfts = sorted(r["ttft_ms"] for r in ok if r["ttft_ms"] is not None)
    tpots = sorted(r["tpot_ms"] for r in ok if r["tpot_ms"] is not None)
    total_tokens = sum(len(r["tokens"]) for r in ok)
    good_tokens = sum(len(r["tokens"]) for r in ok
                      if r["ttft_ms"] is not None
                      and r["ttft_ms"] <= ns.ttft_slo_ms)
    rung = {
        "metric": "gateway_serving",
        "gateway_tokens_per_sec": round(total_tokens / wall, 1),
        "gateway_p50_ttft_ms": round(_pct(ttfts, 0.50), 2),
        "gateway_p99_ttft_ms": round(_pct(ttfts, 0.99), 2),
        "gateway_p50_tpot_ms": round(_pct(tpots, 0.50), 2),
        "gateway_p99_tpot_ms": round(_pct(tpots, 0.99), 2),
        "goodput_tokens_per_sec": round(good_tokens / wall, 1),
        "goodput_frac": round(good_tokens / max(total_tokens, 1), 3),
        "requests": ns.requests,
        "completed": len(ok),
        "shed": shed,
        "timeouts": timeouts,
        "conn_errors": sum(r["finish_reason"] == "conn_error"
                           for r in records),
        "wall_s": round(wall, 2),
        "rate_rps": ns.rate,
        "share_frac": ns.share_frac,
        "policy": ns.policy,
        "replicas": ns.replicas,
        "model": ns.model if not urls else "external",
        "ring": getattr(ns, "ring", "on"),
        "delta": getattr(ns, "delta", "on"),
        "patch_fuse": getattr(ns, "patch_fuse", "on"),
        "tick_profile": getattr(ns, "tick_profile", "off"),
        "churn": bool(getattr(ns, "churn", False)),
        "targets": len(targets),
        "diurnal": bool(getattr(ns, "diurnal", False)),
        "telemetry": getattr(ns, "telemetry", "on"),
        "slo_windows": getattr(ns, "slo_windows", 1.0),
    }
    # SLO health in the rung (ISSUE 15 satellite): fired alerts, peak
    # burn and the windowed tok/s trajectory, so bench.py trend lines
    # capture how the run served — not just its end-of-run throughput
    if gw is not None and gw.sampler is not None:
        # final sample pairs with the t0 baseline so even a run that
        # finished inside one sampler interval yields a >=1-point rate
        # series (deterministic under warm compile caches)
        gw.sampler.sample()
        traj = _tok_trajectory(gw.sampler)
        if traj is not None:
            rung["tok_s_trajectory"] = traj
    if gw is not None and gw._slo is not None:
        snap = gw._slo.snapshot()
        rung["alerts"] = list(gw._slo.alerts)
        rung["alerts_fired"] = snap["fires_total"]
        rung["peak_burn_rate"] = max(
            snap["peak_burn"].values(), default=0.0)
        rung["peak_burn_by_class"] = snap["peak_burn"]
    if engines is not None and getattr(ns, "ring", "on") == "on":
        rung["ring_drains"] = sum(e.ring_drains for e in engines)
        rung["ring_blocking_drains"] = sum(e.ring_blocking_drains
                                           for e in engines)
    if engines is not None:
        # ISSUE 14: how the run's slot churn was paid for — one-row
        # patches vs full-state rebuilds, and the H2D bytes either way
        rung["full_rebuilds"] = sum(e.full_rebuilds for e in engines)
        rung["delta_patches"] = sum(e.delta_patches for e in engines)
        rung["h2d_upload_bytes"] = sum(e.h2d_upload_bytes
                                       for e in engines)
        # ISSUE 19: the fleet-level one-dispatch-per-tick evidence —
        # staged rows carried the churn, dispatches/tick stays ~1 plus
        # the run's prefill share
        rung["patches_fused"] = sum(e.patches_fused for e in engines)
        rung["patch_queue_overflows"] = sum(
            e.patch_queue_overflows for e in engines)
        ticks = sum(e.stats["decode_steps"] for e in engines)
        rung["dispatches_per_tick"] = round(
            sum(e.dispatch_count for e in engines) / ticks, 3) \
            if ticks else 0.0
        rung["prefix_hit_tokens"] = sum(
            e.stats["prefix_hit_tokens"] for e in engines)
        # ISSUE 20: where the tick wall went — host (staging + patch
        # flush, h2d broken out as detail), dispatch (python call into
        # the jit program), device (block-until-ready at the readback
        # boundary) and drain (D2H copies). host is the residual of
        # the bracketed phases, so the shares sum to 1.0 of the
        # measured wall by construction — coverage pins that.
        if getattr(ns, "tick_profile", "off") == "on":
            totals = {}
            wall = 0.0
            ticks_p = 0
            for e in engines:
                pt = e.tick_phase_totals
                if pt is None:
                    continue
                for p, v in pt.items():
                    totals[p] = totals.get(p, 0.0) + v
                wall += e.tick_wall_ms_total
                ticks_p += e._prof.ticks
            phase_sum = sum(totals.values())
            rung["phase_breakdown"] = {
                "ticks": ticks_p,
                "wall_ms": round(wall, 3),
                "host_frac": round(
                    (totals.get("host", 0.0)
                     + totals.get("h2d", 0.0)) / wall, 4)
                if wall else 0.0,
                "h2d_frac": round(
                    totals.get("h2d", 0.0) / wall, 4) if wall else 0.0,
                "dispatch_frac": round(
                    totals.get("dispatch", 0.0) / wall, 4)
                if wall else 0.0,
                "device_frac": round(
                    totals.get("device", 0.0) / wall, 4)
                if wall else 0.0,
                "drain_frac": round(
                    totals.get("drain", 0.0) / wall, 4)
                if wall else 0.0,
                "coverage": round(phase_sum / wall, 4)
                if wall else 0.0,
            }
        router = gw.health()["router"]
        rung["prefix_route_hits"] = router["prefix_route_hits"]
        rung["prefix_route_misses"] = router["prefix_route_misses"]
        # KV spill tier A/B (ISSUE 17): re-prefill tokens saved + the
        # fraction of prefix-hit tokens the HOST tier supplied (0.0
        # with --spill off — the regression-gated number). Summed over
        # the LIVE workers, not the launch list: rebuilt engines are
        # where crash-recovery restores land
        rung["spill"] = getattr(ns, "spill", "off")
        rung["migrate"] = getattr(ns, "migrate", "off")
        engs = [w.engine for w in gw._workers] if gw is not None \
            else list(engines)
        restored = sum(e.stats.get("spill_restored_tokens", 0)
                       for e in engs)
        hit_all = sum(e.stats.get("prefix_hit_tokens", 0)
                      for e in engs)
        rung["kv_spill_restored_tokens"] = restored
        rung["kv_spill_hit_frac"] = round(
            restored / hit_all, 4) if hit_all else 0.0
        rung["kv_spill_restores"] = sum(
            e.stats.get("spill_restores", 0) for e in engs)
        rung["kv_spill_restore_failures"] = sum(
            e.stats.get("spill_restore_failures", 0) for e in engs)
        if gw is not None and gw._spill_arena is not None:
            rung["kv_spill_arena"] = gw._spill_arena.snapshot()
    # per-request JSONL (ISSUE 10 satellite): the CLIENT side of the
    # trace join — request id, tenant, SLO class, wire TTFT/TPOT and
    # outcome, one line per request, keyed by the X-Request-Id the
    # server rings recorded
    jsonl = getattr(ns, "jsonl", None)
    if jsonl:
        tmp = jsonl + ".tmp"
        with open(tmp, "w") as f:
            for r in sorted(records,
                            key=lambda r: r.get("request_id") or ""):
                f.write(json.dumps({
                    "request_id": r.get("request_id"),
                    "tenant": r.get("tenant"),
                    "slo": r.get("slo"),
                    "status": r.get("status"),
                    "outcome": r.get("finish_reason"),
                    "ttft_ms": r.get("ttft_ms"),
                    "tpot_ms": r.get("tpot_ms"),
                    "tokens": len(r.get("tokens", ())),
                    "shared": r.get("shared"),
                }) + "\n")
        os.replace(tmp, jsonl)
        rung["jsonl"] = jsonl
    if gw is not None:
        await gw.drain()
        # server-side trace rings, dumped AFTER drain (the tick
        # threads close every in-flight trace before exiting), where
        # trace_report expects them:
        #   python tools/trace_report.py TRACE_DIR --jsonl JSONL
        trace_dir = getattr(ns, "trace_dir", None)
        if trace_dir:
            rung["trace_rings"] = gw.dump_traces(trace_dir)
    if chaos:
        rung["chaos"] = _verify_chaos(ns, gw, engine_factory, records,
                                      chaos_events)
        if gw is not None:
            from paddle_tpu.serving import kvxfer as _kvx
            rung["kv_xfer"] = _kvx.counters_snapshot(gw.name)
    if getattr(ns, "migrate", "off") == "on" and gw is not None:
        # cross-replica KV transfer A/B (ISSUE 18): the dedicated
        # two-gateway drain-migration probe — the main run's final
        # drain has no in-flight work left to migrate, so the knob's
        # regression-gated numbers come from a mid-stream drain pair
        # (migrate vs re-prefill control) on the same workload
        probe = await _migrate_probe(ns)
        rung["migrate_probe"] = probe
        rung["kv_xfer_hit_frac"] = probe["kv_xfer_hit_frac"]
        rung["recompute_tokens_saved"] = \
            probe["recompute_tokens_saved"]
        rung["recompute_amplification"] = \
            probe["recompute_amplification"]
    if fe is not None:
        # fleet rung (ISSUE 13): fleet_tokens_per_sec is the headline
        # bench.py promotes; goodput-per-replica divides the good
        # tokens by REPLICA-SECONDS (the autoscaler's chip-cost
        # denominator), so a fleet that scales down through the trough
        # scores higher than one that holds peak capacity all run
        hz = fe.healthz()
        rep_secs = (scaler.replica_seconds if scaler is not None
                    else fleet * wall)
        rung["metric"] = "fleet_serving"
        rung["fleet_tokens_per_sec"] = round(total_tokens / wall, 1)
        rung["fleet_replicas"] = fleet
        rung["fleet_peer_failovers"] = sum(
            f.healthz()["peer_failovers"] for f in fes) \
            if len(fes) > 1 else hz["peer_failovers"]
        rung["fleet_retry_budget_exhausted"] = \
            hz["retry_budget_exhausted"]
        if len(fes) > 1:
            # frontend HA accounting (ISSUE 16): the client-observed
            # failover story — severed streams must all be resumed
            # with the committed prefix intact
            resumed = [r for r in records if r.get("resumes", 0) > 0]
            rung["frontend_ha"] = {
                "frontends": len(fes),
                "frontend_kills": fe_kill_events,
                "resumed_streams": sum(
                    1 for r in resumed
                    if r["finish_reason"] == "stop"),
                "resumed_failed": sum(
                    1 for r in resumed
                    if r["finish_reason"] != "stop"),
                "resume_mismatches": sum(
                    1 for r in records if r.get("resume_mismatch")),
                "gossip": [ln.snapshot() for ln in fe_links],
            }
        rung["replica_seconds"] = round(rep_secs, 2)
        rung["mean_replicas"] = round(rep_secs / max(wall, 1e-9), 2)
        rung["goodput_per_replica"] = round(
            good_tokens / max(rep_secs, 1e-9), 2)
        rung["router"] = hz["router"]
        if fleet_kill_events:
            rung["fleet_kills"] = fleet_kill_events
        if scaler is not None:
            snap = scaler.snapshot()
            rung["autoscale"] = {
                "scale_ups": snap["scale_ups"],
                "scale_downs": snap["scale_downs"],
                "min_replicas": snap["min_replicas"],
                "max_replicas": snap["max_replicas"],
                "signal_mode": snap["signal_mode"],
                "signal_window_s": snap["signal_window_s"],
                "events": snap["events"],
            }
        trace_dir = getattr(ns, "trace_dir", None)
        if trace_dir:
            rung["trace_rings"] = fe.dump_traces(trace_dir)
        if local_sampler is not None:
            # fleet SLO health (ISSUE 15): the frontend-side tok/s
            # trajectory plus the peers' federated burn/alert state,
            # read off the SAME probe caches /metricsz serves
            local_sampler.stop()
            local_sampler.sample()   # final point (see gateway twin)
            traj = _tok_trajectory(local_sampler,
                                   base="fleet_proxied_tokens_total")
            if traj is not None:
                rung["tok_s_trajectory"] = traj
            recent = []
            peak = {}
            total_fires = 0
            for peer, cache in fe.metricsz()["replicas"].items():
                slo = (cache.get("doc") or {}).get("slo") or {}
                recent += [dict(a, peer=peer)
                           for a in slo.get("alerts", ())]
                # fires_total is the UNTRUNCATED count — the peers'
                # snapshot "alerts" field is only the recent tail, so
                # counting fires off it would undercount alert-heavy
                # runs (and disagree with single-gateway mode)
                total_fires += int(slo.get("fires_total", 0))
                for cls, v in (slo.get("peak_burn") or {}).items():
                    peak[cls] = max(peak.get(cls, 0.0), v)
            rung["alerts"] = recent
            rung["alerts_fired"] = total_fires
            rung["peak_burn_rate"] = max(peak.values(), default=0.0)
            rung["peak_burn_by_class"] = peak
        if ns.model == "stub":
            rung["fleet_gate"] = _verify_fleet(
                ns, hz, records, fleet_kill_events,
                frontend_kills=fe_kill_events)
        for ln in fe_links:
            ln.stop()
        for j, f in enumerate(fes if fes else [fe]):
            if j in fe_dead:
                continue          # a killed frontend has no streams
            await f.drain()
        manager.stop_all()
    return rung


def _verify_fleet(ns, fleet_health, records, kill_events,
                  frontend_kills=()):
    """The fleet acceptance gate (ISSUE 13): replay every COMPLETED
    greedy stream on a fresh single-engine reference (same stub
    geometry the replica processes run — ``replica_main.py`` is the
    single source of truth) and demand bitwise token equality: a
    cross-process failover that duplicated, dropped or rewrote a token
    shows up as a corrupted stream. Error counts must stay within the
    retry-budget bound (process kills <= budget ==> zero 5xx) and the
    completed fraction must clear ``--goodput-floor``.

    ISSUE 16: a stream that crossed a FRONTEND kill reaches here as
    its client-side merge (committed prefix + survivor's remainder) —
    the same bitwise replay proves the resume dropped and duplicated
    nothing; ``resume_mismatches`` (client merge vs the survivor's
    authoritative final list) must be zero too."""
    from paddle_tpu.generation.paged import PagedEngine
    from paddle_tpu.generation.stub import TickStubModel
    from paddle_tpu.serving.fleet.replica_main import stub_engine_kw
    ref = PagedEngine(TickStubModel(),
                      **stub_engine_kw(ns.sys_tokens or 8))
    done = [r for r in records if r["finish_reason"] == "stop"]
    for r in done:
        ref.submit(r["request_id"], r["prompt"],
                   max_new_tokens=r.get("max_new", ns.max_new))
    expect = ref.run()
    corrupted = [r["request_id"] for r in done
                 if r["tokens"] != expect[r["request_id"]]]
    errors = sum(r["finish_reason"] in ("error", "conn_error")
                 for r in records) \
        + sum(r["status"] in (500, 503) for r in records)
    budget = getattr(ns, "failover_budget", 2)
    floor = float(getattr(ns, "goodput_floor", 0.95))
    error_bound = 0 if len(kill_events) <= budget else ns.requests
    completed_frac = len(done) / max(ns.requests, 1)
    mismatches = sum(1 for r in records if r.get("resume_mismatch"))
    resumed_ok = sum(1 for r in done if r.get("resumes", 0) > 0)
    gate = {
        "kills": len(kill_events),
        "frontend_kills": len(frontend_kills),
        "failover_budget": budget,
        "peer_failovers": int(fleet_health["peer_failovers"]),
        "replays_checked": len(done),
        "resumed_streams_checked": resumed_ok,
        "corrupted_streams": len(corrupted),
        "corrupted_ids": corrupted[:8],
        "resume_mismatches": mismatches,
        "errors_5xx": errors,
        "error_bound": error_bound,
        "completed_frac": round(completed_frac, 3),
        "goodput_floor": floor,
    }
    gate["ok"] = (not corrupted and not mismatches
                  and errors <= error_bound
                  and completed_frac >= floor)
    return gate


def _verify_chaos(ns, gw, engine_factory, records, chaos_events):
    """The --chaos acceptance gate (ISSUE 12): replay every COMPLETED
    greedy stream on a fresh reference engine and demand bitwise
    equality — a failover that duplicated, dropped or rewrote a token
    shows up as a corrupted stream; assert the error count stays
    within the retry-budget bound (kills <= budget ==> every stream
    survives, so zero 5xx) and the completed fraction clears the
    goodput floor. ``ok`` False flips the CLI's exit code."""
    ref = engine_factory()
    done = [r for r in records if r["finish_reason"] == "stop"]
    for r in done:
        ref.submit(r["request_id"], r["prompt"],
                   max_new_tokens=r.get("max_new", ns.max_new))
    expect = ref.run()
    corrupted = [r["request_id"] for r in done
                 if r["tokens"] != expect[r["request_id"]]]
    errors = sum(r["finish_reason"] == "error" for r in records) \
        + sum(r["status"] in (500, 503) for r in records)
    h = gw.health()
    budget = getattr(ns, "failover_budget", 2)
    floor = float(getattr(ns, "goodput_floor", 0.95))
    # the documented amplification bound: a request rides at most one
    # failover per replica kill, so kills within the budget mean no
    # request can exhaust it — any 5xx is then a real defect
    error_bound = 0 if len(chaos_events) <= budget else ns.requests
    completed_frac = len(done) / max(ns.requests, 1)
    ch = {
        "events": chaos_events,
        "kills": len(chaos_events),
        "failover_budget": budget,
        "failovers": int(h["failovers"]),
        "retry_budget_exhausted": int(h["retry_budget_exhausted"]),
        "replays_checked": len(done),
        "corrupted_streams": len(corrupted),
        "corrupted_ids": corrupted[:8],
        "errors_5xx": errors,
        "error_bound": error_bound,
        "completed_frac": round(completed_frac, 3),
        "goodput_floor": floor,
    }
    ch["ok"] = (not corrupted and errors <= error_bound
                and completed_frac >= floor)
    return ch


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--rate", type=float, default=16.0,
                    help="offered arrival rate, req/s (open loop)")
    ap.add_argument("--share-frac", type=float, default=0.5,
                    help="fraction of requests carrying the shared "
                         "system prompt")
    ap.add_argument("--sys-tokens", type=int, default=32)
    ap.add_argument("--tail-tokens", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--interactive-frac", type=float, default=0.7)
    ap.add_argument("--ttft-slo-ms", type=float, default=1000.0)
    ap.add_argument("--timeout-s", type=float, default=60.0)
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--policy", default="prefix",
                    choices=("prefix", "least_loaded", "round_robin"))
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--model", default="tiny",
                    choices=("tiny", "stub"))
    ap.add_argument("--ring", default="on", choices=("on", "off"),
                    help="async token-ring decode on the replica "
                         "engines (off = synchronous per-tick "
                         "readback, the ISSUE 11 A/B reference)")
    ap.add_argument("--delta", default="on", choices=("on", "off"),
                    help="delta slot transitions on the replica "
                         "engines (off = full mirror rebuild per "
                         "transition, the ISSUE 14 A/B reference)")
    ap.add_argument("--churn", action="store_true",
                    help="transition-heavy workload mix (ISSUE 14): "
                         "short staggered max-new budgets so slots "
                         "finish + readmit every few ticks; the rung "
                         "records full_rebuilds/delta_patches")
    ap.add_argument("--patch-fuse", dest="patch_fuse", default="on",
                    choices=("on", "off"),
                    help="fused patch+tick program (ISSUE 19): stage "
                         "transition descriptors into the device "
                         "queue the next tick applies in-program (off "
                         "= one standalone patch dispatch per "
                         "transition, the PR 12 A/B reference); the "
                         "rung records patches_fused and "
                         "dispatches_per_tick")
    ap.add_argument("--tick-profile", dest="tick_profile",
                    default="off", choices=("on", "off"),
                    help="tick-phase profiler on the replica engines "
                         "(ISSUE 20): per-tick host/h2d/dispatch/"
                         "device/drain attribution; the rung banks "
                         "phase_breakdown (requires in-process "
                         "replicas)")
    ap.add_argument("--spill", default="off", choices=("on", "off"),
                    help="host-RAM KV spill tier (ISSUE 17): one "
                         "shared KVSpillArena across the replicas "
                         "(and every supervisor rebuild), so evicted "
                         "or crash-killed warm prefixes restore via "
                         "one H2D scatter instead of re-prefilling; "
                         "the rung banks kv_spill_hit_frac + "
                         "kv_spill_restored_tokens (off = the "
                         "bitwise A/B reference)")
    ap.add_argument("--spill-mb", type=int, default=256,
                    help="arena capacity in MiB under --spill on")
    ap.add_argument("--migrate", default="off", choices=("on", "off"),
                    help="cross-replica KV transfer (ISSUE 18): the "
                         "gateway cuts live requests over on drain "
                         "(terminal migrated events + resume_kv "
                         "spans; implies a spill arena) and the run "
                         "appends a two-gateway drain-migration A/B "
                         "probe — migrate vs re-prefill control — "
                         "banking kv_xfer_hit_frac, "
                         "recompute_tokens_saved and the "
                         "amplification ratio in the rung; under "
                         "--fleet the replica processes get "
                         "--spill-mb/--migrate so SIGTERM scale-downs "
                         "migrate instead of finishing in place")
    ap.add_argument("--migrate-requests", type=int, default=6,
                    help="in-flight streams the migrate probe drains "
                         "mid-run (per A/B side)")
    ap.add_argument("--chaos", action="store_true",
                    help="seeded chaos harness (ISSUE 12): kill/hang "
                         "replicas mid-run, then assert zero "
                         "corrupted streams (bitwise replay against "
                         "a fresh reference engine), errors within "
                         "the retry-budget bound, and the goodput "
                         "floor; nonzero exit on violation")
    ap.add_argument("--chaos-kills", type=int, default=2,
                    help="replica faults to inject, spread evenly "
                         "over the request stream")
    ap.add_argument("--chaos-mode", default="mix",
                    choices=("kill", "hang", "mix"),
                    help="tick-thread crash, stuck dispatch, or "
                         "alternating")
    ap.add_argument("--failover-budget", type=int, default=2,
                    help="replica failures one request may ride "
                         "through before it errors (Gateway "
                         "failover_budget)")
    ap.add_argument("--watchdog-timeout-s", type=float, default=0.5,
                    help="dispatch-to-drain watchdog deadline under "
                         "--chaos")
    ap.add_argument("--goodput-floor", type=float, default=0.95,
                    help="minimum completed-request fraction the "
                         "chaos run must clear")
    ap.add_argument("--slo-windows", type=float, default=1.0,
                    help="scale the burn-rate alert windows (ISSUE "
                         "15): 1.0 = production-shaped (60s/300s "
                         "page pair), 0.01 lets a CI-length run fire "
                         "and resolve real alerts")
    ap.add_argument("--telemetry", default="on",
                    choices=("on", "off"),
                    help="time-series sampler + burn-rate alerting "
                         "on the gateways (off = the pre-ISSUE-15 "
                         "snapshot-only stack, the bitwise A/B "
                         "reference)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--url", action="append", default=None,
                    help="attach to HOST:PORT instead of self-hosting "
                         "(repeatable: client-side round-robin over "
                         "several fleet front doors)")
    ap.add_argument("--diurnal", action="store_true",
                    help="modulate the offered rate along a seeded "
                         "sinusoid over the run (the autoscaler's "
                         "evaluation trace; see --diurnal-amp/-cycles)")
    ap.add_argument("--diurnal-amp", type=float, default=0.8,
                    help="sinusoid amplitude as a fraction of --rate")
    ap.add_argument("--diurnal-cycles", type=float, default=1.0,
                    help="full day-cycles compressed into the run")
    ap.add_argument("--fleet", type=int, default=0,
                    help="self-host N SEPARATE gateway processes "
                         "behind an in-process FleetFrontend "
                         "(remote-replica adapter routing, ISSUE 13)")
    ap.add_argument("--fleet-kill", type=int, default=0,
                    help="SIGKILL this many replica processes at "
                         "seeded mid-run points (fleet chaos: bitwise "
                         "replay gate + goodput floor apply)")
    ap.add_argument("--frontends", type=int, default=1,
                    help="run N sibling FleetFrontends over the same "
                         "replica fleet, gossip-linked (leaderless "
                         "frontend HA, ISSUE 16); clients round-robin "
                         "and resume severed streams on a sibling")
    ap.add_argument("--frontend-kill", type=int, default=0,
                    help="kill this many FRONTENDS at seeded mid-run "
                         "points (needs --frontends >= 2 and must "
                         "leave a survivor); the fleet gate then also "
                         "demands zero dropped/duplicated committed "
                         "tokens across the client-side resumes")
    ap.add_argument("--autoscale", action="store_true",
                    help="run the closed-loop FleetAutoscaler over "
                         "the run (pair with --diurnal)")
    ap.add_argument("--autoscale-min", type=int, default=1)
    ap.add_argument("--autoscale-max", type=int, default=4)
    ap.add_argument("--autoscale-cooldown-s", type=float, default=3.0)
    ap.add_argument("--autoscale-mode", default="windowed",
                    choices=("windowed", "instant"),
                    help="decision signals: windowed means over "
                         "--autoscale-window-s (ISSUE 15 default) vs "
                         "the single-sample instant reference")
    ap.add_argument("--autoscale-window-s", type=float, default=1.0)
    ap.add_argument("--out", default=OUT_DEFAULT,
                    help="rung file bench.py auto-ingests "
                         "('' disables the write)")
    ap.add_argument("--jsonl", default="",
                    help="per-request JSONL for trace_report's "
                         "client-side join ('' disables)")
    ap.add_argument("--trace-dir", default="", dest="trace_dir",
                    help="dump the gateway's request-trace rings here "
                         "(self-hosted mode; '' disables)")
    ns = ap.parse_args(argv)
    if ns.fleet and ns.out == OUT_DEFAULT:
        # the fleet rung is its own bench ladder entry
        ns.out = OUT_FLEET
    _force_platform()
    import jax
    device = jax.devices()[0].device_kind
    started = time.strftime("%Y-%m-%d %H:%M:%S")
    rung = asyncio.run(run_loadgen(ns))
    print("LOADGEN_JSON " + json.dumps(rung))
    if ns.out:
        tmp = ns.out + ".tmp"
        section = "fleet" if ns.fleet else "gateway"
        with open(tmp, "w") as f:
            json.dump({"started": started, "device": device,
                       section: rung}, f, indent=1)
        os.replace(tmp, ns.out)
        print(f"wrote {ns.out}", file=sys.stderr)
    ch = rung.get("chaos")
    if ch is not None and not ch["ok"]:
        print("CHAOS FAILED: "
              f"corrupted={ch['corrupted_streams']} "
              f"errors_5xx={ch['errors_5xx']} (bound "
              f"{ch['error_bound']}) completed_frac="
              f"{ch['completed_frac']} (floor {ch['goodput_floor']})",
              file=sys.stderr)
        return 1
    mp = rung.get("migrate_probe")
    if mp is not None and not mp["ok"]:
        on, off = mp["modes"]["on"], mp["modes"]["off"]
        print("MIGRATE PROBE FAILED: "
              f"parity_ok={mp['parity_ok']} "
              f"corrupted on/off={on['corrupted_streams']}/"
              f"{off['corrupted_streams']} "
              f"errors on/off={on['errors']}/{off['errors']}",
              file=sys.stderr)
        return 1
    fg = rung.get("fleet_gate")
    if fg is not None and not fg["ok"]:
        print("FLEET GATE FAILED: "
              f"corrupted={fg['corrupted_streams']} "
              f"errors_5xx={fg['errors_5xx']} (bound "
              f"{fg['error_bound']}) completed_frac="
              f"{fg['completed_frac']} (floor {fg['goodput_floor']})",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
