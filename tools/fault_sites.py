#!/usr/bin/env python
"""Print the fault-injection site inventory (thin wrapper so ops
tooling under tools/ has one obvious entry point; equivalent to
``python -m paddle_tpu.utils.faults --list``)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.utils import faults  # noqa: E402

if __name__ == "__main__":
    sys.exit(faults.main(["--list"]))
