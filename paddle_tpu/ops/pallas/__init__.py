"""Pallas TPU kernels + the one shared gating policy for routing to them
(flash/decode attention, fused dequant matmul)."""
import os


def interpret_enabled() -> bool:
    """PADDLE_TPU_PALLAS_INTERPRET=1 runs every Pallas kernel in interpret
    mode AND makes the dispatch layers route to them — CI on CPU then
    exercises the same glue (slicing, padding, scalar plumbing) that runs
    on hardware."""
    return bool(os.environ.get("PADDLE_TPU_PALLAS_INTERPRET"))


def tpu_backend() -> bool:
    import jax
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False


def kernels_enabled() -> bool:
    return interpret_enabled() or tpu_backend()
