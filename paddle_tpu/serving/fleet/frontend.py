"""Fleet front door: proxy ``/v1/generate`` over remote peer gateways
(ISSUE 13 tentpole; reference: the router/edge tier production LLM
fleets put in front of N model-server processes — an SSE-aware reverse
proxy with cache-affinity routing and mid-stream failover, restated
stdlib-only over the gateway's own HTTP surface).

:class:`FleetFrontend` turns N gateway PROCESSES into one service:

- **Routing** — the same :class:`~..router.PrefixAffinityRouter`
  ladder the in-process gateway uses, over :class:`~.remote
  .RemoteReplica` adapters (duck-typed seam: ``healthy``/``load``/
  ``has_prefix`` read cached HTTP-probe snapshots). Affinity keys are
  computed standalone (:func:`~.remote.prefix_digest_chain` — pinned
  byte-for-byte to the engine's digests), probed against each peer's
  GOSSIPED digest set, so the prefix cache is a fleet asset: a request
  lands on ANY warm peer.
- **Proxying** — the chosen peer's response is relayed BYTE-FOR-BYTE
  (status line, headers, every SSE event — pinned bitwise against a
  direct connection by test). Relaying parses events as they pass so
  the frontend always knows the committed ``(token, logprob)`` prefix
  of every in-flight stream.
- **Mid-stream failover** — a peer that dies mid-stream (connection
  drop, process kill, 5xx; the ``peer_conn_drop`` fault site injects
  it deterministically) routes the request through the same
  resume seam the in-process failover uses (ISSUE 12), now over HTTP:
  resubmit ``prompt + committed`` with ``resume_tokens``/
  ``resume_lps`` on a surviving peer, skip the re-emitted committed
  prefix when relaying, and the client sees no duplicated and no
  missing token — greedy streams finish BITWISE identical to an
  uninterrupted run (tokens AND logprobs); seeded sampled requests
  re-derive a per-hop seed (distribution-preserving, not bitwise —
  the ISSUE 12 contract, unchanged). ``failover_budget`` bounds the
  hops; a fully-committed-at-the-kill stream is synthesized from the
  committed prefix, never retried.
- **Federated live metrics** (ISSUE 15) — ``GET /metricsz`` folds
  every peer's CACHED windowed telemetry doc (fetched on the probe
  rounds, staleness-bounded) into one fleet view: per-replica
  sections plus summed token/request rates, queue depth, worst
  goodput and the max SLO burn per class with every active alert
  tagged by peer — the "is the fleet healthy NOW" answer that used to
  take N manual scrapes and a join.
- **Rejoin** — a peer evicted by probe failures or a dropped stream
  carries a :class:`~..supervisor.CircuitBreaker`: after backoff the
  router hands it AT MOST ONE live probation probe; a proxied success
  closes the breaker and re-admits the peer (remote failures heal the
  same way local ones do).

The frontend is deliberately model-free: no engine, no jax — it can
run on a 2-vCPU edge box in front of a pod of accelerator hosts.
"""
from __future__ import annotations

import asyncio
import base64
import itertools
import json
import time
import uuid
from typing import Any, Dict, List, Optional

from ...utils import faults
from ...utils import observability as obs
from ..gateway import _SSE_HEAD  # noqa: F401  (re-export convenience)
from ..gateway import _http_response, _json_response, _query_param
from ..reqtrace import RequestTrace, RequestTraceRing
from ..router import NoReplicaError, PrefixAffinityRouter
from ..supervisor import BREAKER_CLOSED, CircuitBreaker
from .remote import RemoteReplica, prefix_digest_chain

__all__ = ["FleetFrontend"]

_frontend_ids = itertools.count()

# the per-hop seed fold for sampled requests, same constant the
# in-process failover uses (docs/FAULT_TOLERANCE.md §4b)
_SEED_FOLD = 0x9E3779B1


class _ProxyState:
    """Committed prefix of one proxied stream: exactly the (token,
    logprob) units FORWARDED to the client (a unit read off the peer
    but dropped by a fault/crash before forwarding is NOT committed —
    the client never saw it)."""

    __slots__ = ("tokens", "lps", "head_sent", "final", "t_first",
                 "migrated")

    def __init__(self):
        self.tokens: List[int] = []
        self.lps: List[Optional[float]] = []
        self.head_sent = False
        self.final: Optional[Dict[str, Any]] = None
        self.t_first: Optional[float] = None
        # the intercepted terminal "migrated" event of a draining peer
        # (ISSUE 18): committed stream + resume_kv digest, never
        # forwarded to the client
        self.migrated: Optional[Dict[str, Any]] = None


class FleetFrontend:
    """Serve ``/v1/generate`` over N remote peer gateways.

    ``peers``: list of :class:`RemoteReplica` (more can join at
    runtime via :meth:`add_peer` — the autoscaler's spawn path).
    ``chunk_tokens`` must match the peers' engines'
    ``chunk_prefill_tokens`` for affinity routing (None disables
    affinity: pure load balancing)."""

    def __init__(self, peers: List[RemoteReplica],
                 host: str = "127.0.0.1", port: int = 0, *,
                 chunk_tokens: Optional[int] = None,
                 routing: str = "prefix", spill_margin: float = 8.0,
                 failover_budget: int = 2,
                 peer_read_timeout_s: float = 30.0,
                 peer_connect_timeout_s: float = 5.0,
                 migrate: bool = True,
                 xfer_timeout_s: float = 2.0,
                 breakers: bool = True,
                 breaker_backoff_s: float = 1.0,
                 breaker_backoff_max_s: float = 30.0,
                 breaker_probes: int = 1,
                 name: Optional[str] = None,
                 trace: bool = True, trace_capacity: int = 512,
                 clock=time.monotonic):
        self.name = name or f"fleet{next(_frontend_ids)}"
        self.host, self.port = host, port
        self.chunk_tokens = chunk_tokens
        self._failover_budget = int(failover_budget)
        self._peer_read_timeout_s = float(peer_read_timeout_s)
        self._peer_connect_timeout_s = float(peer_connect_timeout_s)
        # cross-replica KV transfer (ISSUE 18): with migrate on, a
        # draining peer's migrated streams resubmit with an inline
        # resume_kv blob fetched over /kvz (bounded by xfer_timeout_s)
        # so the survivor restores instead of re-prefilling; off, the
        # same cutover just rides today's resume_tokens re-prefill.
        self._migrate = bool(migrate)
        self._xfer_timeout_s = float(xfer_timeout_s)
        self._breakers = bool(breakers)
        # the whole control plane is clock-injectable (ISSUE 16): the
        # fleet sim drives this frontend's breakers — and everything
        # downstream of them — on a simulated clock
        self._clock = clock
        self._breaker_kw = dict(backoff_s=breaker_backoff_s,
                                backoff_max_s=breaker_backoff_max_s,
                                probes_to_close=breaker_probes,
                                clock=clock)
        self._draining = False
        self._killed = False
        self._server: Optional[asyncio.AbstractServer] = None
        self._active = 0
        # live client writers, tracked so kill() can sever them
        # mid-stream (the in-process stand-in for a frontend SIGKILL)
        self._writers: set = set()
        self.peers: List[RemoteReplica] = []
        self._labels = {"gateway": self.name}
        reg = obs.registry()
        self._c_requests = reg.counter("fleet_requests_total",
                                       **self._labels)
        self._c_tokens = reg.counter("fleet_proxied_tokens_total",
                                     **self._labels)
        self._c_failovers = reg.counter("fleet_peer_failovers_total",
                                        **self._labels)
        self._c_exhausted = reg.counter(
            "fleet_retry_budget_exhausted_total", **self._labels)
        self._c_disconnects = reg.counter("fleet_disconnects_total",
                                          **self._labels)
        self._c_migrated = reg.counter("fleet_migrated_requests_total",
                                       **self._labels)
        self._g_replicas = reg.gauge("fleet_replicas", **self._labels)
        self._h_ttft = reg.histogram("fleet_ttft_ms",
                                     buckets=obs.SERVING_MS_BUCKETS,
                                     **self._labels)
        # start the router EMPTY: every peer joins through the one
        # membership path (add_peer — breaker attach + prober start)
        self._router = PrefixAffinityRouter(
            [], policy=routing, spill_margin=spill_margin,
            labels=self._labels)
        self.ring = RequestTraceRing(
            capacity=trace_capacity,
            labels=dict(self._labels, replica="frontend")) \
            if trace else None
        self.autoscaler = None      # attached via attach_autoscaler()
        for p in peers:
            self.add_peer(p)

    # --------------------------------------------------------- membership
    def add_peer(self, peer: RemoteReplica):
        """Join a peer (initial fleet, autoscaler spawn, rejoin):
        attach its breaker, start its prober, enter rotation."""
        if self._breakers and peer.breaker is None:
            peer.breaker = CircuitBreaker(
                on_state=self._breaker_state_cb(peer),
                **self._breaker_kw)
        self._router.add_replica(peer)
        if peer not in self.peers:
            self.peers.append(peer)
        peer.start()
        self._g_replicas.set(len(self.peers))
        obs.record_event("fleet_peer_join", fleet=self.name,
                         peer=peer.name)

    def remove_peer(self, peer: RemoteReplica):
        """Leave rotation (autoscaler drain / permanent death). The
        peer's prober stops; in-flight proxied streams to it finish on
        their own (a draining peer completes what it owns)."""
        self._router.remove_replica(peer)
        if peer in self.peers:
            self.peers.remove(peer)
        peer.stop()
        self._g_replicas.set(len(self.peers))
        obs.record_event("fleet_peer_leave", fleet=self.name,
                         peer=peer.name)

    def _breaker_state_cb(self, peer: RemoteReplica):
        def cb(state: str):
            if state == BREAKER_CLOSED:
                peer.mark(True)
            obs.record_event("fleet_breaker", fleet=self.name,
                             peer=peer.name, state=state)
        return cb

    def attach_autoscaler(self, scaler):
        self.autoscaler = scaler

    # ---------------------------------------------------------- lifecycle
    async def start(self):
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        if self.autoscaler is not None:
            self.autoscaler.start()
        obs.record_event("fleet_start", fleet=self.name,
                         port=self.port, peers=len(self.peers))
        return self

    async def drain(self, timeout: float = 30.0):
        """Stop admitting, let in-flight proxies finish, stop the
        autoscaler and probers, close the listener."""
        self._draining = True
        if self.autoscaler is not None:
            self.autoscaler.stop()
        deadline = time.monotonic() + timeout
        while self._active > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        for p in list(self.peers):
            p.stop()
        if self._server is not None:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass
            self._server = None
        obs.record_event("fleet_drain", fleet=self.name)

    def kill(self):
        """In-process stand-in for ``SIGKILL`` of this frontend
        (ISSUE 16 HA tests): abort the listener and sever every live
        client stream mid-flight WITHOUT draining — in-flight requests
        die exactly as they would when the process dies, and clients
        must recover by retrying against a surviving sibling frontend
        with their committed prefix as ``resume_tokens``. Also stops
        the probers and the autoscaler so the corpse stops probing."""
        self._killed = True
        self._draining = True
        if self._server is not None:
            self._server.close()
            self._server = None
        for w in list(self._writers):
            try:
                w.transport.abort()
            except Exception:
                pass
        if self.autoscaler is not None:
            self.autoscaler.stop()
        for p in list(self.peers):
            p.stop()
        obs.record_event("fleet_kill", fleet=self.name)

    def dump_traces(self, directory: str) -> List[str]:
        """Write the frontend's own request-trace ring (the fleet's
        hop records — what ``trace_report``'s fleet merge joins with
        the peer gateways' rings by request id)."""
        import os
        if self.ring is None:
            return []
        os.makedirs(directory, exist_ok=True)
        return [self.ring.dump(os.path.join(
            directory, f"reqtrace_{self.name}_frontend.json"))]

    # ------------------------------------------------------------- health
    def healthz(self) -> Dict[str, Any]:
        return {
            "fleet": self.name,
            "draining": self._draining,
            "requests": int(self._c_requests.value),
            "proxied_tokens": int(self._c_tokens.value),
            "peer_failovers": int(self._c_failovers.value),
            "migrated_requests": int(self._c_migrated.value),
            "retry_budget_exhausted": int(self._c_exhausted.value),
            "disconnects": int(self._c_disconnects.value),
            "failover_budget": self._failover_budget,
            "router": self._router.snapshot(),
            "peers": {p.name: {"healthy": p.healthy(),
                               "load": p.load(),
                               "url": f"{p.host}:{p.port}"}
                      for p in self.peers},
        }

    def debugz(self) -> Dict[str, Any]:
        return {
            "fleet": self.name,
            "draining": self._draining,
            "router": self._router.snapshot(),
            "peers": {p.name: p.snapshot() for p in self.peers},
            "autoscaler": self.autoscaler.snapshot()
            if self.autoscaler is not None else None,
            "trace_ring": self.ring.summary()
            if self.ring is not None else None,
        }

    def metricsz(self, window_s: Optional[float] = None
                 ) -> Dict[str, Any]:
        """Federated ``GET /metricsz`` (ISSUE 15): every peer's cached
        windowed doc under its own per-replica key, plus fleet totals
        (summed token/request rates, queue depth, worst goodput, max
        burn per SLO class, every active alert tagged with its peer).
        Reads ONLY the probe caches — no network on the serving path;
        a stale peer is excluded from totals, the same staleness bound
        routing applies. ``?window_s=N`` re-targets the probers' next
        rounds (cached federation converges within one interval)."""
        if window_s:
            for p in self.peers:
                p.set_metrics_window(window_s)
        replicas: Dict[str, Any] = {}
        tok_rate = req_rate = queue_depth = 0.0
        goodput_min: Optional[float] = None
        burn_max: Dict[str, float] = {}
        alerts_active: List[dict] = []
        live = 0
        for p in self.peers:
            mz = p.metricsz()
            replicas[p.name] = mz
            doc = mz.get("doc")
            if mz.get("stale") or not doc or not doc.get("enabled"):
                continue
            live += 1
            # fold ONLY the peer's own gateway="<name>" label variants:
            # a peer co-hosted with other gateways in one process (one
            # shared registry) samples THEIR series too, and summing
            # every variant would double-count the fleet totals
            own = doc.get("gateway")
            tag = f'gateway="{own}"' if own else None
            for full, view in (doc.get("metrics") or {}).items():
                if tag is not None and "{" in full and tag not in full:
                    continue
                base = full.split("{", 1)[0]
                if base == "gateway_tokens_total":
                    tok_rate += view.get("rate_per_s", 0.0)
                elif base == "gateway_requests_total":
                    req_rate += view.get("rate_per_s", 0.0)
                elif base == "gateway_queue_depth":
                    queue_depth += view.get("last", 0.0)
                elif base == "gateway_goodput_frac":
                    v = view.get("last", 1.0)
                    goodput_min = v if goodput_min is None \
                        else min(goodput_min, v)
            slo = doc.get("slo") or {}
            for cls, by_window in (slo.get("burn") or {}).items():
                for b in by_window.values():
                    if b > burn_max.get(cls, 0.0):
                        burn_max[cls] = b
            for a in slo.get("active") or ():
                alerts_active.append(dict(a, peer=p.name))
        return {
            "fleet": self.name,
            "enabled": True,
            "window_s": float(window_s) if window_s else None,
            "peers": len(self.peers),
            "live_peers": live,
            "replicas": replicas,
            "totals": {
                "tokens_per_sec": round(tok_rate, 3),
                "requests_per_sec": round(req_rate, 3),
                "queue_depth": queue_depth,
                "goodput_frac_min": goodput_min,
                "burn_rate_max": {k: round(v, 3)
                                  for k, v in burn_max.items()},
                "alerts_active": alerts_active,
            },
        }

    # ----------------------------------------------------- frontend HA
    def gossipz(self) -> Dict[str, Any]:
        """What a SIBLING frontend may adopt from us (ISSUE 16
        leaderless HA; served at ``GET /gossipz`` over the same HTTP
        transport the probers already ride). Three kinds of state:

        - per-peer prefix digest sets + the PEER's generation counter
          (authoritative — comparable across frontends, so the fresher
          view always wins regardless of who probed last);
        - sticky routing assignments as ``{digest: peer name}`` (a
          sibling adopts only digests it has no opinion on);
        - health + breaker state per peer as HINTS only — every
          frontend re-derives liveness from its OWN probes (trusting a
          sibling's verdict would let one partitioned frontend blind
          the whole tier)."""
        return {
            "fleet": self.name,
            "draining": self._draining,
            "peers": {p.name: p.gossip_view() for p in self.peers},
            "sticky": self._router.export_sticky(),
        }

    # ---------------------------------------------------- profilez federation
    async def _serve_profilez(self, query: str, writer):
        """``GET /profilez?duration_s=N&replica=<peer>`` (ISSUE 20):
        federate the gateway capture — one call on the frontend
        profiles a CHOSEN replica gateway (default: the first healthy
        peer). The blocking peer fetch runs in a thread so the capture
        window never stalls the frontend's event loop; the peer's own
        report is returned verbatim under ``report``."""
        dur = _query_param(query, "duration_s")
        dur = 1.0 if dur is None else max(0.05, min(float(dur), 30.0))
        want = _query_param(query, "replica", str)
        peer = None
        for p in self.peers:
            if want is not None:
                if p.name == want:
                    peer = p
                    break
            elif p.healthy():
                peer = p
                break
        if peer is None:
            writer.write(_json_response(
                404, {"error": f"no such replica {want!r}"
                      if want is not None else "no healthy peer"}))
            await writer.drain()
            return
        report = await asyncio.get_event_loop().run_in_executor(
            None, lambda: peer.fetch_profilez(dur))
        if report is None:
            writer.write(_json_response(
                502, {"error": f"peer {peer.name} capture failed"}))
        else:
            writer.write(_json_response(200, {
                "fleet": self.name, "replica": peer.name,
                "report": report}))
        await writer.drain()

    def apply_gossip(self, doc: Dict[str, Any]) -> Dict[str, int]:
        """Merge a sibling's :meth:`gossipz` doc. Only ever ADDS
        knowledge: digest sets move forward by generation guard,
        sticky entries fill local gaps, and nothing a local probe or
        route decision established is overridden. Unknown peer names
        are skipped — membership changes travel through the manager,
        not through gossip."""
        by_name = {p.name: p for p in self.peers}
        adopted_digests = 0
        for name, view in (doc.get("peers") or {}).items():
            peer = by_name.get(name)
            if peer is None or not isinstance(view, dict):
                continue
            if peer.adopt_digests(view.get("digests") or (),
                                  view.get("generation", -1),
                                  spilled=view.get("spilled") or ()):
                adopted_digests += 1
        adopted_sticky = self._router.merge_sticky(
            doc.get("sticky") or {}, by_name)
        if adopted_digests or adopted_sticky:
            obs.record_event("fleet_gossip_merge", fleet=self.name,
                             source=doc.get("fleet", "?"),
                             digest_sets=adopted_digests,
                             sticky=adopted_sticky)
        return {"digest_sets": adopted_digests,
                "sticky": adopted_sticky}

    # ---------------------------------------------------------------- HTTP
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        self._writers.add(writer)
        try:
            line = await asyncio.wait_for(reader.readline(), 30)
            parts = line.decode("latin1").split()
            if len(parts) < 3:
                return
            method, path = parts[0], parts[1]
            headers: Dict[str, str] = {}
            while True:
                h = await asyncio.wait_for(reader.readline(), 30)
                if h in (b"\r\n", b"\n", b""):
                    break
                k, _, v = h.decode("latin1").partition(":")
                headers[k.strip().lower()] = v.strip()
            try:
                n = int(headers.get("content-length", "0") or 0)
                if n < 0:
                    raise ValueError("negative")
            except ValueError:
                writer.write(_json_response(
                    400, {"error": "bad Content-Length"}))
                await writer.drain()
                return
            body = await asyncio.wait_for(reader.readexactly(n), 30) \
                if n else b""
            path, _, query = path.partition("?")
            path = path.rstrip("/") or "/"
            if method == "GET" and path == "/healthz":
                writer.write(_json_response(200, self.healthz()))
                await writer.drain()
            elif method == "GET" and path == "/debugz":
                writer.write(_json_response(200, self.debugz()))
                await writer.drain()
            elif method == "GET" and path == "/metricsz":
                window_s = _query_param(query, "window_s")
                writer.write(_json_response(
                    200, self.metricsz(window_s)))
                await writer.drain()
            elif method == "GET" and path == "/metrics":
                writer.write(_http_response(
                    200, obs.registry().prometheus_text().encode(),
                    ctype="text/plain; version=0.0.4"))
                await writer.drain()
            elif method == "GET" and path == "/gossipz":
                writer.write(_json_response(200, self.gossipz()))
                await writer.drain()
            elif method == "GET" and path == "/profilez":
                await self._serve_profilez(query, writer)
            elif method == "POST" and path == "/v1/generate":
                self._active += 1
                try:
                    await self._generate(body, headers, writer)
                finally:
                    self._active -= 1
            else:
                writer.write(_json_response(
                    404, {"error": f"no route {path}"}))
                await writer.drain()
        except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                ConnectionError, OSError):
            pass
        finally:
            self._writers.discard(writer)
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    # ------------------------------------------------------------ generate
    async def _generate(self, body: bytes, headers: Dict[str, str],
                        writer: asyncio.StreamWriter):
        if self._draining:
            writer.write(_json_response(
                503, {"error": "draining: not admitting new requests"},
                extra={"Retry-After": "1"}))
            await writer.drain()
            return
        try:
            spec = json.loads(body.decode())
            if not isinstance(spec, dict):
                raise ValueError("request body must be a JSON object")
            ids = spec.get("prompt", spec.get("input_ids"))
            if not isinstance(ids, list) or not ids \
                    or not all(isinstance(t, int) for t in ids):
                raise ValueError("prompt must be a non-empty list of "
                                 "token ids")
        except ValueError as e:
            writer.write(_json_response(400, {"error": str(e)}))
            await writer.drain()
            return
        # one id across every hop and every process: body field wins,
        # then the inbound header, then a minted one — written back
        # into the proxied body so every peer's ring records the SAME
        # id (what the fleet trace merge joins on)
        rid = spec.get("request_id") \
            or headers.get("x-request-id") \
            or uuid.uuid4().hex[:16]
        spec = dict(spec, request_id=rid, prompt=list(ids))
        spec.pop("input_ids", None)
        self._c_requests.inc()
        trace = None
        if self.ring is not None:
            trace = RequestTrace(rid,
                                 tenant=str(spec.get("tenant",
                                                     "default")),
                                 slo=str(spec.get("slo",
                                                  "interactive")))
            trace.ev("accept", stream=bool(spec.get("stream", True)),
                     prompt_tokens=len(ids), fleet=self.name)
        digests = spec.get("affinity_key")
        if digests is None and self.chunk_tokens:
            # longest span first — the router's probe order
            digests = prefix_digest_chain(ids, self.chunk_tokens)[::-1]
        orig_prompt = list(ids)
        orig_max_new = int(spec.get("max_new_tokens", 32))
        orig_seed = spec.get("seed")
        st = _ProxyState()
        hops = 0
        t0 = time.monotonic()
        while True:
            meta: Dict[str, Any] = {}
            try:
                replica = self._router.route(
                    digests, trace=trace, allow_probe=hops == 0,
                    meta=meta)
            except NoReplicaError as e:
                await self._terminal_error(writer, st, trace, 503,
                                           str(e))
                return
            probe = meta.get("verdict") == "probe"
            if trace is not None:
                trace.ev("proxy_to", replica=replica.name,
                         attempt=hops)
            outcome = await self._proxy_stream(replica, spec, rid,
                                               writer, st, t0)
            if outcome == "done":
                final = st.final or {}
                reason = final.get("finish_reason",
                                   "error" if "error" in final
                                   else "stop")
                self._probe_done(replica, probe,
                                 True if reason == "stop" else None)
                if reason == "stop" and probe \
                        and replica.breaker is not None \
                        and replica.breaker.state == BREAKER_CLOSED \
                        and trace is not None:
                    trace.ev("breaker_close", replica=replica.name)
                self._finish_trace(trace, {
                    "stop": "stop", "timeout": "timeout",
                    "cancelled": "cancelled"}.get(reason, "error"),
                    st)
                return
            if outcome == "client_gone":
                self._c_disconnects.inc()
                self._probe_done(replica, probe, None)
                self._finish_trace(trace, "disconnect", st)
                return
            if outcome == "shed":
                # the peer shed with 429 (forwarded verbatim): the
                # fleet is overloaded, not broken — no eviction, no
                # budget charge, the client backs off
                self._probe_done(replica, probe, None)
                self._finish_trace(trace, "shed", st)
                return
            if outcome == "peer_shed":
                # a SURVIVOR shed a mid-stream failover resubmit:
                # overload, not failure — terminal for this request
                # (an SSE error event; the head is already out), but
                # the healthy peer is neither evicted nor charged
                self._probe_done(replica, probe, None)
                await self._terminal_error(
                    writer, st, trace, 503,
                    "failover resubmit shed: fleet overloaded",
                    outcome="shed")
                return
            # ------------------------------------ peer failed / migrated
            migrated = outcome == "peer_migrated"
            mig = (st.migrated or {}) if migrated else {}
            st.migrated = None
            resume_toks = list(st.tokens)
            resume_lps = list(st.lps)
            if migrated:
                # planned drain cutover (ISSUE 18): the peer is
                # draining, not broken — no eviction, no breaker
                # charge, but the hop still counts against the budget.
                # Adopt the event's committed stream when it extends
                # what we relayed (it includes tokens the peer held
                # back from emission): the skip-count dedupe forwards
                # the extension as the survivor re-emits it.
                self._probe_done(replica, probe, None)
                self._c_migrated.inc()
                # exclude the origin from the resubmit route NOW —
                # its healthz already answers draining:True but the
                # cached probe snapshot may not have observed it yet,
                # and a hop bounced off its 503 would both charge the
                # budget and drop the resume_kv we are about to attach
                replica.mark(False)
                toks = mig.get("tokens")
                if isinstance(toks, list) \
                        and len(toks) >= len(st.tokens) \
                        and [int(t) for t in
                             toks[:len(st.tokens)]] == st.tokens:
                    resume_toks = [int(t) for t in toks]
                    lps = mig.get("logprobs") or []
                    resume_lps = (list(lps) + [None] * len(toks)
                                  )[:len(toks)]
                if trace is not None:
                    trace.ev("peer_migrated", replica=replica.name,
                             committed=len(resume_toks),
                             resume_kv=str(mig.get("resume_kv")
                                           or "")[:12])
                obs.record_event("fleet_peer_migrated",
                                 fleet=self.name, peer=replica.name,
                                 request_id=rid,
                                 committed=len(resume_toks))
            else:
                self._c_failovers.inc()
                replica.note_proxy_failure()
                self._router.evict_unhealthy()
                self._probe_done(replica, probe, False)
                if trace is not None:
                    trace.ev("peer_fail", replica=replica.name,
                             reason=outcome)
                    if replica.breaker is not None:
                        trace.ev("breaker_open", replica=replica.name)
                obs.record_event("fleet_peer_fail", fleet=self.name,
                                 peer=replica.name, reason=outcome,
                                 request_id=rid)
            hops += 1
            remaining = orig_max_new - len(resume_toks)
            # checked BEFORE the retry budget (the ISSUE 12 rule): a
            # result the client already fully holds is never errored
            if resume_toks and remaining <= 0:
                # fully committed at the kill/cutover boundary:
                # forward any committed-but-unrelayed tail (tokens a
                # migrated event carried past what the peer streamed),
                # then synthesize the final event — never re-run or
                # 503 a complete result
                try:
                    for i in range(len(st.tokens), len(resume_toks)):
                        writer.write(b"data: " + json.dumps(
                            {"token": resume_toks[i],
                             "lp": resume_lps[i]}).encode() + b"\n\n")
                        self._c_tokens.inc()
                    st.tokens = list(resume_toks)
                    st.lps = list(resume_lps)
                    st.final = {"tokens": list(resume_toks),
                                "logprobs": [v for v in resume_lps],
                                "finish_reason": "stop", "done": True}
                    writer.write(b"data: "
                                 + json.dumps(st.final).encode()
                                 + b"\n\n")
                    await writer.drain()
                except (ConnectionError, OSError):
                    pass
                self._finish_trace(trace, "stop", st)
                return
            if hops > self._failover_budget:
                self._c_exhausted.inc()
                await self._terminal_error(
                    writer, st, trace, 503,
                    f"failover budget exhausted after "
                    f"{self._failover_budget} peer failures")
                return
            spec.pop("resume_kv", None)
            if resume_toks:
                # the HTTP face of the ISSUE 12 resume seam: re-prefill
                # prompt+committed on the survivor and skip the
                # re-emitted committed prefix while relaying
                spec = dict(spec,
                            prompt=orig_prompt + list(resume_toks),
                            resume_tokens=list(resume_toks),
                            resume_lps=list(resume_lps),
                            max_new_tokens=remaining)
                if migrated and self._migrate:
                    # ISSUE 18: resolve the migrated span to an inline
                    # wire blob the survivor injects — restore instead
                    # of re-prefill; any failure just leaves the
                    # re-prefill resume above (bitwise identical)
                    ref = await self._fetch_resume_kv(
                        replica, str(mig.get("resume_kv") or ""))
                    if ref:
                        spec = dict(spec, resume_kv=ref)
            if orig_seed is not None:
                # sampled streams re-derive a per-hop seed: the dead
                # peer consumed an unknown amount of the original
                # stream (distribution-preserving, not bitwise)
                spec = dict(spec,
                            seed=int(orig_seed) + _SEED_FOLD * hops)
            if trace is not None:
                trace.ev("resubmit", to_replica="", attempt=hops)
                trace.ev("resume_offset", offset=len(st.tokens),
                         committed=len(resume_toks))

    def _probe_done(self, replica, probe: bool,
                    success: Optional[bool]):
        if probe and replica.breaker is not None:
            replica.breaker.probe_done(success)

    def _finish_trace(self, trace, outcome: str, st: _ProxyState):
        if self.ring is not None and trace is not None:
            if st.t_first is not None:
                self._h_ttft.observe(
                    (st.t_first) * 1e3, exemplar=trace.request_id)
            self.ring.finish(trace, outcome, tokens=len(st.tokens))

    async def _terminal_error(self, writer, st: _ProxyState, trace,
                              status: int, msg: str,
                              outcome: str = "error"):
        try:
            if st.head_sent:
                writer.write(b"data: " + json.dumps(
                    {"error": msg, "done": True}).encode() + b"\n\n")
            else:
                writer.write(_json_response(
                    status, {"error": msg}, extra={"Retry-After": "1"}))
            await writer.drain()
        except (ConnectionError, OSError):
            pass
        self._finish_trace(trace, outcome, st)

    async def _fetch_resume_kv(self, origin: RemoteReplica,
                               digest: str) -> str:
        """Resolve a migrated span to an inline ``resume_kv`` blob
        (``b64:`` wire record) the survivor can inject without a
        fleet round-trip of its own. The drained origin is tried
        first — its arena provably holds the span and it keeps
        answering ``/kvz`` through the drain window — then any peer
        whose gossiped spilled tier claims the digest. Every failure
        (timeout, refused, corrupt, no digest) returns ``""``: the
        caller just resubmits on the re-prefill path, which is
        bitwise identical anyway."""
        if not digest:
            return ""
        cand = [origin] + [p for p in self.peers
                           if p is not origin and p.has_prefix(digest)]
        loop = asyncio.get_running_loop()
        for peer in cand:
            try:
                blob = await asyncio.wait_for(
                    loop.run_in_executor(
                        None, peer.fetch_kv, digest,
                        self._xfer_timeout_s),
                    self._xfer_timeout_s + 0.5)
            except (asyncio.TimeoutError, OSError, RuntimeError):
                continue
            if blob:
                return "b64:" + base64.b64encode(blob).decode("ascii")
        return ""

    # --------------------------------------------------------------- proxy
    async def _proxy_stream(self, replica: RemoteReplica,
                            spec: Dict[str, Any], rid: str,
                            writer: asyncio.StreamWriter,
                            st: _ProxyState, t0: float) -> str:
        """One proxy attempt against ``replica``. Returns ``"done"``
        (a terminal event/response was forwarded), ``"shed"`` (peer
        429, forwarded), ``"client_gone"``, or a peer-failure reason
        (``"peer_conn_drop"`` / ``"peer_error"`` / ``"peer_timeout"``
        — the caller runs the failover loop). Forwarding is
        byte-for-byte; the committed prefix in ``st`` advances only
        when a unit has actually been written to the client."""
        timeout = self._peer_read_timeout_s
        body = json.dumps(spec).encode()
        try:
            # bounded connect: a black-holed peer (SYN dropped) must
            # fail over in seconds, not the OS connect timeout —
            # peer_read_timeout_s only guards reads on an open conn
            pr, pw = await asyncio.wait_for(
                asyncio.open_connection(replica.host, replica.port),
                self._peer_connect_timeout_s)
        except (OSError, asyncio.TimeoutError):
            return "peer_error"
        try:
            pw.write((f"POST /v1/generate HTTP/1.1\r\n"
                      f"Host: {replica.host}\r\n"
                      f"X-Request-Id: {rid}\r\n"
                      f"Content-Length: {len(body)}\r\n\r\n").encode()
                     + body)
            await pw.drain()
            status_line = await asyncio.wait_for(pr.readline(), timeout)
            parts = status_line.split()
            if len(parts) < 2:
                return "peer_conn_drop"
            status = int(parts[1])
            head = status_line
            clen = 0
            sse = False
            while True:
                ln = await asyncio.wait_for(pr.readline(), timeout)
                if not ln:
                    return "peer_conn_drop"
                head += ln
                if ln in (b"\r\n", b"\n"):
                    break
                low = ln.lower()
                if low.startswith(b"content-length:"):
                    clen = int(ln.split(b":", 1)[1])
                if low.startswith(b"content-type:") \
                        and b"text/event-stream" in low:
                    sse = True
            if not sse:
                # one-shot JSON (nonstream, 4xx, 5xx): buffer, then
                # decide — forwarded verbatim or treated as a peer
                # failure the caller retries elsewhere
                payload = await asyncio.wait_for(
                    pr.readexactly(clen), timeout) if clen else b""
                if status >= 500:
                    return "peer_error"
                if st.head_sent:
                    # mid-SSE we cannot splice a fresh status line. A
                    # 429 from a survivor is OVERLOAD, not failure —
                    # terminal for this request (the ISSUE 12 rule:
                    # failover traffic is still sheddable, which is
                    # what stops a peer death amplifying into a retry
                    # storm) but never evicts or charges the budget;
                    # any other non-stream answer (peer restarted into
                    # draining, resume rejected) is a failed hop.
                    if status == 429:
                        return "peer_shed"
                    return "peer_error"
                try:
                    writer.write(head + payload)
                    await writer.drain()
                except (ConnectionError, OSError):
                    return "client_gone"
                if status == 429:
                    return "shed"
                st.final = {"finish_reason": "stop"} if status == 200 \
                    else {"error": f"peer status {status}",
                          "finish_reason": "error"}
                if status == 200:
                    try:
                        doc = json.loads(payload)
                        st.final = dict(doc,
                                        finish_reason=doc.get(
                                            "finish_reason", "stop"))
                        st.tokens = list(doc.get("tokens", ()))
                    except ValueError:
                        pass
                elif status == 504:
                    st.final = {"finish_reason": "timeout"}
                return "done"
            # ------------------------------------------------- SSE stream
            if not st.head_sent:
                try:
                    writer.write(head)
                    await writer.drain()
                except (ConnectionError, OSError):
                    return "client_gone"
                st.head_sent = True
            skip = len(st.tokens)   # survivor re-emits the committed
            seen = 0                # prefix first: drop, don't forward
            while True:
                try:
                    ln = await asyncio.wait_for(pr.readline(), timeout)
                except asyncio.TimeoutError:
                    return "peer_timeout"
                if not ln:
                    return "peer_conn_drop"
                unit = ln
                if ln.rstrip(b"\r\n"):
                    # data/comment line: its blank terminator belongs
                    # to the same unit — forward them together so the
                    # committed count only ever covers whole events
                    try:
                        nxt = await asyncio.wait_for(pr.readline(),
                                                     timeout)
                    except asyncio.TimeoutError:
                        return "peer_timeout"
                    if not nxt:
                        return "peer_conn_drop"
                    unit += nxt
                if not ln.startswith(b"data: "):
                    # SSE comment (half-close probe): relay verbatim
                    try:
                        writer.write(unit)
                        await writer.drain()
                    except (ConnectionError, OSError):
                        return "client_gone"
                    continue
                try:
                    ev = json.loads(ln[6:])
                except ValueError:
                    return "peer_error"
                if ev.get("done"):
                    if ev.get("finish_reason") == "migrated":
                        # planned drain cutover (ISSUE 18): NEVER
                        # forwarded — the caller resubmits to a
                        # survivor carrying the event's committed
                        # stream and resume_kv reference; the client
                        # just sees the stream continue
                        st.migrated = ev
                        return "peer_migrated"
                    if faults.inject("peer_conn_drop",
                                     replica=replica.name):
                        # severed between the last token and the done
                        # event — the fully-committed-at-the-kill case
                        return "peer_conn_drop"
                    try:
                        writer.write(unit)
                        await writer.drain()
                    except (ConnectionError, OSError):
                        return "client_gone"
                    st.final = ev
                    if isinstance(ev.get("tokens"), list):
                        st.tokens = list(ev["tokens"])
                    return "done"
                seen += 1
                if seen <= skip:
                    continue        # committed prefix replay: dedupe
                if faults.inject("frontend_conn_drop",
                                 frontend=self.name,
                                 replica=replica.name):
                    # the FRONTEND dies mid-stream (ISSUE 16 HA): the
                    # client's connection is severed with the unit
                    # unforwarded — the client holds only its committed
                    # prefix and must resume against a sibling frontend
                    try:
                        writer.transport.abort()
                    except Exception:
                        pass
                    return "client_gone"
                if faults.inject("peer_conn_drop",
                                 replica=replica.name):
                    # sever the peer leg BEFORE forwarding: the unit
                    # dies unseen, exactly like a real mid-wire kill
                    return "peer_conn_drop"
                try:
                    writer.write(unit)
                    await writer.drain()
                except (ConnectionError, OSError):
                    return "client_gone"
                if st.t_first is None:
                    st.t_first = time.monotonic() - t0
                st.tokens.append(int(ev["token"]))
                st.lps.append(ev.get("lp"))
                self._c_tokens.inc()
        except (asyncio.TimeoutError,):
            return "peer_timeout"
        except (ConnectionError, OSError, asyncio.IncompleteReadError):
            return "peer_conn_drop"
        finally:
            try:
                pw.close()
            except Exception:
                pass
