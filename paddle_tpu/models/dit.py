"""DiT and SD3-style MMDiT (reference: PaddleMIX ppdiffusers/models/
transformer_2d.py DiTTransformer2DModel and sd3_transformer_2d.py —
adaLN-Zero diffusion transformer; MMDiT joint image/text blocks).

TPU-native design: patchify = strided conv (MXU GEMM); adaLN modulation is
a fused per-block 6-way linear off the pooled conditioning vector; MMDiT
runs ONE attention over the concatenated [text; image] token streams
(static split sizes) so XLA sees a single big matmul instead of two
cross-attending towers.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer, Parameter
from ..ops.attention import dense_attention
from ..parallel.layers import ColumnParallelLinear, RowParallelLinear


def timestep_embedding(t, dim: int, max_period: float = 10000.0):
    """Sinusoidal timestep features, fp32 (reference: ppdiffusers
    embeddings.get_timestep_embedding)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def sincos_pos_embed_2d(grid: int, dim: int):
    """Fixed 2D sin-cos position table [1, grid*grid, dim] (reference:
    DiT's non-learned get_2d_sincos_pos_embed). Half the channels encode
    the row coordinate, half the column; each half is sin‖cos."""
    assert dim % 4 == 0, "sincos embed needs dim divisible by 4"
    quarter = dim // 4
    omega = 1.0 / (10000.0 ** (jnp.arange(quarter, dtype=jnp.float32)
                               / quarter))
    coords = jnp.arange(grid, dtype=jnp.float32)
    ys, xs = jnp.meshgrid(coords, coords, indexing="ij")

    def encode(pos):          # [g*g] → [g*g, dim/2]
        args = pos.reshape(-1)[:, None] * omega[None]
        return jnp.concatenate([jnp.sin(args), jnp.cos(args)], axis=-1)

    return jnp.concatenate([encode(ys), encode(xs)], axis=-1)[None]


class TimestepEmbedder(Layer):
    def __init__(self, hidden_size: int, freq_dim: int = 256):
        super().__init__()
        self.freq_dim = freq_dim
        self.fc1 = nn.Linear(freq_dim, hidden_size)
        self.fc2 = nn.Linear(hidden_size, hidden_size)

    def forward(self, t):
        h = timestep_embedding(t, self.freq_dim)
        return self.fc2(F.silu(self.fc1(h)))


class LabelEmbedder(Layer):
    """Class conditioning with a learned null class for CFG dropout."""

    def __init__(self, num_classes: int, hidden_size: int):
        super().__init__()
        self.num_classes = num_classes
        self.table = nn.Embedding(num_classes + 1, hidden_size)

    def forward(self, labels, drop_mask=None):
        if drop_mask is not None:  # 1 → replace with null class
            labels = jnp.where(drop_mask, self.num_classes, labels)
        return self.table(labels)


def modulate(x, shift, scale):
    return x * (1 + scale[:, None]) + shift[:, None]


@dataclass
class DiTConfig:
    input_size: int = 32          # latent spatial size
    patch_size: int = 2
    in_channels: int = 4
    hidden_size: int = 1152
    num_hidden_layers: int = 28
    num_attention_heads: int = 16
    mlp_ratio: float = 4.0
    num_classes: int = 1000
    learn_sigma: bool = True
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def out_channels(self) -> int:
        return self.in_channels * (2 if self.learn_sigma else 1)


def dit_tiny(**overrides) -> DiTConfig:
    base = dict(input_size=8, patch_size=2, in_channels=4, hidden_size=64,
                num_hidden_layers=2, num_attention_heads=4, num_classes=10)
    base.update(overrides)
    return DiTConfig(**base)


def dit_xl_2(**overrides) -> DiTConfig:
    return DiTConfig(**overrides)


class DiTBlock(Layer):
    """adaLN-Zero block: 6 modulation signals from the conditioning vector;
    gates initialised to zero so each block starts as identity."""

    def __init__(self, config: DiTConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        mlp = int(h * config.mlp_ratio)
        self.norm1 = nn.LayerNorm(h, epsilon=1e-6, weight_attr=False,
                                  bias_attr=False)
        self.qkv = ColumnParallelLinear(h, 3 * h, has_bias=True,
                                        gather_output=False)
        self.proj = RowParallelLinear(h, h, has_bias=True,
                                      input_is_parallel=True)
        self.norm2 = nn.LayerNorm(h, epsilon=1e-6, weight_attr=False,
                                  bias_attr=False)
        self.fc1 = ColumnParallelLinear(h, mlp, has_bias=True,
                                        gather_output=False)
        self.fc2 = RowParallelLinear(mlp, h, has_bias=True,
                                     input_is_parallel=True)
        self.ada = nn.Linear(h, 6 * h,
                             weight_attr=I.Constant(0.0),
                             bias_attr=I.Constant(0.0))

    def _attn(self, x):
        cfg = self.config
        b, s, _ = x.shape
        nh, d = cfg.num_attention_heads, cfg.head_dim
        qkv = self.qkv(x).reshape(b, s, 3, nh, d)
        out = dense_attention(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                              causal=False)
        return self.proj(out.reshape(b, s, nh * d))

    def forward(self, x, cond):
        m = self.ada(F.silu(cond))
        sh1, sc1, g1, sh2, sc2, g2 = jnp.split(m, 6, axis=-1)
        x = x + g1[:, None] * self._attn(modulate(self.norm1(x), sh1, sc1))
        h = modulate(self.norm2(x), sh2, sc2)
        x = x + g2[:, None] * self.fc2(F.gelu(self.fc1(h), approximate=True))
        return x


class DiT(Layer):
    def __init__(self, config: DiTConfig):
        super().__init__()
        self.config = config
        p, h = config.patch_size, config.hidden_size
        self.patch_embed = nn.Conv2D(config.in_channels, h, p, stride=p)
        grid = config.input_size // p
        # fixed (non-learned) sin-cos table, exactly as reference DiT
        self.pos_embed = Parameter(sincos_pos_embed_2d(grid, h),
                                   trainable=False)
        self.t_embedder = TimestepEmbedder(h)
        self.y_embedder = LabelEmbedder(config.num_classes, h)
        self.blocks = nn.LayerList(
            [DiTBlock(config) for _ in range(config.num_hidden_layers)])
        self.final_norm = nn.LayerNorm(h, epsilon=1e-6, weight_attr=False,
                                       bias_attr=False)
        self.final_ada = nn.Linear(h, 2 * h, weight_attr=I.Constant(0.0),
                                   bias_attr=I.Constant(0.0))
        self.final_proj = nn.Linear(h, p * p * config.out_channels,
                                    weight_attr=I.Constant(0.0),
                                    bias_attr=I.Constant(0.0))
        if config.dtype != jnp.float32:
            self.to(dtype=config.dtype)

    def unpatchify(self, x):
        cfg = self.config
        p, c = cfg.patch_size, cfg.out_channels
        g = cfg.input_size // p
        b = x.shape[0]
        x = x.reshape(b, g, g, p, p, c)
        x = jnp.einsum("bhwpqc->bchpwq", x)
        return x.reshape(b, c, g * p, g * p)

    def forward(self, latents, timesteps, labels, drop_mask=None):
        x = self.patch_embed(latents)
        b, c = x.shape[:2]
        x = x.reshape(b, c, -1).transpose(0, 2, 1) + \
            self.pos_embed.astype(latents.dtype)
        cond = self.t_embedder(timesteps) + self.y_embedder(labels, drop_mask)
        cond = cond.astype(x.dtype)
        for block in self.blocks:
            x = block(x, cond)
        sh, sc = jnp.split(self.final_ada(F.silu(cond)), 2, axis=-1)
        x = self.final_proj(modulate(self.final_norm(x), sh, sc))
        return self.unpatchify(x)


# --------------------------------------------------------------- SD3 MMDiT

@dataclass
class MMDiTConfig:
    input_size: int = 64
    patch_size: int = 2
    in_channels: int = 16
    hidden_size: int = 1536
    num_hidden_layers: int = 24
    num_attention_heads: int = 24
    context_dim: int = 4096        # T5/CLIP joint text embedding width
    pooled_dim: int = 2048         # pooled CLIP vector width
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def out_channels(self) -> int:
        return self.in_channels


def mmdit_tiny(**overrides) -> MMDiTConfig:
    base = dict(input_size=8, patch_size=2, in_channels=4, hidden_size=64,
                num_hidden_layers=2, num_attention_heads=4, context_dim=48,
                pooled_dim=32)
    base.update(overrides)
    return MMDiTConfig(**base)


class _StreamParams(Layer):
    """Per-stream (image or text) weights of one MMDiT joint block.
    ``attn_only`` (SD3's context_pre_only) skips the post-attention
    weights the final text stream never uses."""

    def __init__(self, h: int, n_mod: int, attn_only: bool = False):
        super().__init__()
        self.norm1 = nn.LayerNorm(h, epsilon=1e-6, weight_attr=False,
                                  bias_attr=False)
        self.qkv = nn.Linear(h, 3 * h)
        if not attn_only:
            self.proj = nn.Linear(h, h)
            self.norm2 = nn.LayerNorm(h, epsilon=1e-6, weight_attr=False,
                                      bias_attr=False)
            self.fc1 = nn.Linear(h, 4 * h)
            self.fc2 = nn.Linear(4 * h, h)
        self.ada = nn.Linear(h, n_mod * h, weight_attr=I.Constant(0.0),
                             bias_attr=I.Constant(0.0))


class MMDiTBlock(Layer):
    """Joint block: both streams project QKV with their own weights, then a
    single attention runs over the concatenation (reference: SD3
    JointTransformerBlock)."""

    def __init__(self, config: MMDiTConfig, context_last: bool = False):
        super().__init__()
        self.config = config
        self.context_last = context_last  # last block: text stream unused after attn
        self.img = _StreamParams(config.hidden_size, 6)
        self.txt = _StreamParams(config.hidden_size, 2 if context_last else 6,
                                 attn_only=context_last)

    def _qkv(self, stream: _StreamParams, x, sh, sc):
        cfg = self.config
        b, s, _ = x.shape
        h = modulate(stream.norm1(x), sh, sc)
        qkv = stream.qkv(h).reshape(b, s, 3, cfg.num_attention_heads,
                                    cfg.head_dim)
        return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

    def forward(self, x_img, x_txt, cond):
        cfg = self.config
        s_txt = x_txt.shape[1]
        mi = self.img.ada(F.silu(cond))
        i_sh1, i_sc1, i_g1, i_sh2, i_sc2, i_g2 = jnp.split(mi, 6, axis=-1)
        mt = self.txt.ada(F.silu(cond))
        if self.context_last:
            t_sh1, t_sc1 = jnp.split(mt, 2, axis=-1)
        else:
            t_sh1, t_sc1, t_g1, t_sh2, t_sc2, t_g2 = jnp.split(mt, 6, axis=-1)

        qi, ki, vi = self._qkv(self.img, x_img, i_sh1, i_sc1)
        qt, kt, vt = self._qkv(self.txt, x_txt, t_sh1, t_sc1)
        q = jnp.concatenate([qt, qi], axis=1)
        k = jnp.concatenate([kt, ki], axis=1)
        v = jnp.concatenate([vt, vi], axis=1)
        out = dense_attention(q, k, v, causal=False)
        b = out.shape[0]
        out = out.reshape(b, out.shape[1], -1)
        a_txt, a_img = out[:, :s_txt], out[:, s_txt:]

        x_img = x_img + i_g1[:, None] * self.img.proj(a_img)
        h = modulate(self.img.norm2(x_img), i_sh2, i_sc2)
        x_img = x_img + i_g2[:, None] * self.img.fc2(
            F.gelu(self.img.fc1(h), approximate=True))

        if self.context_last:
            return x_img, x_txt
        x_txt = x_txt + t_g1[:, None] * self.txt.proj(a_txt)
        h = modulate(self.txt.norm2(x_txt), t_sh2, t_sc2)
        x_txt = x_txt + t_g2[:, None] * self.txt.fc2(
            F.gelu(self.txt.fc1(h), approximate=True))
        return x_img, x_txt


class MMDiT(Layer):
    """SD3 core: conditioned on timestep + pooled text; the sequence text
    embedding rides along as the second stream."""

    def __init__(self, config: MMDiTConfig):
        super().__init__()
        self.config = config
        p, h = config.patch_size, config.hidden_size
        self.patch_embed = nn.Conv2D(config.in_channels, h, p, stride=p)
        grid = config.input_size // p
        self.pos_embed = Parameter(sincos_pos_embed_2d(grid, h),
                                   trainable=False)
        self.t_embedder = TimestepEmbedder(h)
        self.pooled_proj = nn.Sequential(
            nn.Linear(config.pooled_dim, h), nn.SiLU(), nn.Linear(h, h))
        self.context_proj = nn.Linear(config.context_dim, h)
        self.blocks = nn.LayerList(
            [MMDiTBlock(config,
                        context_last=(i == config.num_hidden_layers - 1))
             for i in range(config.num_hidden_layers)])
        self.final_norm = nn.LayerNorm(h, epsilon=1e-6, weight_attr=False,
                                       bias_attr=False)
        self.final_ada = nn.Linear(h, 2 * h, weight_attr=I.Constant(0.0),
                                   bias_attr=I.Constant(0.0))
        self.final_proj = nn.Linear(h, p * p * config.out_channels,
                                    weight_attr=I.Constant(0.0),
                                    bias_attr=I.Constant(0.0))
        if config.dtype != jnp.float32:
            self.to(dtype=config.dtype)

    def forward(self, latents, timesteps, context, pooled):
        cfg = self.config
        x = self.patch_embed(latents)
        b, c = x.shape[:2]
        x = x.reshape(b, c, -1).transpose(0, 2, 1) + \
            self.pos_embed.astype(latents.dtype)
        cond = self.t_embedder(timesteps) + \
            self.pooled_proj(pooled.astype(jnp.float32))
        cond = cond.astype(x.dtype)
        txt = self.context_proj(context).astype(x.dtype)
        for block in self.blocks:
            x, txt = block(x, txt, cond)
        sh, sc = jnp.split(self.final_ada(F.silu(cond)), 2, axis=-1)
        x = self.final_proj(modulate(self.final_norm(x), sh, sc))
        p = cfg.patch_size
        g = cfg.input_size // p
        x = x.reshape(b, g, g, p, p, cfg.out_channels)
        x = jnp.einsum("bhwpqc->bchpwq", x)
        return x.reshape(b, cfg.out_channels, g * p, g * p)
