"""paddle_tpu.utils."""
from . import compile_cache, faults, observability, rng
from .faults import retry_with_backoff
from .rng import fold_axis, next_key, rng_state, seed
