"""paddle.distribution parity (reference: python/paddle/distribution/ —
Distribution ABC, Normal/Uniform/Categorical/Bernoulli/Beta/Dirichlet/
Gamma/Exponential/Laplace/LogNormal, TransformedDistribution,
kl_divergence registry).

TPU-native: sampling goes through explicit jax PRNG keys (pass ``key=``;
falls back to the framework seed-tree stream so eager use stays
paddle-shaped), log_prob/entropy are pure jnp — everything jit/vmap/grad
composable.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

__all__ = [
    "Distribution", "Normal", "LogNormal", "Uniform", "Categorical",
    "Bernoulli", "Beta", "Dirichlet", "Gamma", "Exponential", "Laplace",
    "kl_divergence", "register_kl",
    "Gumbel", "Cauchy", "Geometric", "Poisson", "Binomial", "Multinomial",
    "MultivariateNormal", "Chi2", "StudentT", "Transform",
    "AffineTransform", "AbsTransform", "ExpTransform", "SigmoidTransform",
    "TransformedDistribution", "Independent", "ContinuousBernoulli",
]


def _key(key):
    if key is not None:
        return key
    from .utils.rng import next_key
    return next_key()


class Distribution:
    def sample(self, shape=(), key=None):
        raise NotImplementedError

    def rsample(self, shape=(), key=None):
        """Reparameterized sample (differentiable where defined)."""
        return self.sample(shape, key=key)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return jnp.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc)
        self.scale = jnp.asarray(scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale ** 2

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        eps = jax.random.normal(_key(key), shape, self.loc.dtype
                                if self.loc.dtype != jnp.int32 else jnp.float32)
        return self.loc + self.scale * eps

    rsample = sample

    def log_prob(self, value):
        var = self.scale ** 2
        return (-((value - self.loc) ** 2) / (2 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)

    def cdf(self, value):
        return 0.5 * (1 + jax.scipy.special.erf(
            (value - self.loc) / (self.scale * math.sqrt(2))))


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.base = Normal(loc, scale)

    @property
    def mean(self):
        return jnp.exp(self.base.loc + self.base.scale ** 2 / 2)

    def sample(self, shape=(), key=None):
        return jnp.exp(self.base.sample(shape, key=key))

    rsample = sample

    def log_prob(self, value):
        return self.base.log_prob(jnp.log(value)) - jnp.log(value)

    def entropy(self):
        return self.base.entropy() + self.base.loc


class Uniform(Distribution):
    def __init__(self, low, high):
        self.low = jnp.asarray(low, jnp.float32)
        self.high = jnp.asarray(high, jnp.float32)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                    self.high.shape)
        u = jax.random.uniform(_key(key), shape)
        return self.low + (self.high - self.low) * u

    rsample = sample

    def log_prob(self, value):
        inside = (value >= self.low) & (value < self.high)
        return jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)

    def entropy(self):
        return jnp.log(self.high - self.low)


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None):
        if (logits is None) == (probs is None):
            raise ValueError("pass exactly one of logits/probs")
        self.logits = (jnp.asarray(logits) if logits is not None
                       else jnp.log(jnp.asarray(probs)))

    @property
    def probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    def sample(self, shape=(), key=None):
        return jax.random.categorical(_key(key), self.logits,
                                      shape=tuple(shape) + self.logits.shape[:-1])

    def log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return jnp.take_along_axis(
            logp, value[..., None].astype(jnp.int32), axis=-1)[..., 0]

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


class Bernoulli(Distribution):
    def __init__(self, probs):
        self.probs = jnp.asarray(probs)

    @property
    def mean(self):
        return self.probs

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + self.probs.shape
        return jax.random.bernoulli(_key(key), self.probs, shape
                                    ).astype(jnp.float32)

    def log_prob(self, value):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return value * jnp.log(p) + (1 - value) * jnp.log1p(-p)

    def entropy(self):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = jnp.asarray(alpha, jnp.float32)
        self.beta = jnp.asarray(beta, jnp.float32)

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.alpha.shape,
                                                    self.beta.shape)
        return jax.random.beta(_key(key), self.alpha, self.beta, shape)

    rsample = sample

    def log_prob(self, value):
        return ((self.alpha - 1) * jnp.log(value)
                + (self.beta - 1) * jnp.log1p(-value)
                - jsp.betaln(self.alpha, self.beta))

    def entropy(self):
        a, b = self.alpha, self.beta
        return (jsp.betaln(a, b) - (a - 1) * jsp.digamma(a)
                - (b - 1) * jsp.digamma(b)
                + (a + b - 2) * jsp.digamma(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = jnp.asarray(concentration, jnp.float32)

    @property
    def mean(self):
        c = self.concentration
        return c / jnp.sum(c, axis=-1, keepdims=True)

    def sample(self, shape=(), key=None):
        return jax.random.dirichlet(_key(key), self.concentration,
                                    tuple(shape) + self.concentration.shape[:-1])

    rsample = sample

    def log_prob(self, value):
        c = self.concentration
        norm = (jnp.sum(jsp.gammaln(c), axis=-1)
                - jsp.gammaln(jnp.sum(c, axis=-1)))
        return jnp.sum((c - 1) * jnp.log(value), axis=-1) - norm


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = jnp.asarray(concentration, jnp.float32)
        self.rate = jnp.asarray(rate, jnp.float32)

    @property
    def mean(self):
        return self.concentration / self.rate

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.concentration.shape, self.rate.shape)
        return jax.random.gamma(_key(key), self.concentration, shape) / self.rate

    rsample = sample

    def log_prob(self, value):
        c, r = self.concentration, self.rate
        return (c * jnp.log(r) + (c - 1) * jnp.log(value) - r * value
                - jsp.gammaln(c))

    def entropy(self):
        c, r = self.concentration, self.rate
        return c - jnp.log(r) + jsp.gammaln(c) + (1 - c) * jsp.digamma(c)


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = jnp.asarray(rate, jnp.float32)

    @property
    def mean(self):
        return 1.0 / self.rate

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + self.rate.shape
        return jax.random.exponential(_key(key), shape) / self.rate

    rsample = sample

    def log_prob(self, value):
        return jnp.log(self.rate) - self.rate * value

    def entropy(self):
        return 1.0 - jnp.log(self.rate)


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    @property
    def mean(self):
        return self.loc

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        return self.loc + self.scale * jax.random.laplace(_key(key), shape)

    rsample = sample

    def log_prob(self, value):
        return (-jnp.abs(value - self.loc) / self.scale
                - jnp.log(2 * self.scale))

    def entropy(self):
        return 1.0 + jnp.log(2 * self.scale)


# --------------------------------------------------------------------- KL
_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    logp = jax.nn.log_softmax(p.logits, axis=-1)
    logq = jax.nn.log_softmax(q.logits, axis=-1)
    return jnp.sum(jnp.exp(logp) * (logp - logq), axis=-1)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    pp = jnp.clip(p.probs, 1e-7, 1 - 1e-7)
    qq = jnp.clip(q.probs, 1e-7, 1 - 1e-7)
    return (pp * (jnp.log(pp) - jnp.log(qq))
            + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return jnp.log((q.high - q.low) / (p.high - p.low))


# ---------------------------------------------------------------- round 4
# (reference: python/paddle/distribution/* — the remaining families,
# transforms, and composition wrappers)

class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    @property
    def mean(self):
        return self.loc + self.scale * 0.5772156649015329  # Euler gamma

    @property
    def variance(self):
        return (math.pi ** 2 / 6) * self.scale ** 2

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        return self.loc + self.scale * jax.random.gumbel(_key(key), shape)

    rsample = sample

    def log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -(z + jnp.exp(-z)) - jnp.log(self.scale)

    def entropy(self):
        return jnp.log(self.scale) + 1.0 + 0.5772156649015329

    def cdf(self, value):
        return jnp.exp(-jnp.exp(-(value - self.loc) / self.scale))


class Cauchy(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        return self.loc + self.scale * jax.random.cauchy(_key(key), shape)

    rsample = sample

    def log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -jnp.log(math.pi * self.scale * (1 + z ** 2))

    def entropy(self):
        return jnp.log(4 * math.pi * self.scale)

    def cdf(self, value):
        return jnp.arctan((value - self.loc) / self.scale) / math.pi + 0.5


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k >= 0 failures before the first success."""

    def __init__(self, probs):
        self.probs = jnp.asarray(probs, jnp.float32)

    @property
    def mean(self):
        return (1 - self.probs) / self.probs

    @property
    def variance(self):
        return (1 - self.probs) / self.probs ** 2

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + self.probs.shape
        u = jax.random.uniform(_key(key), shape, minval=1e-7, maxval=1.0)
        return jnp.floor(jnp.log(u) / jnp.log1p(-self.probs))

    def log_prob(self, value):
        return value * jnp.log1p(-self.probs) + jnp.log(self.probs)

    def entropy(self):
        p = self.probs
        return (-(1 - p) * jnp.log1p(-p) - p * jnp.log(p)) / p


class Poisson(Distribution):
    def __init__(self, rate):
        self.rate = jnp.asarray(rate, jnp.float32)

    mean = property(lambda self: self.rate)
    variance = property(lambda self: self.rate)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + self.rate.shape
        return jax.random.poisson(_key(key), self.rate,
                                  shape).astype(jnp.float32)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        return value * jnp.log(self.rate) - self.rate \
            - gammaln(value + 1.0)


class Binomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = jnp.asarray(total_count, jnp.float32)
        self.probs = jnp.asarray(probs, jnp.float32)

    mean = property(lambda self: self.total_count * self.probs)
    variance = property(
        lambda self: self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.total_count.shape, self.probs.shape)
        return jax.random.binomial(_key(key), self.total_count,
                                   self.probs, shape)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        n, p = self.total_count, jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        comb = gammaln(n + 1) - gammaln(value + 1) - gammaln(n - value + 1)
        return comb + value * jnp.log(p) + (n - value) * jnp.log1p(-p)


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs = jnp.asarray(probs, jnp.float32)

    @property
    def mean(self):
        return self.total_count * self.probs

    def sample(self, shape=(), key=None):
        # batched probs [*B, K] follow torch/paddle semantics: result is
        # shape + B + (K,). The draw axis (total_count) sits between the
        # requested shape and the batch dims so each batch lane samples
        # from its own categorical before the one-hot count collapse.
        shape = tuple(shape)
        batch = self.probs.shape[:-1]
        cat = jax.random.categorical(
            _key(key), jnp.log(self.probs),
            shape=shape + (self.total_count,) + batch)
        return jax.nn.one_hot(cat, self.probs.shape[-1]).sum(
            axis=len(shape))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        p = jnp.clip(self.probs, 1e-12, 1.0)
        return gammaln(jnp.asarray(self.total_count + 1.0)) \
            - jnp.sum(gammaln(value + 1.0), axis=-1) \
            + jnp.sum(value * jnp.log(p), axis=-1)


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, scale_tril=None):
        self.loc = jnp.asarray(loc, jnp.float32)
        if scale_tril is None:
            if covariance_matrix is None:
                raise ValueError("need covariance_matrix or scale_tril")
            scale_tril = jnp.linalg.cholesky(
                jnp.asarray(covariance_matrix, jnp.float32))
        self.scale_tril = jnp.asarray(scale_tril, jnp.float32)

    mean = property(lambda self: self.loc)

    @property
    def covariance_matrix(self):
        return self.scale_tril @ jnp.swapaxes(self.scale_tril, -1, -2)

    def sample(self, shape=(), key=None):
        d = self.loc.shape[-1]
        shape = tuple(shape) + self.loc.shape
        eps = jax.random.normal(_key(key), shape)
        return self.loc + jnp.einsum("...ij,...j->...i",
                                     self.scale_tril, eps)

    rsample = sample

    def log_prob(self, value):
        d = self.loc.shape[-1]
        diff = value - self.loc
        sol = jax.scipy.linalg.solve_triangular(self.scale_tril, diff[..., None],
                                                lower=True)[..., 0]
        logdet = jnp.sum(jnp.log(jnp.diagonal(self.scale_tril, axis1=-2,
                                              axis2=-1)), axis=-1)
        return -0.5 * jnp.sum(sol ** 2, axis=-1) - logdet \
            - 0.5 * d * math.log(2 * math.pi)

    def entropy(self):
        d = self.loc.shape[-1]
        logdet = jnp.sum(jnp.log(jnp.diagonal(self.scale_tril, axis1=-2,
                                              axis2=-1)), axis=-1)
        return 0.5 * d * (1 + math.log(2 * math.pi)) + logdet


class Chi2(Distribution):
    def __init__(self, df):
        self.df = jnp.asarray(df, jnp.float32)
        self._gamma = Gamma(self.df / 2.0, 0.5)

    mean = property(lambda self: self.df)
    variance = property(lambda self: 2.0 * self.df)

    def sample(self, shape=(), key=None):
        return self._gamma.sample(shape, key)

    def log_prob(self, value):
        return self._gamma.log_prob(value)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0):
        self.df = jnp.asarray(df, jnp.float32)
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    @property
    def mean(self):
        return jnp.where(self.df > 1, self.loc, jnp.nan)

    @property
    def variance(self):
        return jnp.where(self.df > 2, self.scale ** 2 * self.df
                         / (self.df - 2), jnp.nan)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.df.shape, self.loc.shape, self.scale.shape)
        return self.loc + self.scale * jax.random.t(_key(key), self.df,
                                                    shape)

    rsample = sample

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        z = (value - self.loc) / self.scale
        d = self.df
        return gammaln((d + 1) / 2) - gammaln(d / 2) \
            - 0.5 * jnp.log(d * math.pi) - jnp.log(self.scale) \
            - (d + 1) / 2 * jnp.log1p(z ** 2 / d)


# ------------------------------------------------------------- transforms

class Transform:
    """Bijector base (reference: paddle.distribution.Transform)."""

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(y))

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def forward(self, x):
        return self.loc + self.scale * x

    def inverse(self, y):
        return (y - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class AbsTransform(Transform):
    """y = |x| (not bijective: inverse returns the positive branch)."""

    def forward(self, x):
        return jnp.abs(x)

    def inverse(self, y):
        return y

    def forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class ExpTransform(Transform):
    def forward(self, x):
        return jnp.exp(x)

    def inverse(self, y):
        return jnp.log(y)

    def forward_log_det_jacobian(self, x):
        return x


class SigmoidTransform(Transform):
    def forward(self, x):
        return jax.nn.sigmoid(x)

    def inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TransformedDistribution(Distribution):
    """base distribution pushed through a chain of transforms."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)

    def sample(self, shape=(), key=None):
        x = self.base.sample(shape, key)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        lp = jnp.zeros_like(jnp.asarray(value, jnp.float32))
        y = value
        for t in reversed(self.transforms):
            x = t.inverse(y)
            lp = lp - t.forward_log_det_jacobian(x)
            y = x
        return lp + self.base.log_prob(y)


class Independent(Distribution):
    """Reinterpret the rightmost batch dims as event dims (sums
    log_prob over them)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)

    def sample(self, shape=(), key=None):
        return self.base.sample(shape, key)

    rsample = sample

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        return jnp.sum(lp, axis=tuple(range(-self.rank, 0)))

    def entropy(self):
        ent = self.base.entropy()
        return jnp.sum(ent, axis=tuple(range(-self.rank, 0)))


class ContinuousBernoulli(Distribution):
    """reference: paddle.distribution.ContinuousBernoulli (Loaiza-
    Ganem & Cunningham 2019)."""

    def __init__(self, probs):
        self.probs = jnp.clip(jnp.asarray(probs, jnp.float32), 1e-6,
                              1 - 1e-6)

    def _log_norm(self):
        p = self.probs
        near_half = jnp.abs(p - 0.5) < 1e-3
        safe = jnp.where(near_half, 0.25, p)
        c = jnp.log(jnp.abs(2.0 * jnp.arctanh(1.0 - 2.0 * safe))) \
            - jnp.log(jnp.abs(1.0 - 2.0 * safe))
        return jnp.where(near_half, math.log(2.0), c)

    def log_prob(self, value):
        p = self.probs
        return value * jnp.log(p) + (1 - value) * jnp.log1p(-p) \
            + self._log_norm()

    def sample(self, shape=(), key=None):
        # inverse-CDF sampling
        shape = tuple(shape) + self.probs.shape
        u = jax.random.uniform(_key(key), shape, minval=1e-6,
                               maxval=1 - 1e-6)
        p = self.probs
        near_half = jnp.abs(p - 0.5) < 1e-3
        safe = jnp.where(near_half, 0.25, p)
        x = (jnp.log1p(u * (2 * safe - 1) / (1 - safe))
             / (jnp.log(safe) - jnp.log1p(-safe)))
        return jnp.where(near_half, u, x)


@register_kl(Gumbel, Gumbel)
def _kl_gumbel(p, q):
    # Monte-Carlo-free closed form exists only for equal scales; use the
    # standard cross-entropy expansion
    g = 0.5772156649015329
    return (jnp.log(q.scale) - jnp.log(p.scale)
            + g * (p.scale / q.scale - 1.0)
            + jnp.expm1((q.loc - p.loc) / q.scale
                        + jax.scipy.special.gammaln(
                            1.0 + p.scale / q.scale))
            - (q.loc - p.loc) / q.scale)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    d = p.loc.shape[-1]
    qinv = jax.scipy.linalg.solve_triangular(
        q.scale_tril, jnp.broadcast_to(jnp.eye(d), q.scale_tril.shape),
        lower=True)
    m = qinv @ p.scale_tril
    tr = jnp.sum(m ** 2, axis=(-2, -1))
    diff = q.loc - p.loc
    maha = jnp.sum((qinv @ diff[..., None])[..., 0] ** 2, axis=-1)
    logdet = (jnp.sum(jnp.log(jnp.diagonal(q.scale_tril, axis1=-2,
                                           axis2=-1)), axis=-1)
              - jnp.sum(jnp.log(jnp.diagonal(p.scale_tril, axis1=-2,
                                             axis2=-1)), axis=-1))
    return 0.5 * (tr + maha - d) + logdet
