"""Replica supervision for the serving fleet (ISSUE 12 tentpole;
reference: the supervisor/health-check loops production LLM fleets run
in front of continuous-batching replicas — k8s liveness probes +
envoy-style outlier ejection, restated in-process over the gateway's
replica workers).

Before this module, a replica failure was terminal three different
ways: a tick-thread crash ran ``_fail_all`` and errored every live
stream, a hung fused dispatch hung every client on that replica
forever (nothing watched the tick thread), and the router's health
eviction had no rejoin path — the fleet only ever shrank. The
supervisor closes all three:

- **Watchdog** — a daemon thread polls every replica worker. A dead
  tick thread (crash, or the ``replica_drop`` fault site's silent
  exit) is detected by ``Thread.is_alive``; a STUCK dispatch is
  detected by a deadline on the worker's dispatch-to-drain latency
  (``t_busy`` is set before the engine step — which, in ring mode,
  includes draining the previous dispatch — and cleared after the
  token dispatch; busy longer than ``dispatch_timeout_s`` fires the
  watchdog). Either way the replica is marked unhealthy, ABANDONED
  (the old thread, if it ever wakes, checks the flag and exits without
  touching shared state), its live requests are handed to the
  gateway's failover path (``Gateway._failover_worker`` — resubmit as
  ``prompt + committed tokens`` on a surviving replica), and its
  engine is rebuilt.

- **Rebuild** — ``engine_factory`` (when the gateway was given one)
  constructs a FRESH engine; otherwise ``PagedEngine.hard_reset()``
  rebuilds the existing engine's pools/mirrors in place (fresh device
  arrays — the dead program may still own the old ones; compiled
  executables survive). A new tick thread takes over the replica name,
  scheduler, trace ring and metric labels.

- **Circuit breaker** — the rebuilt replica does NOT rejoin rotation
  directly. Its :class:`CircuitBreaker` opened on the failure
  (exponential backoff, doubling per consecutive failure); after the
  backoff it goes HALF-OPEN, and the router diverts exactly ONE live
  request at a time to it as a probation probe. ``probes_to_close``
  probe successes close the breaker and the replica re-enters the
  warm -> sticky -> least-loaded ladder; a probe failure re-opens it
  with a longer backoff. Permanent eviction is gone — a replica that
  keeps failing just probes ever more rarely.

Everything here is host-side bookkeeping on its own thread; the hot
serving path gains one timestamp write per tick.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from ..utils import observability as obs

__all__ = ["CircuitBreaker", "ReplicaSupervisor"]

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"

# /debugz + gauge encoding of the state machine (docs/SERVING.md)
_STATE_CODE = {BREAKER_CLOSED: 0, BREAKER_OPEN: 1, BREAKER_HALF_OPEN: 2}


class CircuitBreaker:
    """Half-open circuit breaker gating one replica's traffic.

    closed --failure--> open --(backoff elapses, next route)-->
    half_open --probe success x probes_to_close--> closed
              --probe failure--> open (backoff doubled)

    ``failure_threshold`` consecutive failures open the breaker
    (default 1: a replica crash is conclusive on its own). The backoff
    before the first probe is ``backoff_s * factor**(opens-1)`` capped
    at ``backoff_max_s``. While HALF-OPEN, ``try_probe`` hands out AT
    MOST ONE in-flight probe at a time — the router calls it, and the
    request's terminal path reports ``probe_done``.

    Thread contract: called from the router (asyncio thread), the
    replica tick threads and the supervisor; one internal lock.
    ``clock`` is injectable for deterministic unit tests."""

    def __init__(self, failure_threshold: int = 1,
                 probes_to_close: int = 1,
                 backoff_s: float = 1.0, backoff_factor: float = 2.0,
                 backoff_max_s: float = 30.0,
                 on_state: Optional[Callable[[str], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = max(int(failure_threshold), 1)
        self.probes_to_close = max(int(probes_to_close), 1)
        self.backoff_s = float(backoff_s)
        self.backoff_factor = float(backoff_factor)
        self.backoff_max_s = float(backoff_max_s)
        self._on_state = on_state
        self._clock = clock
        self._lock = threading.Lock()
        self.state = BREAKER_CLOSED
        self._consecutive = 0      # consecutive failures while closed
        self._opens = 0            # total opens (drives the backoff)
        self._probe_ok = 0         # successes this half-open episode
        self._probe_inflight = False
        self._reopen_at = 0.0

    # ----------------------------------------------------------- internals
    def _set(self, state: str):
        if state == self.state:
            return
        self.state = state
        if self._on_state is not None:
            try:
                self._on_state(state)
            except Exception:
                pass   # a callback must never wedge the state machine

    def _open_locked(self):
        self._opens += 1
        self._probe_ok = 0
        self._probe_inflight = False
        back = min(self.backoff_s
                   * self.backoff_factor ** (self._opens - 1),
                   self.backoff_max_s)
        self._reopen_at = self._clock() + back
        self._set(BREAKER_OPEN)

    # -------------------------------------------------------------- events
    def record_failure(self):
        """A replica-level failure (crash / hang / probe failure)."""
        with self._lock:
            self._consecutive += 1
            if self.state == BREAKER_HALF_OPEN \
                    or self._consecutive >= self.failure_threshold:
                self._open_locked()

    def record_success(self):
        """A non-probe success while closed: clears the consecutive-
        failure count (a threshold > 1 needs uninterrupted failures)."""
        with self._lock:
            if self.state == BREAKER_CLOSED:
                self._consecutive = 0

    def try_probe(self) -> bool:
        """Router hook: True iff THIS request should be the probation
        probe (open + backoff elapsed promotes to half-open first;
        half-open with no probe in flight claims the slot)."""
        with self._lock:
            if self.state == BREAKER_OPEN \
                    and self._clock() >= self._reopen_at:
                self._set(BREAKER_HALF_OPEN)
            if self.state != BREAKER_HALF_OPEN or self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def rearm(self):
        """Restart the open-state backoff clock. The supervisor calls
        this when a rebuilt replica actually becomes READY: the
        probation window must not open while the engine is still being
        rebuilt/warmed, or every probe in that gap burns a request
        against a dead worker. A half-open breaker whose probe slot is
        free drops back to open; an in-flight probe is left alone."""
        with self._lock:
            if self.state == BREAKER_HALF_OPEN \
                    and not self._probe_inflight:
                self._set(BREAKER_OPEN)
            if self.state == BREAKER_OPEN:
                back = min(self.backoff_s * self.backoff_factor
                           ** max(self._opens - 1, 0),
                           self.backoff_max_s)
                self._reopen_at = max(self._reopen_at,
                                      self._clock() + back)

    def probe_done(self, success: Optional[bool]):
        """Terminal report for an in-flight probe. ``True`` counts
        toward closing, ``False`` re-opens (longer backoff), ``None``
        (client disconnect / deadline — proves nothing either way)
        just releases the probe slot."""
        with self._lock:
            if not self._probe_inflight:
                return
            self._probe_inflight = False
            if self.state != BREAKER_HALF_OPEN:
                return
            if success is True:
                self._probe_ok += 1
                if self._probe_ok >= self.probes_to_close:
                    self._consecutive = 0
                    self._opens = 0
                    self._probe_ok = 0
                    self._set(BREAKER_CLOSED)
            elif success is False:
                self._open_locked()

    # ------------------------------------------------------------- exports
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self.state,
                "state_code": _STATE_CODE[self.state],
                "opens": self._opens,
                "consecutive_failures": self._consecutive,
                "probe_inflight": self._probe_inflight,
                "probe_successes": self._probe_ok,
                "reopen_in_s": round(
                    max(self._reopen_at - self._clock(), 0.0), 3)
                if self.state == BREAKER_OPEN else 0.0,
            }


class ReplicaSupervisor(threading.Thread):
    """Per-gateway watchdog/restart loop (one daemon thread for the
    whole fleet; per-replica state lives on the workers/breakers).

    The supervisor is intentionally the ONLY writer of replica
    replacement: the tick threads detect their own crashes (and run
    the failover hand-off inline, so requests move the moment the
    exception surfaces), but rebuild + rejoin always happen here —
    one thread, no racing restarts."""

    def __init__(self, gateway, check_interval_s: float = 0.05,
                 dispatch_timeout_s: float = 30.0):
        super().__init__(daemon=True,
                         name=f"supervisor-{gateway.name}")
        self.gw = gateway
        self.check_interval_s = float(check_interval_s)
        self.dispatch_timeout_s = float(dispatch_timeout_s)
        self._halt = threading.Event()
        reg = obs.registry()
        self._c_watchdog = reg.counter("gateway_watchdog_fires_total",
                                       **gateway._labels)
        self._g_breaker: Dict[str, Any] = {}

    def stop(self, timeout: float = 5.0):
        self._halt.set()
        if self.is_alive():
            self.join(timeout)

    # ------------------------------------------------------------ the loop
    def run(self):
        while not self._halt.wait(self.check_interval_s):
            try:
                self._check_once()
            except Exception as e:   # supervision must outlive any bug
                obs.record_event("supervisor_error",
                                 gateway=self.gw.name, err=repr(e))

    def _check_once(self):
        now = time.monotonic()
        for w in list(self.gw._workers):
            if w.draining:
                continue
            if w.failed:
                # already failed over (the crash path runs
                # _failover_worker on the dying tick thread) but still
                # in _workers: the rebuild is ours. A rebuilt worker
                # replaces this entry; ``rebuild_failed`` latches the
                # permanent-eviction path so a raising factory is not
                # retried every pass.
                self._spawn_rebuild(w, w.fail_reason or "crash")
                continue
            if w.abandoned:
                continue           # defensive: failed should be set too
            started = w.ident is not None
            if started and not w.is_alive():
                # dead tick thread WITHOUT the failed latch: a
                # replica_drop-style silent exit — nothing on the dying
                # thread ran, so failover is ours too
                self.gw._failover_worker(w, reason="drop")
                self._spawn_rebuild(w, "drop")
                continue
            t_busy = w.t_busy
            # a cold engine's FIRST dispatch pays the executable
            # build/deserialize: 10x grace until one dispatch lands
            limit = self.dispatch_timeout_s * (1.0 if w.warmed
                                               else 10.0)
            if t_busy is not None and now - t_busy > limit:
                # stuck dispatch: the thread has been inside one
                # step/drain longer than the deadline
                self._c_watchdog.inc()
                obs.record_event("gateway_watchdog_fire",
                                 gateway=self.gw.name,
                                 replica=w.replica.name,
                                 stuck_s=round(now - t_busy, 3))
                self.gw._failover_worker(
                    w, reason="hang",
                    stuck_ms=round((now - t_busy) * 1e3, 1))
                self._spawn_rebuild(w, "hang")
        self._export_breaker_gauges()

    def _spawn_rebuild(self, worker, reason: str):
        """Run the (possibly expensive — engine_factory may compile)
        rebuild OFF the detection loop: failover hand-off is the
        latency-critical half and already happened; a slow rebuild of
        one replica must not delay watchdog detection for the others.
        One rebuild per worker at a time (``rebuilding`` latch)."""
        if worker.rebuild_failed or worker.rebuilding:
            return
        if self.gw._engine_factory is None and worker.is_alive():
            # the in-place reset must wait for the thread to die —
            # spawning a thread per pass just to discover that would
            # churn dozens of threads/second during a long hang
            return
        worker.rebuilding = True
        threading.Thread(
            target=self._rebuild, args=(worker, reason), daemon=True,
            name=f"rebuild-{self.gw.name}-{worker.replica.name}"
        ).start()

    # ------------------------------------------------------------- rebuild
    def _rebuild(self, worker, reason: str):
        """Replace ``worker`` with a fresh tick thread over a rebuilt
        engine; the breaker (already OPEN from the failover hand-off)
        gates its rejoin.

        A hung worker whose thread is STILL ALIVE gets an in-place
        ``hard_reset`` only once the thread has actually died: a
        slow-but-not-wedged step could otherwise return AFTER the
        reset and clobber the replacement's state dict/pools with its
        own. The injected ``dispatch_hang`` wakes and exits via the
        abandoned guard, so deferral is brief; a truly wedged dispatch
        keeps the replica evicted until an ``engine_factory`` can give
        the replacement an isolated engine. (With a factory, a
        replacement SHARING the old model object still serializes on
        the hung thread's per-model tick lock — safe, but it rejoins
        only when the hang clears; give replicas distinct model
        instances, as the chaos loadgen does, for full isolation.)"""
        gw = self.gw
        replica = worker.replica
        if gw._draining:
            worker.rebuilding = False
            return          # a draining fleet never rebuilds (an
                            # in-flight rebuild thread can outlive
                            # supervisor.stop())
        if gw._engine_factory is None and worker.is_alive():
            worker.rebuilding = False
            return          # retried next pass until the thread dies
        obs.registry().counter("replica_restarts_total",
                               reason=reason, **gw._labels).inc()
        if gw._spill_arena is not None and reason != "hang":
            # the dying engine's device pools still live in THIS
            # process: salvage its parked and live spans into the
            # host arena before the factory/hard_reset discards them
            # — the rebuilt worker (or a /kvz peer fetch, ISSUE 18)
            # restores instead of re-prefilling. A hung worker is
            # skipped: its wedged thread may still be touching the
            # pools mid-step.
            try:
                if hasattr(worker.engine, "spill_parked"):
                    worker.engine.spill_parked()
                if hasattr(worker.engine, "spill_live"):
                    worker.engine.spill_live()
            except Exception:
                pass        # salvage only costs warmth, never safety
        try:
            if gw._engine_factory is not None:
                engine = gw._engine_factory()
            else:
                # rebuild in place: fresh pools/mirrors on the same
                # engine object (safe — the old thread is DEAD, gated
                # above)
                engine = worker.engine
                engine.hard_reset()
        except Exception as e:
            obs.record_event("gateway_rebuild_failed",
                             gateway=gw.name, replica=replica.name,
                             err=repr(e))
            # breaker stays open and the latch below stops retries: a
            # failed rebuild evicts the replica permanently (the
            # pre-supervisor behavior)
            worker.rebuild_failed = True
            return
        replica.engine = engine
        new_w = gw._make_worker(replica, sched=worker.sched,
                                ring=worker.ring)
        with gw._fo_lock:
            if gw._draining:
                # drain began while the factory ran: never swap a
                # fresh non-draining worker into a draining fleet
                worker.rebuilding = False
                return
            new_w.draining = gw._draining
            idx = gw._workers.index(worker)
            gw._workers[idx] = new_w
            gw._by_replica[replica] = new_w
        new_w.start()
        b = getattr(replica, "breaker", None)
        if b is not None:
            # probation starts NOW that the replica is ready, not when
            # the failure happened — a rebuild slower than the backoff
            # must not leak probes onto a dead worker
            b.rearm()
        obs.record_event("gateway_replica_restart", gateway=gw.name,
                         replica=replica.name, reason=reason)

    def _export_breaker_gauges(self):
        """``gateway_breaker_state`` gauge per replica (0 closed /
        1 open / 2 half-open) — the scrapeable face of /debugz's
        breaker section."""
        reg = obs.registry()
        for w in list(self.gw._workers):
            b = getattr(w.replica, "breaker", None)
            if b is None:
                continue
            g = self._g_breaker.get(w.replica.name)
            if g is None:
                g = reg.gauge("gateway_breaker_state",
                              replica=w.replica.name,
                              **self.gw._labels)
                self._g_breaker[w.replica.name] = g
            g.set(_STATE_CODE[b.state])
