#!/usr/bin/env python
"""Tier-budget marker audit (ISSUE 6 satellite; sibling of
``fault_sites.py --check``).

The tier-1 verify runs ``pytest -m 'not slow'`` against a hard 870s
wall clock that currently has only ~duration-of-one-sweep headroom, so
a single dropped ``@pytest.mark.slow`` on a bench or sweep test can
blow the whole budget. ``--check`` collects the suite twice with
``pytest --collect-only`` (once ``-m slow``, once ``-m 'not slow'``)
and fails if:

- any MUST_BE_SLOW pattern (wall-clock benches, sweep-style parity
  matrices, multi-subprocess e2e) matches a test in the tier-1
  collection, or
- a pattern matches nothing at all (stale policy entry — the test was
  renamed or deleted and the guard is no longer guarding anything).

``--budget-log LOG`` (ISSUE 11 satellite) additionally parses a pytest
``--durations=N`` report out of LOG (e.g. the tier-1 verify's tee'd
output) and fails if any single tier-1 test exceeded its declared
wall-clock budget: ``DEFAULT_BUDGET_S`` for everything, with explicit
(pattern, seconds) rows in ``BUDGETS`` for the few known-heavy tests
that are allowed more. A new test that quietly costs 20s therefore
fails CI-style review instead of silently eating the cap. Budgets are
calibrated for the tier-1 verify's normal condition — the suite
running ALONE on the machine (same as its 870s cap); a log from a run
that shared the CPU with a bench/profiler inflates durations 2-8x and
will false-positive.

Run without flags for the marker census only.
"""
import os
import re
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# --- per-test tier-1 wall-clock budgets (seconds) ----------------------
# Any single `call` duration above its covering budget fails the audit.
# Keep DEFAULT tight: the suite holds ~740 tests under a 870s cap, so
# the sustainable average is ~1s/test — 12s outliers need a named row
# and a reason.
DEFAULT_BUDGET_S = 12.0
BUDGETS = (
    # torch-parity converters pay a one-off HF model build + save
    (r"test_deepseek_v2\.py", 16.0),
    (r"test_hf_interop\.py", 16.0),
    # conv/attention-tower grads are compile-bound on 1 CPU core
    (r"test_vision_models\.py", 16.0),
    # 2s solo; in-suite it pays the mixed spec/sampled/penalized tick
    # program's compile whose cache state depends on suite order
    # (ISSUE 13's test_fleet.py sorting ahead of it shifted the bill)
    (r"test_mixed_spec_sampled_penalized_slots_one_tick", 16.0),
    # ~12s in-suite: the llama spec-tick twin pays the k+1 verify
    # forward's compile; suite-order cache shifts (ISSUE 14's
    # test_delta_transitions.py sorts ahead of test_paged_spec.py)
    # push it over the default by a hair
    (r"test_llama_tokens_exact_logprobs_close", 16.0),
)


def _parse_durations(lines):
    """Yield (seconds, nodeid) from pytest --durations report lines
    (``  7.96s call     tests/test_x.py::test_y``). Only `call` rows
    count — setup/teardown are fixture costs shared across tests."""
    rx = re.compile(r"^\s*(\d+\.\d+)s\s+call\s+(\S+)")
    for ln in lines:
        m = rx.match(ln)
        if m:
            yield float(m.group(1)), m.group(2)


def audit_durations(lines):
    """Return budget-violation strings for a durations report."""
    bad = []
    for secs, node in _parse_durations(lines):
        budget = DEFAULT_BUDGET_S
        for pat, cap in BUDGETS:
            if re.search(pat, node):
                budget = cap
                break
        if secs > budget:
            bad.append(f"{node}: {secs:.2f}s > budget {budget:.0f}s")
    return bad

# Patterns (regex, matched against pytest node ids) that must stay OUT
# of the tier-1 run. Keep in sync with tests/conftest.py's _SLOW list
# and per-test @pytest.mark.slow decorations.
MUST_BE_SLOW = (
    # ISSUE 6: wall-clock micro-bench + sweep matrices + the 14s
    # full-batch interpret parity (each keeps a tier-1 representative)
    r"test_fused_tick\.py.*microbench",
    r"test_fused_tick\.py.*parity_sweep",
    r"test_fused_tick\.py.*full_batch",
    # ISSUE 7: spec k/ngram + multi-query kernel sweeps and the
    # tokens-per-forward micro-bench (bitwise k=4/g=2 cases, the
    # boundary-lens kernel case, and the dispatch pins stay tier-1)
    r"test_paged_spec\.py.*sweep",
    r"test_paged_spec\.py.*microbench",
    # PR 2: multi-subprocess preemption/elastic e2e (conftest _SLOW)
    r"test_kill_mid_run_then_resume_continues_trajectory",
    r"test_hang_checkpoints_exits_and_supervisor_finishes",
    r"test_nan_window_rolls_back_and_converges",
    # ISSUE 9: open-loop gateway rate sweeps + the subprocess loadgen
    # CLI e2e (each keeps a tier-1 in-process representative:
    # test_loadgen_inprocess_smoke + the single-shot gateway e2e tests)
    r"test_gateway\.py.*open_loop",
    r"test_gateway\.py.*loadgen_cli",
    # ISSUE 10: the many-request trace retention/attribution sweep
    # (tier-1 keeps the single-shot propagation + retention pins)
    r"test_reqtrace\.py.*sweep",
    # ISSUE 7 sweep: the 4-worker speedup wall-clock bench was tier-1's
    # one pre-policy bench (flipped at 2.56x/3.0 under full-suite load;
    # the rest of test_dataloader_mp.py keeps the correctness coverage)
    r"test_dataloader_mp\.py.*speedup",
    # ISSUE 12: the seeded chaos sweep — multi-seed open-loop loadgen
    # runs with mid-run replica kills + full reference replays (tier-1
    # keeps the single-kill failover e2e pins in test_failover.py:
    # test_failover_stream_bitwise_vs_uninterrupted and friends)
    r"test_failover\.py.*chaos",
    # ISSUE 13: the multi-process fleet e2e — spawns real gateway
    # SUBPROCESSES (cold jax import per process) behind the fleet
    # frontend, kills one mid-run, rides an autoscaled diurnal trace
    # (tier-1 keeps the in-process remote-adapter/failover/autoscaler
    # units in test_fleet.py: proxy parity, peer-kill bitwise resume,
    # breaker rejoin, scaler hysteresis)
    r"test_fleet\.py.*multiproc",
    # ISSUE 16: the 1000-stub fleet-sim acceptance runs (tens of
    # seconds of discrete-event CPU each; tier-1 keeps the small
    # 12-16 replica scenario pins in test_fleet_sim.py) and the live
    # two-frontend HA kill e2e (real replica subprocesses + sibling
    # frontends — matched by the multiproc pattern above)
    r"test_fleet_sim\.py.*thousand",
    # ISSUE 11: the seeded sampled-spec distribution sweep (~190s of
    # engine runs; tier-1 keeps the residual-resample marginal unit +
    # the decisive-logits exact pin), and the ISSUE-11 tier-budget
    # pass's conftest _SLOW demotions (each names its surviving tier-1
    # representative in conftest.py)
    r"test_ring_spec\.py.*distribution_parity_sweep",
    # ISSUE 14: the delta-transition ring x chunk x spec parity matrix
    # (tier-1 keeps the single-combination transition-matrix, scoped-
    # drain and upload-counter pins in test_delta_transitions.py)
    r"test_delta_transitions\.py.*parity_sweep",
    # ISSUE 15: the multi-window burn-rate sweep (seeded outcome
    # streams x window scales x thresholds), the multi-PROCESS fleet
    # federation e2e (real replica subprocesses, cold jax import
    # each), and the chaos-alert loadgen e2e (full chaos harness run
    # + bitwise replay). Tier-1 keeps the injected-clock burn units,
    # the in-process federation pin and the sampler-on/off bitwise
    # stream pins in test_telemetry.py.
    r"test_telemetry\.py.*burn_sweep",
    r"test_telemetry\.py.*multiproc",
    r"test_telemetry\.py.*chaos",
    # ISSUE 17: the spill-tier chaos sweep — full chaos loadgen run
    # with the host-RAM KV arena attached (kill -> supervisor rebuild
    # -> warm restore) + bitwise replay gate. Tier-1 keeps the arena
    # units, the spill-on/off bitwise parity pins and the corrupt-
    # fallback pin in test_kvspill.py.
    r"test_kvspill\.py.*chaos",
    # ISSUE 18: the migrate chaos e2e — full chaos loadgen run with
    # kills PLUS the two-gateway drain-migration A/B probe (migrate
    # vs re-prefill control) and its bitwise replay gates. Tier-1
    # keeps the wire-ladder units, the drain-migration bitwise parity
    # pins and the corrupted-transfer-never-emits pin in
    # test_kvxfer.py.
    r"test_kvxfer\.py.*chaos",
    # ISSUE 20: the /profilez capture e2e — real HTTP gateway + fleet
    # frontend federation around a wall-clock capture window (tier-1
    # keeps the injected-clock phase math, the profile-on/off bitwise
    # pins and the reset-flush unit in test_tick_profile.py)
    r"test_tick_profile\.py.*profilez.*e2e",
    r"test_vision_models\.py.*(forward_and_grad|bottleneck_variant"
    r"|grad_through_both_towers)",
    r"TestDeepseekV2Parity.*logits_match_torch",
    r"TestMTP::test_mtp_shapes_and_main_parity",
    r"TestRingFlash",
    r"test_diffusion\.py.*diffusion_loss_with_dit",
    r"test_dataloader_mp\.py.*(worker_info_and_distribution"
    r"|worker_init_fn)",
    r"test_vae_diffusers_roundtrip",
)


def _collect(marker_expr):
    cmd = [sys.executable, "-m", "pytest", "tests/", "--collect-only",
           "-q", "-m", marker_expr, "-p", "no:cacheprovider",
           "--continue-on-collection-errors"]
    out = subprocess.run(cmd, cwd=ROOT, capture_output=True, text=True,
                         timeout=300,
                         env={**os.environ, "JAX_PLATFORMS": "cpu"})
    nodes = [ln.strip() for ln in out.stdout.splitlines()
             if "::" in ln and not ln.startswith(("=", "<", " "))]
    return nodes


def check(budget_log=None) -> int:
    slow = _collect("slow")
    tier1 = _collect("not slow")
    bad, stale = [], []
    for pat in MUST_BE_SLOW:
        rx = re.compile(pat)
        leaked = [n for n in tier1 if rx.search(n)]
        if leaked:
            bad.extend(f"{pat}: IN TIER-1 -> {n}" for n in leaked[:3])
        elif not any(rx.search(n) for n in slow):
            stale.append(pat)
    over = []
    if budget_log:
        with open(budget_log) as f:
            over = audit_durations(f)
    census = (f"tier-1 {len(tier1)} tests, slow {len(slow)} "
              f"(cap 870s; see ROADMAP 'Tier-1 verify')")
    if bad or stale or over:
        print("marker audit FAILED:", file=sys.stderr)
        for line in bad:
            print(f"  budget leak  {line}", file=sys.stderr)
        for pat in stale:
            print(f"  stale policy {pat}: matches no collected test",
                  file=sys.stderr)
        for line in over:
            print(f"  over budget  {line}", file=sys.stderr)
        print(census, file=sys.stderr)
        return 1
    print(f"marker audit OK: {census}; "
          f"{len(MUST_BE_SLOW)} slow-policy patterns enforced"
          + (f"; durations within budget ({budget_log})"
             if budget_log else ""))
    return 0


if __name__ == "__main__":
    log = None
    argv = sys.argv[1:]
    if "--budget-log" in argv:
        i = argv.index("--budget-log")
        if i + 1 >= len(argv):
            print("usage: marker_audit.py [--budget-log "
                  "DURATIONS_LOG]", file=sys.stderr)
            sys.exit(2)
        log = argv[i + 1]
    sys.exit(check(budget_log=log))
