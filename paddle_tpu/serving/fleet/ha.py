"""Leaderless frontend HA: sibling gossip links (ISSUE 16 tentpole;
reference: the peer-to-peer state exchange of leaderless edge tiers —
envoy xDS-less mesh mode, SWIM-style dissemination — restated
stdlib-only over the fleet's existing probe transport).

The FleetFrontend was the fleet's last single point of failure: N
replica gateways survive SIGKILLs bitwise, but one frontend process
owned all routing state. HA here is LEADERLESS — every frontend is a
full peer:

- Each frontend runs its OWN probers against every replica and
  re-derives health/breaker state locally (authoritative state that
  must never travel: a partitioned sibling's "peer X is dead" verdict
  would blind the whole tier).
- What IS gossiped — over ``GET /gossipz``, the same HTTP surface the
  probers already ride — is the state that is expensive or impossible
  to re-derive quickly: per-peer prefix-digest sets (guarded by the
  PEER's own generation counter, so the fresher view wins regardless
  of which frontend probed last), and sticky routing assignments (a
  sibling adopts only digests it has no local opinion on).
- Failover is client-driven: a client whose frontend dies mid-stream
  retries against any surviving sibling carrying its committed
  ``(token, logprob)`` prefix as ``resume_tokens``/``resume_lps`` —
  the same resume seam peers' own failover uses (ISSUE 12), one tier
  up. No committed token is ever lost or duplicated; greedy streams
  stay bitwise.

:class:`FrontendLink` is one directed gossip edge: a background
thread polling a sibling's ``/gossipz`` on the seeded jittered
schedule (:func:`~.remote.probe_delay` — the storm-decorrelated
rounds the fleet sim validates) and merging each doc via
``FleetFrontend.apply_gossip``. :func:`link_frontends` wires the full
mesh (N*(N-1) directed links; at the 2-4 frontends a fleet tier runs,
mesh beats epidemic fan-out on simplicity and convergence time).

The ``gossip_partition`` fault site severs links deterministically —
the partitioned tier must keep serving on locally re-derived state,
degrading only warm-routing optimality.
"""
from __future__ import annotations

import http.client
import json
import threading
from typing import Any, Dict, List, Optional

from ...utils import faults
from ...utils import observability as obs
from .remote import probe_delay, probe_phase

__all__ = ["FrontendLink", "link_frontends"]


class FrontendLink:
    """One directed gossip edge: ``frontend`` polls ``sibling``'s
    ``/gossipz`` and merges the doc into its own state.

    ``sibling`` may be given as a live :class:`FleetFrontend` (same
    process — the loadgen/sim topology: the fetch is then a direct
    method call, no socket) or as a ``(host, port)`` address of a
    sibling in another process. Either way the merge path —
    ``gossipz()`` doc in, ``apply_gossip()`` out — is identical, so
    in-process tests exercise the exact protocol the multi-process
    tier runs."""

    def __init__(self, frontend, sibling, *,
                 interval_s: float = 0.5,
                 timeout_s: float = 2.0,
                 jitter_frac: float = 0.2,
                 seed: int = 0):
        self.frontend = frontend
        self.sibling = sibling
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.jitter_frac = float(jitter_frac)
        self.seed = int(seed)
        self.rounds_total = 0
        self.failures_total = 0
        self.partitioned_total = 0
        self.adopted_digest_sets = 0
        self.adopted_sticky = 0
        self._halt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- naming
    @property
    def name(self) -> str:
        return f"{self.frontend.name}<-{self._sibling_name()}"

    def _sibling_name(self) -> str:
        if isinstance(self.sibling, tuple):
            return f"{self.sibling[0]}:{self.sibling[1]}"
        return getattr(self.sibling, "name", str(self.sibling))

    # ------------------------------------------------------------ one round
    def _fetch(self) -> Dict[str, Any]:
        if not isinstance(self.sibling, tuple):
            return self.sibling.gossipz()
        host, port = self.sibling
        conn = http.client.HTTPConnection(host, int(port),
                                          timeout=self.timeout_s)
        try:
            conn.request("GET", "/gossipz")
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status != 200:
                raise ConnectionError(f"/gossipz answered {resp.status}")
            return json.loads(payload)
        finally:
            conn.close()

    def exchange(self) -> bool:
        """One synchronous gossip round (what the background thread
        loops and what deterministic tests/the sim call directly).
        Returns success; a partitioned or failed round leaves local
        state untouched — gossip is an accelerant, never a
        dependency."""
        self.rounds_total += 1
        if faults.inject("gossip_partition", link=self.name):
            self.partitioned_total += 1
            return False
        try:
            doc = self._fetch()
        except (OSError, ValueError, ConnectionError,
                http.client.HTTPException):
            self.failures_total += 1
            return False
        merged = self.frontend.apply_gossip(doc)
        self.adopted_digest_sets += merged["digest_sets"]
        self.adopted_sticky += merged["sticky"]
        return True

    # ------------------------------------------------------------- thread
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._halt.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"fleet-gossip-{self.name}")
        self._thread.start()

    def stop(self, timeout: float = 2.0):
        self._halt.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)

    def _loop(self):
        # the probe scheduler's seeded phase+jitter (ISSUE 16): N
        # frontends' gossip rounds must not synchronize into the same
        # herd the probe storm sim flags
        if self._halt.wait(probe_phase(self.name, self.interval_s,
                                       seed=self.seed)):
            return
        rnd = 0
        while True:
            try:
                self.exchange()
            except Exception as e:   # the link must outlive any bug
                obs.record_event("fleet_gossip_error", link=self.name,
                                 err=repr(e))
            rnd += 1
            if self._halt.wait(probe_delay(
                    self.name, self.interval_s, rnd,
                    jitter_frac=self.jitter_frac, seed=self.seed)):
                return

    def snapshot(self) -> Dict[str, Any]:
        return {
            "link": self.name,
            "rounds": self.rounds_total,
            "failures": self.failures_total,
            "partitioned": self.partitioned_total,
            "adopted_digest_sets": self.adopted_digest_sets,
            "adopted_sticky": self.adopted_sticky,
        }


def link_frontends(frontends: List[Any], *, interval_s: float = 0.5,
                   jitter_frac: float = 0.2, seed: int = 0,
                   start: bool = True) -> List[FrontendLink]:
    """Wire the full gossip mesh over in-process sibling frontends:
    one directed :class:`FrontendLink` per ordered pair. Returns the
    links (started unless ``start=False`` — the sim drives rounds
    itself on the simulated clock)."""
    links = []
    for fe in frontends:
        for sib in frontends:
            if sib is fe:
                continue
            links.append(FrontendLink(
                fe, sib, interval_s=interval_s,
                jitter_frac=jitter_frac, seed=seed))
    if start:
        for ln in links:
            ln.start()
    return links
