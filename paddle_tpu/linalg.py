"""paddle.linalg parity (reference: python/paddle/tensor/linalg.py — the
PHI linalg kernels: cholesky/svd/qr/eig/solve/lstsq/...).

TPU-native: thin delegates to jnp.linalg/lax.linalg with paddle's
signatures and semantics quirks (e.g. ``norm``'s fro default, ``cond``'s
p conventions, matmul aliasing). Decompositions lower to XLA's custom
calls — batched and differentiable where jax supports it.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "matmul", "norm", "cond", "cov", "corrcoef", "cholesky",
    "cholesky_solve", "svd", "svdvals", "qr", "eig", "eigh", "eigvals",
    "eigvalsh", "inv", "pinv", "det", "slogdet", "solve",
    "triangular_solve", "lstsq", "lu", "lu_unpack", "matrix_power",
    "vector_norm", "matrix_norm", "matrix_exp", "solve_triangular",
    "householder_product", "pca_lowrank", "svd_lowrank", "ormqr",
    "matrix_rank", "multi_dot", "matrix_transpose", "dot", "cross",
    "bmm",
]


def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    return x @ y


def matrix_transpose(x):
    return jnp.swapaxes(x, -1, -2)


def dot(x, y):
    return jnp.sum(x * y, axis=-1)


def cross(x, y, axis=None):
    if axis is None:
        # paddle: the first axis with length 3; no such axis is an error,
        # not a silent 2-D scalar cross on the wrong axis
        axis = next((i for i, s in enumerate(x.shape) if s == 3), None)
        if axis is None:
            raise ValueError(
                f"cross: no axis of length 3 in shape {x.shape}")
    return jnp.cross(x, y, axis=axis)


def bmm(x, y):
    return jnp.matmul(x, y)


def norm(x, p=None, axis=None, keepdim=False):
    """paddle.linalg.norm: p=None -> fro over all dims (matrix) / l2.
    axis=None reduces ALL dims; keepdim then keeps every dim at 1
    (paddle semantics — result broadcasts against x)."""
    if p is None:
        p = "fro" if axis is None and x.ndim >= 2 else 2
    if axis is None:
        out = (jnp.sqrt(jnp.sum(jnp.square(x))) if p == "fro"
               else jnp.linalg.norm(x.reshape(-1), ord=p))
        return out.reshape((1,) * x.ndim) if keepdim else out
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


def cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=int(bool(ddof)),
                   fweights=fweights, aweights=aweights)


def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2).conj() if upper else L


def cholesky_solve(x, y, upper=False):
    """Solve A X = B given y = chol factor of A; paddle arg order (B, L)."""
    import jax.scipy.linalg as jsl
    return jsl.cho_solve((y, not upper), x)


def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def svdvals(x):
    return jnp.linalg.svd(x, compute_uv=False)


def qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


def eig(x):
    return jnp.linalg.eig(x)


def eigh(x, UPLO="L"):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eigvals(x):
    return jnp.linalg.eigvals(x)


def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def inv(x):
    return jnp.linalg.inv(x)


def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


def det(x):
    return jnp.linalg.det(x)


def slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    # paddle returns stacked [sign, logabsdet]
    return jnp.stack([sign, logabs])


def solve(x, y):
    return jnp.linalg.solve(x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    import jax.scipy.linalg as jsl
    return jsl.solve_triangular(x, y, lower=not upper,
                                trans=1 if transpose else 0,
                                unit_diagonal=unitriangular)


def lstsq(x, y, rcond=None, driver=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


def lu(x, pivot=True):
    import jax.scipy.linalg as jsl
    lu_mat, piv = jsl.lu_factor(x)
    return lu_mat, piv + 1  # paddle pivots are 1-based (LAPACK style)


def lu_unpack(lu_mat, pivots, unpack_ludata=True, unpack_pivots=True):
    if lu_mat.ndim > 2:  # batched factors: vmap the 2-D unpack
        return jax.vmap(lambda m, p: lu_unpack(m, p))(lu_mat, pivots)
    n = lu_mat.shape[-2]
    L = jnp.tril(lu_mat, -1) + jnp.eye(n, lu_mat.shape[-1],
                                       dtype=lu_mat.dtype)
    U = jnp.triu(lu_mat)
    # replay the LAPACK row swaps as a scan (jittable, no host loop)
    def swap(pm, ip):
        i, p = ip
        a, b = pm[i], pm[p]
        return pm.at[i].set(b).at[p].set(a), None
    idx = jnp.arange(pivots.shape[-1])
    perm, _ = jax.lax.scan(swap, jnp.arange(n), (idx, pivots - 1))
    P = jnp.eye(n, dtype=lu_mat.dtype)[perm]
    return P.T, L, U


def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


def matrix_rank(x, tol=None, hermitian=False):
    """paddle semantics: ``tol`` is an ABSOLUTE threshold on singular
    values (eigenvalue magnitudes when hermitian); batched inputs get a
    per-matrix threshold."""
    sv = (jnp.abs(jnp.linalg.eigvalsh(x)) if hermitian
          else jnp.linalg.svd(x, compute_uv=False))
    if tol is None:
        eps = jnp.finfo(x.dtype).eps
        thresh = jnp.max(sv, axis=-1, keepdims=True) * max(x.shape[-2:]) * eps
    else:
        thresh = jnp.asarray(tol)
        if thresh.ndim:
            thresh = thresh[..., None]  # per-matrix tol for batched x
    return jnp.sum(sv > thresh, axis=-1)


def multi_dot(mats):
    return jnp.linalg.multi_dot(mats)




# ---------------------------------------------------------------- round 4

def vector_norm(x, p=2.0, axis=None, keepdim=False):
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis,
                       keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** p, axis=axis,
                   keepdims=keepdim) ** (1.0 / p)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False):
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


def matrix_exp(x):
    import jax.scipy.linalg as jsl
    if x.ndim == 2:
        return jsl.expm(x)
    return jax.vmap(jsl.expm)(x.reshape((-1,) + x.shape[-2:])) \
        .reshape(x.shape)


# paddle exposes both names for the same semantics
solve_triangular = triangular_solve


def _apply_reflectors(a, tau, y, adjoint):
    """Apply Q (adjoint=False) or Q^H (adjoint=True) from LAPACK geqrf
    reflectors H_i = I - tau_i v_i v_i^H to y [m, cols] — O(k*m*cols),
    never materializing Q."""
    m = a.shape[0]
    k = tau.shape[0]
    idx = range(k) if adjoint else range(k - 1, -1, -1)
    for i in idx:
        v = jnp.where(jnp.arange(m) == i, 1.0,
                      jnp.where(jnp.arange(m) > i, a[:, i], 0.0))
        t = jnp.conj(tau[i]) if adjoint else tau[i]
        y = y - t * v[:, None] * (jnp.conj(v) @ y)[None, :]
    return y


def householder_product(x, tau):
    """Assemble Q's first n columns from geqrf reflectors (reference:
    paddle.linalg.householder_product): Q = H_0 H_1 ... H_{k-1},
    built by applying the reflectors to eye(m, n) — O(k*m*n)."""
    m, n = x.shape[-2], x.shape[-1]

    def one(a, t):
        return _apply_reflectors(a, t, jnp.eye(m, n, dtype=a.dtype),
                                 adjoint=False)

    if x.ndim == 2:
        return one(x, tau)
    lead = x.shape[:-2]
    flat = jax.vmap(one)(x.reshape((-1, m, n)),
                         tau.reshape((-1, tau.shape[-1])))
    return flat.reshape(lead + (m, n))


def pca_lowrank(x, q=None, center=True, niter=2):
    """Randomized PCA (reference: paddle.linalg.pca_lowrank; Halko et
    al. 2011 subspace iteration, QR re-orthonormalized every step so
    float32 keeps the small singular directions). Batched over leading
    dims. Deterministic: the range-finder seed is fixed (explicit-key
    policy, no global RNG inside)."""
    m, n = x.shape[-2], x.shape[-1]
    q = q if q is not None else min(6, m, n)
    a = x - x.mean(axis=-2, keepdims=True) if center else x
    a_h = jnp.swapaxes(jnp.conj(a), -1, -2)
    key = jax.random.PRNGKey(0)
    omega = jax.random.normal(key, (n, q), a.dtype)
    y, _ = jnp.linalg.qr(a @ omega)
    for _ in range(niter):
        z, _ = jnp.linalg.qr(a_h @ y)
        y, _ = jnp.linalg.qr(a @ z)
    b = jnp.swapaxes(jnp.conj(y), -1, -2) @ a
    u_b, s, vt = jnp.linalg.svd(b, full_matrices=False)
    return y @ u_b, s, jnp.swapaxes(jnp.conj(vt), -1, -2)


def svd_lowrank(x, q=6, niter=2):
    u, s, v = pca_lowrank(x, q=q, center=False, niter=niter)
    return u, s, v


def ormqr(x, tau, y, left=True, transpose=False):
    """Multiply y by Q / Q^H from geqrf reflectors WITHOUT forming Q
    (reference: paddle.linalg.ormqr / LAPACK unmqr)."""
    if left:
        return _apply_reflectors(x, tau, y, adjoint=transpose)
    # y @ Q == (Q^H @ y^H)^H
    yh = jnp.swapaxes(jnp.conj(y), -1, -2)
    out = _apply_reflectors(x, tau, yh, adjoint=not transpose)
    return jnp.swapaxes(jnp.conj(out), -1, -2)
