"""ISSUE 12: fault-tolerant serving fleet — replica supervision,
in-flight request failover, circuit-breaker rejoin, seeded chaos.

Contracts pinned here:

- BREAKER: the closed -> open -> half-open -> closed state machine,
  exponential backoff (doubling per reopen), and the AT-MOST-ONE
  in-flight probe rule.
- ROUTER REJOIN: evict -> probe -> rejoin through the breaker folded
  into the warm/sticky/least-loaded ladder (eviction is no longer
  one-way), and a half-open replica receives at most one probe at a
  time.
- RESUME: ``PagedEngine.export_resumable()`` descriptors resubmitted
  as ``prompt + committed tokens`` continue a greedy stream BITWISE
  identically to the uninterrupted reference — no duplicated and no
  missing token across the kill boundary (tokens AND logprobs).
- FAILOVER E2E: a replica killed (crash / silent drop / hung
  dispatch) mid-stream hands its live requests to a surviving
  replica; the client's SSE stream stays bitwise the no-failure
  stream (the ``_fail_all``-hardening satellite: the bare 500 is gone
  when survivors exist).
- BUDGET: ``failover_budget`` caps resubmissions (counted in
  ``gateway_retry_budget_exhausted_total``), and a DRAINING replica
  never accepts failover traffic.
- CHAOS (slow): the ``serve_loadgen --chaos`` harness — 3-replica
  gateway, seeded mid-run kills — finishes with zero corrupted
  streams and errors within the retry-budget bound.

Everything tier-1 runs the negligible-compute stub with sub-second
watchdog/breaker knobs; the open-loop chaos sweep rides behind
``slow`` (``tools/marker_audit.py`` chaos patterns).
"""
import asyncio
import time

import pytest

from paddle_tpu.serving import (CircuitBreaker, Gateway,
                                PrefixAffinityRouter, ServeRequest)
from paddle_tpu.serving.supervisor import (BREAKER_CLOSED, BREAKER_OPEN,
                                           BREAKER_HALF_OPEN)

from test_gateway import _engine, _http, _load_loadgen, _poll, _sse

PROMPT = list(range(1, 13))


def _direct(prompt=PROMPT, max_new=24, **kw):
    eng = _engine()
    eng.submit("ref", [prompt], max_new_tokens=max_new, **kw)
    eng.run()
    return eng.results["ref"], eng.logprobs["ref"]


# ================================================================= breaker
def test_breaker_state_machine():
    t = [0.0]
    states = []
    b = CircuitBreaker(probes_to_close=2, backoff_s=1.0,
                       backoff_factor=2.0, on_state=states.append,
                       clock=lambda: t[0])
    assert b.state == BREAKER_CLOSED
    b.record_failure()
    assert b.state == BREAKER_OPEN
    assert not b.try_probe()            # backoff (1.0s) not elapsed
    t[0] = 1.1
    assert b.try_probe()                # promotes half-open + claims slot
    assert b.state == BREAKER_HALF_OPEN
    assert not b.try_probe()            # AT MOST one probe in flight
    b.probe_done(True)
    assert b.state == BREAKER_HALF_OPEN  # needs 2 successes
    assert b.try_probe()
    b.probe_done(False)                 # failed probe reopens...
    assert b.state == BREAKER_OPEN
    t[0] = 2.5
    assert not b.try_probe()            # ...with DOUBLED backoff (2.0s)
    t[0] = 3.2
    assert b.try_probe()
    b.probe_done(None)                  # inconclusive: slot released,
    assert b.state == BREAKER_HALF_OPEN  # state unchanged
    assert b.try_probe()
    b.probe_done(True)
    assert b.try_probe()
    b.probe_done(True)                  # 2nd success closes
    assert b.state == BREAKER_CLOSED
    assert b.snapshot()["opens"] == 0   # reset for the next episode
    assert states == [BREAKER_OPEN, BREAKER_HALF_OPEN, BREAKER_OPEN,
                      BREAKER_HALF_OPEN, BREAKER_CLOSED]


def test_breaker_rearm_defers_probation():
    """The supervisor re-arms after a slow rebuild: the probation
    window must not open while the replica is still being rebuilt."""
    t = [0.0]
    b = CircuitBreaker(backoff_s=0.1, clock=lambda: t[0])
    b.record_failure()
    t[0] = 0.5                          # rebuild finished late
    b.rearm()
    assert not b.try_probe()            # backoff restarted from 0.5
    t[0] = 0.65
    assert b.try_probe()


# ================================================================== router
class _FakeReplica:
    def __init__(self, name, load=0.0):
        self.name, self._load, self._healthy = name, load, True
        self.breaker = None

    def healthy(self):
        return self._healthy

    def mark(self, h):
        self._healthy = h

    def has_prefix(self, d):
        return False

    def load(self):
        return self._load


def test_router_evict_probe_rejoin():
    """Satellite pin: eviction is no longer one-way — the breaker
    folds into the ladder as evict -> probe -> rejoin, and a half-open
    replica receives at most ONE probe request at a time."""
    t = [0.0]
    a, b = _FakeReplica("a"), _FakeReplica("b", load=5)
    a.breaker = CircuitBreaker(backoff_s=1.0, clock=lambda: t[0],
                               on_state=lambda s:
                               a.mark(s == BREAKER_CLOSED))
    r = PrefixAffinityRouter([a, b], labels={"gateway": "t-rejoin"})
    assert r.route(None) is a           # least loaded, both healthy
    a.breaker.record_failure()          # replica failed: evicted
    assert not a.healthy()
    assert r.route(None) is b           # out of rotation
    t[0] = 1.5                          # backoff elapsed
    assert r.route(None) is a           # the ONE probation probe
    assert r.route(None) is b           # probe in flight: ladder only
    assert r.route(None, allow_probe=False) is b   # gateway race-retry
    a.breaker.probe_done(True)          # probe succeeded: rejoined
    assert a.healthy()
    assert r.route(None) is a           # back in the ladder
    assert r.snapshot()["breakers"] == {"a": BREAKER_CLOSED}


# ============================================================ engine resume
def test_export_resumable_resume_offset_bitwise():
    """Resume pin: committed tokens exported off a mid-stream engine
    and resubmitted as prompt + committed continue the greedy stream
    BITWISE — the boundary duplicates nothing and drops nothing,
    tokens and logprobs both."""
    full, full_lps = _direct(max_new=16)
    eng = _engine()
    eng.submit("a", [PROMPT], max_new_tokens=16,
               stop_sequences=[[9, 9, 9]])
    for _ in range(7):                  # mid-stream (ring drains lag 1)
        eng.step()
    desc = eng.export_resumable()["a"]
    committed = desc["committed"]
    assert 0 < len(committed) < 16
    assert committed == full[:len(committed)]     # prefix-exact so far
    eng2 = _engine()
    eng2.submit("a", [desc["prompt"]],
                max_new_tokens=desc["remaining"],
                stop_sequences=desc["stop"],
                resume_tokens=desc["committed"],
                resume_lps=desc["committed_lps"])
    eng2.run()
    assert eng2.results["a"] == full              # no dup, no gap
    assert eng2.logprobs["a"] == pytest.approx(full_lps)


def test_export_resumable_rejects_non_tail_resume():
    eng = _engine()
    with pytest.raises(ValueError, match="tail of input_ids"):
        eng.submit("x", [PROMPT], max_new_tokens=4,
                   resume_tokens=[999])


def test_hard_reset_engine_reusable():
    """The supervisor's rebuild-in-place: after hard_reset a mid-run
    engine is empty (all blocks free, no slots/queue) and serves the
    same request bitwise like a fresh engine — compiled executables
    survive, state does not."""
    eng = _engine()
    eng.submit("a", [PROMPT], max_new_tokens=6)
    ref = dict(eng.run())["a"]
    eng.submit("b", [list(range(20, 29))], max_new_tokens=50)
    for _ in range(4):
        eng.step()                      # mid-flight state to destroy
    eng.hard_reset()
    h = eng.health()
    assert h["active_slots"] == 0 and h["queued"] == 0
    assert h["free_blocks"] == eng.P - 1
    assert eng.results == {} and not eng.prefix_cache
    eng.submit("c", [PROMPT], max_new_tokens=6)
    assert eng.run()["c"] == ref


# ============================================================ failover e2e
def _warm_engine():
    """Compile-before-traffic: a cold engine's first step pays the
    executable build — far over the sub-second test watchdog deadline
    — so every fleet engine serves one request before it can take
    watched traffic (what a real fleet's readiness probe guarantees;
    the chaos loadgen's factory does the same)."""
    e = _engine()
    e.submit("warmup", [list(range(1, 5))], max_new_tokens=4)
    e.run()
    e.results.pop("warmup", None)
    e.logprobs.pop("warmup", None)
    return e


def _fleet_gw(n=2, name="t-fo", **kw):
    # 1s watchdog: far above a warmed stub step (~ms) even on a
    # contended full-suite CPU, far below the test budget
    base = dict(watchdog_timeout_s=1.0, watchdog_interval_s=0.02,
                breaker_backoff_s=0.05, name=name)
    base.update(kw)
    return Gateway([_warm_engine() for _ in range(n)], **base)


async def _kill_serving(gw, kind):
    w = next(w for w in gw._workers if w._live)
    w.inject_fault(kind)
    return w.replica.name


@pytest.mark.parametrize("kind", ["crash", "drop", "hang"])
def test_failover_stream_bitwise_vs_uninterrupted(kind, monkeypatch):
    """Acceptance pin: a replica killed mid-stream (tick crash, silent
    thread drop, or hung dispatch caught by the watchdog) hands its
    live request to the surviving replica and the client's SSE stream
    stays BITWISE the uninterrupted reference — tokens, final token
    list and logprobs. Also the ``_fail_all`` hardening satellite: no
    bare 500 when survivors exist."""
    monkeypatch.setenv("PADDLE_TPU_FAULT_DISPATCH_HANG_S", "2.5")
    killed = {}

    async def run():
        gw = _fleet_gw(name=f"t-fo-{kind}")
        await gw.start()
        try:
            async def kill():
                killed["replica"] = await _kill_serving(gw, kind)

            st, _, toks, fin = await _sse(
                gw.port, dict(prompt=PROMPT, max_new_tokens=24),
                on_first=kill)
        finally:
            await gw.drain()
        return st, toks, fin, gw.health(), gw.debugz()

    st, toks, fin, health, dbz = asyncio.run(run())
    direct, direct_lps = _direct()
    assert st == 200 and fin["finish_reason"] == "stop"
    assert toks == direct, f"{kind}: streamed tokens diverged"
    assert fin["tokens"] == direct
    assert fin["logprobs"] == pytest.approx(direct_lps)
    assert health["failovers"] >= 1
    assert health["retry_budget_exhausted"] == 0
    assert "replica" in killed
    if kind == "hang":
        assert dbz["supervisor"]["watchdog_fires"] >= 1


def test_breaker_rejoins_replica_after_crash():
    """Evict -> probe -> rejoin, end to end: after a crash the replica
    is out of rotation (breaker open), the supervisor rebuilds it, a
    later request probes it, and the fleet is back to full strength —
    permanent eviction is gone."""
    async def run():
        gw = _fleet_gw(name="t-rejoin-e2e")
        await gw.start()
        try:
            st, _, toks, fin = await _sse(
                gw.port, dict(prompt=PROMPT, max_new_tokens=16),
                on_first=lambda: _kill_serving(gw, "crash"))
            assert st == 200 and fin["finish_reason"] == "stop"

            async def recovered():
                # traffic drives the probe: keep sending until the
                # probe lands and the breaker closes (a request racing
                # the rebuild may error — that's what the NEXT one is
                # for, so don't assert on individual outcomes)
                st2, _, _, fin2 = await _sse(
                    gw.port, dict(prompt=PROMPT, max_new_tokens=2))
                if st2 != 200 or (fin2 or {}).get(
                        "finish_reason") != "stop":
                    return False
                snap = gw.health()["router"]
                return snap["replicas_up"] == 2 and all(
                    s == BREAKER_CLOSED
                    for s in snap.get("breakers", {}).values())

            ok = False
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and not ok:
                ok = await recovered()
                await asyncio.sleep(0.05)
            return ok, gw.health()
        finally:
            await gw.drain()

    ok, health = asyncio.run(run())
    assert ok, "crashed replica never rejoined rotation"
    assert health["router"]["replicas_up"] == 2


def test_retry_budget_exhaustion_errors_cleanly():
    """Budget pin: ``failover_budget=0`` turns the first failover into
    a clean client error (no retry storm, counter incremented) while
    the fleet itself recovers."""
    async def run():
        gw = _fleet_gw(name="t-budget", failover_budget=0)
        await gw.start()
        try:
            st, _, toks, fin = await _sse(
                gw.port, dict(prompt=PROMPT, max_new_tokens=24),
                on_first=lambda: _kill_serving(gw, "crash"))
        finally:
            await gw.drain()
        return st, fin, gw.health()

    st, fin, health = asyncio.run(run())
    assert st == 200 and fin.get("error")
    assert "budget" in fin["error"]
    assert health["retry_budget_exhausted"] == 1
    assert health["failovers"] == 0


def test_draining_replica_never_accepts_failover():
    """Drain/breaker composition satellite: failover target selection
    skips draining replicas — SIGTERM drain composes with an open
    breaker instead of dumping failed traffic onto an exiting
    worker."""
    gw = Gateway([_engine(), _engine()], name="t-drainfo")
    w1, w2 = gw._workers
    for w in (w1, w2):                  # threads never started: fake
        w.is_alive = lambda: True       # liveness for the filter
    req = ServeRequest("r1", PROMPT, {"max_new_tokens": 4})
    req.owner = w1
    w2.draining = True
    gw._resubmit(req, None, w1)
    assert w2.sched.depth() == 0        # draining survivor refused it
    assert int(gw._c_failovers.value) == 0
    req2 = ServeRequest("r2", PROMPT, {"max_new_tokens": 4})
    req2.owner = w1
    w2.draining = False
    gw._resubmit(req2, None, w1)
    assert w2.sched.depth() == 1        # healthy survivor takes it
    assert int(gw._c_failovers.value) == 1


def test_failover_trace_events_and_retention():
    """Reqtrace satellite: a failed-over request's ring entry carries
    the typed failure events (replica_fail, resubmit, resume_offset,
    breaker_open) with ``failovers`` counted top-level, and is
    RETAINED even though it finished fast and clean."""
    async def run():
        gw = _fleet_gw(name="t-fo-trace")
        await gw.start()
        try:
            st, _, _, fin = await _sse(
                gw.port, dict(prompt=PROMPT, max_new_tokens=16,
                              request_id="fo-req"),
                on_first=lambda: _kill_serving(gw, "crash"))
            assert st == 200 and fin["finish_reason"] == "stop"
            await _poll(lambda: any(
                e["request_id"] == "fo-req"
                for w in gw._workers if w.ring is not None
                for e in w.ring.snapshot()))
            entries = [e for w in gw._workers if w.ring is not None
                       for e in w.ring.snapshot()
                       if e["request_id"] == "fo-req"]
        finally:
            await gw.drain()
        return entries

    entries = asyncio.run(run())
    assert len(entries) == 1
    e = entries[0]
    assert e["outcome"] == "stop"
    assert e["failovers"] == 1
    assert e["retained"] and e["events"]
    kinds = [k for _, k, _ in e["events"]]
    for k in ("replica_fail", "breaker_open", "resubmit",
              "resume_offset"):
        assert k in kinds, f"missing {k} in {kinds}"
    ro = next(f for _, k, f in e["events"] if k == "resume_offset")
    assert ro["committed"] >= ro["offset"] >= 0


def test_debugz_exposes_breaker_and_supervisor():
    async def run():
        gw = _fleet_gw(name="t-fo-dbz")
        await gw.start()
        try:
            st, _, _, fin = await _sse(
                gw.port, dict(prompt=PROMPT, max_new_tokens=16),
                on_first=lambda: _kill_serving(gw, "crash"))
            assert st == 200 and fin["finish_reason"] == "stop"
            import json
            st2, _, payload = await _http(gw.port, "GET", "/debugz")
            return st2, json.loads(payload)
        finally:
            await gw.drain()

    st, dbz = asyncio.run(run())
    assert st == 200
    assert dbz["failover_budget"] == 2 and dbz["failovers"] >= 1
    assert dbz["supervisor"]["alive"]
    states = {r["breaker"]["state"] for r in dbz["replicas"].values()
              if r["breaker"] is not None}
    assert states & {BREAKER_CLOSED, BREAKER_OPEN, BREAKER_HALF_OPEN}


def test_expired_probe_releases_breaker_slot():
    """Regression: a probation probe that dies in the scheduler queue
    (expiry / queue flush) must still report to the breaker — a leaked
    probe slot would freeze the replica half-open forever (the silent
    one-way eviction this PR removes)."""
    gw = Gateway([_engine()], name="t-probeleak", supervise=True)
    w = gw._workers[0]
    b = CircuitBreaker(backoff_s=0.0)
    w.replica.breaker = b
    b.record_failure()
    assert b.try_probe()                    # the slot our probe holds
    req = ServeRequest("p1", PROMPT, {"max_new_tokens": 2},
                       deadline=time.monotonic() - 1.0)
    req.probe = True
    w.sched.enqueue(req)
    w.flush_queue(503, "dead worker")       # reaps the expired probe
    assert not b.snapshot()["probe_inflight"]
    assert b.try_probe()                    # slot reusable again


# ================================================================== chaos
def _chaos_ns(**kw):
    import types
    base = dict(requests=24, rate=60.0, share_frac=0.5, sys_tokens=8,
                tail_tokens=4, max_new=8, interactive_frac=0.7,
                ttft_slo_ms=5000.0, timeout_s=60.0, tenants=2,
                replicas=3, policy="prefix", max_queue=256,
                model="stub", seed=0, url=None, out="",
                chaos=True, chaos_kills=2, chaos_mode="mix",
                failover_budget=2, watchdog_timeout_s=0.5,
                goodput_floor=0.95)
    base.update(kw)
    return types.SimpleNamespace(**base)


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_loadgen_zero_corruption():
    """The ISSUE 12 acceptance run: 3-replica gateway under open-loop
    load with >=2 seeded mid-run replica kills (crash + hung
    dispatch). Every finished greedy stream must replay bitwise
    against a fresh reference engine, errors must stay within the
    retry-budget bound (kills <= budget ==> zero 5xx), and the
    completed fraction must clear the goodput floor — across seeds."""
    slg = _load_loadgen()
    for seed in (0, 3):
        rung = asyncio.run(slg.run_loadgen(_chaos_ns(seed=seed)))
        ch = rung["chaos"]
        assert ch["kills"] == 2
        assert ch["corrupted_streams"] == 0, ch
        assert ch["errors_5xx"] == 0, ch
        assert ch["failovers"] >= 1
        assert ch["completed_frac"] >= 0.95
        assert ch["ok"], ch
