"""paddle.autograd + paddle.distribution parity (reference:
python/paddle/autograd/, python/paddle/distribution/) — PyLayer lowers
to jax.custom_vjp; distributions check against scipy/torch moments."""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import autograd, distribution as D


class TestAutograd:
    def test_grad_of_function(self):
        g = autograd.grad(lambda x: jnp.sum(x ** 3), jnp.asarray([1.0, 2.0]))
        np.testing.assert_allclose(np.asarray(g), [3.0, 12.0], rtol=1e-6)

    def test_grad_rejects_tensor(self):
        with pytest.raises(TypeError, match="functional"):
            autograd.grad(jnp.ones(3), jnp.ones(3))

    def test_jacobian_hessian(self):
        f = lambda x: jnp.asarray([x[0] ** 2, x[0] * x[1]])  # noqa: E731
        x = jnp.asarray([2.0, 3.0])
        J = np.asarray(autograd.jacobian(f, x))
        np.testing.assert_allclose(J, [[4.0, 0.0], [3.0, 2.0]], rtol=1e-6)
        H = np.asarray(autograd.hessian(lambda x: jnp.sum(x ** 3), x))
        np.testing.assert_allclose(H, np.diag([12.0, 18.0]), rtol=1e-6)

    def test_vjp_jvp(self):
        f = lambda x: x ** 2  # noqa: E731
        x = jnp.asarray([1.0, 2.0])
        out, g = autograd.vjp(f, x, v=jnp.asarray([1.0, 1.0]))
        np.testing.assert_allclose(np.asarray(g), [2.0, 4.0], rtol=1e-6)
        out, t = autograd.jvp(f, x, v=jnp.asarray([1.0, 0.0]))
        np.testing.assert_allclose(np.asarray(t), [2.0, 0.0], rtol=1e-6)

    def test_pylayer_custom_backward(self):
        class ScaledTanh(autograd.PyLayer):
            @staticmethod
            def forward(ctx, x, k):
                y = jnp.tanh(k * x)
                ctx.save_for_backward(y, k)
                return y

            @staticmethod
            def backward(ctx, grad):
                y, k = ctx.saved_tensor()
                return grad * k * (1 - y ** 2), None  # no grad for k

        x = jnp.asarray([0.3, -0.7])
        k = jnp.asarray(2.0)
        out = ScaledTanh.apply(x, k)
        np.testing.assert_allclose(np.asarray(out), np.tanh(2 * np.asarray(x)),
                                   rtol=1e-6)
        g = jax.grad(lambda x: jnp.sum(ScaledTanh.apply(x, k)))(x)
        ref = 2 * (1 - np.tanh(2 * np.asarray(x)) ** 2)
        np.testing.assert_allclose(np.asarray(g), ref, rtol=1e-5)

    def test_pylayer_wrong_backward_is_respected(self):
        """The custom vjp REPLACES the real one (that's the point)."""
        class DoubleButClaimTriple(autograd.PyLayer):
            @staticmethod
            def forward(ctx, x):
                return 2 * x

            @staticmethod
            def backward(ctx, grad):
                return 3 * grad

        g = jax.grad(lambda x: jnp.sum(DoubleButClaimTriple.apply(x)))(
            jnp.ones(2))
        np.testing.assert_allclose(np.asarray(g), [3.0, 3.0])

    def test_pylayer_jittable(self):
        class Sq(autograd.PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x

            @staticmethod
            def backward(ctx, grad):
                (x,) = ctx.saved_tensor()
                return 2 * x * grad

        f = jax.jit(jax.grad(lambda x: jnp.sum(Sq.apply(x))))
        np.testing.assert_allclose(np.asarray(f(jnp.asarray([3.0]))), [6.0])


class TestDistributions:
    def test_normal_moments_logprob_kl(self):
        p = D.Normal(1.0, 2.0)
        q = D.Normal(0.0, 1.0)
        x = jnp.asarray([0.5, 1.0, 3.0])
        ref = -((np.asarray(x) - 1) ** 2) / 8 - math.log(2) \
            - 0.5 * math.log(2 * math.pi)
        np.testing.assert_allclose(np.asarray(p.log_prob(x)), ref, rtol=1e-5)
        kl = float(D.kl_divergence(p, q))
        ref_kl = 0.5 * (4 + 1 - 1 - math.log(4))
        np.testing.assert_allclose(kl, ref_kl, rtol=1e-5)
        s = p.sample((20000,), key=jax.random.key(0))
        assert abs(float(jnp.mean(s)) - 1.0) < 0.05
        assert abs(float(jnp.std(s)) - 2.0) < 0.05

    def test_rsample_differentiable(self):
        def f(mu):
            return jnp.mean(D.Normal(mu, 1.0).rsample((1000,),
                                                      key=jax.random.key(1)))
        g = float(jax.grad(f)(jnp.float32(0.0)))
        assert abs(g - 1.0) < 1e-4  # d mean / d mu == 1 exactly

    def test_categorical_and_bernoulli(self):
        c = D.Categorical(logits=jnp.log(jnp.asarray([0.2, 0.3, 0.5])))
        np.testing.assert_allclose(np.asarray(c.probs), [0.2, 0.3, 0.5],
                                   rtol=1e-5)
        lp = float(c.log_prob(jnp.asarray(2)))
        np.testing.assert_allclose(lp, math.log(0.5), rtol=1e-5)
        ent = float(c.entropy())
        ref = -(0.2 * math.log(0.2) + 0.3 * math.log(0.3) + 0.5 * math.log(0.5))
        np.testing.assert_allclose(ent, ref, rtol=1e-5)
        b = D.Bernoulli(0.3)
        np.testing.assert_allclose(float(b.log_prob(jnp.asarray(1.0))),
                                   math.log(0.3), rtol=1e-4)

    def test_beta_gamma_dirichlet_exponential_laplace(self):
        sp = pytest.importorskip("scipy.stats")
        x = 0.4
        np.testing.assert_allclose(
            float(D.Beta(2.0, 3.0).log_prob(jnp.asarray(x))),
            sp.beta.logpdf(x, 2, 3), rtol=1e-4)
        np.testing.assert_allclose(
            float(D.Gamma(2.0, 3.0).log_prob(jnp.asarray(x))),
            sp.gamma.logpdf(x, 2, scale=1 / 3), rtol=1e-4)
        np.testing.assert_allclose(
            float(D.Exponential(1.5).log_prob(jnp.asarray(x))),
            sp.expon.logpdf(x, scale=1 / 1.5), rtol=1e-4)
        np.testing.assert_allclose(
            float(D.Laplace(0.0, 2.0).log_prob(jnp.asarray(x))),
            sp.laplace.logpdf(x, scale=2), rtol=1e-4)
        conc = jnp.asarray([1.0, 2.0, 3.0])
        v = jnp.asarray([0.2, 0.3, 0.5])
        np.testing.assert_allclose(
            float(D.Dirichlet(conc).log_prob(v)),
            sp.dirichlet.logpdf(np.asarray(v), np.asarray(conc)), rtol=1e-4)

    def test_kl_unregistered_raises(self):
        with pytest.raises(NotImplementedError):
            D.kl_divergence(D.Normal(0, 1), D.Laplace(0, 1))

    def test_sampling_in_jit(self):
        @jax.jit
        def draw(key):
            return D.Normal(0.0, 1.0).sample((4,), key=key)
        out = draw(jax.random.key(2))
        assert out.shape == (4,)

def test_pylayer_integer_arg_nondiff():
    """None grad for an int32 arg must produce a float0 cotangent, not an
    int zeros array (custom_vjp contract)."""
    from paddle_tpu import autograd

    class Gather(autograd.PyLayer):
        @staticmethod
        def forward(ctx, x, idx):
            ctx.save_for_backward(idx, x.shape[0])
            return x[idx]

        @staticmethod
        def backward(ctx, grad):
            idx, n = ctx.saved_tensor()
            return jnp.zeros((n,) + grad.shape[1:], grad.dtype).at[idx].add(
                grad), None

    x = jnp.asarray([1.0, 2.0, 3.0])
    idx = jnp.asarray([2, 0], jnp.int32)
    g = jax.grad(lambda x: jnp.sum(Gather.apply(x, idx)))(x)
    np.testing.assert_allclose(np.asarray(g), [1.0, 0.0, 1.0])


def test_pylayer_subclass_overrides_backward():
    """A subclass overriding only backward must get its OWN vjp rule."""
    from paddle_tpu import autograd

    class Base(autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            return 2 * x

        @staticmethod
        def backward(ctx, grad):
            return 2 * grad

    class Swapped(Base):
        @staticmethod
        def backward(ctx, grad):
            return 5 * grad

    gb = jax.grad(lambda x: jnp.sum(Base.apply(x)))(jnp.ones(2))
    gs = jax.grad(lambda x: jnp.sum(Swapped.apply(x)))(jnp.ones(2))
    np.testing.assert_allclose(np.asarray(gb), [2.0, 2.0])
    np.testing.assert_allclose(np.asarray(gs), [5.0, 5.0])


def test_jacobian_batch_axis():
    from paddle_tpu import autograd
    f = lambda x: x ** 2  # noqa: E731
    x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    J = autograd.jacobian(f, x, batch_axis=0)
    assert J.shape == (2, 2, 2)  # per-sample jacobians, no cross blocks
    np.testing.assert_allclose(np.asarray(J[1]), np.diag([6.0, 8.0]))


def test_multinomial_batched_probs():
    """Advisor r4: batched probs must follow torch semantics — result is
    shape + batch + (K,), each batch lane sampling its own categorical."""
    from paddle_tpu.distribution import Multinomial
    probs = jnp.asarray([[0.9, 0.1, 0.0], [0.0, 0.1, 0.9]])
    d = Multinomial(20, probs)
    s = d.sample((5,), key=jax.random.PRNGKey(0))
    assert s.shape == (5, 2, 3)
    np.testing.assert_array_equal(np.asarray(s.sum(-1)), 20)
    # lanes draw from their OWN probs: lane 0 never emits class 2,
    # lane 1 never emits class 0
    assert float(s[:, 0, 2].max()) == 0.0
    assert float(s[:, 1, 0].max()) == 0.0
    lp = d.log_prob(s)
    assert lp.shape == (5, 2)
    assert np.all(np.isfinite(np.asarray(lp)))
    # 1-D probs unchanged: shape + (K,)
    d1 = Multinomial(7, jnp.asarray([0.5, 0.5]))
    s1 = d1.sample((3,), key=jax.random.PRNGKey(1))
    assert s1.shape == (3, 2)
    np.testing.assert_array_equal(np.asarray(s1.sum(-1)), 7)
