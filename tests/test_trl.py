"""SFT/DPO fine-tuning (C33: paddlenlp.trl parity) + chat templates."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.tokenizer import render_chat_template
from paddle_tpu.trainer import TrainingArguments
from paddle_tpu.trl import (DataCollatorForSFT, DPOTrainer, SFTTrainer,
                            compute_sequence_logps, dpo_loss, sequence_logps,
                            sft_loss)


def _model():
    pt.seed(0)
    return LlamaForCausalLM(llama_tiny())


class TestSFT:
    def test_loss_masks_prompt(self):
        rs = np.random.RandomState(0)
        logits = jnp.asarray(rs.randn(2, 8, 16), jnp.float32)
        ids = jnp.asarray(rs.randint(0, 16, (2, 8)))
        full = sft_loss(logits, ids, jnp.ones((2, 8), jnp.int32))
        # manual shifted CE mean
        lp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        want = -np.take_along_axis(np.asarray(lp),
                                   np.asarray(ids)[:, 1:, None],
                                   axis=-1).mean()
        np.testing.assert_allclose(float(full), want, rtol=1e-6)
        # masking out everything but one position isolates that token
        mask = np.zeros((2, 8), np.int32)
        mask[0, 5] = 1
        one = sft_loss(logits, ids, jnp.asarray(mask))
        want_one = -float(np.asarray(lp)[0, 4, int(ids[0, 5])])
        np.testing.assert_allclose(float(one), want_one, rtol=1e-6)

    def test_collator(self):
        coll = DataCollatorForSFT(max_length=10, pad_token_id=9)
        batch = coll([
            {"prompt_ids": [1, 2, 3], "response_ids": [4, 5]},
            {"prompt_ids": [6], "response_ids": list(range(20))},  # trunc
        ])
        ids, mask = np.asarray(batch["input_ids"]), np.asarray(batch["loss_mask"])
        assert ids.shape == (2, 10)
        np.testing.assert_array_equal(ids[0, :5], [1, 2, 3, 4, 5])
        assert (ids[0, 5:] == 9).all()
        np.testing.assert_array_equal(mask[0], [0, 0, 0, 1, 1, 0, 0, 0, 0, 0])
        assert mask[1, 0] == 0 and mask[1, 1:].all()  # prompt len 1

    def test_sft_trainer_learns_response_only(self, tmp_path):
        model = _model()
        coll = DataCollatorForSFT(max_length=16, pad_token_id=0)
        rs = np.random.RandomState(0)
        examples = [{"prompt_ids": rs.randint(1, 256, 6).tolist(),
                     "response_ids": rs.randint(1, 256, 8).tolist()}
                    for _ in range(4)]
        batch = coll(examples)
        tr = SFTTrainer(model, pt.optimizer.AdamW(learning_rate=1e-2),
                        TrainingArguments(output_dir=str(tmp_path),
                                          max_steps=15, logging_steps=5,
                                          resume_from_checkpoint=False),
                        train_dataloader=[batch])
        tr.train()
        hist = tr.logger.history["loss"]
        assert hist[-1][1] < hist[0][1]


class TestPacking:
    def test_collator_packs_and_segments(self):
        from paddle_tpu.trl import DataCollatorForSFT
        coll = DataCollatorForSFT(max_length=12, pad_token_id=0,
                                  packing=True)
        batch = coll([
            {"prompt_ids": [1, 2], "response_ids": [3, 4]},      # len 4
            {"prompt_ids": [5], "response_ids": [6, 7, 8]},      # len 4
            {"prompt_ids": [9], "response_ids": [10, 11]},       # len 3
            {"prompt_ids": [12] * 8, "response_ids": [13] * 3},  # len 11
        ])
        ids = np.asarray(batch["input_ids"])
        segs = np.asarray(batch["segment_ids"])
        mask = np.asarray(batch["loss_mask"])
        assert ids.shape[0] == 2  # 4+4+3 packed into row 0, 11 into row 1
        np.testing.assert_array_equal(
            segs[0], [1, 1, 1, 1, 2, 2, 2, 2, 3, 3, 3, 0])
        np.testing.assert_array_equal(
            mask[0], [0, 0, 1, 1, 0, 1, 1, 1, 0, 1, 1, 0])
        assert segs[1, 10] == 1 and segs[1, 11] == 0

    def test_packed_inputs_positions_and_mask(self):
        from paddle_tpu.trl import packed_sft_inputs
        seg = jnp.asarray([[1, 1, 1, 2, 2, 0]])
        pos, attn = packed_sft_inputs(seg)
        np.testing.assert_array_equal(np.asarray(pos[0]), [0, 1, 2, 0, 1, 0])
        a = np.asarray(attn[0, 0])
        assert a[1, 0] and not a[0, 1]          # causal within segment 1
        assert a[4, 3] and not a[3, 1]          # no cross-segment attention
        assert not a[5, 4] and a[5, 5]          # pad: self-only

    def test_packed_logits_match_individual_forward(self):
        """The packing correctness property: each packed example's logits
        equal its standalone forward (same positions, no leakage)."""
        from paddle_tpu.trl import packed_sft_inputs
        model = _model()
        fn, params = model.functional()
        rs = np.random.RandomState(3)
        a = rs.randint(1, 256, 5)
        b = rs.randint(1, 256, 4)
        packed = np.zeros((1, 12), np.int64)
        packed[0, :5], packed[0, 5:9] = a, b
        seg = np.zeros((1, 12), np.int64)
        seg[0, :5], seg[0, 5:9] = 1, 2
        pos, attn = packed_sft_inputs(jnp.asarray(seg))
        lp = fn(dict(params), jnp.asarray(packed), positions=pos,
                attn_mask=attn)
        la = fn(dict(params), jnp.asarray(a)[None])
        lb = fn(dict(params), jnp.asarray(b)[None])
        np.testing.assert_allclose(np.asarray(lp[0, :5]),
                                   np.asarray(la[0]), atol=2e-4)
        np.testing.assert_allclose(np.asarray(lp[0, 5:9]),
                                   np.asarray(lb[0]), atol=2e-4)

    def test_boundary_targets_dropped(self):
        """Segment k's last token must not be trained to predict segment
        k+1's first token, even when that first token's loss_mask is 1
        (mask_prompt=False)."""
        from paddle_tpu.trl import sft_loss
        rs = np.random.RandomState(5)
        logits = jnp.asarray(rs.randn(1, 6, 16), jnp.float32)
        ids = jnp.asarray(rs.randint(0, 16, (1, 6)))
        seg = jnp.asarray([[1, 1, 1, 2, 2, 0]])
        mask_all = jnp.asarray([[1, 1, 1, 1, 1, 0]])
        loss = sft_loss(logits, ids, mask_all, segment_ids=seg)
        # manual: targets at positions 1,2 (seg1) and 4 (seg2); position 3
        # (first of seg2) and 5 (pad) are dropped
        lp = jax.nn.log_softmax(np.asarray(logits[0]), axis=-1)
        want = -(lp[0, int(ids[0, 1])] + lp[1, int(ids[0, 2])]
                 + lp[3, int(ids[0, 4])]) / 3
        np.testing.assert_allclose(float(loss), want, rtol=1e-6)

    def test_pack_rows_static_shape(self):
        from paddle_tpu.trl import DataCollatorForSFT
        coll = DataCollatorForSFT(max_length=8, packing=True, pack_rows=3)
        small = [{"prompt_ids": [1], "response_ids": [2, 3]}]
        big = small * 5
        assert coll(small)["input_ids"].shape == (3, 8)
        assert coll(big)["input_ids"].shape == (3, 8)
        with pytest.raises(ValueError, match="pack_rows"):
            coll(small * 12)

    def test_packed_fallback_for_models_without_segment_ids(self, tmp_path):
        """Models whose forward lacks segment_ids (GPT) take the explicit
        block-causal-mask fallback; packing still trains."""
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny
        from paddle_tpu.trl import DataCollatorForSFT
        pt.seed(0)
        model = GPTForCausalLM(gpt_tiny())
        rs = np.random.RandomState(6)
        coll = DataCollatorForSFT(max_length=24, packing=True)
        batch = coll([{"prompt_ids": rs.randint(1, 256, 4).tolist(),
                       "response_ids": rs.randint(1, 256, 6).tolist()}
                      for _ in range(4)])
        tr = SFTTrainer(model, pt.optimizer.AdamW(learning_rate=1e-2),
                        TrainingArguments(output_dir=str(tmp_path),
                                          max_steps=8, logging_steps=4,
                                          resume_from_checkpoint=False),
                        train_dataloader=[batch])
        tr.train()
        hist = tr.logger.history["loss"]
        assert hist[-1][1] < hist[0][1]

    def test_sft_trainer_packed_learns(self, tmp_path):
        from paddle_tpu.trl import DataCollatorForSFT
        model = _model()
        rs = np.random.RandomState(4)
        coll = DataCollatorForSFT(max_length=24, packing=True)
        batch = coll([{"prompt_ids": rs.randint(1, 256, 4).tolist(),
                       "response_ids": rs.randint(1, 256, 6).tolist()}
                      for _ in range(6)])
        tr = SFTTrainer(model, pt.optimizer.AdamW(learning_rate=1e-2),
                        TrainingArguments(output_dir=str(tmp_path),
                                          max_steps=12, logging_steps=4,
                                          resume_from_checkpoint=False),
                        train_dataloader=[batch])
        tr.train()
        hist = tr.logger.history["loss"]
        assert hist[-1][1] < hist[0][1]


class TestDPO:
    def test_dpo_loss_neutral_point(self):
        z = jnp.zeros((4,))
        loss, cr, rr = dpo_loss(z, z, z, z, beta=0.1)
        np.testing.assert_allclose(float(loss), np.log(2.0), rtol=1e-6)
        # improving chosen relative to reference lowers the loss
        better, _, _ = dpo_loss(z + 1.0, z, z, z, beta=0.1)
        assert float(better) < float(loss)

    def test_sequence_logps_and_precompute(self):
        model = _model()
        rs = np.random.RandomState(1)
        ids = jnp.asarray(rs.randint(0, 256, (3, 12)))
        mask = jnp.asarray((rs.rand(3, 12) > 0.3).astype(np.int32))
        fn, params = model.functional()
        direct = sequence_logps(fn(dict(params), ids), ids, mask)
        pre = compute_sequence_logps(model, ids, mask, batch_size=2)
        np.testing.assert_allclose(np.asarray(direct), np.asarray(pre),
                                   rtol=1e-5)
        assert (np.asarray(direct) <= 0).all()

    def test_dpo_trainer_improves_preference(self, tmp_path):
        model = _model()
        rs = np.random.RandomState(2)
        chosen = jnp.asarray(rs.randint(1, 256, (4, 12)))
        rejected = jnp.asarray(rs.randint(1, 256, (4, 12)))
        mask = jnp.ones((4, 12), jnp.int32)
        ref_c = compute_sequence_logps(model, chosen, mask)
        ref_r = compute_sequence_logps(model, rejected, mask)
        batch = {"chosen_ids": chosen, "chosen_mask": mask,
                 "rejected_ids": rejected, "rejected_mask": mask,
                 "ref_chosen_logps": ref_c, "ref_rejected_logps": ref_r}
        tr = DPOTrainer(model, pt.optimizer.AdamW(learning_rate=5e-3),
                        TrainingArguments(output_dir=str(tmp_path),
                                          max_steps=10, logging_steps=5,
                                          resume_from_checkpoint=False),
                        beta=0.1, train_dataloader=[batch])
        tr.train()
        hist = tr.logger.history["loss"]
        assert hist[0][1] <= np.log(2.0) + 0.2
        assert hist[-1][1] < hist[0][1]
        # post-training: the policy now prefers chosen over rejected
        fn, params = model.functional()
        pc = sequence_logps(fn(dict(params), chosen), chosen, mask)
        pr = sequence_logps(fn(dict(params), rejected), rejected, mask)
        margin = float((pc - ref_c).mean() - (pr - ref_r).mean())
        assert margin > 0, margin


class TestChatTemplates:
    MSGS = [{"role": "system", "content": "be brief"},
            {"role": "user", "content": "hi"}]

    def test_llama3(self):
        s = render_chat_template(self.MSGS, "llama3")
        assert s.startswith("<|begin_of_text|>")
        assert "<|start_header_id|>system<|end_header_id|>\n\nbe brief" in s
        assert s.endswith("<|start_header_id|>assistant<|end_header_id|>\n\n")

    def test_chatml_qwen(self):
        s = render_chat_template(self.MSGS, "qwen2",
                                 add_generation_prompt=False)
        assert s == ("<|im_start|>system\nbe brief<|im_end|>\n"
                     "<|im_start|>user\nhi<|im_end|>\n")

    def test_unknown_template_and_bad_message(self):
        with pytest.raises(KeyError, match="unknown chat template"):
            render_chat_template(self.MSGS, "nope")
        with pytest.raises(ValueError, match="role"):
            render_chat_template([{"content": "x"}], "llama3")

    def test_apply_with_tokenizer(self):
        from paddle_tpu.tokenizer import apply_chat_template

        class Tok:
            def encode(self, text):
                return [ord(c) % 97 for c in text[:5]]

        out = apply_chat_template(Tok(), self.MSGS, "chatml")
        assert len(out) == 5
