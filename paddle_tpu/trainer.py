"""Trainer (reference: PaddleNLP paddlenlp/trainer/trainer.py — the
train loop with gradient accumulation, hybrid-parallel awareness, AMP,
checkpointing/auto-resume, callbacks, and eval).

TPU-native: ONE jitted train step (loss -> grads -> clip -> optimizer)
with donated (params, opt_state) so the update is in-place in HBM.
Gradient accumulation folds into the same program via `lax.scan` over the
microbatch dim — not N python-side steps. Hybrid parallelism is ambient:
if a mesh is installed, params are sharded by their partition metadata
(fleet.distributed_model) and the step compiles to SPMD; the loop itself
is identical single-chip vs pod. Aux wiring: JSONL metrics (C21), NaN
watchdog (C20), orbax auto-resume (C14)."""
from __future__ import annotations

import functools
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .models.llama import causal_lm_loss
from .nn.layer import Layer
from .optimizer.optimizers import Optimizer
from .utils.logging import LogWriter
from .utils.watchdog import StepWatchdog


@dataclass
class TrainingArguments:
    """Reference: paddlenlp.trainer.TrainingArguments (subset that matters)."""
    output_dir: str = "output"
    max_steps: int = 1000
    gradient_accumulation_steps: int = 1
    logging_steps: int = 10
    save_steps: int = 0              # 0 = no periodic ckpt
    eval_steps: int = 0
    resume_from_checkpoint: bool = True
    max_grad_norm: float = 1.0
    seed: int = 42
    nan_patience: int = 3
    donate_state: bool = True


class TrainerCallback:
    def on_step_end(self, step: int, logs: Dict[str, float]):  # noqa: D401
        pass

    def on_save(self, step: int):
        pass

    def on_train_end(self, step: int):
        pass


class Trainer:
    def __init__(self, model: Layer, optimizer: Optimizer,
                 args: Optional[TrainingArguments] = None,
                 loss_fn: Optional[Callable] = None,
                 train_dataloader: Optional[Iterable] = None,
                 eval_dataloader: Optional[Iterable] = None,
                 callbacks: Optional[List[TrainerCallback]] = None):
        self.model = model
        self.optimizer = optimizer
        self.args = args or TrainingArguments()
        # loss_fn(pure_fn, params, batch) -> scalar; default: causal LM on
        # a batch of token ids (the flagship recipe)
        self.loss_fn = loss_fn or (
            lambda fn, p, batch: causal_lm_loss(fn(p, batch), batch))
        self.train_dataloader = train_dataloader
        self.eval_dataloader = eval_dataloader
        self.callbacks = callbacks or []
        self.logger = LogWriter(os.path.join(self.args.output_dir, "runs"))
        self.watchdog = StepWatchdog(nan_patience=self.args.nan_patience)
        self._pure_fn, self._params = model.functional()
        self._opt_state = None
        self._step_fn = None
        self.global_step = 0

    # ------------------------------------------------------------ jit step
    def _build_step(self):
        fn, opt, args = self._pure_fn, self.optimizer, self.args
        accum = args.gradient_accumulation_steps

        def loss_of(p, batch):
            return self.loss_fn(fn, p, batch)

        if accum == 1:
            def step(params, state, stepno, batch):
                loss, grads = jax.value_and_grad(loss_of)(params, batch)
                params, state = opt.apply(params, grads, state, stepno)
                return params, state, loss
        else:
            def step(params, state, stepno, batch):
                # batch leading dim = accum: scan microbatches, mean grads
                def micro(carry, mb):
                    gsum, lsum = carry
                    loss, g = jax.value_and_grad(loss_of)(params, mb)
                    gsum = jax.tree.map(jnp.add, gsum, g)
                    return (gsum, lsum + loss), None
                zeros = jax.tree.map(jnp.zeros_like, params)
                (gsum, lsum), _ = jax.lax.scan(micro, (zeros, 0.0), batch)
                grads = jax.tree.map(lambda g: g / accum, gsum)
                params, state = opt.apply(params, grads, state, stepno)
                return params, state, lsum / accum

        donate = (0, 1) if args.donate_state else ()
        return jax.jit(step, donate_argnums=donate)

    # ------------------------------------------------------------- train
    def train(self, max_steps: Optional[int] = None):
        args = self.args
        max_steps = max_steps or args.max_steps
        if self._opt_state is None:
            self._opt_state = self.optimizer.init(self._params)
        if args.resume_from_checkpoint and args.save_steps:
            self._try_resume()
        if self._step_fn is None:
            self._step_fn = self._build_step()

        assert self.train_dataloader is not None, "pass train_dataloader"
        data = iter(self.train_dataloader)
        t_last = time.perf_counter()
        while self.global_step < max_steps:
            try:
                batch = next(data)
            except StopIteration:
                data = iter(self.train_dataloader)
                batch = next(data)
            batch = self._prep_batch(batch)
            self._params, self._opt_state, loss = self._step_fn(
                self._params, self._opt_state, jnp.int32(self.global_step),
                batch)
            self.global_step += 1
            if self.global_step % args.logging_steps == 0 or \
                    self.global_step == max_steps:
                loss_val = float(loss)
                self.watchdog.check_loss(loss_val, self.global_step)
                now = time.perf_counter()
                logs = {"loss": loss_val,
                        "steps_per_sec": args.logging_steps / (now - t_last)}
                t_last = now
                self.logger.add_scalars(logs, self.global_step)
                for cb in self.callbacks:
                    cb.on_step_end(self.global_step, logs)
            if args.save_steps and self.global_step % args.save_steps == 0:
                self.save_checkpoint()
            if args.eval_steps and self.eval_dataloader is not None and \
                    self.global_step % args.eval_steps == 0:
                self.evaluate()
        for cb in self.callbacks:
            cb.on_train_end(self.global_step)
        # leave the module tree holding the trained weights
        self.model.bind(self._params)
        return self

    def _prep_batch(self, batch):
        accum = self.args.gradient_accumulation_steps
        if accum > 1 and hasattr(batch, "shape"):
            b = batch.shape[0]
            assert b % accum == 0, f"batch {b} % accum {accum} != 0"
            batch = batch.reshape((accum, b // accum) + batch.shape[1:])
        return batch

    # ------------------------------------------------------------- eval
    def evaluate(self) -> float:
        assert self.eval_dataloader is not None
        fn = self._pure_fn
        losses = []
        eval_loss = jax.jit(lambda p, b: self.loss_fn(fn, p, b))
        for batch in self.eval_dataloader:
            losses.append(float(eval_loss(self._params, batch)))
        mean = float(np.mean(losses)) if losses else float("nan")
        self.logger.add_scalar("eval_loss", mean, self.global_step)
        return mean

    # --------------------------------------------------------- checkpoint
    def _ckpt_dir(self):
        return os.path.join(self.args.output_dir, "checkpoints")

    def save_checkpoint(self, wait: bool = False):
        from .checkpoint.distributed_ckpt import DistributedCheckpoint
        ckpt = DistributedCheckpoint(self._ckpt_dir())
        ckpt.save(self.global_step,
                  {"params": dict(self._params),
                   "opt_state": self._opt_state}, wait=wait)
        ckpt.wait_until_finished() if wait else None
        ckpt.close()
        for cb in self.callbacks:
            cb.on_save(self.global_step)

    def _try_resume(self):
        from .checkpoint.distributed_ckpt import DistributedCheckpoint
        if not os.path.isdir(self._ckpt_dir()):
            return
        ckpt = DistributedCheckpoint(self._ckpt_dir())
        step = ckpt.latest_complete_step()
        if step is not None:
            restored = ckpt.restore(step, like={
                "params": dict(self._params), "opt_state": self._opt_state})
            self._params = restored["params"]
            self._opt_state = restored["opt_state"]
            self.global_step = step
        ckpt.close()
