"""Serving-time projection fusion (reference: PaddleNLP's
``fuse_attention_qkv`` / ``fuse_attention_ffn`` flags on the Llama
family).

Decode is HBM-bound: each token step reads every weight matrix once, and
launching q/k/v (and gate/up) as separate small matmuls leaves MXU tiles
idle while XLA cannot always merge them horizontally. ``fuse_projections``
rewrites a loaded model IN PLACE — concat the q/k/v weights into one
``[h, (nh + 2*kvh) * d]`` matmul and gate/up into one ``[h, 2*ffn]`` —
the attention/MLP forwards detect the fused module and split the single
product.

Apply AFTER from_pretrained / checkpoint load (the pass consumes the
unfused weights), like the quantization pass. Single-chip / replicated
serving only: the fused column order is not tp-head-aligned, so under a
tp mesh keep the unfused layout.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..parallel.layers import ColumnParallelLinear

__all__ = ["fuse_projections"]


def _fuse_linears(mods, has_bias: bool):
    """Concat N same-input ColumnParallelLinear along the out dim."""
    from . import initializer as I
    w = jnp.concatenate([m.weight for m in mods], axis=1)
    # Constant init: no random matrix materialized, no global RNG key
    # consumed — the fused weight overwrites it immediately
    fused = ColumnParallelLinear(w.shape[0], w.shape[1],
                                 weight_attr=I.Constant(0.0),
                                 has_bias=has_bias, gather_output=False)
    fused.weight = w
    if has_bias:
        fused.bias = jnp.concatenate([m.bias for m in mods])
    return fused


def fuse_projections(model, attention: bool = True, mlp: bool = True):
    """Fuse q/k/v (and gate/up) projections of every Llama-family block
    of ``model`` in place; returns the model. Idempotent."""
    for layer in getattr(model, "model", model).layers:
        attn = getattr(layer, "self_attn", None)
        if attention and attn is not None and \
                hasattr(attn, "q_proj") and not hasattr(attn, "qkv_proj"):
            has_bias = attn.q_proj.bias is not None
            attn.qkv_proj = _fuse_linears(
                [attn.q_proj, attn.k_proj, attn.v_proj], has_bias)
            del attn.q_proj, attn.k_proj, attn.v_proj
        mlp_mod = getattr(layer, "mlp", None)
        if mlp and mlp_mod is not None and \
                hasattr(mlp_mod, "gate_proj") and \
                not hasattr(mlp_mod, "gate_up_proj"):
            mlp_mod.gate_up_proj = _fuse_linears(
                [mlp_mod.gate_proj, mlp_mod.up_proj], has_bias=False)
            del mlp_mod.gate_proj, mlp_mod.up_proj
    return model
