"""Transformer layers (reference: python/paddle/nn/layer/transformer.py).
MultiHeadAttention keeps paddle's [batch, seq, heads, dim] internal layout
and dispatches through F.scaled_dot_product_attention → Pallas flash kernel
on TPU."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..utils.rng import next_key
from . import functional as F
from .common import Dropout, Linear
from .container import LayerList
from .layer import Layer
from .norm import LayerNorm


class MultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None, name=None):
        super().__init__(name)
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        b, sq, _ = query.shape
        q = self.q_proj(query).reshape(b, sq, self.num_heads, self.head_dim)
        k = self.k_proj(key).reshape(b, key.shape[1], self.num_heads, self.head_dim)
        v = self.v_proj(value).reshape(b, value.shape[1], self.num_heads, self.head_dim)
        if cache is not None:
            k = jnp.concatenate([cache[0], k], axis=1)
            v = jnp.concatenate([cache[1], v], axis=1)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.dropout if self.training else 0.0,
            dropout_key=next_key() if (self.training and self.dropout > 0) else None)
        out = out.reshape(b, sq, self.embed_dim)
        out = self.out_proj(out)
        if cache is not None:
            return out, (k, v)
        return out


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, name=None):
        super().__init__(name)
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead,
                                            dropout=attn_dropout if attn_dropout is not None else dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout if act_dropout is not None else dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None):
        residual = src
        x = self.norm1(src) if self.normalize_before else src
        x = self.self_attn(x, attn_mask=src_mask)
        x = residual + self.dropout1(x)
        if not self.normalize_before:
            x = self.norm1(x)
        residual = x
        y = self.norm2(x) if self.normalize_before else x
        y = self.linear2(self.act_dropout(self.activation(self.linear1(y))))
        y = residual + self.dropout2(y)
        if not self.normalize_before:
            y = self.norm2(y)
        return y


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer_fn, num_layers, norm=None):
        super().__init__()
        if isinstance(encoder_layer_fn, Layer):
            import copy
            layers = [encoder_layer_fn] + [copy.deepcopy(encoder_layer_fn)
                                           for _ in range(num_layers - 1)]
        else:
            layers = [encoder_layer_fn() for _ in range(num_layers)]
        self.layers = LayerList(layers)
        self.norm = norm

    def forward(self, src, src_mask=None):
        x = src
        for layer in self.layers:
            x = layer(x, src_mask=src_mask)
        if self.norm is not None:
            x = self.norm(x)
        return x


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", normalize_before=False, name=None):
        super().__init__(name)
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=dropout)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None):
        residual = tgt
        x = self.norm1(tgt) if self.normalize_before else tgt
        x = residual + self.dropout1(self.self_attn(x, attn_mask=tgt_mask))
        if not self.normalize_before:
            x = self.norm1(x)
        residual = x
        y = self.norm2(x) if self.normalize_before else x
        y = residual + self.dropout2(self.cross_attn(y, memory, memory, attn_mask=memory_mask))
        if not self.normalize_before:
            y = self.norm2(y)
        residual = y
        z = self.norm3(y) if self.normalize_before else y
        z = residual + self.dropout3(self.linear2(self.activation(self.linear1(z))))
        if not self.normalize_before:
            z = self.norm3(z)
        return z


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer_fn, num_layers, norm=None):
        super().__init__()
        if isinstance(decoder_layer_fn, Layer):
            import copy
            layers = [decoder_layer_fn] + [copy.deepcopy(decoder_layer_fn)
                                           for _ in range(num_layers - 1)]
        else:
            layers = [decoder_layer_fn() for _ in range(num_layers)]
        self.layers = LayerList(layers)
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None):
        x = tgt
        for layer in self.layers:
            x = layer(x, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)
        if self.norm is not None:
            x = self.norm(x)
        return x


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", normalize_before=False):
        super().__init__()
        self.encoder = TransformerEncoder(
            lambda: TransformerEncoderLayer(d_model, nhead, dim_feedforward,
                                            dropout, activation,
                                            normalize_before=normalize_before),
            num_encoder_layers, LayerNorm(d_model) if normalize_before else None)
        self.decoder = TransformerDecoder(
            lambda: TransformerDecoderLayer(d_model, nhead, dim_feedforward,
                                            dropout, activation, normalize_before),
            num_decoder_layers, LayerNorm(d_model) if normalize_before else None)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)
