"""Pipeline parallelism (reference: fleet.meta_parallel.PipelineLayer +
pp_utils: 1F1B interleaved schedule, NCCL p2p send/recv between stage
ranks).

TPU-native: SPMD pipelining inside `shard_map` over the ``pp`` axis.
Stage weights are *stacked* on a leading [pp] dim (each device holds its
stage's slice); activations hand off between neighbors with `lax.ppermute`
(ICI p2p). The schedule is a static `lax.scan` over
``n_micro + n_stages - 1`` ticks: at tick t, stage s computes microbatch
``t - s`` (classic GPipe fill/drain). Because ppermute and scan are
differentiable, `jax.grad` of the pipelined forward *is* the reverse-order
pipeline — the 1F1B backward emerges from autodiff + XLA scheduling rather
than a hand-maintained schedule.

The GSPMD-only fallback (no shard_map) is simply running the stacked-stage
scan with the stage dim sharded over pp — XLA overlaps stages across
microbatches the same way.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..distributed.env import get_mesh


def spmd_pipeline(stage_fn: Callable, axis_name: str = "pp"):
    """Wrap `stage_fn(stage_params, x) -> y` into a pipelined
    `fn(stacked_params, microbatches) -> outputs` to be called INSIDE
    shard_map with in_specs P('pp') for params (leading stacked dim) and
    replicated microbatches [n_micro, mb, ...].

    Within shard_map each device sees stage_params with leading dim 1.
    """

    def pipelined(stacked_params, microbatches):
        n_stages = lax.axis_size(axis_name)
        stage = lax.axis_index(axis_name)
        n_micro = microbatches.shape[0]
        params = jax.tree.map(lambda p: p[0], stacked_params)  # my stage
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        ticks = n_micro + n_stages - 1

        out_shape = jax.eval_shape(stage_fn, params, microbatches[0])
        outputs0 = jnp.zeros((n_micro,) + out_shape.shape, out_shape.dtype)

        def tick(carry, t):
            recv, outputs = carry
            # stage 0 pulls microbatch t from the feed; others use recv
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            x_in = jnp.where(stage == 0,
                             microbatches[mb_idx].astype(recv.dtype), recv)
            y = stage_fn(params, x_in)
            # mask ticks where this stage has no live microbatch
            my_mb = t - stage
            live = (my_mb >= 0) & (my_mb < n_micro)
            y = jnp.where(live, y, jnp.zeros_like(y))
            # last stage records its finished microbatch
            write_idx = jnp.clip(my_mb, 0, n_micro - 1)
            is_last = stage == n_stages - 1
            outputs = lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(live & is_last, y,
                          lax.dynamic_index_in_dim(outputs, write_idx, 0,
                                                   keepdims=False)),
                write_idx, 0)
            recv = lax.ppermute(y, axis_name, perm)
            return (recv, outputs), None

        recv0 = jnp.zeros(out_shape.shape, out_shape.dtype)
        (_, outputs), _ = lax.scan(tick, (recv0, outputs0), jnp.arange(ticks))
        # only the last stage holds real outputs; broadcast them ringwise
        outputs = lax.psum(
            jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
        return outputs

    return pipelined


def pipeline_apply(stage_fn: Callable, stacked_params, microbatches,
                   axis_name: str = "pp", mesh=None):
    """Run the pipelined computation over the global mesh.

    stacked_params: pytree with leading dim n_stages (sharded over pp).
    microbatches: [n_micro, micro_batch, ...] (replicated).
    Requires stage_fn's output shape == its input shape (transformer blocks).
    """
    mesh = mesh or get_mesh()
    fn = spmd_pipeline(stage_fn, axis_name)
    return jax.shard_map(
        fn, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis_name), stacked_params), P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, microbatches)


def stack_stage_params(per_stage_params: list):
    """[{name: Array}, ...] per stage -> {name: Array[n_stages, ...]}."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)
