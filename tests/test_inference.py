"""Predictor serving layer (VERDICT r2 weak#7): shape bucketing bounds
engine compiles with exact results; the micro-batching policy coalesces
concurrent requests into one engine call per bucket."""
from concurrent.futures import wait

import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.inference import BatchingPredictor, Config, Predictor


def _model():
    pt.seed(0)
    return nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))


def test_bucketing_exact_and_bounded_compiles():
    model = _model()
    traces = [0]
    fn = model.functional()[0]

    def counting_fn(p, x):
        traces[0] += 1
        return fn(p, x)

    pred = Predictor(model)
    pred._fn = counting_fn
    import jax
    pred._engine = jax.jit(counting_fn)

    rs = np.random.RandomState(0)
    ref_engine = jax.jit(fn)
    for b in (1, 2, 3, 4, 5, 7, 8, 6, 3, 2):
        x = rs.randn(b, 16).astype(np.float32)
        out = pred.run(x)
        assert out.shape == (b, 4)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref_engine(pred._params,
                                                   jnp.asarray(x))),
            rtol=1e-6)
    # buckets hit: 1, 2, 4, 8 -> exactly 4 traces for 10 batch sizes
    assert traces[0] == 4, traces[0]


def test_bucketing_disabled_traces_every_shape():
    model = _model()
    pred = Predictor(model, Config().set_batch_buckets(None))
    for b in (1, 3, 5):
        assert pred.run(np.zeros((b, 16), np.float32)).shape == (b, 4)


def test_batching_predictor_coalesces_and_answers_each():
    model = _model()
    bp = BatchingPredictor(model, max_batch=8, max_delay_ms=20)
    try:
        rs = np.random.RandomState(1)
        xs = [rs.randn(16).astype(np.float32) for _ in range(12)]
        futs = [bp.submit(x) for x in xs]
        wait(futs, timeout=60)
        ref = Predictor(model)
        for x, f in zip(xs, futs):
            got = np.asarray(f.result())
            want = np.asarray(ref.run(x[None]))[0]
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    finally:
        bp.close()


def test_batching_predictor_propagates_errors():
    model = _model()
    bp = BatchingPredictor(model, max_batch=4, max_delay_ms=1)
    try:
        fut = bp.submit(np.zeros((99,), np.float32))  # wrong feature dim
        err = fut.exception(timeout=30)
        assert err is not None
    finally:
        bp.close()