"""paddle.autograd parity (reference: python/paddle/autograd/ — grad,
functional jacobian/hessian/vjp/jvp, and PyLayer custom ops).

TPU-native: autograd IS jax's functional transforms, so these are thin
adapters with paddle's calling conventions. ``PyLayer`` (the custom
forward/backward op API) maps onto ``jax.custom_vjp`` — the backward you
write is the VJP rule XLA differentiates through.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp

__all__ = ["grad", "jacobian", "hessian", "vjp", "jvp", "PyLayer",
           "no_grad"]


def grad(outputs, inputs, grad_outputs=None, create_graph=False,
         retain_graph=None, allow_unused=False):
    """Differentiate ``outputs = fn(inputs)`` the paddle way is not
    expressible without the graph; the functional form is
    ``grad(fn)(inputs)``. This adapter accepts a CALLABLE as ``outputs``
    (the idiomatic migration: pass the fn, not a traced tensor) and
    returns gradients w.r.t. ``inputs``."""
    if not callable(outputs):
        raise TypeError(
            "paddle_tpu.autograd.grad takes the loss FUNCTION, not a "
            "tensor: autograd here is functional (jax). Migrate "
            "`paddle.grad(loss, xs)` to `autograd.grad(loss_fn, xs)`.")
    fn = outputs
    single = not isinstance(inputs, (tuple, list))
    xs = (inputs,) if single else tuple(inputs)
    if grad_outputs is None:
        g = jax.grad(lambda *a: jnp.sum(fn(*a)), argnums=tuple(range(len(xs))))(*xs)
    else:
        out, pull = jax.vjp(fn, *xs)
        if (isinstance(grad_outputs, (list, tuple))
                and len(grad_outputs) == 1
                and not isinstance(out, (list, tuple))):
            grad_outputs = grad_outputs[0]  # paddle's [g] for single output
        g = pull(grad_outputs)
    return g[0] if single else list(g)


def jacobian(func: Callable, xs, batch_axis: Optional[int] = None):
    """paddle.autograd.jacobian: reverse-mode rows (jacrev). With
    ``batch_axis``, per-sample jacobians via vmap (no cross-batch
    zero blocks)."""
    if batch_axis is not None:
        if isinstance(xs, (tuple, list)):
            raise NotImplementedError(
                "batch_axis with multiple inputs is not supported")
        return jax.vmap(jax.jacrev(func), in_axes=batch_axis)(xs)
    if not isinstance(xs, (tuple, list)):
        return jax.jacrev(func)(xs)
    args = tuple(xs)
    return list(jax.jacrev(func, argnums=tuple(range(len(args))))(*args))


def hessian(func: Callable, xs, batch_axis: Optional[int] = None):
    if batch_axis is not None:
        if isinstance(xs, (tuple, list)):
            raise NotImplementedError(
                "batch_axis with multiple inputs is not supported")
        return jax.vmap(jax.hessian(func), in_axes=batch_axis)(xs)
    if not isinstance(xs, (tuple, list)):
        return jax.hessian(func)(xs)
    args = tuple(xs)
    return list(jax.hessian(func, argnums=tuple(range(len(args))))(*args))


def vjp(func: Callable, xs, v=None):
    """(outputs, vjp_result) — paddle.incubate.autograd.vjp signature."""
    single = not isinstance(xs, (tuple, list))
    args = (xs,) if single else tuple(xs)
    out, pull = jax.vjp(func, *args)
    if v is None:
        v = jax.tree.map(jnp.ones_like, out)
    g = pull(v)
    return out, (g[0] if single else list(g))


def jvp(func: Callable, xs, v=None):
    single = not isinstance(xs, (tuple, list))
    args = (xs,) if single else tuple(xs)
    if v is None:
        tangents = jax.tree.map(jnp.ones_like, args)
    else:
        tangents = (v,) if single else tuple(v)
    out, t = jax.jvp(func, args, tangents)
    return out, t


class _PyLayerContext:
    """ctx object passed to forward/backward (save_for_backward parity)."""

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        return self._saved


class PyLayerMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)
        # rebuild the op whenever the class (re)defines forward OR
        # backward: a subclass overriding only backward must not silently
        # keep the parent's vjp rule
        if name == "PyLayer" or not (
                ("forward" in ns or "backward" in ns)
                and hasattr(cls, "forward") and hasattr(cls, "backward")):
            return

        @jax.custom_vjp
        def op(*args):
            ctx = _PyLayerContext()
            return cls.forward(ctx, *args)

        def fwd(*args):
            ctx = _PyLayerContext()
            out = cls.forward(ctx, *args)
            return out, (ctx._saved, args)

        def bwd(res, g):
            import numpy as _np
            ctx = _PyLayerContext()
            ctx._saved = res[0]
            grads = cls.backward(ctx, g)
            if not isinstance(grads, tuple):
                grads = (grads,)
            # pad Nones (non-differentiable args): float args get zeros,
            # integer args need float0 cotangents (custom_vjp contract)
            args = res[1]
            full = []
            for i, a in enumerate(args):
                gi = grads[i] if i < len(grads) else None
                if gi is None:
                    if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) \
                            or jnp.issubdtype(jnp.asarray(a).dtype,
                                              jnp.complexfloating):
                        gi = jnp.zeros_like(a)
                    else:
                        gi = _np.zeros(jnp.shape(a), jax.dtypes.float0)
                full.append(gi)
            return tuple(full)

        op.defvjp(fwd, bwd)
        cls._op = op


class PyLayer(metaclass=PyLayerMeta):
    """Custom op with hand-written backward (reference:
    paddle.autograd.PyLayer). Subclass with @staticmethod forward(ctx, *x)
    and backward(ctx, grad); call via ``MyOp.apply(*x)``. Lowers to
    ``jax.custom_vjp`` — fully jittable and composable with the rest of
    the autograd stack."""

    @classmethod
    def apply(cls, *args):
        return cls._op(*args)


class no_grad:
    """Context/decorator parity: gradients only flow through jax.grad
    traces, so eager code is already grad-free; this is a no-op marker."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __call__(self, fn):
        return fn
