"""ISSUE 7: speculative multi-token ticks — prompt-lookup decoding
inside the PagedEngine fused tick.

Contracts, each against an independent reference:

- STREAM EXACTNESS: a ``spec_tokens=k`` engine must emit the SAME
  streams as the spec-off fused tick. On the lookup stub (logits are a
  pure per-token table read, so the verify's query count cannot
  perturb them) that is pinned BITWISE — tokens AND logprobs — across
  eos / stop-string / budget landing mid-accepted-window, mixed
  spec/sampled/penalized slots, and mid-stream submits. On the real
  tiny llama, verify (q_len=k+1) vs decode (q_len=1) forwards differ
  by float epsilon (pre-existing; documented in test_speculative.py),
  so tokens are pinned exactly on decisive logits and logprobs to
  tight tolerance.
- DISPATCH: spec ticks keep the ISSUE 6 steady-state contract — one
  compiled dispatch, zero host->device mirror uploads — while
  committing MULTIPLE tokens per dispatch on repetitive streams.
- FALLBACK: rows without block headroom, with collapsed accept EMA,
  sampled, or penalized decode 1 token per tick inside the same
  program, with the stream unchanged.
- KERNEL: the ragged kernel's multi-query rows (per-position causal
  masking within a row) match the dense per-position reference.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.generation.paged import (PagedEngine, PagedKV,
                                         paged_chunk_attention,
                                         paged_decode_attention,
                                         paged_decode_write,
                                         paged_prefill_write)
from paddle_tpu.generation.prompt_lookup import (accept_length,
                                                 propose_ngram,
                                                 propose_ngram_rows)
from paddle_tpu.models import LlamaForCausalLM
from paddle_tpu.models.llama import llama_tiny


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    m = LlamaForCausalLM(llama_tiny())
    # decisive logits (see test_speculative.py): verify vs decode
    # forwards differ by float epsilon; widening every argmax gap 10x
    # keeps token exactness off the seed lottery
    m.lm_head.weight = m.lm_head.weight * 10.0
    return m


# --------------------------------------------------------------- lookup stub
class _StubCfg:
    vocab_size = 64
    num_hidden_layers = 1
    num_key_value_heads = 1
    head_dim = 8
    dtype = jnp.float32


class LookupStub:
    """CausalLM-contract stub whose logits are a pure per-token TABLE
    READ: token t deterministically argmaxes to (t+1) % period with an
    8.0 margin. The paged cache write + attention still run every call
    (so dispatch/upload counters measure the real tick machinery), but
    their output joins the logits with weight 0.0 — logits are
    bitwise-independent of the query count, making fused-spec vs
    spec-off streams comparable BITWISE, logprobs included.

    ``period`` small -> the greedy stream cycles and prompt-lookup
    accepts nearly every draft; period >= prompt+budget -> the stream
    never repeats an n-gram and acceptance is structurally zero."""

    config = _StubCfg()

    def __init__(self, period=7):
        self.period = period

    def functional(self):
        d, V = self.config.head_dim, self.config.vocab_size
        key = jax.random.PRNGKey(0)
        emb = jax.random.normal(key, (V, d))
        table = jax.nn.one_hot((jnp.arange(V) + 1) % self.period,
                               V) * 8.0
        params = dict(emb=emb, table=table)

        def fn(params, tokens, kv_caches=None, positions=None,
               paged_chunk=False, paged_decode=False):
            x = params["emb"][tokens]              # [R, s, d]
            kv = x[:, :, None, :]
            pk = kv_caches[0]
            if tokens.shape[1] == 1 or paged_decode:
                pk = paged_decode_write(pk, kv, kv)
                o = paged_decode_attention(x[:, :, None, :], pk)[:, :, 0]
            else:
                pk = paged_prefill_write(
                    pk, kv, kv,
                    positions=positions[0] if paged_chunk else None)
                o = paged_chunk_attention(x[:, :, None, :], pk,
                                          positions)[:, :, 0]
            logits = params["table"][tokens] \
                + 0.0 * jnp.sum(o, axis=-1, keepdims=True)
            return logits, [pk]

        return fn, params


def _stub_engine(period=7, **kw):
    base = dict(max_slots=4, num_blocks=64, block_size=64,
                max_blocks_per_seq=4, prefill_buckets=(16,))
    base.update(kw)
    return PagedEngine(LookupStub(period), **base)


def _drain(eng, submits):
    for rid, ids, kw in submits:
        eng.submit(rid, ids, **kw)
    res = eng.run()
    return res, dict(eng.logprobs)


def _cyc(n, start=1, period=7):
    return np.asarray([[(start + i) % period for i in range(n)]])


# --------------------------------------------------- stream bit-identity
class TestSpecStreamBitIdentity:
    def test_greedy_bit_identical_and_fewer_forwards(self):
        """THE tentpole pin: fused-spec tokens AND logprobs equal the
        spec-off fused tick bitwise, while repetitive streams commit
        multiple tokens per forward (fewer decode dispatches)."""
        subs = [
            ("a", _cyc(6), dict(max_new_tokens=30)),
            ("b", _cyc(9, start=3), dict(max_new_tokens=25)),
            ("c", np.asarray([[2, 9, 4]]), dict(max_new_tokens=20)),
        ]
        off = _stub_engine()
        r_off, lp_off = _drain(off, subs)
        on = _stub_engine(spec_tokens=4)
        r_on, lp_on = _drain(on, subs)
        assert r_off == r_on
        assert lp_off == lp_on
        assert on.stats["spec_accepted"] > 0
        # multi-token commits: meaningfully fewer decode dispatches
        assert on.stats["decode_steps"] < off.stats["decode_steps"] / 1.5

    def test_eos_lands_mid_accepted_window(self):
        """eos inside the accepted window: the commit truncates at the
        eos token and the stream equals the spec-off engine's exactly
        (which test_paged.py pins against generate())."""
        subs = [("e", _cyc(8), dict(max_new_tokens=30, eos_token_id=5))]
        r_off, lp_off = _drain(_stub_engine(), subs)
        eng = _stub_engine(spec_tokens=4)
        r_on, lp_on = _drain(eng, subs)
        assert r_off == r_on and lp_off == lp_on
        assert r_on["e"][-1] == 5 and 5 not in r_on["e"][:-1]
        assert eng.stats["spec_accepted"] > 0   # eos truncation was real

    def test_stop_sequence_lands_mid_window(self):
        """Stop matching stays host-side: a stop completing inside the
        accepted window finishes (and trims) the request even though
        the device committed past it."""
        subs = [("s", _cyc(7), dict(max_new_tokens=30,
                                    stop_sequences=[[3, 4]]))]
        r_off, lp_off = _drain(_stub_engine(), subs)
        r_on, lp_on = _drain(_stub_engine(spec_tokens=4), subs)
        assert r_off == r_on and lp_off == lp_on
        assert tuple(r_on["s"][-2:]) != (3, 4)   # trimmed

    def test_budget_exhausts_mid_window(self):
        """max_new_tokens not a multiple of the accept run: the budget
        clamp truncates the window and sets done."""
        for n in (1, 9, 13):
            subs = [("m", _cyc(6), dict(max_new_tokens=n))]
            r_off, lp_off = _drain(_stub_engine(), subs)
            r_on, lp_on = _drain(_stub_engine(spec_tokens=4), subs)
            assert r_off == r_on and lp_off == lp_on
            assert len(r_on["m"]) == n

    def test_mixed_spec_sampled_penalized_slots_one_tick(self):
        """One tick, three slot kinds (ISSUE 11 semantics): a greedy
        spec row (bitwise), a seeded LOW-temperature sampled row
        (rejection-sampled verify — the distribution is preserved, and
        on the stub's decisive 8.0-margin logits at T=0.2 every
        filtered distribution is numerically a point mass, so the
        stream is deterministically the greedy one: the exact-pin the
        acceptance criteria name), and a repetition-penalized greedy
        row (the per-position penalty scan keeps it bitwise WHILE
        drafting — the old engine fell it back to 1-token ticks).
        Every stream stays exact."""
        subs = [
            ("spec", _cyc(8), dict(max_new_tokens=24)),
            ("samp", _cyc(5, start=2),
             dict(max_new_tokens=18, temperature=0.2, top_k=12, seed=3)),
            ("pen", _cyc(6, start=4),
             dict(max_new_tokens=15, repetition_penalty=1.3)),
        ]
        off = _stub_engine()
        r_off, lp_off = _drain(off, subs)
        eng = _stub_engine(spec_tokens=4)
        r_on, lp_on = _drain(eng, subs)
        assert r_off == r_on
        assert lp_off == lp_on
        assert eng.stats["spec_accepted"] > 0
        # the sampled AND penalized rows actually rode the multi-token
        # path: meaningfully fewer decode dispatches overall
        assert eng.stats["decode_steps"] < off.stats["decode_steps"]

    def test_midstream_submit_bit_identical(self):
        """Continuous batching under spec: a submit landing mid-decode
        refreshes mirrors (slot transition) and both the joined and
        running streams stay exact — emission order included."""
        def run(**kw):
            eng = _stub_engine(**kw)
            eng.submit("r0", _cyc(6), max_new_tokens=26)
            out = []
            for n, pair in enumerate(eng.stream()):
                out.append(pair)
                if n == 3:
                    eng.submit("r1", _cyc(9, start=2), max_new_tokens=14)
            return out, dict(eng.results), dict(eng.logprobs)

        so, ro, lo = run()
        ss, rs_, ls = run(spec_tokens=4)
        assert ro == rs_ and lo == ls
        assert sorted(so) == sorted(ss)   # same tokens per request
        # spec commits several tokens per tick, so interleaving may
        # differ — but each request's own emission order must not
        for rid in ro:
            assert [t for r, t in so if r == rid] == \
                [t for r, t in ss if r == rid]

    def test_table_capacity_exhausts_mid_window_1_token_fallback(self):
        """Block exhaustion mid-window: the request's table runs out of
        headroom as it approaches max_blocks_per_seq*block_size, so the
        device-side write-capacity clamp shrinks kprop tick by tick
        down to the plain 1-token tick — stream stays exact to the very
        last token."""
        subs = [("x", _cyc(6), dict(max_new_tokens=10))]
        kw = dict(block_size=8, max_blocks_per_seq=2, num_blocks=16)
        r_off, lp_off = _drain(_stub_engine(**kw), subs)
        eng = _stub_engine(spec_tokens=4, **kw)
        r_on, lp_on = _drain(eng, subs)
        assert r_off == r_on and lp_off == lp_on
        assert len(r_on["x"]) == 10          # filled the table exactly
        assert eng.stats["spec_accepted"] > 0

    def test_chunked_prefill_and_prefix_cache_with_spec(self):
        """Chunked prefill interleaves with spec ticks (mid-prefill
        slots ride the program as inactive rows; every chunk's refresh
        reseeds their committed-stream buffer), and prefix-cache block
        adoption composes (spec writes land at positions >= the
        prompt, never inside shared prefix blocks). Streams bitwise
        exact in both configs."""
        base = dict(block_size=8, max_blocks_per_seq=8, num_blocks=48,
                    chunk_prefill_tokens=8, prefill_buckets=(8,))
        shared = list(range(1, 7)) * 2 + [2, 3]   # 14-token prefix
        subs = [
            ("a", np.asarray([shared + [4, 5]]),
             dict(max_new_tokens=18)),
            ("b", np.asarray([shared + [1, 2]]),
             dict(max_new_tokens=12)),
            ("c", _cyc(11, start=2), dict(max_new_tokens=9)),
        ]
        # prefix_cache=True exercises chunking AND adoption; the
        # cache-off chunked variant rides the slow-tier sweep's budget
        kw = dict(base, enable_prefix_cache=True)
        r_off, lp_off = _drain(_stub_engine(**kw), subs)
        eng = _stub_engine(spec_tokens=4, **kw)
        r_on, lp_on = _drain(eng, subs)
        assert r_off == r_on and lp_off == lp_on
        assert eng.stats["spec_accepted"] > 0

    def test_llama_tokens_exact_logprobs_close(self, model):
        """Real-model twin of the bitwise pins: seeded submit/stop/eos
        mix on the decisive tiny llama — tokens exactly equal, logprobs
        within float-epsilon of the spec-off engine (the q_len=1 vs
        q_len=k+1 accumulation-order difference test_speculative.py
        documents)."""
        def eng(**kw):
            base = dict(max_slots=4, num_blocks=32, block_size=8,
                        max_blocks_per_seq=8, prefill_buckets=(16, 32))
            base.update(kw)
            return PagedEngine(model, **base)

        rs = np.random.RandomState(21)
        subs = [
            ("a", rs.randint(1, 200, (1, 5)), dict(max_new_tokens=18)),
            ("b", rs.randint(1, 200, (1, 9)),
             dict(max_new_tokens=16, stop_sequences=[[7], [3, 5]])),
            ("c", rs.randint(1, 200, (1, 3)),
             dict(max_new_tokens=14, eos_token_id=2)),
            ("d", rs.randint(1, 200, (1, 7)),
             dict(max_new_tokens=10, temperature=0.9, top_k=20,
                  seed=5)),
        ]
        r_off, lp_off = _drain(eng(), subs)
        r_on, lp_on = _drain(eng(spec_tokens=3), subs)
        for key in ("a", "b", "c"):      # greedy rows: tokens exact
            assert r_off[key] == r_on[key]
            np.testing.assert_allclose(lp_on[key], lp_off[key],
                                       atol=1e-4, rtol=1e-4)
        # the sampled row rides the rejection-sampled verify (ISSUE
        # 11): its stream is preserved in DISTRIBUTION, not bitwise
        # (the PRNG consumption pattern differs from 1-token ticks by
        # design — the distribution pins live in test_ring_spec.py).
        # Here: seeded determinism — the same seed through the spec
        # engine twice is bitwise-identical
        r_on2, lp_on2 = _drain(eng(spec_tokens=3), subs)
        assert r_on["d"] == r_on2["d"] and lp_on["d"] == lp_on2["d"]
        assert len(r_on["d"]) == len(r_off["d"])   # budget honored


# ------------------------------------------------------ dispatch contract
class TestSpecDispatchContract:
    def test_one_dispatch_zero_uploads_per_steady_spec_tick(self):
        """The ISSUE 6 steady-state counters survive speculation: N
        spec ticks = N dispatches, 0 mirror uploads — while each tick
        commits MULTIPLE tokens."""
        eng = _stub_engine(spec_tokens=4)
        for i in range(4):
            eng.submit(f"r{i}", _cyc(8), max_new_tokens=60)
        for _ in range(4):       # admit + prefill + first refresh
            eng.step()
        d0, u0 = eng.dispatch_count, eng.h2d_uploads
        t0 = sum(len(s.tokens) for s in eng.slots if s is not None)
        n = 6
        for _ in range(n):
            eng.step()
        toks = sum(len(s.tokens) for s in eng.slots
                   if s is not None) - t0
        assert eng.dispatch_count - d0 == n
        assert eng.h2d_uploads - u0 == 0
        # repetitive stream: well past 1 token per dispatch
        assert toks >= 2 * n * 4

    def test_collapsed_accept_rate_stops_drafting(self):
        """A stream that never repeats an n-gram (period > budget):
        the accept EMA decays below the floor after a handful of ticks
        and drafting stops (probe ticks only) — the clean per-request
        fallback. Stream stays exact throughout."""
        subs = [("r", np.asarray([[1, 2, 3]]),
                 dict(max_new_tokens=36))]
        r_off, lp_off = _drain(_stub_engine(period=60), subs)
        eng = _stub_engine(period=60, spec_tokens=4)
        r_on, lp_on = _drain(eng, subs)
        assert r_off == r_on and lp_off == lp_on
        assert eng.stats["spec_accepted"] == 0
        # ema 1.0 -> floor in ~5 ticks of k drafts, then probes only
        assert 0 < eng.stats["spec_proposed"] <= 24

    def test_counters_health_and_prometheus_pinned(self):
        """spec_proposed_total / spec_accepted_total ride the same
        registry a /metrics scrape exports; health() derives the accept
        rate from those exact objects (PR 4 pattern)."""
        from paddle_tpu.utils import observability as obs
        eng = _stub_engine(spec_tokens=4)
        eng.submit("r", _cyc(8), max_new_tokens=30)
        eng.run()
        snap = eng.stats
        assert snap["spec_proposed"] > 0
        assert 0 < snap["spec_accepted"] <= snap["spec_proposed"]
        h = eng.health()
        assert h["spec_accept_rate"] == round(
            snap["spec_accepted"] / snap["spec_proposed"], 4)
        label = eng._obs_labels["engine"]
        text = obs.registry().prometheus_text()
        for name, key in (("paged_spec_proposed_total", "spec_proposed"),
                          ("paged_spec_accepted_total", "spec_accepted")):
            line = next(ln for ln in text.splitlines()
                        if ln.startswith(name)
                        and f'engine="{label}"' in ln)
            assert float(line.rsplit(" ", 1)[1]) == snap[key]
        # tokens-per-forward histogram observed once per active row tick
        _, tot, cnt = eng._h_tpf.export()
        assert cnt == eng.stats["decode_steps"]
        assert tot == eng.stats["active_slot_steps"]

    def test_spec_requires_fused_tick(self):
        with pytest.raises(ValueError, match="fused_tick"):
            _stub_engine(spec_tokens=2, fused_tick=False)


# ----------------------------------------------- kernel + primitive parity
def _dense_multi_reference(q, kp, vp, tables, lens, window=None):
    """Per-position causal reference for multi-query rows."""
    from paddle_tpu.ops.attention import dense_attention
    R, T = q.shape[0], q.shape[1]
    kvh, d = kp.shape[2], kp.shape[3]
    ks = kp[tables].reshape(R, -1, kvh, d)
    vs = vp[tables].reshape(R, -1, kvh, d)
    kpos = jnp.arange(ks.shape[1])[None, None, :]
    qpos = lens[:, None, None] + jnp.arange(T)[None, :, None]
    keep = kpos <= qpos
    if window is not None:
        keep &= kpos > qpos - window
    return dense_attention(q, ks, vs, attn_mask=keep[:, None])


class TestMultiQueryRagged:
    @pytest.fixture(autouse=True)
    def _interpret(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")

    @pytest.mark.parametrize("window", [None, 12])
    def test_multi_query_parity(self, window):
        """T=5 verify rows over uneven/boundary seq_lens: each query
        position t attends 0..len+t — exact vs the dense per-position
        reference. The tier-1 representative of the slow sweep."""
        from paddle_tpu.ops.pallas.ragged_paged_attention import \
            ragged_paged_attention_pallas
        rs = np.random.RandomState(7)
        R, P, B, M, kvh, h, d, T = 4, 24, 8, 4, 2, 4, 64, 5
        q = jnp.asarray(rs.randn(R, T, h, d), jnp.float32)
        kp = jnp.asarray(rs.randn(P, B, kvh, d), jnp.float32)
        vp = jnp.asarray(rs.randn(P, B, kvh, d), jnp.float32)
        tables = jnp.asarray(
            rs.permutation(np.arange(P))[:R * M].reshape(R, M),
            jnp.int32)
        lens = jnp.asarray([0, B - 1, B, 2 * B + 3], jnp.int32)
        got = ragged_paged_attention_pallas(q, kp, vp, tables, lens,
                                            d ** -0.5, window=window)
        ref = _dense_multi_reference(q, kp, vp, tables, lens,
                                     window=window)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_paged_decode_attention_routes_multi_query(self, monkeypatch):
        """The dispatch layer: ragged and dense modes agree on T>1;
        grid mode (single-query kernel) falls back to dense."""
        rs = np.random.RandomState(8)
        R, P, B, M, kvh, h, d, T = 3, 16, 16, 4, 2, 4, 64, 3
        pk = PagedKV(jnp.asarray(rs.randn(P, B, kvh, d), jnp.float32),
                     jnp.asarray(rs.randn(P, B, kvh, d), jnp.float32),
                     jnp.asarray(rs.randint(0, P, (R, M)), jnp.int32),
                     jnp.asarray([3, 30, 57], jnp.int32))
        q = jnp.asarray(rs.randn(R, T, h, d), jnp.float32)
        outs = {}
        for mode in ("ragged", "grid", "dense"):
            monkeypatch.setenv("PADDLE_TPU_PAGED_ATTN", mode)
            outs[mode] = np.asarray(paged_decode_attention(q, pk))
        np.testing.assert_allclose(outs["ragged"], outs["dense"],
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_array_equal(outs["grid"], outs["dense"])

    @pytest.mark.slow
    @pytest.mark.parametrize("h,kvh,d,T,window",
                             [(8, 4, 64, 3, None), (16, 2, 128, 5, None),
                              (4, 4, 64, 2, 20), (8, 2, 64, 5, 3),
                              (16, 8, 64, 4, None)])
    def test_multi_query_parity_sweep(self, h, kvh, d, T, window):
        """Exhaustive GQA/T/window matrix (sweep-style -> slow tier;
        the boundary-lens case above is the tier-1 representative)."""
        from paddle_tpu.ops.pallas.ragged_paged_attention import \
            ragged_paged_attention_pallas
        rs = np.random.RandomState(9)
        R, P, B, M = 6, 48, 16, 8
        q = jnp.asarray(rs.randn(R, T, h, d), jnp.float32)
        kp = jnp.asarray(rs.randn(P, B, kvh, d), jnp.float32)
        vp = jnp.asarray(rs.randn(P, B, kvh, d), jnp.float32)
        tables = jnp.asarray(
            rs.permutation(np.arange(P))[:R * M].reshape(R, M),
            jnp.int32)
        lens = jnp.asarray([0, 15, 16, 63, 100, 120], jnp.int32)
        got = ragged_paged_attention_pallas(q, kp, vp, tables, lens,
                                            d ** -0.5, window=window)
        ref = _dense_multi_reference(q, kp, vp, tables, lens,
                                     window=window)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


class TestPromptLookupHelpers:
    def test_propose_ngram_most_recent_match(self):
        seq = jnp.asarray([5, 9, 5, 9, 7, 5, 9, 0, 0, 0], jnp.int32)
        # committed = first 7; suffix 2-gram (5, 9) most recently at
        # index 2 (index 5 is the suffix itself) -> continuation seq[4:]
        draft = propose_ngram(seq, jnp.int32(7), 3, 2, fill=-1)
        np.testing.assert_array_equal(np.asarray(draft), [7, 5, 9])
        # no match -> fill
        seq2 = jnp.asarray([1, 2, 3, 4, 5, 0, 0, 0], jnp.int32)
        draft2 = propose_ngram(seq2, jnp.int32(5), 3, 2, fill=-1)
        np.testing.assert_array_equal(np.asarray(draft2), [-1, -1, -1])

    def test_propose_rows_and_accept_length(self):
        seqs = jnp.asarray([[5, 9, 5, 9, 7, 0], [1, 2, 3, 4, 5, 6]],
                           jnp.int32)
        drafts = propose_ngram_rows(seqs, jnp.asarray([4, 6]), 2, 2)
        np.testing.assert_array_equal(np.asarray(drafts),
                                      [[5, 9], [-1, -1]])
        m = accept_length(jnp.asarray([[5, 9], [-1, -1]]),
                          jnp.asarray([[5, 9, 1], [2, 3, 4]]))
        np.testing.assert_array_equal(np.asarray(m), [2, 0])
        # mismatch mid-prefix stops the count
        assert int(accept_length(jnp.asarray([4, 9, 9]),
                                 jnp.asarray([4, 8, 9, 1]))) == 1

    def test_multi_write_diverts_overflow_to_garbage_block(self):
        """Positions past a row's table (or its allocated blocks: table
        entry 0) must scatter into the garbage block, never clamp onto
        a live block."""
        P, B, M, kvh, d = 4, 4, 2, 1, 8
        kp = jnp.zeros((P, B, kvh, d))
        pk = PagedKV(kp, kp, jnp.asarray([[1, 2]], jnp.int32),
                     jnp.asarray([6], jnp.int32))
        k = jnp.ones((1, 4, kvh, d))           # positions 6..9; cap = 8
        out = paged_decode_write(pk, k, k)
        got = np.asarray(out.kp)
        assert (got[1] == 0).all()             # block 1 untouched
        assert (got[2, 2:] == 1).all()         # positions 6, 7 landed
        assert (got[3] == 0).all()             # never allocated
        assert (got[0, :2] == 1).all()         # 8, 9 -> garbage block


# --------------------------------------------------------------- slow tier
@pytest.mark.slow
def test_microbench_spec_tokens_per_forward():
    """ISSUE 7 acceptance: >= 2.0 tokens per forward in the paged spec
    tick on a repetitive stub stream (the profiler's
    paged_spec_tokens_per_sec rung measures the same machinery)."""
    eng = _stub_engine(spec_tokens=4, max_slots=4, num_blocks=32,
                       block_size=64, max_blocks_per_seq=4)
    for i in range(4):
        eng.submit(f"r{i}", _cyc(8), max_new_tokens=120)
    res = eng.run()
    toks = sum(len(v) for v in res.values())
    # per-row tokens per forward: identical streams finish in the same
    # tick, so every row was live for all decode_steps forwards
    tpf = (toks - 4) / 4 / max(eng.stats["decode_steps"], 1)
    assert tpf >= 2.0, (toks, eng.stats["decode_steps"])


@pytest.mark.slow
@pytest.mark.parametrize("k,g", [(1, 1), (2, 2), (6, 3), (4, 1)])
def test_spec_param_sweep_bit_identical(k, g):
    """k x ngram sweep: every config stays bitwise exact vs spec-off
    (sweep-style -> slow tier; the k=4/g=2 cases above are the tier-1
    representatives). The k=2 case runs on chunked-prefill engines
    WITHOUT the prefix cache — the chunked variant the tier-1
    composition test leaves to this sweep."""
    subs = [
        ("a", _cyc(8), dict(max_new_tokens=26)),
        ("b", np.asarray([[3, 1, 4, 1]]), dict(max_new_tokens=17)),
        ("c", _cyc(5, start=2),
         dict(max_new_tokens=21, eos_token_id=6)),
    ]
    kw = dict(block_size=8, max_blocks_per_seq=8, num_blocks=48,
              chunk_prefill_tokens=8, prefill_buckets=(8,)) \
        if k == 2 else {}
    r_off, lp_off = _drain(_stub_engine(**kw), subs)
    r_on, lp_on = _drain(_stub_engine(spec_tokens=k, spec_ngram=g,
                                      **kw), subs)
    assert r_off == r_on and lp_off == lp_on
