"""paddle_tpu.vision — transforms + datasets + model re-exports
(reference: python/paddle/vision: transforms, datasets, models)."""
from . import datasets
from . import transforms
from ..models.resnet import ResNet, resnet18, resnet34, resnet50, resnet50_vd
from ..models.vit import ViTForImageClassification
