#!/usr/bin/env python
"""One-shot TPU validation queue (SURVEY §8 / VERDICT r3 item 1).

Run the moment the axon tunnel is up (it flaps — bank everything in one
window): hardware compile-checks for every interpret-only Pallas kernel,
then the full bench ladder + decode rung, writing BENCH_SELF_r04.json.
Every stage is wrapped and timed; a hang in one stage cannot eat the
window (subprocess timeouts), and partial results are still written.

Usage:  timeout 1800 python tools/tpu_validate.py
"""
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
OUT = os.path.join(REPO, "BENCH_SELF_r05.json")

KERNEL_CHECK = r"""
import json, time, numpy as np
import jax, jax.numpy as jnp
import sys; sys.path.insert(0, %(repo)r)
results = {}

def check(name, fn):
    t0 = time.time()
    try:
        fn()
        results[name] = {"ok": True, "s": round(time.time() - t0, 1)}
    except Exception as e:
        results[name] = {"ok": False, "error": repr(e)[:300]}
    print(name, results[name], flush=True)

rs = np.random.RandomState(0)
from paddle_tpu.ops.pallas.flash_attention import flash_attention_bshd
from paddle_tpu.ops.attention import dense_attention, segment_mask

b, s, h, kv, d = 2, 512, 8, 4, 64
q = jnp.asarray(rs.randn(b, s, h, d), jnp.bfloat16)
k = jnp.asarray(rs.randn(b, s, kv, d), jnp.bfloat16)
v = jnp.asarray(rs.randn(b, s, kv, d), jnp.bfloat16)
seg = jnp.asarray(np.repeat(np.arange(1, 5), s // 4)[None].repeat(b, 0))

def seg_flash():
    out = flash_attention_bshd(q, k, v, causal=True, segment_ids=seg)
    ref = dense_attention(q, k, v, causal=True, attn_mask=segment_mask(seg))
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    assert err < 3e-2, err
    g = jax.grad(lambda q: flash_attention_bshd(
        q, k, v, causal=True, segment_ids=seg).astype(jnp.float32).sum())(q)
    np.asarray(g)  # D2H forces completion over the tunnel
check("flash_segmented_fwd_bwd", seg_flash)

def win_flash():
    out = flash_attention_bshd(q, k, v, causal=True, window=128)
    ref = dense_attention(q, k, v, causal=True, window=128)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    assert err < 3e-2, err
    g = jax.grad(lambda q: flash_attention_bshd(
        q, k, v, causal=True, window=128).astype(jnp.float32).sum())(q)
    np.asarray(g)
check("flash_window_fwd_bwd", win_flash)

from paddle_tpu.quant.weight_only import (dequantize_weight,
                                          quantize_blockwise)
from paddle_tpu.ops.pallas.quant_matmul import quant_matmul_pallas
w = jnp.asarray(rs.randn(1024, 512), jnp.float32)
x = jnp.asarray(rs.randn(8, 1024), jnp.bfloat16)

def qmm(bits):
    def run():
        qw, sc = quantize_blockwise(w, bits=bits, block_size=128)
        out = quant_matmul_pallas(x, qw, sc, bits)
        ref = x @ dequantize_weight(qw, sc, bits, 128, jnp.bfloat16)
        err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                    - ref.astype(jnp.float32))))
        rel = err / float(jnp.max(jnp.abs(ref.astype(jnp.float32))))
        assert rel < 3e-2, (err, rel)
    return run
check("quant_matmul_int8", qmm(8))
check("quant_matmul_int4", qmm(4))

from paddle_tpu.ops.pallas.decode_attention import decode_attention_pallas
ck = jnp.asarray(rs.randn(8, 2048, kv, d), jnp.bfloat16)
cv = jnp.asarray(rs.randn(8, 2048, kv, d), jnp.bfloat16)
q1 = jnp.asarray(rs.randn(8, h, d), jnp.bfloat16)

def deco():
    out = decode_attention_pallas(q1, ck, cv, jnp.int32(1000),
                                  d ** -0.5)[:, None]
    mask = (jnp.arange(2048) <= 1000)[None, None, None, :]
    ref = dense_attention(q1[:, None], ck, cv, attn_mask=mask)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    assert err < 3e-2, err
check("decode_kernel", deco)

def paged_kernel():
    from paddle_tpu.ops.pallas.paged_attention import paged_attention_pallas
    from paddle_tpu.ops.attention import dense_attention as da
    R, P, B, M, kvh2, h2, d2 = 4, 64, 16, 16, 4, 8, 128
    qq = jnp.asarray(rs.randn(R, h2, d2), jnp.bfloat16)
    kp = jnp.asarray(rs.randn(P, B, kvh2, d2), jnp.bfloat16)
    vp = jnp.asarray(rs.randn(P, B, kvh2, d2), jnp.bfloat16)
    tables = jnp.asarray(rs.permutation(np.arange(P))[:R * M]
                         .reshape(R, M), jnp.int32)
    lens = jnp.asarray([0, 31, 100, 255], jnp.int32)
    out = paged_attention_pallas(qq, kp, vp, tables, lens, d2 ** -0.5)
    ks = kp[tables].reshape(R, -1, kvh2, d2)
    vs = vp[tables].reshape(R, -1, kvh2, d2)
    kpos = jnp.arange(ks.shape[1])[None, :]
    ref = da(qq[:, None], ks, vs,
             attn_mask=(kpos <= lens[:, None])[:, None, None, :])[:, 0]
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    assert err < 3e-2, err
check("paged_attention_kernel", paged_kernel)

def ragged_paged_kernel():
    # ISSUE 6: the schedule-driven ragged kernel (the serving default)
    # must compile and match the dense gather on hardware, same ragged
    # rows as the grid kernel check above
    from paddle_tpu.ops.pallas.ragged_paged_attention import \
        ragged_paged_attention_pallas
    from paddle_tpu.ops.attention import dense_attention as da
    R, P, B, M, kvh2, h2, d2 = 4, 64, 16, 16, 4, 8, 128
    qq = jnp.asarray(rs.randn(R, h2, d2), jnp.bfloat16)
    kp = jnp.asarray(rs.randn(P, B, kvh2, d2), jnp.bfloat16)
    vp = jnp.asarray(rs.randn(P, B, kvh2, d2), jnp.bfloat16)
    tables = jnp.asarray(rs.permutation(np.arange(P))[:R * M]
                         .reshape(R, M), jnp.int32)
    lens = jnp.asarray([0, 31, 100, 255], jnp.int32)
    out = ragged_paged_attention_pallas(qq, kp, vp, tables, lens,
                                        d2 ** -0.5)
    ks = kp[tables].reshape(R, -1, kvh2, d2)
    vs = vp[tables].reshape(R, -1, kvh2, d2)
    kpos = jnp.arange(ks.shape[1])[None, :]
    ref = da(qq[:, None], ks, vs,
             attn_mask=(kpos <= lens[:, None])[:, None, None, :])[:, 0]
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    assert err < 3e-2, err
check("ragged_paged_attention_kernel", ragged_paged_kernel)

def ragged_paged_multiquery_kernel():
    # ISSUE 7: the speculative verify's multi-query rows (q [R, T, h, d];
    # query t of row r attends 0..len+t) must compile and match the
    # dense per-position reference on hardware — the serving spec tick
    # routes through this shape
    from paddle_tpu.ops.pallas.ragged_paged_attention import \
        ragged_paged_attention_pallas
    from paddle_tpu.ops.attention import dense_attention as da
    R, P, B, M, kvh2, h2, d2, T = 4, 64, 16, 16, 4, 8, 128, 5
    qq = jnp.asarray(rs.randn(R, T, h2, d2), jnp.bfloat16)
    kp = jnp.asarray(rs.randn(P, B, kvh2, d2), jnp.bfloat16)
    vp = jnp.asarray(rs.randn(P, B, kvh2, d2), jnp.bfloat16)
    tables = jnp.asarray(rs.permutation(np.arange(P))[:R * M]
                         .reshape(R, M), jnp.int32)
    lens = jnp.asarray([0, 31, 100, 250], jnp.int32)
    out = ragged_paged_attention_pallas(qq, kp, vp, tables, lens,
                                        d2 ** -0.5)
    ks = kp[tables].reshape(R, -1, kvh2, d2)
    vs = vp[tables].reshape(R, -1, kvh2, d2)
    kpos = jnp.arange(ks.shape[1])[None, None, :]
    qpos = lens[:, None, None] + jnp.arange(T)[None, :, None]
    ref = da(qq, ks, vs, attn_mask=(kpos <= qpos)[:, None])
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    assert err < 3e-2, err
check("ragged_paged_multiquery_kernel", ragged_paged_multiquery_kernel)

def ring_tick_program():
    # ISSUE 11: the ring-mode fused tick program (device-resident ring
    # buffer + write cursors carried in the tick state, no per-tick
    # readback) must compile and stream correctly on hardware. The
    # negligible-compute stub keeps this a TICK-MACHINERY check, like
    # the loadgen's --model stub.
    from paddle_tpu.generation.paged import PagedEngine
    from paddle_tpu.generation.stub import TickStubModel
    eng = PagedEngine(TickStubModel(), max_slots=4, num_blocks=32,
                      block_size=8, max_blocks_per_seq=8,
                      prefill_buckets=(8,))
    assert eng._ring
    for i in range(3):
        eng.submit(i, np.arange(1, 6)[None], max_new_tokens=12)
    res = eng.run()
    assert all(len(v) == 12 for v in res.values()), res
    assert eng.ring_drains > 0
check("ring_tick_program", ring_tick_program)

def rejection_spec_tick():
    # ISSUE 11: both rejection-sampled speculative tick shapes — the
    # all-greedy program (argmax prefix rule) and the mixed program
    # (per-position accept/residual-resample with per-row key folds) —
    # must compile on hardware; the ring rides both.
    from paddle_tpu.generation.paged import PagedEngine
    from paddle_tpu.generation.stub import TickStubModel

    def run(**kw):
        eng = PagedEngine(TickStubModel(), max_slots=4, num_blocks=32,
                          block_size=8, max_blocks_per_seq=8,
                          prefill_buckets=(8,), spec_tokens=3)
        eng.submit("g", np.asarray([1, 2, 3, 1, 2, 3])[None],
                   max_new_tokens=10)
        if kw.get("mixed"):
            eng.submit("s", np.asarray([2, 3, 4, 2, 3])[None],
                       max_new_tokens=10, temperature=0.8, seed=1)
        res = eng.run()
        assert all(len(v) == 10 for v in res.values()), res
    run()              # all-greedy spec program
    run(mixed=True)    # mixed greedy+sampled spec program
check("rejection_spec_tick", rejection_spec_tick)

def delta_patch_program():
    # ISSUE 14: the delta-transition patch program — admit-row scatter
    # plus table-row append into the device-resident tick state — must
    # compile and stream correctly on hardware at the r05 serving
    # block geometry (block_size 16 x 16 blocks/seq). Churny short
    # requests (more requests than slots, budgets crossing the block
    # grid) force admit/finish/growth patches; after the first
    # dispatch's rebuild, every transition must ride a patch.
    # patch_fuse=False pins the STANDALONE per-row program — since
    # ISSUE 19 it is the fused queue's overflow fallback, so it must
    # keep compiling on hardware even though the default never uses it.
    from paddle_tpu.generation.paged import PagedEngine
    from paddle_tpu.generation.stub import TickStubModel
    eng = PagedEngine(TickStubModel(), max_slots=4, num_blocks=64,
                      block_size=16, max_blocks_per_seq=16,
                      prefill_buckets=(16,), patch_fuse=False)
    assert eng._delta
    eng.submit("w", np.arange(1, 6)[None], max_new_tokens=2)
    eng.run()
    fr0 = eng.full_rebuilds
    for i in range(8):
        # 9 + 24 = 33 tokens: crosses two block boundaries -> growth
        eng.submit(i, np.arange(1, 10)[None], max_new_tokens=24)
    res = eng.run()
    assert all(len(v) == 24 for k, v in res.items() if k != "w"), res
    assert eng.delta_patches > 0
    assert eng.full_rebuilds == fr0, (eng.full_rebuilds, fr0)
check("delta_patch_program", delta_patch_program)

def fused_patch_tick_program():
    # ISSUE 19: the fused patch+tick program — the masked batched
    # scatter stage prepended to the tick, fed by the device-resident
    # [Q, D] descriptor queue — must compile as ONE executable on
    # hardware at the same r05 geometry and absorb churn with zero
    # post-warmup standalone patch dispatches and zero rebuilds: the
    # dispatch counter must advance exactly once per tick + once per
    # prefill across a churny run.
    from paddle_tpu.generation.paged import PagedEngine
    from paddle_tpu.generation.stub import TickStubModel
    eng = PagedEngine(TickStubModel(), max_slots=4, num_blocks=64,
                      block_size=16, max_blocks_per_seq=16,
                      prefill_buckets=(16,))
    assert eng._fuse_patches
    eng.submit("w", np.arange(1, 6)[None], max_new_tokens=2)
    eng.run()                      # warmup: compiles tick + prefill
    fr0, d0 = eng.full_rebuilds, eng.dispatch_count
    t0, p0 = eng.stats["decode_steps"], eng.stats["prefills"]
    for i in range(8):
        eng.submit(i, np.arange(1, 10)[None], max_new_tokens=24)
    res = eng.run()
    assert all(len(v) == 24 for k, v in res.items() if k != "w"), res
    assert eng.patches_fused > 0
    assert eng.delta_patches == 0, eng.delta_patches
    assert eng.patch_queue_overflows == 0
    assert eng.full_rebuilds == fr0, (eng.full_rebuilds, fr0)
    ticks = eng.stats["decode_steps"] - t0
    prefills = eng.stats["prefills"] - p0
    assert eng.dispatch_count - d0 == ticks + prefills, \
        (eng.dispatch_count - d0, ticks, prefills)
check("fused_patch_tick_program", fused_patch_tick_program)

def spill_reupload_program():
    # ISSUE 17: the spill re-upload program — one batched H2D scatter
    # of a host-RAM arena span into freshly allocated blocks (donated
    # pools, pad rows onto garbage block 0) — must compile on hardware
    # and restore BITWISE: a fresh engine re-attached to the arena
    # serves the spilled prefix without re-prefilling it.
    from paddle_tpu.generation.paged import PagedEngine
    from paddle_tpu.generation.stub import TickStubModel
    from paddle_tpu.serving.kvspill import KVSpillArena
    arena = KVSpillArena(8 << 20, name="validate")

    def eng():
        e = PagedEngine(TickStubModel(), max_slots=4, num_blocks=32,
                        block_size=8, max_blocks_per_seq=8,
                        prefill_buckets=(8,), chunk_prefill_tokens=8,
                        enable_prefix_cache=True)
        e.attach_spill(arena)
        return e
    prompt = np.arange(1, 17)[None]
    e0 = eng()
    e0.submit("a", prompt, max_new_tokens=8)
    ref = e0.run()["a"]
    assert e0.spill_parked() > 0         # drain-spill the parked span
    e1 = eng()                           # fresh pools, same arena
    e1.submit("b", prompt, max_new_tokens=8)
    res = e1.run()["b"]
    assert res == ref, (res, ref)
    assert e1.stats["spill_restores"] > 0, e1.stats
    assert e1.stats["prefix_hit_tokens"] > 0, e1.stats
check("spill_reupload_program", spill_reupload_program)

def kv_xfer_restore_program():
    # ISSUE 18: the cross-replica restore program — a spilled span
    # serialized to the wire format (crc32 + geometry header),
    # injected into a DIFFERENT replica's arena, must compile the
    # same batched H2D scatter on hardware and restore BITWISE on the
    # receiving engine (live migration / peer fetch is this program
    # behind HTTP).
    from paddle_tpu.generation.paged import PagedEngine
    from paddle_tpu.generation.stub import TickStubModel
    from paddle_tpu.serving import kvxfer
    from paddle_tpu.serving.kvspill import KVSpillArena

    def eng(arena):
        e = PagedEngine(TickStubModel(), max_slots=4, num_blocks=32,
                        block_size=8, max_blocks_per_seq=8,
                        prefill_buckets=(8,), chunk_prefill_tokens=8,
                        enable_prefix_cache=True)
        e.attach_spill(arena)
        return e
    src = KVSpillArena(8 << 20, name="validate-xfer-src")
    dst = KVSpillArena(8 << 20, name="validate-xfer-dst")
    prompt = np.arange(1, 17)[None]
    e0 = eng(src)
    e0.submit("a", prompt, max_new_tokens=8)
    ref = e0.run()["a"]
    assert e0.spill_parked() > 0
    geo = e0._spill_geometry()
    ids = list(range(1, 17))
    chain = [c for c in e0._chunk_digests(ids, len(ids) - 1)
             if src.probe(c) is not None]
    assert chain, "no resident chain digest after spill"
    blob = kvxfer.export_span(src, chain[-1].hex(), geo,
                              gateway="validate")
    assert blob is not None
    assert kvxfer.inject_span(dst, blob, geo,
                              gateway="validate") is not None
    e1 = eng(dst)                       # fresh pools, PEER arena
    e1.submit("b", prompt, max_new_tokens=8)
    res = e1.run()["b"]
    assert res == ref, (res, ref)
    assert e1.stats["spill_restores"] > 0, e1.stats
    snap = kvxfer.counters_snapshot("validate")
    assert snap["kv_xfer_hits_total"] >= 1, snap
    assert snap["kv_xfer_checksum_failures_total"] == 0, snap
check("kv_xfer_restore_program", kv_xfer_restore_program)

def profilez_capture():
    # ISSUE 20: the /profilez capture path on hardware — tick-phase
    # profiling must not perturb the token stream (bitwise vs off),
    # the five phase totals must sum to the measured tick wall
    # (residual construction), and a bounded jax.profiler capture +
    # tickphase ring dump (what the gateway endpoint does) must land
    # without contending the single-trace owner.
    import os, tempfile
    from paddle_tpu.generation.paged import PagedEngine
    from paddle_tpu.generation.stub import TickStubModel
    from paddle_tpu.utils import observability as obs
    from paddle_tpu.utils.profiler import Profiler

    def run(profile):
        e = PagedEngine(TickStubModel(), max_slots=4, num_blocks=32,
                        block_size=8, max_blocks_per_seq=8,
                        prefill_buckets=(8,), chunk_prefill_tokens=8,
                        tick_profile=profile)
        for i in range(3):
            e.submit("r" + str(i), np.arange(1, 9)[None],
                     max_new_tokens=8)
        return e, e.run()
    e_on, res_on = run(True)
    e_off, res_off = run(False)
    assert res_on == res_off, "profile-on stream diverged"
    doc = e_on.tick_profile_doc()
    assert doc is not None and doc["ticks"] > 0
    bad = obs.validate_tickphase_doc(doc)
    assert not bad, bad
    d = tempfile.mkdtemp(prefix="profilez_")
    prof = Profiler(logdir=d)
    prof.start()
    try:
        e_cap, _ = run(True)
    finally:
        prof.stop()
    path = e_cap.dump_tick_profile(
        os.path.join(d, "tickphase_validate.json"))
    assert path and os.path.exists(path), path
check("profilez_capture", profilez_capture)

def prefill_flash():
    # the generate() prefill branch: flash at cache_index==0 must match
    # the masked-dense-over-cache path it replaced (llama.py)
    import paddle_tpu as pt
    from paddle_tpu.models import LlamaForCausalLM
    from paddle_tpu.models.llama import llama_tiny
    pt.seed(0)
    mf = LlamaForCausalLM(llama_tiny(hidden_size=256,
                                     num_attention_heads=4,
                                     max_position_embeddings=512,
                                     dtype=jnp.bfloat16))
    pt.seed(0)
    md = LlamaForCausalLM(llama_tiny(hidden_size=256,
                                     num_attention_heads=4,
                                     max_position_embeddings=512,
                                     dtype=jnp.bfloat16,
                                     use_flash_attention=False))
    ids = jnp.asarray(rs.randint(0, 256, (2, 256)))
    cf = mf.init_kv_caches(2, 384)
    lf, _ = mf(ids, kv_caches=cf, cache_index=0)
    cd = md.init_kv_caches(2, 384)
    ld, _ = md(ids, kv_caches=cd, cache_index=0)
    err = float(jnp.max(jnp.abs(lf - ld)))
    # both paths are end-to-end bf16; flash vs dense differ by bf16
    # accumulation order, so judge RELATIVE to logit magnitude (the r5
    # absolute-5e-2 gate tripped at err=0.066 on |logits|~8 — pure noise)
    rel = err / max(float(jnp.max(jnp.abs(ld))), 1e-6)
    assert rel < 2.5e-2, (err, rel)
check("prefill_flash_vs_dense", prefill_flash)

print("KERNELS_JSON " + json.dumps(results), flush=True)
"""


def run_stage(name, cmd, timeout, env=None):
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, timeout=timeout,
                              env={**os.environ, **(env or {})})
        out = proc.stdout.decode(errors="replace")
        return {"rc": proc.returncode, "s": round(time.time() - t0, 1),
                "stdout": out[-4000:],
                "stderr": proc.stderr.decode(errors="replace")[-1500:]}
    except subprocess.TimeoutExpired as e:
        return {"rc": 124, "timeout": True,
                "s": round(time.time() - t0, 1),
                "stdout": ((e.stdout or b"").decode(errors="replace"))[-4000:],
                "stderr": ((e.stderr or b"").decode(
                    errors="replace"))[-1500:]}


def main():
    report = {"comment": "Self-run TPU validation, round 5. Stages run "
                         "in subprocesses with timeouts (tunnel flaps).",
              "started": time.strftime("%Y-%m-%d %H:%M:%S")}

    # 0) probe
    probe = run_stage("probe", [sys.executable, os.path.join(REPO, "bench.py")],
                      60, env={"_PADDLE_TPU_BENCH_CHILD": "probe"})
    report["probe"] = {k: probe[k] for k in ("rc", "s")}
    if probe["rc"] != 0:
        report["error"] = "probe failed - tunnel down"
        print(json.dumps(report["probe"]))
        with open(OUT + ".failed", "w") as f:
            json.dump(report, f, indent=1)
        return 1

    def bank():
        # write after EVERY stage: a kill mid-bench must not lose the
        # kernel results already banked
        with open(OUT, "w") as f:
            json.dump(report, f, indent=1)

    # 1) kernel compile-checks (the r3 interpret-only queue)
    kc = run_stage("kernels", [sys.executable, "-c",
                               KERNEL_CHECK % {"repo": REPO}], 600)
    report["kernel_checks_rc"] = kc["rc"]
    for line in kc["stdout"].splitlines():
        if line.startswith("KERNELS_JSON "):
            report["kernels"] = json.loads(line[len("KERNELS_JSON "):])
    if "kernels" not in report:
        report["kernels_raw"] = kc
    bank()

    # 2) full bench ladder (writes its own JSON line)
    bench = run_stage("bench", [sys.executable, os.path.join(REPO, "bench.py")],
                      700, env={"PADDLE_TPU_BENCH_BUDGET": "600"})
    for line in reversed(bench["stdout"].strip().splitlines()):
        try:
            report["train"] = json.loads(line)
            break
        except ValueError:
            continue
    report["bench_rc"] = bench["rc"]
    if "train" not in report:
        report["bench_raw"] = bench  # keep the evidence of what died
    if "train" in report and "decode" in report.get("train", {}):
        report["decode"] = report["train"].pop("decode")
    bank()
    print(json.dumps({k: report.get(k) for k in
                      ("probe", "kernels", "bench_rc")}, indent=1))
    print(f"wrote {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
