"""DeepSeek-V2/V3 family with Multi-head Latent Attention (reference:
PaddleNLP paddlenlp/transformers/deepseek_v2/modeling.py —
DeepseekV2Attention's q/kv low-rank compression, decoupled RoPE keys, and
the fine-grained MoE with shared experts).

MLA, TPU-native:
- TRAIN/PREFILL: expand the compressed latents to per-head K/V and run
  the ordinary fused attention (the MXU wants the big matmuls anyway).
- DECODE: the ABSORBED form — fold ``W_uk`` into the query so attention
  runs directly against the cached latent: scores = (q_nope W_uk) · c_kv
  + q_pe · k_pe, out = (probs · c_kv) W_uv. The KV cache per token is
  ``kv_lora_rank + qk_rope_head_dim`` floats instead of
  ``2 * heads * head_dim`` — the ~10-50x cache compression that lets one
  chip hold long contexts, and the whole point of MLA.
- RoPE uses DeepSeek's INTERLEAVED (complex-pair) convention, applied
  only to the decoupled q_pe / single-head k_pe dims.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..nn.layer import Layer
from ..parallel.layers import (ColumnParallelLinear, RowParallelLinear,
                               VocabParallelEmbedding)
from ..parallel.moe import MoEMLP
from ..parallel.sharding import constraint
from .base import CausalLMBase
from .llama import (LlamaConfig, LlamaMLP, causal_lm_loss,  # noqa: F401
                    yarn_get_mscale, yarn_params)


@dataclass
class DeepseekV2Config(LlamaConfig):
    vocab_size: int = 102400
    hidden_size: int = 2048
    intermediate_size: int = 10944         # dense layers' FFN width
    # ---- MLA
    q_lora_rank: Optional[int] = None      # None = full q proj (V2-Lite)
    kv_lora_rank: int = 512
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128
    # ---- MoE (DeepSeek fine-grained + shared)
    num_experts: int = 64                  # n_routed_experts
    num_experts_per_tok: int = 6
    moe_intermediate_size: int = 1408
    num_shared_experts: int = 2            # n_shared_experts
    first_k_dense_replace: int = 1
    routed_scaling_factor: float = 1.0
    # DeepSeek group-limited-greedy routing (n_group=1 -> plain greedy)
    n_group: int = 1
    topk_group: int = 1
    # V3 router: sigmoid expert scores + top-2-sum group scores
    scoring: str = "softmax"
    group_score_mode: str = "max"
    # V3 yarn: get_mscale(factor, mscale_all_dim)^2 multiplies the
    # softmax scale (on top of the cos/sin attention factor)
    yarn_mscale_all_in_scale: bool = False
    # yarn context extension (HF rope_scaling dict: factor, beta_fast/slow,
    # mscale, mscale_all_dim, original_max_position_embeddings); None =
    # plain RoPE. Real DeepSeek-V2 checkpoints all ship yarn.
    rope_scaling: Optional[Dict[str, Any]] = None
    norm_topk_prob: bool = False           # normalize selected gates to 1
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.001
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    attention_bias: bool = False
    # ---- V3 multi-token prediction (HF config name): D extra depth
    # modules, each predicting one token further ahead. The loss weight
    # (the paper's lambda, 0.3 early / 0.1 late) is a TRAINING
    # hyperparameter — pass it to deepseek_mtp_loss, not the config.
    num_nextn_predict_layers: int = 0

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim


def deepseek_v2_tiny(**overrides) -> DeepseekV2Config:
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                q_lora_rank=32, kv_lora_rank=32, qk_nope_head_dim=16,
                qk_rope_head_dim=8, v_head_dim=16,
                num_experts=4, num_experts_per_tok=2,
                moe_intermediate_size=32, num_shared_experts=1,
                first_k_dense_replace=1, max_position_embeddings=128,
                dtype=jnp.float32)
    base.update(overrides)
    return DeepseekV2Config(**base)


def rope_interleaved(x, positions, theta: float, inv_freq=None,
                     attention_scaling: float = 1.0):
    """DeepSeek's complex-pair RoPE: pairs are (x[2i], x[2i+1]) and
    freqs index i — torch's view_as_complex convention, NOT rotate-half.
    x [b, s, h, d]; positions [b, s]. ``inv_freq``/``attention_scaling``
    override the plain schedule (yarn)."""
    d = x.shape[-1]
    if inv_freq is None:
        inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2,
                                               dtype=jnp.float32) / d))
    ang = positions.astype(jnp.float32)[..., None] * inv_freq  # [b, s, d/2]
    cos = jnp.cos(ang)[:, :, None, :] * attention_scaling
    sin = jnp.sin(ang)[:, :, None, :] * attention_scaling
    x1, x2 = x[..., ::2].astype(jnp.float32), x[..., 1::2].astype(jnp.float32)
    r1 = x1 * cos - x2 * sin
    r2 = x2 * cos + x1 * sin
    return jnp.stack([r1, r2], axis=-1).reshape(x.shape).astype(x.dtype)


class MLAttention(Layer):
    """Multi-head Latent Attention (reference: DeepseekV2Attention)."""

    def __init__(self, config: DeepseekV2Config):
        super().__init__()
        self.config = config
        cfg = config
        h = cfg.num_attention_heads
        if cfg.q_lora_rank is None:
            self.q_proj = ColumnParallelLinear(
                cfg.hidden_size, h * cfg.qk_head_dim,
                has_bias=cfg.attention_bias, gather_output=False)
        else:
            self.q_a_proj = nn.Linear(cfg.hidden_size, cfg.q_lora_rank,
                                      bias_attr=cfg.attention_bias or False)
            self.q_a_layernorm = nn.RMSNorm(cfg.q_lora_rank,
                                            cfg.rms_norm_eps)
            self.q_b_proj = ColumnParallelLinear(
                cfg.q_lora_rank, h * cfg.qk_head_dim, has_bias=False,
                gather_output=False)
        # [h, kv_lora_rank + rope_dim]: latent + the single decoupled key
        self.kv_a_proj_with_mqa = nn.Linear(
            cfg.hidden_size, cfg.kv_lora_rank + cfg.qk_rope_head_dim,
            bias_attr=cfg.attention_bias or False)
        self.kv_a_layernorm = nn.RMSNorm(cfg.kv_lora_rank, cfg.rms_norm_eps)
        self.kv_b_proj = ColumnParallelLinear(
            cfg.kv_lora_rank,
            h * (cfg.qk_nope_head_dim + cfg.v_head_dim),
            has_bias=False, gather_output=False)
        self.o_proj = RowParallelLinear(h * cfg.v_head_dim, cfg.hidden_size,
                                        has_bias=cfg.attention_bias,
                                        input_is_parallel=True)
        self.scale = cfg.qk_head_dim ** -0.5
        if getattr(cfg, "rope_scaling", None):
            self._inv_freq, self._rope_af = yarn_params(
                cfg.qk_rope_head_dim, cfg.rope_theta, cfg.rope_scaling,
                cfg.max_position_embeddings)
            msall = cfg.rope_scaling.get("mscale_all_dim", 0)
            if getattr(cfg, "yarn_mscale_all_in_scale", False) and msall:
                ms = yarn_get_mscale(cfg.rope_scaling["factor"], msall)
                self.scale = self.scale * ms * ms  # V3 semantics
        else:
            self._inv_freq, self._rope_af = None, 1.0

    def _queries(self, x, positions):
        cfg = self.config
        b, s, _ = x.shape
        h = cfg.num_attention_heads
        if cfg.q_lora_rank is None:
            q = self.q_proj(x)
        else:
            q = self.q_b_proj(self.q_a_layernorm(self.q_a_proj(x)))
        q = q.reshape(b, s, h, cfg.qk_head_dim)
        q_nope = q[..., :cfg.qk_nope_head_dim]
        q_pe = rope_interleaved(q[..., cfg.qk_nope_head_dim:], positions,
                                cfg.rope_theta, self._inv_freq,
                                self._rope_af)
        return q_nope, q_pe

    def _latents(self, x, positions):
        """x -> (c_kv normed [b, s, r], k_pe roped [b, s, rope_d])."""
        cfg = self.config
        ckv = self.kv_a_proj_with_mqa(x)
        c, k_pe = (ckv[..., :cfg.kv_lora_rank],
                   ckv[..., cfg.kv_lora_rank:])
        c = self.kv_a_layernorm(c)
        k_pe = rope_interleaved(k_pe[:, :, None, :], positions,
                                cfg.rope_theta, self._inv_freq,
                                self._rope_af)[:, :, 0]
        return c, k_pe

    def _expand(self, c):
        """latent [b, s, r] -> (k_nope [b, s, h, nope], v [b, s, h, v])."""
        cfg = self.config
        h = cfg.num_attention_heads
        kv = self.kv_b_proj(c).reshape(
            c.shape[0], c.shape[1], h, cfg.qk_nope_head_dim + cfg.v_head_dim)
        return kv[..., :cfg.qk_nope_head_dim], kv[..., cfg.qk_nope_head_dim:]

    def forward(self, x, positions, kv_cache=None, cache_index=None,
                attn_mask=None, attn_start=None):
        cfg = self.config
        b, s, _ = x.shape
        h = cfg.num_attention_heads
        q_nope, q_pe = self._queries(x, positions)
        c, k_pe = self._latents(x, positions)

        if kv_cache is not None:
            cc, cpe = kv_cache  # [b, T, r], [b, T, rope_d]
            cc = jax.lax.dynamic_update_slice(cc, c.astype(cc.dtype),
                                              (0, cache_index, 0))
            cpe = jax.lax.dynamic_update_slice(cpe, k_pe.astype(cpe.dtype),
                                               (0, cache_index, 0))
            new_cache = (cc, cpe)
            T = cc.shape[1]
            wkv = self.kv_b_proj.weight.reshape(
                cfg.kv_lora_rank, h, cfg.qk_nope_head_dim + cfg.v_head_dim)
            w_uk = wkv[..., :cfg.qk_nope_head_dim]   # [r, h, nope]
            w_uv = wkv[..., cfg.qk_nope_head_dim:]   # [r, h, v]
            # ABSORBED decode: queries project into latent space once,
            # attention runs over the compressed cache directly
            q_lat = jnp.einsum("bshn,rhn->bshr", q_nope, w_uk)
            scores = (jnp.einsum("bshr,btr->bhst", q_lat, cc)
                      + jnp.einsum("bshd,btd->bhst", q_pe, cpe)
                      ).astype(jnp.float32) * self.scale
            kpos = jnp.arange(T)[None, None, None, :]
            qpos = cache_index + jnp.arange(s)[None, None, :, None]
            keep = kpos <= qpos
            if attn_start is not None:
                # left-padded serving rows: mask each row's pad prefix
                # out of the cache; pad-prefix queries keep themselves so
                # no softmax row is fully masked (cf. llama.py)
                pad_ok = kpos >= attn_start[:, None, None, None]
                self_ok = kpos == qpos
                keep = keep & (pad_ok | self_ok)
            scores = jnp.where(keep, scores, -jnp.inf)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            o_lat = jnp.einsum("bhst,btr->bshr", probs, cc)
            out = jnp.einsum("bshr,rhv->bshv", o_lat, w_uv)
        else:
            new_cache = None
            k_nope, v = self._expand(c)
            k = jnp.concatenate(
                [k_nope, jnp.broadcast_to(k_pe[:, :, None, :],
                                          (b, s, h, cfg.qk_rope_head_dim))],
                axis=-1)
            q = jnp.concatenate([q_nope, q_pe], axis=-1)
            from ..ops.attention import dense_attention
            out = dense_attention(q, k, v, causal=attn_mask is None,
                                  attn_mask=attn_mask, scale=self.scale)
        out = self.o_proj(out.reshape(b, s, h * cfg.v_head_dim))
        return (out, new_cache) if kv_cache is not None else out


class DeepseekV2DecoderLayer(Layer):
    def __init__(self, config: DeepseekV2Config, layer_idx: int):
        super().__init__()
        self.config = config
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          config.rms_norm_eps)
        self.self_attn = MLAttention(config)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   config.rms_norm_eps)
        self.is_dense = layer_idx < config.first_k_dense_replace
        if self.is_dense:
            self.mlp = LlamaMLP(config)
        else:
            self.mlp = MoEMLP(
                config.hidden_size, config.moe_intermediate_size,
                num_experts=config.num_experts,
                top_k=config.num_experts_per_tok,
                capacity_factor=config.capacity_factor,
                num_shared_experts=config.num_shared_experts,
                shared_intermediate_size=(config.moe_intermediate_size
                                          * config.num_shared_experts),
                aux_loss_weight=config.aux_loss_weight,
                routed_scaling_factor=config.routed_scaling_factor,
                norm_topk_prob=config.norm_topk_prob,
                n_group=config.n_group, topk_group=config.topk_group,
                scoring=config.scoring,
                group_score_mode=config.group_score_mode)

    def forward(self, x, positions, kv_cache=None, cache_index=None,
                attn_mask=None, attn_start=None):
        attn = self.self_attn(self.input_layernorm(x), positions,
                              kv_cache=kv_cache, cache_index=cache_index,
                              attn_mask=attn_mask, attn_start=attn_start)
        new_cache = None
        if kv_cache is not None:
            attn, new_cache = attn
        x = x + attn
        x = x + self.mlp(self.post_attention_layernorm(x))
        x = constraint(x, ("dp", "fsdp"), "sp", None)
        return (x, new_cache) if kv_cache is not None else x


class DeepseekV3MTP(Layer):
    """One V3 multi-token-prediction depth module (reference: DeepSeek-V3
    tech report §2.2 / HF checkpoint layout model.layers.{L+k}): RMSNorm
    the previous depth's hidden and the (k+1)-shifted token embedding,
    concat, project 2h -> h, run one full (MoE) decoder block. The final
    norm lives here; the LM head is SHARED with the main model."""

    def __init__(self, config: DeepseekV2Config):
        super().__init__()
        h = config.hidden_size
        self.enorm = nn.RMSNorm(h, config.rms_norm_eps)
        self.hnorm = nn.RMSNorm(h, config.rms_norm_eps)
        self.eh_proj = nn.Linear(2 * h, h, bias_attr=False)
        # MTP blocks are MoE in V3 (they sit past first_k_dense_replace)
        self.block = DeepseekV2DecoderLayer(config,
                                            config.num_hidden_layers)
        self.norm = nn.RMSNorm(h, config.rms_norm_eps)
        if config.dtype != jnp.float32:
            self.to(dtype=config.dtype)

    def forward(self, h_prev, emb_next, positions, attn_mask=None,
                kv_cache=None, cache_index=None):
        """Training path (no cache): returns the final-normed hidden for
        the shared lm_head. Decode path (kv_cache given — MTP-as-draft
        speculative decoding): returns ``(normed, pre, new_cache)`` so
        the caller can chain the PRE-norm block output as the next
        step's ``h_prev`` (Eagle-style self-draft)."""
        x = self.eh_proj(jnp.concatenate(
            [self.hnorm(h_prev), self.enorm(emb_next)], axis=-1))
        if kv_cache is not None:
            x, new_cache = self.block(x, positions, kv_cache=kv_cache,
                                      cache_index=cache_index,
                                      attn_mask=attn_mask)
            return self.norm(x), x, new_cache
        x = self.block(x, positions, attn_mask=attn_mask)
        return self.norm(x)


class DeepseekV2Model(Layer):
    def __init__(self, config: DeepseekV2Config):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                   config.hidden_size)
        self.layers = nn.LayerList(
            [DeepseekV2DecoderLayer(config, i)
             for i in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        if config.dtype != jnp.float32:
            self.to(dtype=config.dtype)

    def forward(self, input_ids, positions=None, kv_caches=None,
                cache_index=None, attn_mask=None, attn_start=None,
                return_prenorm: bool = False):
        b, s = input_ids.shape
        if positions is None:
            start = cache_index if cache_index is not None else 0
            positions = start + jnp.arange(s)[None, :].repeat(b, axis=0)
            if attn_start is not None:
                # RoPE position 0 sits at each row's first REAL token
                positions = jnp.maximum(positions - attn_start[:, None], 0)
        x = self.embed_tokens(input_ids)
        x = constraint(x, ("dp", "fsdp"), "sp", None)
        new_caches = [] if kv_caches is not None else None
        for i, layer in enumerate(self.layers):
            if kv_caches is not None:
                x, nc = layer(x, positions, kv_cache=kv_caches[i],
                              cache_index=cache_index, attn_mask=attn_mask,
                              attn_start=attn_start)
                new_caches.append(nc)
            else:
                x = layer(x, positions, attn_mask=attn_mask)
        pre = x  # the MTP modules consume the PRE-final-norm hidden
        x = self.norm(x)
        if return_prenorm:
            return (x, pre, new_caches) if kv_caches is not None \
                else (x, pre)
        return (x, new_caches) if kv_caches is not None else x


class DeepseekV2ForCausalLM(CausalLMBase):
    def __init__(self, config: Optional[DeepseekV2Config] = None):
        super().__init__()
        config = config or DeepseekV2Config()
        self.config = config
        self.model = DeepseekV2Model(config)
        self.lm_head = ColumnParallelLinear(config.hidden_size,
                                            config.vocab_size,
                                            has_bias=False,
                                            gather_output=True)
        if config.num_nextn_predict_layers > 0:
            self.mtp = nn.LayerList(
                [DeepseekV3MTP(config)
                 for _ in range(config.num_nextn_predict_layers)])
        if config.dtype != jnp.float32:
            self.lm_head.to(dtype=config.dtype)

    def init_kv_caches(self, batch_size: int, max_len: int, dtype=None):
        """MLA cache: (latent [b, T, kv_lora_rank], k_pe [b, T, rope_d])
        per layer — kv_lora_rank + rope_d floats per token instead of
        2 * heads * head_dim."""
        cfg = self.config
        dtype = dtype or cfg.dtype
        return [(jnp.zeros((batch_size, max_len, cfg.kv_lora_rank), dtype),
                 jnp.zeros((batch_size, max_len, cfg.qk_rope_head_dim),
                           dtype))
                for _ in range(cfg.num_hidden_layers)]

    def init_mtp_cache(self, batch_size: int, max_len: int, dtype=None):
        """One MLA cache for the depth-0 MTP block (MTP-as-draft decode)."""
        cfg = self.config
        dtype = dtype or cfg.dtype
        return (jnp.zeros((batch_size, max_len, cfg.kv_lora_rank), dtype),
                jnp.zeros((batch_size, max_len, cfg.qk_rope_head_dim),
                          dtype))

    def forward(self, input_ids, positions=None, kv_caches=None,
                cache_index=None, attn_mask=None, attn_start=None,
                return_mtp: bool = False, return_prenorm: bool = False):
        """``return_mtp`` (training-time, no cache): additionally return
        the list of MTP depth logits — depth k's logits[:, i] predict
        token i+2+k. The MTP chain consumes the pre-final-norm hidden
        and the (k+1)-shifted token embedding; the LM head is shared.

        ``return_prenorm`` (decode-time, works WITH caches): additionally
        return the pre-final-norm hidden — the MTP-as-draft speculative
        path feeds it to the depth modules."""
        if return_mtp:
            if kv_caches is not None:
                raise ValueError("return_mtp is a training-time path "
                                 "(no kv cache)")
            D = self.config.num_nextn_predict_layers
            if D == 0:
                raise ValueError("config.num_nextn_predict_layers == 0")
            out, pre = self.model(input_ids, positions, attn_mask=attn_mask,
                                  attn_start=attn_start,
                                  return_prenorm=True)
            logits = self.lm_head(out).astype(jnp.float32)
            b, s = input_ids.shape
            # the MTP blocks see the SAME attention context as the main
            # stack: per-row shifted positions (left padding) and any
            # segment/packing mask, sliced to each depth's length
            if positions is None:
                positions_full = jnp.arange(s)[None, :].repeat(b, axis=0)
                if attn_start is not None:
                    positions_full = jnp.maximum(
                        positions_full - attn_start[:, None], 0)
            else:
                positions_full = positions
            mtp_logits = []
            h = pre
            for k, mod in enumerate(self.mtp):
                # depth k: h[:, : s-1-k] pairs with emb of tokens shifted
                # k+1 right; the chained h shrinks by one each depth
                sl = s - 1 - k
                emb = self.model.embed_tokens(input_ids[:, k + 1:])
                am = (None if attn_mask is None
                      else attn_mask[:, :, :sl, :sl])
                h = mod(h[:, :sl], emb, positions_full[:, :sl],
                        attn_mask=am)
                mtp_logits.append(self.lm_head(h).astype(jnp.float32))
            return logits, mtp_logits
        out = self.model(input_ids, positions, kv_caches, cache_index,
                         attn_mask, attn_start=attn_start,
                         return_prenorm=return_prenorm)
        caches = None
        pre = None
        if kv_caches is not None:
            if return_prenorm:
                out, pre, caches = out
            else:
                out, caches = out
        elif return_prenorm:
            out, pre = out
        logits = self.lm_head(out).astype(jnp.float32)
        if return_prenorm:
            # decode-time MTP-as-draft needs the pre-final-norm hidden
            # alongside the logits (generation/speculative.py)
            return (logits, pre, caches) if kv_caches is not None \
                else (logits, pre)
        return (logits, caches) if kv_caches is not None else logits


def deepseek_mtp_loss(logits, mtp_logits, labels, weight: float = 0.1,
                      ignore_index: int = -100):
    """V3 training objective: main next-token CE plus ``weight`` (the
    paper's lambda) times the mean over MTP depths of each depth's CE —
    depth k's logits[:, i] predict token i+2+k (reference: DeepSeek-V3
    tech report eq. 24-25)."""
    from ..nn import functional as F
    loss = causal_lm_loss(logits, labels, ignore_index)
    if not mtp_logits:
        return loss
    mtp = jnp.float32(0.0)
    for k, ml in enumerate(mtp_logits):
        sl = labels.shape[1] - 2 - k
        mtp = mtp + F.cross_entropy(ml[:, :sl], labels[:, 2 + k:],
                                    ignore_index=ignore_index,
                                    reduction="mean")
    return loss + weight * mtp / len(mtp_logits)
