"""paddle.Model — the high-level Keras-style API (reference:
python/paddle/hapi/model.py: Model.prepare/fit/evaluate/predict/save/load,
paddle.summary).

TPU-native: ``prepare`` builds ONE jitted train step (loss -> grads ->
optimizer update, params/opt-state donated) and one jitted eval step;
``fit`` is then a plain host loop feeding static-shape batches. Metrics
update from device outputs only at log points. The same Model runs
un-sharded on one chip or SPMD over an ambient mesh — exactly the
Trainer's execution model, packaged behind paddle's beginner surface.
"""
from __future__ import annotations

import functools
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .metric import Metric
from .nn.layer import Layer

__all__ = ["Model", "summary"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _update_metric(m: Metric, preds, labels):
    """paddle's metric protocol: compute() (if defined) pre-reduces the
    device outputs and update() takes its result; metrics without
    compute() (Precision/Recall/Auc) take update(preds, labels)."""
    if hasattr(m, "compute"):
        m.update(m.compute(preds, labels))
    else:
        m.update(preds, labels)


class Model:
    """Reference: paddle.Model(network). input/label specs are accepted
    for signature parity; shapes are taken from the actual batches (each
    distinct shape compiles once)."""

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._pure_fn, self._params = network.functional()
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._opt_state = None
        self._train_step = None
        self._eval_step = None
        self._predict_fn = None

    # ---------------------------------------------------------- prepare
    def prepare(self, optimizer=None, loss=None, metrics=None):
        """optimizer: paddle_tpu.optimizer.*; loss: callable
        (logits, label) -> scalar or an nn loss layer; metrics: Metric(s)."""
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        fn = self._pure_fn

        if optimizer is not None and loss is not None:
            opt = optimizer

            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def train_step(params, state, stepno, x, y):
                def loss_fn(p):
                    return jnp.asarray(self._loss(fn(p, x), y),
                                       jnp.float32)
                l, g = jax.value_and_grad(loss_fn)(params)
                params, state = opt.apply(params, g, state, stepno)
                return params, state, l
            self._train_step = train_step

        if loss is not None:
            @jax.jit
            def eval_step(params, x, y):
                out = fn(params, x)
                return jnp.asarray(self._loss(out, y), jnp.float32), out
            self._eval_step = eval_step

        self._predict_fn = jax.jit(fn)
        return self

    def _require(self, what, attr):
        if getattr(self, attr) is None:
            raise RuntimeError(f"call prepare() with {what} first")

    @staticmethod
    def _split_batch(batch):
        if isinstance(batch, (tuple, list)):
            if len(batch) == 2:
                return batch[0], batch[1]
            raise TypeError(
                f"fit/evaluate expect (input, label) 2-tuples, got "
                f"{len(batch)} elements — multi-input networks should "
                "pack their inputs into one structure")
        raise TypeError("fit/evaluate expect (input, label) batches; got "
                        f"{type(batch)}")

    # -------------------------------------------------------------- fit
    def fit(self, train_data=None, eval_data=None, batch_size: int = 1,
            epochs: int = 1, log_freq: int = 10, verbose: int = 1,
            shuffle: bool = True, callbacks=None):
        """train_data: DataLoader-like iterable of (x, y) batches, or a
        Dataset (wrapped in a DataLoader with ``batch_size``/``shuffle``).
        callbacks: objects with (any of) ``on_train_batch_end(step, logs)``
        / ``on_epoch_end(epoch, logs)`` — invoked at log points."""
        self._require("an optimizer and a loss", "_train_step")
        loader = self._as_loader(train_data, batch_size, shuffle)
        callbacks = _to_list(callbacks)
        for cb in callbacks:
            if hasattr(cb, "set_model"):
                cb.set_model(self)
        if self._opt_state is None:
            self._opt_state = self._optimizer.init(self._params)
        stepno = 0
        history = {"loss": []}
        loss = None
        try:
            for epoch in range(epochs):
                logged = False
                for batch in loader:
                    x, y = self._split_batch(batch)
                    x, y = jnp.asarray(x), jnp.asarray(y)
                    self._params, self._opt_state, loss = self._train_step(
                        self._params, self._opt_state, jnp.int32(stepno),
                        x, y)
                    stepno += 1
                    logged = stepno % log_freq == 0
                    if logged:
                        lv = float(loss)
                        history["loss"].append(lv)
                        if verbose:
                            print(f"epoch {epoch + 1}/{epochs} step "
                                  f"{stepno}: loss {lv:.4f}", flush=True)
                        for cb in callbacks:  # duck-typed callback hook
                            if hasattr(cb, "on_train_batch_end"):
                                cb.on_train_batch_end(stepno, {"loss": lv})
                if loss is not None and not logged:
                    # epoch-end loss, unless the last step just logged it
                    history["loss"].append(float(loss))
                if eval_data is not None:
                    eres = self.evaluate(eval_data, batch_size=batch_size,
                                         verbose=verbose)
                    history.setdefault("eval_loss", []).append(eres["loss"])
                try:
                    for cb in callbacks:
                        if hasattr(cb, "on_epoch_end"):
                            cb.on_epoch_end(epoch, {k: v[-1] for k, v in
                                                    history.items() if v})
                except StopIteration:
                    break  # a callback (EarlyStopping) ended training
        finally:
            # the step DONATES params; on an abort between steps, write the
            # live arrays back so the network never holds deleted buffers
            try:
                self.network.bind(self._params)
            except Exception:
                pass
        return history

    def _as_loader(self, data, batch_size, shuffle):
        from .io.dataset import Dataset
        if isinstance(data, Dataset):
            from .io import DataLoader
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle)
        return data

    # --------------------------------------------------------- evaluate
    def evaluate(self, eval_data, batch_size: int = 1, log_freq: int = 10,
                 verbose: int = 1):
        self._require("a loss", "_eval_step")
        loader = self._as_loader(eval_data, batch_size, shuffle=False)
        was_training = self.network.training
        self.network.eval()
        try:
            losses = []
            for m in self._metrics:
                m.reset()
            for batch in loader:
                x, y = self._split_batch(batch)
                loss, out = self._eval_step(self._params, jnp.asarray(x),
                                            jnp.asarray(y))
                losses.append(float(loss))
                for m in self._metrics:
                    _update_metric(m, out, jnp.asarray(y))
        finally:
            if was_training:
                self.network.train()
        result = {"loss": float(np.mean(losses)) if losses else float("nan")}
        for m in self._metrics:
            result[m.name() if callable(m.name) else m.name] = m.accumulate()
        if verbose:
            print(f"eval: {result}", flush=True)
        return result

    # ---------------------------------------------------------- predict
    def predict(self, test_data, batch_size: int = 1):
        self._require("prepare()", "_predict_fn")
        loader = self._as_loader(test_data, batch_size, shuffle=False)
        was_training = self.network.training
        self.network.eval()
        try:
            outs = []
            for batch in loader:
                x = batch[0] if isinstance(batch, (tuple, list)) else batch
                outs.append(np.asarray(self._predict_fn(self._params,
                                                        jnp.asarray(x))))
        finally:
            if was_training:
                self.network.train()
        return outs

    # ------------------------------------------------------- save/load
    def save(self, path: str, training: bool = True):
        from .checkpoint import save as _save
        self.network.bind(self._params)
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._opt_state is not None:
            _save({"opt_state": self._opt_state}, path + ".pdopt")

    def load(self, path: str, reset_optimizer: bool = False):
        import os
        from .checkpoint import load as _load
        self.network.set_state_dict(_load(path + ".pdparams"))
        self._pure_fn, self._params = self.network.functional()
        opt_path = path + ".pdopt"
        # checkpoint.save appends .npz to array archives
        if not reset_optimizer and (os.path.exists(opt_path) or
                                    os.path.exists(opt_path + ".npz")):
            self._opt_state = _load(opt_path)["opt_state"]
        return self

    def parameters(self):
        return self.network.parameters()


def summary(net: Layer, input_size=None, dtypes=None):
    """paddle.summary parity: layer tree with parameter counts."""
    rows = []
    total = 0
    for name, sub in net.named_sublayers(include_self=True):
        own = sum(int(np.prod(v.shape)) for v in sub._parameters.values())
        total += own
        if own or not name:
            rows.append((name or type(net).__name__,
                         type(sub).__name__, own))
    lines = [f"{'Layer':40s} {'Type':24s} {'Params':>12s}"]
    lines += [f"{n:40s} {t:24s} {p:>12,d}" for n, t, p in rows]
    lines.append(f"{'Total params':>66s}: {total:,d}")
    text = "\n".join(lines)
    print(text, flush=True)
    return {"total_params": total, "text": text}
