"""Deterministic fault injection + retry/backoff (chaos hardening).

The optimistic halves of fault tolerance (StepWatchdog, elastic
supervise, orbax auto-resume) only matter if the recovery paths they
feed actually run. This module makes failures *injectable on purpose* —
seeded, occurrence-addressed, and identical run-to-run — so every
recovery path has a tier-1 test that kills/corrupts/overloads and
asserts the run still converges or degrades gracefully.

Two control channels, one registry:

- env var ``PADDLE_TPU_FAULTS`` — read per ``inject()`` call (cheap, and
  it propagates into spawned DataLoader workers / elastic relaunches for
  free);
- context manager ``scoped(spec)`` — scoped arming for in-process tests.

Spec grammar (comma-separated entries)::

    site[@WHEN][xCOUNT][~PROB]

    step_nan                 fire on every occurrence
    step_nan@8               fire only on occurrence 8 (0-based call count)
    ckpt_corrupt@2+          every occurrence >= 2
    worker_crash@1-3         occurrences 1..3 inclusive
    collective_fail x2       at most 2 fires total (spaces optional)
    hang~0.1                 each occurrence fires with p=0.1 from a PRNG
                             seeded by PADDLE_TPU_FAULT_SEED + site name
                             (deterministic across runs)

``inject(site, **ctx)`` answers "should this site's fault fire now?" —
the *call-site* owns what firing means (NaN the params, flip bytes,
``os._exit``, sleep, raise), keeping each fault's blast radius next to
the code it breaks. The wired sites are listed in ``SITES`` and printed
by ``python -m paddle_tpu.utils.faults --list``.

``retry_with_backoff`` is the shared transient-failure helper (jittered
exponential backoff, max-attempts, retryable-exception filter) adopted
by ``distributed.elastic.supervise`` and the eager collective wrappers.
"""
from __future__ import annotations

import os
import random
import sys
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

ENV_VAR = "PADDLE_TPU_FAULTS"
# per-site stderr/flight-event verbosity cap (ISSUE 16: storm-class
# sites fire thousands of times per armed window)
VERBOSE_FIRES_PER_SITE = 8
SEED_ENV_VAR = "PADDLE_TPU_FAULT_SEED"
HANG_ENV_VAR = "PADDLE_TPU_FAULT_HANG_S"
PREFETCH_STALL_ENV_VAR = "PADDLE_TPU_FAULT_PREFETCH_STALL_S"
DISPATCH_HANG_ENV_VAR = "PADDLE_TPU_FAULT_DISPATCH_HANG_S"
STREAM_STALL_ENV_VAR = "PADDLE_TPU_FAULT_STREAM_STALL_S"
SLOW_REPLICA_ENV_VAR = "PADDLE_TPU_FAULT_SLOW_REPLICA_S"
PEER_SLOW_ENV_VAR = "PADDLE_TPU_FAULT_PEER_SLOW_S"
SPILL_SLOW_ENV_VAR = "PADDLE_TPU_FAULT_SPILL_SLOW_S"
XFER_SLOW_ENV_VAR = "PADDLE_TPU_FAULT_XFER_SLOW_S"

__all__ = [
    "SITES", "inject", "scoped", "configure", "reset", "parse_spec",
    "retry_with_backoff", "BackpressureError", "RequestTimeoutError",
    "hang_seconds", "prefetch_stall_seconds", "dispatch_hang_seconds",
    "stream_stall_seconds", "slow_replica_seconds",
    "peer_slow_seconds", "spill_slow_seconds", "xfer_slow_seconds",
    "main",
]

# ------------------------------------------------------------- inventory
# site name -> (wired location, what firing does there). ONE source of
# truth: the CLI prints this, the docs table is generated from the same
# text, and tests assert every listed site is actually wired.
SITES: Dict[str, Tuple[str, str]] = {
    "step_nan": (
        "paddle_tpu/trainer.py:Trainer.train",
        "poison the just-finished step: loss and float params become NaN "
        "(numeric divergence; exercises StepWatchdog nan_patience + the "
        "Trainer's bounded checkpoint-rollback loop)"),
    "ckpt_corrupt": (
        "paddle_tpu/checkpoint/distributed_ckpt.py:"
        "DistributedCheckpoint._write_manifest",
        "flip bytes in a committed checkpoint step's files AFTER its "
        "manifest is written (bit rot; exercises checksum verification "
        "and the previous-complete-step restore fallback)"),
    "worker_crash": (
        "paddle_tpu/io/worker.py:_worker_loop",
        "hard-exit (os._exit) a DataLoader worker process while a batch "
        "is outstanding (OOM-kill stand-in; exercises the pool's "
        "dead-worker detection instead of an eternal queue.get)"),
    "hang": (
        "paddle_tpu/trainer.py:Trainer.train",
        "sleep PADDLE_TPU_FAULT_HANG_S (default 3600) seconds before the "
        "next step (preempted-chip stand-in; exercises the StepWatchdog "
        "hang path: checkpoint + exit for the elastic supervisor)"),
    "collective_fail": (
        "paddle_tpu/distributed/collective.py:_eager",
        "raise CollectiveError before an eager collective runs "
        "(transient ICI/DCN failure; exercises retry_with_backoff "
        "around the collective wrappers)"),
    "preempt": (
        "paddle_tpu/trainer.py:Trainer.train",
        "request graceful shutdown at the next step boundary (SIGTERM "
        "stand-in for a scheduler preemption notice): the Trainer "
        "checkpoints its exact step, drains the async writer, and exits "
        "PREEMPTED_RC — which elastic.supervise restarts without "
        "consuming a max_restarts attempt"),
    "prefetch_stall": (
        "paddle_tpu/io/device_prefetch.py:_PrefetchIterator._produce",
        "sleep PADDLE_TPU_FAULT_PREFETCH_STALL_S (default 30) in the "
        "device-prefetch producer thread before its next fetch (slow or "
        "wedged host input pipeline stand-in; the consumer's stall "
        "timeout degrades the trainer to synchronous feeding instead of "
        "deadlocking the step loop)"),
    # --- serving-fleet chaos (ISSUE 12): the five replica-level sites
    # the chaos harness (tools/serve_loadgen.py --chaos) and the
    # supervisor/failover tests arm. All wired into the gateway's
    # replica tick loop / SSE writer.
    "tick_crash": (
        "paddle_tpu/serving/gateway.py:_ReplicaWorker.run",
        "raise RuntimeError on the replica's tick thread before the "
        "next engine.step() (software crash stand-in; exercises "
        "_fail_all's failover hand-off: live requests resubmit to a "
        "surviving replica, the supervisor rebuilds the engine and "
        "rejoins it through the circuit breaker)"),
    "dispatch_hang": (
        "paddle_tpu/serving/gateway.py:_ReplicaWorker.run",
        "sleep PADDLE_TPU_FAULT_DISPATCH_HANG_S (default 3600) on the "
        "tick thread with the dispatch-busy marker set (wedged fused "
        "dispatch stand-in; exercises the supervisor watchdog's "
        "dispatch-to-drain deadline: the replica is abandoned, its "
        "requests fail over, the engine is rebuilt)"),
    "replica_drop": (
        "paddle_tpu/serving/gateway.py:_ReplicaWorker.run",
        "hard-exit the replica's tick thread with NO cleanup (process "
        "kill stand-in; exercises the supervisor's dead-thread "
        "detection + failover — nothing on the dying thread runs)"),
    "stream_stall": (
        "paddle_tpu/serving/gateway.py:Gateway._stream_sse",
        "sleep PADDLE_TPU_FAULT_STREAM_STALL_S (default 5) in the SSE "
        "writer before the next token event (slow client / congested "
        "wire stand-in; one stalled stream must not stall the replica "
        "tick loop or corrupt the stream's token order)"),
    "slow_replica": (
        "paddle_tpu/serving/gateway.py:_ReplicaWorker.run",
        "sleep PADDLE_TPU_FAULT_SLOW_REPLICA_S (default 0.05) per tick "
        "on the replica's tick thread (degraded-host stand-in; the "
        "watchdog must NOT fire below its deadline, and least-loaded "
        "routing shifts traffic off the slow replica)"),
    # --- multi-host fleet chaos (ISSUE 13): remote-replica fault
    # sites wired into the fleet frontend's proxy path and the peer
    # prober — the remote analogues of tick_crash/slow_replica.
    "peer_conn_drop": (
        "paddle_tpu/serving/fleet/frontend.py:"
        "FleetFrontend._proxy_stream",
        "sever the frontend->peer connection of an in-flight proxied "
        "stream (peer gateway process death / network partition "
        "stand-in; exercises the fleet failover path: resubmit "
        "prompt+committed on a surviving peer, greedy streams stay "
        "bitwise the uninterrupted run)"),
    "peer_slow": (
        "paddle_tpu/serving/fleet/remote.py:RemoteReplica._probe_once",
        "sleep PADDLE_TPU_FAULT_PEER_SLOW_S (default 0.05) in a remote "
        "replica's health/gossip probe (congested peer stand-in; the "
        "staleness bound must evict a peer whose probes stop landing, "
        "never wedge the router)"),
    # --- frontend HA chaos (ISSUE 16): the frontend tier's own
    # failure modes, exercised by the fleet sim's chaos schedules and
    # the --frontend-kill loadgen.
    "frontend_conn_drop": (
        "paddle_tpu/serving/fleet/frontend.py:"
        "FleetFrontend._proxy_stream",
        "sever the CLIENT->frontend leg of an in-flight proxied "
        "stream (frontend process death stand-in; the client holds "
        "only its committed prefix and must resume against a "
        "surviving sibling frontend via resume_tokens — zero lost, "
        "zero duplicated committed tokens)"),
    "gossip_partition": (
        "paddle_tpu/serving/fleet/remote.py:RemoteReplica._probe_once",
        "partition the GOSSIP channel only: the health leg lands but "
        "digest/metrics fetches are dropped (also severs "
        "frontend<->frontend /gossipz links in serving/fleet/ha.py); "
        "peers stay routable while warm routing degrades toward "
        "least-loaded — a partition must never read as an outage"),
    "peer_storm": (
        "paddle_tpu/serving/fleet/remote.py:probe_delay",
        "collapse the seeded probe-round jitter to zero delay so "
        "every armed peer's next round fires NOW (thundering-herd "
        "stand-in at N frontends x M peers; the fleet sim's "
        "probe-storm schedule arms it and must page, while the "
        "jittered clean twin stays quiet)"),
    # --- KV spill tier chaos (ISSUE 17): the host-RAM arena's own
    # failure modes. All wired inside KVSpillArena so EVERY producer
    # (eviction spill, drain spill) and consumer (warm-miss restore)
    # inherits them.
    "spill_corrupt": (
        "paddle_tpu/serving/kvspill.py:KVSpillArena.spill",
        "flip one byte of a span's host payload AFTER its crc32 is "
        "banked (silent host-RAM bit rot stand-in; the take-side "
        "checksum must catch it, drop the record, count "
        "kv_spill_checksum_failures_total, and fall back to re-prefill "
        "with the greedy stream bitwise identical to spill-off)"),
    "spill_slow": (
        "paddle_tpu/serving/kvspill.py:KVSpillArena.take",
        "sleep PADDLE_TPU_FAULT_SPILL_SLOW_S (default 0.05) in the "
        "arena's D2H spill / H2D restore path (host memory-bandwidth "
        "contention stand-in; a slow arena must only delay the one "
        "admission, never wedge the engine tick loop or corrupt "
        "restored spans)"),
    "spill_drop": (
        "paddle_tpu/serving/kvspill.py:KVSpillArena.spill",
        "refuse a span's store outright (arena allocation failure / "
        "capacity-pressure stand-in; the span is counted in "
        "kv_spill_drops_total and its next warm miss re-prefills "
        "normally — a lost spill costs latency, never tokens)"),
    # --- cross-replica KV transfer chaos (ISSUE 18): the wire between
    # gateway arenas. corrupt/trunc live in the kvxfer encoder so every
    # sender (the /kvz endpoint, drain migration blobs) inherits them;
    # slow lives in the gateway handler, bounded by the fetch side's
    # xfer_timeout_s.
    "xfer_corrupt": (
        "paddle_tpu/serving/kvxfer.py:encode_span",
        "flip one payload byte of a wire record AFTER its header crc32 "
        "is banked (wire bit rot stand-in; the receiver's decode ladder "
        "must catch it, count kv_xfer_checksum_failures_total, and fall "
        "back to re-prefill — a corrupted transfer never emits a "
        "token)"),
    "xfer_trunc": (
        "paddle_tpu/serving/kvxfer.py:encode_span",
        "cut a wire record to half its length (transfer severed "
        "mid-body; the receiver's byte-count rung refuses it, counts "
        "kv_xfer_fallbacks_total, and the stream re-prefills bitwise "
        "identically)"),
    "xfer_slow": (
        "paddle_tpu/serving/gateway.py:Gateway._dispatch_http",
        "sleep PADDLE_TPU_FAULT_XFER_SLOW_S (default 0.05) before "
        "serving a GET /kvz span (congested inter-replica link "
        "stand-in; the fetch side bounds the wait with xfer_timeout_s "
        "and falls back to re-prefill on expiry — a slow transfer "
        "costs latency, never tokens)"),
}


# ------------------------------------------------------------ exceptions
class BackpressureError(RuntimeError):
    """Serving admission queue at capacity: the request was rejected
    immediately rather than queued (the caller should back off/shed)."""


class RequestTimeoutError(TimeoutError):
    """A served request exceeded its per-request deadline and was
    cancelled before (or instead of) completing."""


# ------------------------------------------------------------- fault plan
@dataclass
class _Rule:
    site: str
    lo: int = 0                      # first firing occurrence (inclusive)
    hi: Optional[int] = None         # last firing occurrence (inclusive)
    times: Optional[int] = None      # max total fires
    prob: Optional[float] = None     # per-occurrence probability
    fired: int = 0

    def matches(self, occ: int, rng: random.Random) -> bool:
        if occ < self.lo or (self.hi is not None and occ > self.hi):
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self.prob is not None and rng.random() >= self.prob:
            return False
        self.fired += 1
        return True


class FaultPlan:
    """Parsed spec + per-site occurrence counters. Deterministic: the
    probabilistic stream is seeded by (seed, site), and occurrence
    counters advance once per ``inject()`` call regardless of outcome."""

    def __init__(self, rules: List[_Rule], seed: int = 0, raw: str = ""):
        self.raw = raw
        self.rules: Dict[str, List[_Rule]] = {}
        for r in rules:
            self.rules.setdefault(r.site, []).append(r)
        self._occ: Dict[str, int] = {}
        self._fires: Dict[str, int] = {}
        self._rng: Dict[str, random.Random] = {
            s: random.Random(f"{seed}:{s}") for s in self.rules}
        self._lock = threading.Lock()

    def should_fire(self, site: str) -> Tuple[bool, int, int]:
        """Returns (fired, occurrence index, fire index). The fire
        index drives per-site verbosity capping — high-frequency sites
        (``peer_storm`` fires every armed probe round; the fleet sim
        arms it at thousands of rounds) must not flood stderr or evict
        the flight-recorder window."""
        with self._lock:
            occ = self._occ.get(site, 0)
            self._occ[site] = occ + 1
            for rule in self.rules.get(site, ()):
                if rule.matches(occ, self._rng[site]):
                    n = self._fires.get(site, 0)
                    self._fires[site] = n + 1
                    return True, occ, n
        return False, occ, self._fires.get(site, 0)

    def occurrences(self, site: str) -> int:
        with self._lock:
            return self._occ.get(site, 0)


def parse_spec(spec: str, seed: Optional[int] = None) -> FaultPlan:
    """Parse a spec string (grammar in the module docstring). Unknown
    site names raise: a typo'd chaos experiment that silently never
    fires is worse than no experiment."""
    if seed is None:
        seed = int(os.environ.get(SEED_ENV_VAR, "0"))
    rules = []
    for entry in spec.split(","):
        entry = entry.replace(" ", "")
        if not entry:
            continue
        prob = None
        if "~" in entry:
            entry, p = entry.split("~", 1)
            prob = float(p)
        times = None
        if "x" in entry:
            # the times suffix is "<site>x<N>": split on the LAST "x"
            # and only when an integer follows, so site names that
            # themselves contain an "x" (xfer_corrupt, ...) parse
            head, t = entry.rsplit("x", 1)
            if t.isdigit():
                entry, times = head, int(t)
        lo, hi = 0, None
        if "@" in entry:
            entry, when = entry.split("@", 1)
            if when.endswith("+"):
                lo = int(when[:-1])
            elif "-" in when:
                a, b = when.split("-", 1)
                lo, hi = int(a), int(b)
            else:
                lo = hi = int(when)
        if entry not in SITES:
            raise ValueError(
                f"unknown fault site {entry!r}; known: {sorted(SITES)}")
        rules.append(_Rule(entry, lo=lo, hi=hi, times=times, prob=prob))
    return FaultPlan(rules, seed=seed, raw=spec)


# ------------------------------------------------------------ global state
_env_plan: Optional[FaultPlan] = None   # cache keyed by the raw env value
_configured: Optional[FaultPlan] = None
_scoped_stack: List[FaultPlan] = []
_state_lock = threading.Lock()


def _active_plan() -> Optional[FaultPlan]:
    with _state_lock:
        if _scoped_stack:
            return _scoped_stack[-1]
        if _configured is not None:
            return _configured
        global _env_plan
        raw = os.environ.get(ENV_VAR, "")
        if not raw:
            _env_plan = None
        elif _env_plan is None or _env_plan.raw != raw:
            # re-read on change so monkeypatched env in tests (and the
            # spawned-worker inheritance path) takes effect without an
            # explicit reset; counters restart with the new plan
            _env_plan = parse_spec(raw)
        return _env_plan


def inject(site: str, **ctx) -> bool:
    """Injection-site hook: True iff the armed plan says this occurrence
    of ``site`` should fail. Unarmed (the production default) this is a
    dict lookup + env read — cheap enough for per-step call sites."""
    if site not in SITES:
        raise ValueError(f"unregistered fault site {site!r}")
    plan = _active_plan()
    if plan is None:
        return False
    fired, occ, nth = plan.should_fire(site)
    if fired:
        # verbose for the first few fires per site, then one suppression
        # notice: a storm-class site fires thousands of times per armed
        # window and must not flood stderr or evict the flight window
        # (the counter keeps the full tally either way)
        if nth < VERBOSE_FIRES_PER_SITE:
            info = " ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
            print(f"[faults] firing {site} (occurrence {occ})"
                  + (f" {info}" if info else ""),
                  file=sys.stderr, flush=True)
        elif nth == VERBOSE_FIRES_PER_SITE:
            print(f"[faults] {site} keeps firing; further fires "
                  f"logged only to fault_fires_total",
                  file=sys.stderr, flush=True)
        # observability: the early fires land in the flight recorder
        # (the postmortem window must show WHICH chaos preceded the
        # crash) and every fire in a per-site counter. Imported lazily
        # on the fired path; the unarmed hot path stays a dict lookup
        # + env read.
        try:
            from . import observability as obs
            if nth <= VERBOSE_FIRES_PER_SITE:
                obs.record_event("fault_fire", site=site,
                                 occurrence=occ, **ctx)
            obs.counter("fault_fires_total", site=site).inc()
        except Exception:
            pass      # telemetry must never break the chaos experiment
    return fired


def configure(spec: Optional[str], seed: Optional[int] = None) -> None:
    """Install a process-global plan (None reverts to env-var control)."""
    global _configured
    with _state_lock:
        _configured = parse_spec(spec, seed=seed) if spec else None


class scoped:
    """``with faults.scoped("ckpt_corrupt@1"):`` — arm a plan for the
    dynamic extent of the block, then restore whatever was active."""

    def __init__(self, spec: str, seed: Optional[int] = None):
        self.plan = parse_spec(spec, seed=seed)

    def __enter__(self) -> FaultPlan:
        with _state_lock:
            _scoped_stack.append(self.plan)
        return self.plan

    def __exit__(self, *exc):
        with _state_lock:
            _scoped_stack.remove(self.plan)
        return False


def reset() -> None:
    """Drop all armed plans and counters (tests)."""
    global _configured, _env_plan
    with _state_lock:
        _configured = None
        _env_plan = None
        _scoped_stack.clear()


def hang_seconds() -> float:
    """How long a fired ``hang`` site should sleep."""
    return float(os.environ.get(HANG_ENV_VAR, "3600"))


def prefetch_stall_seconds() -> float:
    """How long a fired ``prefetch_stall`` site wedges the producer."""
    return float(os.environ.get(PREFETCH_STALL_ENV_VAR, "30"))


def dispatch_hang_seconds() -> float:
    """How long a fired ``dispatch_hang`` site wedges the tick thread."""
    return float(os.environ.get(DISPATCH_HANG_ENV_VAR, "3600"))


def stream_stall_seconds() -> float:
    """How long a fired ``stream_stall`` site delays the SSE writer."""
    return float(os.environ.get(STREAM_STALL_ENV_VAR, "5"))


def slow_replica_seconds() -> float:
    """Per-tick delay of a fired ``slow_replica`` site."""
    return float(os.environ.get(SLOW_REPLICA_ENV_VAR, "0.05"))


def peer_slow_seconds() -> float:
    """Per-probe delay of a fired ``peer_slow`` site."""
    return float(os.environ.get(PEER_SLOW_ENV_VAR, "0.05"))


def spill_slow_seconds() -> float:
    """Per-copy delay of a fired ``spill_slow`` site."""
    return float(os.environ.get(SPILL_SLOW_ENV_VAR, "0.05"))


def xfer_slow_seconds() -> float:
    """Per-span delay of a fired ``xfer_slow`` site."""
    return float(os.environ.get(XFER_SLOW_ENV_VAR, "0.05"))


# ---------------------------------------------------------------- retry
def retry_with_backoff(fn: Callable, *, max_attempts: int = 3,
                       base_delay: float = 0.05, factor: float = 2.0,
                       max_delay: float = 30.0, jitter: float = 0.25,
                       retryable=(Exception,),
                       on_retry: Optional[Callable] = None,
                       sleep: Callable[[float], None] = time.sleep,
                       seed: Optional[int] = None):
    """Call ``fn()``; on a ``retryable`` exception, sleep a jittered
    exponential backoff and try again, up to ``max_attempts`` total
    attempts (then re-raise the last exception). Non-retryable
    exceptions propagate immediately.

    delay_k = min(max_delay, base_delay * factor**k) * (1 + jitter*u_k).
    By default u_k is seeded per-process (pid), so a preempted FLEET does
    not retry in lockstep — jitter's whole job is decorrelating the
    herd. Pass an explicit ``seed`` for a reproducible schedule (tests;
    the injection layer's determinism contract).
    ``on_retry(exc, attempt, delay)`` observes each retry.
    """
    if max_attempts < 1:
        raise ValueError("max_attempts must be >= 1")
    rng = random.Random(os.getpid() if seed is None else seed)
    for attempt in range(1, max_attempts + 1):
        try:
            return fn()
        except retryable as e:
            if attempt == max_attempts:
                raise
            delay = min(max_delay, base_delay * (factor ** (attempt - 1)))
            delay *= 1.0 + jitter * rng.random()
            if on_retry is not None:
                on_retry(e, attempt, delay)
            sleep(delay)


# ------------------------------------------------------------------ CLI
def main(argv: Optional[List[str]] = None) -> int:
    """``python -m paddle_tpu.utils.faults --list`` — self-describing
    inventory of the wired injection sites."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("--list", "list"):
        try:
            print(f"fault injection sites (arm via ${ENV_VAR} or "
                  f"paddle_tpu.utils.faults.scoped):")
            for name in sorted(SITES):
                where, what = SITES[name]
                print(f"\n  {name}")
                print(f"      wired: {where}")
                print(f"      fires: {what}")
            print(f"\nspec grammar: site[@WHEN][xCOUNT][~PROB], "
                  f"comma-separated; seed via ${SEED_ENV_VAR}")
        except BrokenPipeError:   # `... --list | head` is fine
            pass
        return 0
    print("usage: python -m paddle_tpu.utils.faults --list",
          file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
