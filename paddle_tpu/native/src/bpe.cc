// Native BPE encoder (reference: the reference stack tokenizes with a
// compiled tokenizer — HF tokenizers' Rust merge loop — while our
// tokenizer/bpe.py runs the merge loop in Python; this is the C++ hot
// path for data prep).
//
// Works on RAW BYTES: the GPT-2 byte<->unicode table is a bijection, so
// running the rank-ordered merge loop on byte strings yields exactly the
// ids the printable-alphabet form does. Python keeps the regex
// pretokenizer and special-token handling; each pretokenized word comes
// here as bytes. A word-level memo cache makes corpus encoding O(unique
// words).

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#define PT_API extern "C" __attribute__((visibility("default")))

namespace {

struct Bpe {
  // token bytes -> id (for multi-byte lookups after merges)
  std::unordered_map<std::string, int32_t> vocab;
  // id -> token bytes (to key the pair map by content)
  std::vector<std::string> id_bytes;
  // (left_id << 32 | right_id) -> {rank, merged_id}
  std::unordered_map<uint64_t, std::pair<int32_t, int32_t>> pairs;
  int32_t byte_id[256];
  std::unordered_map<std::string, std::vector<int32_t>> cache;
  std::mutex cache_mu;
  size_t cache_cap = 1 << 16;
};

inline uint64_t pack(int32_t l, int32_t r) {
  return (uint64_t(uint32_t(l)) << 32) | uint32_t(r);
}

}  // namespace

PT_API Bpe* pt_bpe_create(int32_t n_vocab, const uint8_t* blob,
                          const int32_t* offsets, const int32_t* ids,
                          int32_t max_id, int32_t n_merges,
                          const int32_t* merge_l, const int32_t* merge_r,
                          const int32_t* merge_m) {
  auto* t = new Bpe();
  for (int i = 0; i < 256; ++i) t->byte_id[i] = -1;
  t->id_bytes.assign(size_t(max_id) + 1, std::string());
  for (int32_t i = 0; i < n_vocab; ++i) {
    std::string tok(reinterpret_cast<const char*>(blob + offsets[i]),
                    size_t(offsets[i + 1] - offsets[i]));
    int32_t id = ids[i];
    t->vocab.emplace(tok, id);
    if (id >= 0 && size_t(id) < t->id_bytes.size()) t->id_bytes[id] = tok;
    if (tok.size() == 1) t->byte_id[uint8_t(tok[0])] = id;
  }
  for (int32_t i = 0; i < n_merges; ++i) {
    // LAST occurrence wins on duplicate pairs — exactly the dict
    // comprehension the Python side builds ranks with
    t->pairs[pack(merge_l[i], merge_r[i])] = std::make_pair(i, merge_m[i]);
  }
  return t;
}

PT_API void pt_bpe_destroy(Bpe* t) { delete t; }

static void encode_uncached(Bpe* t, const uint8_t* word, int32_t len,
                            std::vector<int32_t>& out) {
  out.clear();
  for (int32_t i = 0; i < len; ++i) {
    int32_t id = t->byte_id[word[i]];
    if (id < 0) {  // byte not in vocab: caller falls back to Python
      out.clear();
      out.push_back(-1);
      return;
    }
    out.push_back(id);
  }
  while (out.size() > 1) {
    int32_t best_rank = INT32_MAX, merged = -1;
    for (size_t i = 0; i + 1 < out.size(); ++i) {
      auto it = t->pairs.find(pack(out[i], out[i + 1]));
      if (it != t->pairs.end() && it->second.first < best_rank) {
        best_rank = it->second.first;
        merged = it->second.second;
      }
    }
    if (merged < 0) break;
    // fuse every occurrence of the chosen pair in one pass
    std::vector<int32_t> next;
    next.reserve(out.size());
    for (size_t i = 0; i < out.size();) {
      if (i + 1 < out.size()) {
        auto it = t->pairs.find(pack(out[i], out[i + 1]));
        if (it != t->pairs.end() && it->second.first == best_rank) {
          next.push_back(it->second.second);
          i += 2;
          continue;
        }
      }
      next.push_back(out[i]);
      ++i;
    }
    out.swap(next);
  }
}

// Encode a batch of pretokenized words (concatenated bytes + offsets).
// Returns total ids written, or -(failed_word_index + 1) if a word needs
// the Python fallback (unknown byte), or -1000000 if out_cap too small.
PT_API int64_t pt_bpe_encode_words(Bpe* t, const uint8_t* blob,
                                   const int32_t* offsets, int32_t n_words,
                                   int32_t* out, int64_t out_cap,
                                   int32_t* word_ends) {
  int64_t n_out = 0;
  std::vector<int32_t> ids;
  for (int32_t w = 0; w < n_words; ++w) {
    const uint8_t* word = blob + offsets[w];
    int32_t len = offsets[w + 1] - offsets[w];
    std::string key(reinterpret_cast<const char*>(word), size_t(len));
    bool cached = false;
    {
      std::lock_guard<std::mutex> lk(t->cache_mu);
      auto it = t->cache.find(key);
      if (it != t->cache.end()) {
        ids = it->second;
        cached = true;
      }
    }
    if (!cached) {
      encode_uncached(t, word, len, ids);
      if (ids.size() == 1 && ids[0] == -1) return -int64_t(w) - 1;
      std::lock_guard<std::mutex> lk(t->cache_mu);
      if (t->cache.size() < t->cache_cap) t->cache.emplace(key, ids);
    }
    if (n_out + int64_t(ids.size()) > out_cap) return -1000000;
    std::memcpy(out + n_out, ids.data(), ids.size() * sizeof(int32_t));
    n_out += int64_t(ids.size());
    word_ends[w] = int32_t(n_out);
  }
  return n_out;
}
